"""Long-context training showcase: ring attention + flash attention.

New scope beyond the reference (it has no sequence-scaling machinery,
SURVEY §5.7): a causal LM whose sequence dimension is sharded over the
``seq`` mesh axis — K/V blocks rotate between chips via ppermute (ring
attention) so max context grows linearly with chips at constant per-chip
memory — while per-chip attention blocks use the Pallas flash kernel.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py --seq-len 512
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import optax

from autodist_tpu.mesh import build_mesh
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.parallel import make_ring_attention
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark


def main():
    p = benchmark_args("long-context LM (sequence parallelism)")
    p.set_defaults(strategy="PartitionedPS", batch_size=4)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--seq-shards", type=int, default=4)
    p.add_argument("--data-shards", type=int, default=2)
    args = p.parse_args()

    axes = {"data": args.data_shards, "seq": args.seq_shards}
    mesh = build_mesh(axes)
    spec = transformer_lm(
        vocab_size=2048, num_layers=2, num_heads=4, head_dim=32, d_ff=512,
        max_len=args.seq_len, seq_len=args.seq_len,
        attn_fn=make_ring_attention(mesh))
    params = spec.init(jax.random.PRNGKey(0))

    ad = make_autodist(args, mesh_axes=axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adamw(args.lr),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session(mesh=mesh)
    run_selected_benchmark(
        spec, sess, args, unit="tokens",
        items_per_batch=args.batch_size * args.seq_len)


if __name__ == "__main__":
    main()
