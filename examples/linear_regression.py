"""Linear regression — the minimal end-to-end example.

Parity target: reference ``examples/linear_regression.py`` (TF1 graph built
under ``ad.scope()``, trained via ``ad.create_distributed_session()``).
TPU-native version: capture a functional program, run distributed steps.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/linear_regression.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.strategy import PSLoadBalancing

TRUE_W, TRUE_B = 3.0, 2.0
NUM_EXAMPLES = 2000
LR = 0.01
STEPS = 200


def main():
    rng = np.random.RandomState(42)
    inputs = rng.randn(NUM_EXAMPLES).astype(np.float32)
    noises = rng.randn(NUM_EXAMPLES).astype(np.float32)
    outputs = inputs * TRUE_W + TRUE_B + noises * 0.1

    params = {"w": jnp.array(5.0), "b": jnp.array(0.0)}

    def loss_fn(params, batch):
        pred = params["w"] * batch["x"] + params["b"]
        return jnp.mean((batch["y"] - pred) ** 2)

    ad = AutoDist(strategy_builder=PSLoadBalancing())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(LR), loss_fn=loss_fn)
    sess = ad.create_distributed_session()

    batch = {"x": inputs, "y": outputs}
    for step in range(STEPS):
        metrics = sess.run(batch)
        if step % 50 == 0:
            print(f"step {step:4d} loss {float(metrics['loss']):.5f}")

    final = sess.params
    print(f"learned w={float(final['w']):.3f} (true {TRUE_W}), "
          f"b={float(final['b']):.3f} (true {TRUE_B})")
    assert abs(float(final["w"]) - TRUE_W) < 0.1
    assert abs(float(final["b"]) - TRUE_B) < 0.1
    print("OK")


if __name__ == "__main__":
    main()
