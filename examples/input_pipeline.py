"""End-to-end input pipeline: native C++ loader → fit → async checkpoints.

Parity target: the reference fed training through feed_dict remapping
(``autodist/remapper.py:81-123``) with no input pipeline of its own.  Here
the full TPU-era loop: the native prefetching ``DataLoader`` (C++ threads
gather + bf16-cast batches on host) feeds ``session.fit`` (device
prefetch + async dispatch), while an ``async_save`` Saver persists
checkpoints in the background of training.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/input_pipeline.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--rows", type=int, default=4096)
    p.add_argument("--checkpoint-dir", default="/tmp/autodist_tpu_pipeline")
    args = p.parse_args()

    from autodist_tpu import AutoDist, TimeHistory
    from autodist_tpu.runtime.data_loader import DataLoader
    from autodist_tpu.strategy import PSLoadBalancing

    # Synthetic regression dataset, float32 on host; the loader casts the
    # features to bf16 while gathering (C++ threads, not the TPU's time).
    rng = np.random.RandomState(0)
    x = rng.randn(args.rows, 64).astype(np.float32)
    w = rng.randn(64, 8).astype(np.float32)
    y = (x @ w).astype(np.float32)
    loader = DataLoader({"x": x, "y": y}, batch_size=args.batch_size,
                        shuffle=True, to_bf16=["x"], num_threads=4,
                        prefetch_depth=2)

    params = {"w": jnp.zeros((64, 8)), "b": jnp.zeros((8,))}

    def loss_fn(p, batch):
        pred = batch["x"].astype(jnp.float32) @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    ad = AutoDist(strategy_builder=PSLoadBalancing())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(0.05),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()

    th = TimeHistory(items_per_step=args.batch_size)
    hist = sess.fit(loader, epochs=args.epochs, callbacks=[th],
                    log_every=20, checkpoint_dir=args.checkpoint_dir,
                    async_checkpoints=True)
    for e, rate in enumerate(th.items_per_sec):
        print(f"epoch {e}: {rate:,.0f} samples/sec, "
              f"loss {hist.history['epoch_loss'][e]:.5f}")
    print(f"final loss {hist.history['epoch_loss'][-1]:.6f} after "
          f"{hist.steps_run} steps; checkpoints in {args.checkpoint_dir}")


if __name__ == "__main__":
    main()
