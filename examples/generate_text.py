"""Train a tiny LM through AutoDist, then decode from it with the
KV-cache generator (``models/generate.py``) — the serving-side loop.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generate_text.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--new-tokens", type=int, default=12)
    args = p.parse_args()

    from autodist_tpu import AutoDist
    from autodist_tpu.models import make_generator, transformer_lm
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.strategy import Parallax

    vocab = 64
    spec = transformer_lm(vocab_size=vocab, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=64, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))

    # A learnable toy language: ascending token runs with wraparound.
    rng = np.random.RandomState(0)

    def make_batch(n=32):
        start = rng.randint(0, vocab, (n, 1))
        seq = (start + np.arange(16)[None, :]) % vocab
        return {"tokens": seq.astype(np.int32)}

    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    for i in range(args.steps):
        out = sess.run(make_batch())
        if i % 10 == 0:
            print(f"step {i:3d} loss {float(out['loss']):.4f}")

    gen = make_generator(spec)
    prompt = np.array([[5, 6, 7, 8], [40, 41, 42, 43]], np.int32)
    tokens = np.asarray(gen(sess.sharded_params, prompt, args.new_tokens))
    for row in tokens:
        print("generated:", " ".join(map(str, row.tolist())))
    # The model should have learned to continue the ascending run.
    cont = tokens[:, 4:]
    expect = (tokens[:, 3:4] + 1 + np.arange(args.new_tokens)) % vocab
    acc = float((cont == expect).mean())
    print(f"ascending-run continuation accuracy: {acc:.2f}")
    assert acc > 0.9, acc

    # The rest of the serving surface on the same generator:
    beam_tokens, beam_lp = gen.beam_search(sess.sharded_params, prompt,
                                           args.new_tokens, num_beams=4)
    print("beam-4 suffix logprob:", [round(float(x), 3)
                                     for x in np.asarray(beam_lp)])
    ll, ppl = gen.score(sess.sharded_params, np.asarray(tokens))
    print("self-scored perplexity of the generations:",
          [round(float(x), 3) for x in np.asarray(ppl)])
    # a trained pattern-follower should be near-certain of its own output
    assert float(np.asarray(ppl).mean()) < 2.0


if __name__ == "__main__":
    main()
