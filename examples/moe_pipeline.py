"""Pipelined MoE LM showcase: pipeline × expert × data parallelism.

New scope beyond the reference (SURVEY §2.8: PP and EP absent): the
stage-stacked MoE transformer — layer stack sharded over ``pipe``
(microbatch ppermute ring), expert weights over ``expert`` (GSPMD
all-to-all dispatch), batch over ``data``.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_pipeline.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import optax

from autodist_tpu.mesh import build_mesh
from autodist_tpu.models.pipelined_moe_lm import pipelined_moe_transformer_lm
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark


def main():
    p = benchmark_args("pipelined MoE LM (pp x ep x dp)")
    p.set_defaults(strategy="PSLoadBalancing", batch_size=8)
    p.add_argument("--pipe", type=int, default=2)
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize stage internals in backward "
                        "(cuts stashed activation memory)")
    args = p.parse_args()

    axes = {"pipe": args.pipe, "expert": 2, "data": 2}
    mesh = build_mesh(axes)
    spec = pipelined_moe_transformer_lm(
        mesh, vocab_size=2048, num_layers=args.num_layers, num_heads=4,
        head_dim=32, d_ff=512, num_experts=args.experts,
        max_len=args.seq_len, seq_len=args.seq_len, remat=args.remat)
    params = spec.init(jax.random.PRNGKey(0))

    ad = make_autodist(args, mesh_axes=axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adamw(args.lr),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                   pipeline_vars=spec.pipeline_vars,
                   expert_vars=spec.expert_vars)
    sess = ad.create_distributed_session(mesh=mesh)
    run_selected_benchmark(
        spec, sess, args, unit="tokens",
        items_per_batch=args.batch_size * args.seq_len)


if __name__ == "__main__":
    main()
