"""HTTP serving demo (``serving/server.py``).

Starts an :class:`EngineServer` over the continuous-batching engine,
then acts as its own client: concurrent blocking completions, one SSE
streaming completion, and a stats read — the deployable serving loop
(model → engine → HTTP) the reference framework (training-only) has no
counterpart for.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve_http.py
Point a real client at it with --port 8000 --hold.
"""
import argparse
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=0,
                   help="0 picks an ephemeral port")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--window", type=int, default=96)
    p.add_argument("--hold", action="store_true",
                   help="keep serving until Ctrl-C instead of exiting")
    args = p.parse_args()

    from autodist_tpu.models import transformer_lm
    from autodist_tpu.serving import serve

    system_prompt = list(range(40, 52))     # the shared cached prefix
    # pos_embed must hold prefix + a full window of request positions
    spec = transformer_lm(vocab_size=331, num_layers=2, num_heads=4,
                          head_dim=16, d_ff=128,
                          max_len=args.window + len(system_prompt) + 4,
                          seq_len=32)
    params = spec.init(jax.random.PRNGKey(0))
    srv = serve(spec, params, port=args.port, slots=args.slots,
                window=args.window, chunk=8,
                temperature=0.8, top_p=0.95, rng=jax.random.PRNGKey(7),
                prefix_tokens=system_prompt)
    host, port = srv.address
    print(f"serving on http://{host}:{port}  "
          f"(POST /v1/completions, GET /v1/stats)")

    def post(path, body):
        c = http.client.HTTPConnection(host, port, timeout=300)
        c.request("POST", path, json.dumps(body),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        out = json.loads(r.read())
        c.close()
        return r.status, out

    # Concurrent blocking completions (more than the slot count).
    rng = np.random.RandomState(0)
    outs = {}

    def issue(i):
        prompt = rng.randint(0, 331, rng.randint(2, 8)).tolist()
        # every other request: per-request greedy override + the shared
        # system-prompt prefix as cached context
        body = {"prompt_tokens": prompt,
                "max_new_tokens": int(rng.randint(4, 12))}
        if i % 2:
            body["temperature"] = 0.0
            body["use_prefix"] = True
        outs[i] = post("/v1/completions", body)

    threads = [threading.Thread(target=issue, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(outs) == len(threads), \
        f"only {len(outs)}/{len(threads)} client threads completed"
    for i in sorted(outs):
        status, body = outs[i]
        assert status == 200, body
        print(f"  completion[{i}]: {len(body['new_tokens'])} new tokens "
              f"-> {body['new_tokens'][:8]}...")

    # One SSE streaming completion.
    c = http.client.HTTPConnection(host, port, timeout=300)
    c.request("POST", "/v1/completions",
              json.dumps({"prompt_tokens": [5, 9, 2],
                          "max_new_tokens": 12, "stream": True}),
              {"Content-Type": "application/json"})
    r = c.getresponse()
    assert r.status == 200, r.read()
    deltas = 0
    while True:
        line = r.readline()
        if not line:   # EOF: server closed without a done event
            print("  stream: closed early after "
                  f"{deltas} delta events")
            break
        line = line.strip()
        if line.startswith(b"data: "):
            ev = json.loads(line[6:])
            if ev.get("done"):
                if "tokens" in ev:
                    print(f"  stream: {deltas} delta events, final "
                          f"{len(ev['tokens'])} tokens")
                else:   # terminal timeout/cancelled event
                    print(f"  stream: terminated ({ev})")
                break
            deltas += 1
    c.close()

    st = post("/v1/cancel", {"id": 999})[1]
    print(f"  cancel unknown id -> cancelled={st['cancelled']}")
    c = http.client.HTTPConnection(host, port, timeout=60)
    c.request("GET", "/v1/stats")
    stats = json.loads(c.getresponse().read())
    c.close()
    print(f"  stats: served={stats['requests_served']} "
          f"completed={stats['completed']} "
          f"util={stats['slot_utilization']:.2f}")

    if args.hold:
        print("serving (Ctrl-C to stop) ...")
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
    srv.close()
    print("serve_http demo OK")


if __name__ == "__main__":
    main()
