"""NCF (neural collaborative filtering) benchmark, samples/sec.

Parity target: reference ``examples/benchmark`` NCF on MovieLens.  The
user/item embedding tables are the sparse-gradient variables; PS-family
strategies shard them across the mesh.

Run (CPU mesh, tiny):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/benchmark/ncf.py --num-users 1024 --num-items 512
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from autodist_tpu.models.ncf import ncf
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark


def main():
    p = benchmark_args("NCF benchmark")
    p.set_defaults(strategy="PSLoadBalancing", batch_size=256)
    p.add_argument("--num-users", type=int, default=138496)
    p.add_argument("--num-items", type=int, default=26752)
    args = p.parse_args()

    spec = ncf(num_users=args.num_users, num_items=args.num_items)
    params = spec.init(jax.random.PRNGKey(0))

    ad = make_autodist(args)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(args.lr),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    run_selected_benchmark(spec, sess, args, unit="samples")


if __name__ == "__main__":
    main()
