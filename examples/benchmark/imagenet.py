"""ImageNet-family training benchmark (synthetic data).

Parity target: reference ``examples/benchmark/imagenet.py`` — ResNet101 /
DenseNet121 / InceptionV3 / VGG16 via keras.applications with a chosen
AutoDist strategy, reporting images/sec.  Same families here (plus
ResNet-50, the BASELINE.md headline model) from the TPU-first model zoo.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/benchmark/imagenet.py --model resnet50 \
        --image-size 64 --batch-size 16
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import optax

from autodist_tpu import models
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark


def main():
    p = benchmark_args("ImageNet model-family benchmark")
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "vgg16", "densenet121",
                            "inception_v3"])
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    args = p.parse_args()

    spec = models.ALL_MODELS[args.model](num_classes=args.num_classes,
                                         image_size=args.image_size)
    params = spec.init(__import__("jax").random.PRNGKey(0))

    ad = make_autodist(args)
    with ad.scope():
        ad.capture(params=params,
                   optimizer=optax.sgd(args.lr, momentum=0.9),
                   loss_fn=spec.loss_fn,
                   untrainable_vars=spec.untrainable_vars)
    sess = ad.create_distributed_session()
    run_selected_benchmark(spec, sess, args, unit="images")


if __name__ == "__main__":
    main()
