"""BERT pre-training benchmark (synthetic MLM data).

Parity target: reference ``examples/benchmark/bert.py`` (BERT-large
uncased pre-training, samples/sec).

Run (CPU mesh, tiny):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/benchmark/bert.py --size tiny --batch-size 8
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from autodist_tpu.models.bert import bert, bert_base, bert_large
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark

SIZES = {
    "tiny": lambda **kw: bert(num_layers=2, num_heads=2, head_dim=32,
                              d_ff=256, vocab_size=1024, **kw),
    "base": bert_base,
    "large": bert_large,
}


def main():
    p = benchmark_args("BERT pre-training benchmark")
    p.add_argument("--size", default="base", choices=sorted(SIZES))
    p.add_argument("--seq-len", type=int, default=128)
    args = p.parse_args()

    spec = SIZES[args.size](seq_len=args.seq_len)
    params = spec.init(jax.random.PRNGKey(0))

    ad = make_autodist(args)
    with ad.scope():
        ad.capture(params=params,
                   optimizer=optax.adamw(args.lr),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    run_selected_benchmark(spec, sess, args, unit="samples")


if __name__ == "__main__":
    main()
