"""Shared harness for the benchmark examples.

Parity target: the reference's ``examples/benchmark`` scripts measure
throughput with a ``TimeHistory`` Keras callback
(``examples/benchmark/imagenet.py:85-120``); here one loop serves every
model family: build the ModelSpec, capture it under AutoDist, run warmup +
timed steps with async dispatch, report items/sec.
"""
import argparse
import time

import numpy as np


def benchmark_args(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--strategy", default="AllReduce",
                   help="strategy builder name (PS, PSLoadBalancing, "
                        "PartitionedPS, AllReduce, PartitionedAR, Parallax, …)")
    p.add_argument("--resource-spec", default=None,
                   help="resource_spec.yml path (default: local devices)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--epochs", type=int, default=0,
                   help="when >0, train through session.fit (epochs x "
                        "--steps fresh batches) with the TimeHistory "
                        "callback instead of the single-batch timing loop")
    return p


def make_autodist(args, mesh_axes=None):
    from autodist_tpu import AutoDist
    from autodist_tpu import strategy as strategies

    builder = getattr(strategies, args.strategy)()
    return AutoDist(resource_spec_file=args.resource_spec,
                    strategy_builder=builder, mesh_axes=mesh_axes)


def run_benchmark(spec, sess, batch_size: int, steps: int, warmup: int,
                  unit: str = "samples", items_per_batch: int = None):
    """Warmup, then timed steps with async dispatch (the input pipeline
    re-feeds one pre-placed batch, isolating compute+sync throughput)."""
    batch = sess.place_batch(spec.sample_batch(batch_size))
    for _ in range(warmup):
        sess.run(batch, sync=False)
    loss = float(sess.run(batch)["loss"])

    t0 = time.perf_counter()
    for _ in range(steps - 1):
        sess.run(batch, sync=False)
    metrics = sess.run(batch)  # host sync closes the timing window
    dt = time.perf_counter() - t0

    items = (items_per_batch or batch_size) * steps
    rate = items / dt
    print(f"{spec.name}: {rate:,.1f} {unit}/sec "
          f"({steps} steps x batch {batch_size} in {dt:.2f}s), "
          f"loss {loss:.4f} -> {float(metrics['loss']):.4f}")
    assert np.isfinite(float(metrics["loss"]))
    return rate


def run_fit_benchmark(spec, sess, batch_size: int, steps_per_epoch: int,
                      epochs: int, unit: str = "samples",
                      items_per_batch: int = None):
    """Epoch-style benchmark through ``session.fit`` — the reference's
    ``model.fit(..., callbacks=[TimeHistory()])`` measurement shape
    (examples/benchmark/imagenet.py:85-120), with fresh batches each
    epoch flowing through the prefetch pipeline."""
    from autodist_tpu import TimeHistory

    def epoch_batches():
        rng = np.random.RandomState(0)
        return (spec.make_batch(rng, batch_size)
                for _ in range(steps_per_epoch))

    th = TimeHistory(items_per_step=items_per_batch or batch_size)
    hist = sess.fit(epoch_batches, epochs=epochs, callbacks=[th])
    for e, (dt, rate) in enumerate(zip(th.epoch_times, th.items_per_sec)):
        print(f"{spec.name}: epoch {e}: {rate:,.1f} {unit}/sec "
              f"({dt:.2f}s), loss {hist.history['epoch_loss'][e]:.4f}")
    assert np.isfinite(hist.history["epoch_loss"][-1])
    return th.items_per_sec[-1]


def run_selected_benchmark(spec, sess, args, unit: str = "samples",
                           items_per_batch: int = None):
    """Dispatch on ``--epochs``: the fit/TimeHistory path when set, the
    single-batch timing loop otherwise — so every benchmark script honors
    the shared flag."""
    if getattr(args, "epochs", 0):
        return run_fit_benchmark(spec, sess, args.batch_size, args.steps,
                                 args.epochs, unit=unit,
                                 items_per_batch=items_per_batch)
    return run_benchmark(spec, sess, args.batch_size, args.steps,
                         args.warmup, unit=unit,
                         items_per_batch=items_per_batch)
