"""Zero-code-change adoption: a plain optax training script distributed by
wrapping it in ``ad.scope()`` — no ``capture()`` call, no session plumbing
in the model code (the reference's ``PatchTensorFlow`` promise,
``autodist/patch.py:40-116``; here via ``autodist_tpu/patch.py``).

Run on a virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/implicit_capture.py

or through the launcher with a cluster spec:

    python -m autodist_tpu.run -r pod.yml examples/implicit_capture.py
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist


def loss_fn(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def main():
    params = {"w": jnp.zeros((8, 4)), "b": jnp.zeros(4)}

    ad = AutoDist()  # spec auto-derived (or from the launcher env)
    with ad.scope():
        # ---- an ordinary single-device optax script prefix ----
        optimizer = optax.chain(optax.clip_by_global_norm(10.0),
                                optax.adamw(5e-2))
        opt_state = optimizer.init(params)            # params captured
        value_and_grad = jax.value_and_grad(loss_fn)  # loss_fn captured
        # -------------------------------------------------------

    session = ad.create_distributed_session()
    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 4).astype(np.float32)
    for step in range(40):
        x = rng.randn(64, 8).astype(np.float32)
        batch = {"x": x, "y": x @ w_true + 0.1}
        metrics = session.run(batch)
        if step % 5 == 0:
            print(f"step {step:3d}  loss {float(metrics['loss']):.5f}  "
                  f"mesh {dict(session.mesh.shape)}")


if __name__ == "__main__":
    main()
