"""Continuous-batching serving demo (``serving/engine.py``).

A mixed-length request stream through a slot pool: finished requests
are harvested and queued ones admitted (with parallel prompt prefill)
without stopping the batch — the production decode loop the reference
framework (training-only) stops short of.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serving_engine.py
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--window", type=int, default=96)
    p.add_argument("--requests", type=int, default=12)
    args = p.parse_args()

    from autodist_tpu.models import make_generator, transformer_lm
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.serving import DecodeEngine

    vocab, eos = 64, 2
    spec = transformer_lm(vocab_size=vocab, num_layers=2, num_heads=2,
                          head_dim=16, d_ff=64, max_len=args.window,
                          seq_len=32, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    eng = DecodeEngine(spec, params, slots=args.slots,
                       window=args.window, chunk=8, eos_id=eos)
    reqs = {}
    for _ in range(args.requests):
        prompt = rng.randint(0, vocab, rng.randint(2, 9)).astype(np.int32)
        n = int(rng.randint(4, 24))
        reqs[eng.submit(prompt, n)] = (prompt, n)
    print(f"submitted {len(reqs)} requests "
          f"(P=2..8, N=4..23) into {args.slots} slots")

    t0 = time.perf_counter()
    results = eng.run()
    dt = time.perf_counter() - t0
    s = eng.stats
    print(f"decoded {s.generated_tokens} tokens in {dt:.2f}s "
          f"({s.generated_tokens / dt:.0f} tok/s aggregate)")
    print(f"ticks={s.ticks} chunks={s.chunks} "
          f"slot_utilization={s.slot_utilization:.2f} "
          f"prefill_admissions={s.prefill_admissions}")

    # Spot-check three results against the per-request oracle decode.
    gen = make_generator(spec)
    for rid in list(results)[:3]:
        prompt, n = reqs[rid]
        want = np.asarray(gen(params, prompt[None, :], n, eos_id=eos))[0]
        got = results[rid]
        assert np.array_equal(got, want[:got.size]), rid
        print(f"  req {rid}: P={prompt.size} -> {got.size - prompt.size} "
              f"tokens (oracle-exact)")


if __name__ == "__main__":
    main()
