"""lm1b LSTM language-model training (synthetic data), words/sec.

Parity target: reference ``examples/lm1b/lm1b_train.py`` — the 793k-vocab
LSTM LM whose embedding/softmax variables are the reference's flagship
sparse-gradient / PartitionedPS workload (SURVEY §5.7).  The Parallax
strategy reproduces its hybrid: dense grads allreduced, embedding grads
sharded onto the owning vocab shard.

Run (CPU mesh, tiny vocab):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm1b/lm1b_train.py --vocab-size 4096 --batch-size 16
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from autodist_tpu.models.lm1b import lm1b
from examples.benchmark.common import benchmark_args, make_autodist, \
    run_selected_benchmark


def main():
    p = benchmark_args("lm1b LSTM LM benchmark")
    p.set_defaults(strategy="Parallax")
    p.add_argument("--vocab-size", type=int, default=793472)
    p.add_argument("--seq-len", type=int, default=20)
    p.add_argument("--emb-dim", type=int, default=512)
    p.add_argument("--hidden-dim", type=int, default=2048)
    args = p.parse_args()

    spec = lm1b(vocab_size=args.vocab_size, seq_len=args.seq_len,
                emb_dim=args.emb_dim, hidden_dim=args.hidden_dim)
    params = spec.init(jax.random.PRNGKey(0))

    ad = make_autodist(args)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adagrad(args.lr),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    run_selected_benchmark(
        spec, sess, args, unit="words",
        items_per_batch=args.batch_size * args.seq_len)


if __name__ == "__main__":
    main()
