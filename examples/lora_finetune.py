"""LoRA finetuning demo (``models/lora.py`` + the freeze machinery).

Pretrains a small TransformerLM on one distribution, then LoRA-finetunes
it onto a shifted distribution with the base frozen — optimizer state
exists only for the adapters — and decodes from the merged weights.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lora_finetune.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rank", type=int, default=4)
    p.add_argument("--steps", type=int, default=60)
    args = p.parse_args()

    import optax

    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models import lora_setup, make_generator, \
        transformer_lm
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.strategy import AllReduce

    spec = transformer_lm(vocab_size=97, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=64, max_len=48, seq_len=16,
                          attn_fn=dense_attention)

    # -- pretrain (full-parameter) on "even tokens" sequences -------------
    rng = np.random.RandomState(0)

    def batch_of(parity, n=32):
        toks = rng.randint(0, 48, (n, 17)) * 2 + parity
        return {"tokens": toks[:, :16].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32)}

    params = spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(5e-3),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(args.steps):
        out = sess.run(batch_of(0))
    base = sess.params
    print(f"pretrain (even tokens): loss {float(out['loss']):.3f}")

    # -- LoRA-finetune onto "odd tokens" with the base frozen --------------
    _reset_default_autodist_for_testing()
    setup = lora_setup(base, spec.loss_fn, rng=jax.random.PRNGKey(1),
                       rank=args.rank,
                       targets=[("*/attn/out/*", 2), "*/attn/*",
                                "*/mlp/*"])
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(base))
    print(f"adapters: {setup.num_adapter_params:,} params "
          f"({100 * setup.num_adapter_params / n_base:.1f}% of base)")
    ad2 = AutoDist(strategy_builder=AllReduce())
    with ad2.scope():
        ad2.capture(**setup.capture_args, optimizer=optax.adam(5e-3))
    sess2 = ad2.create_distributed_session()
    l0 = float(sess2.run(batch_of(1))["loss"])
    for _ in range(args.steps):
        out = sess2.run(batch_of(1))
    l1 = float(out["loss"])
    print(f"finetune (odd tokens): loss {l0:.3f} -> {l1:.3f}")
    assert l1 < l0, "adapters did not learn"

    after = sess2.params
    drift = max(float(np.abs(np.asarray(a) - np.asarray(b)).max())
                for a, b in zip(jax.tree_util.tree_leaves(after["base"]),
                                jax.tree_util.tree_leaves(base)))
    print(f"base drift: {drift} (must be 0.0)")
    assert drift == 0.0

    merged = setup.merge(after)
    gen = make_generator(spec)
    prompt = np.asarray([[1, 3]], np.int32)
    toks = np.asarray(gen(merged, prompt, 8))[0]
    odd = sum(int(t) % 2 for t in toks[2:])
    print(f"merged decode after odd-token finetune: {toks.tolist()} "
          f"({odd}/8 odd)")
    print("lora_finetune demo OK")


if __name__ == "__main__":
    main()
