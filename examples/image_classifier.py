"""Minimal image-classifier training (reference ``examples/image_classifier.py``).

The reference's simplest end-to-end GPU script: ResNet-50 under an
AutoDist scope with a fixed strategy, a few training steps.  Same shape
here on the TPU mesh (BASELINE.json parity config: "ResNet-50 —
AllReduce").  For the measured benchmark loop use
``benchmark/imagenet.py``.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/image_classifier.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")

    from autodist_tpu import AutoDist
    from autodist_tpu.models.resnet import resnet50
    from autodist_tpu.strategy import AllReduce

    spec = resnet50(num_classes=100, image_size=args.image_size)
    params = spec.init(jax.random.PRNGKey(0))

    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1, momentum=0.9),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        metrics = sess.run(spec.make_batch(rng, args.batch_size))
        print(f"step {step}: loss {float(metrics['loss']):.4f}")
    assert np.isfinite(float(metrics["loss"]))


if __name__ == "__main__":
    main()
