"""Sentiment classifier — reference ``examples/sentiment_classifier.py``
parity: embedding → mean-pool → 2-layer MLP → binary cross entropy,
trained under PartitionedPS (the vocab-sized embedding is what the
variable partitioner is for).  Synthetic separable data stands in for
IMDB, like the reference's random batches.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sentiment_classifier.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch-size", type=int, default=128)
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import PartitionedPS

    vocab, emb_dim, hidden, seq = 10000, 16, 16, 20
    rng = np.random.RandomState(0)
    params = {
        "emb": jnp.asarray(rng.rand(vocab, emb_dim), jnp.float32),
        "w1": jnp.asarray(rng.rand(emb_dim, hidden) * 0.1, jnp.float32),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jnp.asarray(rng.rand(hidden, 1) * 0.1, jnp.float32),
        "b2": jnp.zeros((1,), jnp.float32),
    }
    # Planted signal: each token leans +1/-1; a document's label is the
    # sign of its mean leaning.  Borderline documents (|mean| small) are
    # resampled away so the task is cleanly separable — the reference's
    # synthetic stand-in for IMDB polarity.
    w_tok = np.where(rng.rand(vocab) < 0.5, -1.0, 1.0).astype(np.float32)

    def make_batch(n):
        rows = []
        while len(rows) < n:
            x = rng.randint(0, vocab, (4 * n, seq)).astype(np.int32)
            score = w_tok[x].mean(axis=1)
            keep = np.abs(score) >= 0.3
            rows.extend(zip(x[keep], (score[keep] > 0)))
        x = np.stack([r[0] for r in rows[:n]])
        y = np.array([r[1] for r in rows[:n]], np.float32)
        return {"x": x, "y": y}

    def loss_fn(p, batch):
        h = jnp.take(p["emb"], batch["x"], axis=0).mean(axis=1)
        h = jax.nn.relu(h @ p["w1"] + p["b1"])
        logits = (h @ p["w2"] + p["b2"])[:, 0]
        y = batch["y"]
        return jnp.mean(jnp.maximum(logits, 0) - logits * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    ad = AutoDist(strategy_builder=PartitionedPS())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, sparse_vars=("emb",))
    sess = ad.create_distributed_session()
    for step in range(args.steps):
        out = sess.run(make_batch(args.batch_size))
        if step % 20 == 0:
            print(f"step {step:3d} loss {float(out['loss']):.4f}")
    final = float(out["loss"])
    print(f"final loss {final:.4f}")
    assert final < 0.45, final   # well below chance (~0.69)


if __name__ == "__main__":
    main()
