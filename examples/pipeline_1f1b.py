"""Pipelined LM trained through the 1F1B schedule (hand-built backward).

Two specs of the same model train side by side: GPipe (autodiff through
the tick-scan — O(M) stashed activations) and 1F1B
(``parallel/pipeline_1f1b.py`` — backward interleaved into the ring,
O(S·V) stashed activations, plugged in via ``capture(grad_fn=...)``).
Their losses match step for step; the memory difference is what you buy.
``--virtual-stages V`` selects the interleaved layout for BOTH schedules
(each device holds V chunks; the warmup/drain bubble shrinks — see the
algebra in ``parallel/pipeline_1f1b.py``).

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_1f1b.py --virtual-stages 2 --num-layers 8
(num_layers must divide into pipe x virtual-stages chunks.)
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pipe", type=int, default=4)
    p.add_argument("--virtual-stages", type=int, default=1)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    from autodist_tpu import AutoDist
    from autodist_tpu.autodist import _reset_default_autodist_for_testing
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    axes = {"pipe": args.pipe, "data": 2}
    mesh = build_mesh(axes)
    if args.num_layers % (args.pipe * args.virtual_stages):
        p.error("--num-layers must divide into pipe x virtual-stages "
                "chunks")
    kw = dict(vocab_size=2048, num_layers=args.num_layers, num_heads=4,
              head_dim=16, d_ff=64, max_len=args.seq_len,
              seq_len=args.seq_len,
              num_virtual_stages=args.virtual_stages)

    losses = {}
    for sched in ("1f1b", "gpipe"):
        # DEMO-ONLY: a real training script builds ONE AutoDist per
        # process (the reference's rule).  This side-by-side comparison
        # needs two, so it uses the testing reset (requires
        # AUTODIST_IS_TESTING=True, like the test matrices do).
        os.environ.setdefault("AUTODIST_IS_TESTING", "True")
        _reset_default_autodist_for_testing()
        spec = pipelined_transformer_lm(mesh, schedule=sched, **kw)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, grad_fn=spec.grad_fn,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        batch = spec.sample_batch(args.batch_size)
        losses[sched] = [float(sess.run(batch)["loss"])
                         for _ in range(args.steps)]
        print(f"{sched:>6}: " + " ".join(f"{v:.4f}" for v in losses[sched]))

    drift = max(abs(a - b) / abs(a)
                for a, b in zip(losses["1f1b"], losses["gpipe"]))
    print(f"max relative drift 1F1B vs GPipe: {drift:.2e}")
    assert drift < 1e-3


if __name__ == "__main__":
    main()
