"""Speculative decoding with a genuinely TRAINED draft model.

The bench's draft==target run shows the mechanical upper bound
(acceptance 1.0); this example shows the real pipeline: train a target
LM through the framework, train a much smaller draft on the same data,
then decode speculatively — the draft proposes ``gamma`` tokens per
verify pass, the target accepts a measured fraction, and the output is
STILL token-exact target-greedy (the greedy-acceptance guarantee holds
regardless of draft quality).

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/speculative_draft.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--gamma", type=int, default=4)
    args = p.parse_args()

    from autodist_tpu import AutoDist
    from autodist_tpu.models import make_generator, transformer_lm
    from autodist_tpu.models.speculative import make_speculative_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.strategy import Parallax

    vocab, seq = 32, 24
    max_len = seq + args.new_tokens + args.gamma + 4
    # Task: x[t+1] = (3*x[t] + 7) mod V with 10% noise.  Learnable by
    # both models, so the trained draft tracks the target closely and
    # acceptance is high — the regime where speculation pays.  (A task
    # only the deeper target can learn drives acceptance toward zero;
    # greedy-acceptance correctness holds either way.)
    rng = np.random.RandomState(0)

    def make_batch(n=64):
        s = np.zeros((n, seq), np.int64)
        s[:, 0] = rng.randint(0, vocab, n)
        for t in range(1, seq):
            s[:, t] = (3 * s[:, t - 1] + 7) % vocab
        noise = rng.random((n, seq)) < 0.10
        s[noise] = rng.randint(0, vocab, int(noise.sum()))
        return {"tokens": s.astype(np.int32)}

    target_spec = transformer_lm(
        vocab_size=vocab, num_layers=3, num_heads=4, head_dim=16,
        d_ff=128, max_len=max_len, seq_len=seq, attn_fn=dense_attention)
    draft_spec = transformer_lm(
        vocab_size=vocab, num_layers=1, num_heads=2, head_dim=8,
        d_ff=32, max_len=max_len, seq_len=seq, attn_fn=dense_attention)

    # Target: trained through the framework session path.
    t_params = target_spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=t_params, optimizer=optax.adam(3e-3),
                   loss_fn=target_spec.loss_fn,
                   sparse_vars=target_spec.sparse_vars)
    sess = ad.create_distributed_session()
    for i in range(args.steps):
        out = sess.run(make_batch())
        if i % 50 == 0:
            print(f"target step {i:3d} loss {float(out['loss']):.4f}")
    t_params = jax.device_get(sess.params)

    # Draft: a ~30x-smaller model trained on the same stream with a
    # plain optax loop (a draft is typically produced offline).
    d_params = draft_spec.init(jax.random.PRNGKey(1))
    opt = optax.adam(3e-3)
    opt_state = opt.init(d_params)

    @jax.jit
    def draft_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(draft_spec.loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(args.steps):
        d_params, opt_state, loss = draft_step(d_params, opt_state,
                                               make_batch())
        if i % 50 == 0:
            print(f"draft  step {i:3d} loss {float(loss):.4f}")

    n_t = sum(x.size for x in jax.tree_util.tree_leaves(t_params))
    n_d = sum(x.size for x in jax.tree_util.tree_leaves(d_params))
    print(f"target params: {n_t:,}  draft params: {n_d:,} "
          f"({n_t / n_d:.1f}x smaller draft)")

    prompt = make_batch(4)["tokens"][:, :8]
    sg = make_speculative_generator(target_spec, draft_spec)
    tokens, stats = sg(t_params, d_params, prompt, args.new_tokens,
                       args.gamma)
    acc = float(stats["accepted"]) / max(float(stats["proposed"]), 1.0)
    iters = int(stats["iterations"])
    # The honest comparison is TARGET work: plain batched greedy decode
    # runs the target for new_tokens sequential ticks; speculation runs
    # it for `iters` batched verify passes (plus gamma cheap draft ticks
    # per pass — the draft is the ~30x-smaller model).
    print(f"acceptance rate: {acc:.2f}  "
          f"(target: {args.new_tokens} sequential decode ticks -> "
          f"{iters} batched verify passes, + {args.gamma} draft ticks "
          f"per pass)")

    # The guarantee: speculative output IS target-greedy, token-exact,
    # no matter how good or bad the draft is.
    gen = make_generator(target_spec)
    want = np.asarray(gen(t_params, prompt, args.new_tokens))
    np.testing.assert_array_equal(np.asarray(tokens), want)
    print("speculative output == target greedy decode (token-exact)")

    # A trained draft on a learnable task should be accepted most of
    # the time — this is the number that makes speculation pay.
    assert acc > 0.5, f"trained-draft acceptance unexpectedly low: {acc}"


if __name__ == "__main__":
    main()
