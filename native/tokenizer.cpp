// Native byte-level BPE tokenizer for the serving path.
//
// The reference framework has no text pipeline at all (its examples feed
// pre-tokenized ids); this rounds out the TPU build's serving story:
// EngineServer's text mode needs an encode/decode pair, and encode is a
// host-side hot loop (per-request, latency-sensitive) — exactly the kind
// of work the native runtime layer exists for (cf. runtime.cpp's loader).
//
// Model: byte-level BPE with GPT-2-style pretokenization.  Every byte is
// a base token (the Python side guarantees ids 0..255 are the single
// bytes); ranked pair merges apply within pretoken segments only (merges
// never cross word/space boundaries).  The pretokenizer is a hand-rolled
// byte-class scanner equivalent in structure to GPT-2's pattern
//   's|'t|'re|'ve|'m|'ll|'d| ?L+| ?N+| ?P+|\s+(?!\S)|\s+
// under a byte-level class map: L = ASCII letters plus every byte >=
// 0x80 (so UTF-8 continuation/lead bytes group as "letters" — the right
// byte-level approximation without Unicode tables), N = ASCII digits,
// \s = ASCII whitespace, P = everything else.  The same scanner exists
// in pure Python (runtime/tokenizer.py) and the two must match
// BIT-FOR-BIT; change them together.
//
// Encode within a segment is heap-based best-merge: a priority queue of
// candidate pairs ordered by (rank, position) with lazy invalidation
// over a doubly-linked symbol arena — O(n log n), replacing the old
// O(n * merges) full rescan (pathological on long uniform inputs).
// Semantics are unchanged: repeatedly apply the globally lowest-rank
// pair, leftmost occurrence first (heap pop order == global min by
// (rank, pos); stale entries are detected by their recorded pair ids).
//
// C ABI (ctypes-bound in autodist_tpu/runtime/tokenizer.py):
//   ad_bpe_create_v2(merges[n*3] as (left,right,new_id) in rank order,
//                    n_merges, pretokenize)
//   ad_bpe_encode(text bytes -> out_ids, returns count)
//   ad_bpe_destroy
// The _v2 suffix is load-bearing: the pretokenize flag changed the
// create arity, and a RENAME makes a stale prebuilt .so fail the
// binding (AttributeError -> pure-Python fallback) instead of silently
// calling the old 2-arg function with the flag ignored.
#include <cstddef>
#include <cstdint>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct Bpe {
  // (left_id << 32 | right_id) -> (rank << 32 | new_id)
  std::unordered_map<uint64_t, uint64_t> ranks;
  bool pretokenize = false;
};

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

// Byte classes for the pretokenizer (see module comment).
enum Cls { kSpace, kLetter, kDigit, kPunct };

inline Cls classify(uint8_t b) {
  if (b == ' ' || b == '\t' || b == '\n' || b == '\r' || b == '\f' ||
      b == '\v')
    return kSpace;
  if ((b >= 'a' && b <= 'z') || (b >= 'A' && b <= 'Z') || b >= 0x80)
    return kLetter;
  if (b >= '0' && b <= '9') return kDigit;
  return kPunct;
}

// Length of a contraction ('s 't 'm 'd 're 've 'll) starting at text[i],
// or 0.  Lowercase-only, like GPT-2's pattern.
inline int32_t contraction_len(const uint8_t* text, int32_t n, int32_t i) {
  if (text[i] != '\'' || i + 1 >= n) return 0;
  const uint8_t c = text[i + 1];
  if (c == 's' || c == 't' || c == 'm' || c == 'd') return 2;
  if (i + 2 < n) {
    const uint8_t d = text[i + 2];
    if ((c == 'r' && d == 'e') || (c == 'v' && d == 'e') ||
        (c == 'l' && d == 'l'))
      return 3;
  }
  return 0;
}

// Emit [start, end) pretoken boundaries into segs as (start, end) pairs.
// Mirrors runtime/tokenizer.py _pretokenize — keep in lockstep.
void pretokenize(const uint8_t* text, int32_t n,
                 std::vector<std::pair<int32_t, int32_t>>* segs) {
  int32_t i = 0;
  while (i < n) {
    const int32_t cl = contraction_len(text, n, i);
    if (cl) {
      segs->emplace_back(i, i + cl);
      i += cl;
      continue;
    }
    if (classify(text[i]) == kSpace) {
      int32_t j = i;
      while (j < n && classify(text[j]) == kSpace) ++j;
      if (j == n) {  // trailing whitespace run: one token
        segs->emplace_back(i, j);
        i = j;
        continue;
      }
      if (j - i > 1) {  // \s+(?!\S): all but the last space
        segs->emplace_back(i, j - 1);
        i = j - 1;
        continue;
      }
      if (text[i] != ' ') {  // the ' ?' prefix is a LITERAL space:
        segs->emplace_back(i, j);  // lone \t or \n is its own \s+ token
        i = j;
        continue;
      }
      // single literal space before non-space: falls into ' ?class+'
    }
    // optional single leading space + maximal same-class run
    int32_t start = i;
    if (text[i] == ' ') ++i;  // the ' ?' space (literal 0x20 only)
    const Cls cls = classify(text[i]);
    ++i;
    while (i < n && classify(text[i]) == cls) ++i;
    segs->emplace_back(start, i);
  }
}

// Heap-based BPE over one segment.  id/next/prev are arena arrays the
// caller owns; [lo, hi) is the segment.  After return the linked list
// starting at lo (following next, stopping at >= hi or -1) holds the
// merged ids.
void merge_segment(const Bpe* t, std::vector<int32_t>* id_v,
                   std::vector<int32_t>* next_v, std::vector<int32_t>* prev_v,
                   int32_t lo, int32_t hi) {
  auto& id = *id_v;
  auto& next = *next_v;
  auto& prev = *prev_v;
  struct Cand {
    uint64_t key;  // rank << 32 | pos  (min-heap by rank then pos)
    int32_t a, b;  // pair ids at push time (stale detection)
  };
  struct Cmp {
    bool operator()(const Cand& x, const Cand& y) const {
      return x.key > y.key;
    }
  };
  std::priority_queue<Cand, std::vector<Cand>, Cmp> heap;
  auto push = [&](int32_t i) {
    const int32_t j = next[i];
    if (j < 0 || j >= hi) return;
    auto it = t->ranks.find(pair_key(id[i], id[j]));
    if (it == t->ranks.end()) return;
    const uint64_t rank = it->second >> 32;
    heap.push(Cand{(rank << 32) | static_cast<uint32_t>(i), id[i], id[j]});
  };
  for (int32_t i = lo; i < hi - 1; ++i) push(i);
  while (!heap.empty()) {
    const Cand c = heap.top();
    heap.pop();
    const int32_t i = static_cast<int32_t>(c.key & 0xffffffffu);
    const int32_t j = next[i];
    // Stale if i was absorbed, the pair changed, or j left the segment.
    if (id[i] != c.a || j < 0 || j >= hi || id[j] != c.b) continue;
    auto it = t->ranks.find(pair_key(c.a, c.b));
    id[i] = static_cast<int32_t>(it->second & 0xffffffffu);
    const int32_t k = next[j];
    id[j] = -1;  // tombstone: any heap entry at j is now stale
    next[i] = k;
    if (k != -1) prev[k] = i;
    if (prev[i] != -1 && prev[i] >= lo) push(prev[i]);
    push(i);
  }
}

}  // namespace

extern "C" {

void* ad_bpe_create_v2(const int32_t* merges, int32_t n_merges,
                    int32_t pretokenize_flag) {
  Bpe* t = new Bpe();
  t->pretokenize = pretokenize_flag != 0;
  t->ranks.reserve(static_cast<size_t>(n_merges) * 2);
  for (int32_t r = 0; r < n_merges; ++r) {
    const int32_t left = merges[3 * r], right = merges[3 * r + 1],
                  out = merges[3 * r + 2];
    // First (lowest) rank wins on duplicates, matching the fallback.
    t->ranks.emplace(pair_key(left, right),
                     (static_cast<uint64_t>(r) << 32) |
                         static_cast<uint32_t>(out));
  }
  return t;
}

void ad_bpe_destroy(void* tok) { delete static_cast<Bpe*>(tok); }

// Encode n bytes of text; out_ids must hold >= n entries (merges only
// shrink the sequence).  Returns the id count.
int32_t ad_bpe_encode(void* tok, const uint8_t* text, int32_t n,
                      int32_t* out_ids) {
  const Bpe* t = static_cast<const Bpe*>(tok);
  if (n <= 0) return 0;
  std::vector<int32_t> id(n), next(n), prev(n);
  for (int32_t i = 0; i < n; ++i) {
    id[i] = text[i];  // base tokens ARE the bytes
    next[i] = (i + 1 < n) ? i + 1 : -1;
    prev[i] = i - 1;  // -1 at head
  }
  std::vector<std::pair<int32_t, int32_t>> segs;
  if (t->pretokenize) {
    pretokenize(text, n, &segs);
  } else {
    segs.emplace_back(0, n);
  }
  for (const auto& s : segs) {
    // Sever the list at segment boundaries so merges cannot cross them.
    if (s.second < n) next[s.second - 1] = -1;
    merge_segment(t, &id, &next, &prev, s.first, s.second);
  }
  int32_t count = 0;
  for (const auto& s : segs)
    for (int32_t i = s.first; i != -1 && i < s.second; i = next[i])
      out_ids[count++] = id[i];
  return count;
}

}  // extern "C"
