// Native byte-level BPE tokenizer for the serving path.
//
// The reference framework has no text pipeline at all (its examples feed
// pre-tokenized ids); this rounds out the TPU build's serving story:
// EngineServer's text mode needs an encode/decode pair, and encode is a
// host-side hot loop (per-request, latency-sensitive) — exactly the kind
// of work the native runtime layer exists for (cf. runtime.cpp's loader).
//
// Model: plain byte-level BPE, no regex pretokenization — every byte is a
// base token (the Python side guarantees ids 0..255 are the single bytes),
// then ranked pair merges apply in rank order.  Encode is the standard
// repeated-best-merge loop over a doubly-linked symbol list:
// O(n * merges_applied) with an O(1) pair-rank hash lookup.
//
// C ABI (ctypes-bound in autodist_tpu/runtime/tokenizer.py, pure-Python
// fallback there must match bit-for-bit):
//   ad_bpe_create(merges[n*3] as (left,right,new_id) in rank order)
//   ad_bpe_encode(text bytes -> out_ids, returns count)
//   ad_bpe_destroy
#include <cstddef>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace {

struct Bpe {
  // (left_id << 32 | right_id) -> (rank << 32 | new_id)
  std::unordered_map<uint64_t, uint64_t> ranks;
};

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

}  // namespace

extern "C" {

void* ad_bpe_create(const int32_t* merges, int32_t n_merges) {
  Bpe* t = new Bpe();
  t->ranks.reserve(static_cast<size_t>(n_merges) * 2);
  for (int32_t r = 0; r < n_merges; ++r) {
    const int32_t left = merges[3 * r], right = merges[3 * r + 1],
                  out = merges[3 * r + 2];
    // First (lowest) rank wins on duplicates, matching the fallback.
    t->ranks.emplace(pair_key(left, right),
                     (static_cast<uint64_t>(r) << 32) |
                         static_cast<uint32_t>(out));
  }
  return t;
}

void ad_bpe_destroy(void* tok) { delete static_cast<Bpe*>(tok); }

// Encode n bytes of text; out_ids must hold >= n entries (merges only
// shrink the sequence).  Returns the id count.
int32_t ad_bpe_encode(void* tok, const uint8_t* text, int32_t n,
                      int32_t* out_ids) {
  const Bpe* t = static_cast<const Bpe*>(tok);
  if (n <= 0) return 0;
  // Singly-linked list over a flat arena: next indices, -1 = end
  // (merges always absorb the successor, so no prev links needed).
  std::vector<int32_t> id(n), next(n);
  for (int32_t i = 0; i < n; ++i) {
    id[i] = text[i];  // base tokens ARE the bytes
    next[i] = (i + 1 < n) ? i + 1 : -1;
  }
  const int32_t head = 0;
  while (true) {
    // Find the lowest-rank applicable pair.
    uint64_t best = ~0ull;
    int32_t best_pos = -1;
    for (int32_t i = head; i != -1 && next[i] != -1; i = next[i]) {
      auto it = t->ranks.find(pair_key(id[i], id[next[i]]));
      if (it != t->ranks.end() && it->second < best) {
        best = it->second;
        best_pos = i;
      }
    }
    if (best_pos == -1) break;
    // Merge best_pos with its successor (leftmost occurrence merges
    // first on rank ties along the scan — the fallback matches).
    id[best_pos] = static_cast<int32_t>(best & 0xffffffffu);
    next[best_pos] = next[next[best_pos]];
  }
  int32_t count = 0;
  for (int32_t i = head; i != -1; i = next[i]) out_ids[count++] = id[i];
  return count;
}

}  // extern "C"
