// AutoDist-TPU native host runtime.
//
// The reference delegated all native-performance work to the TF C++ runtime
// (SURVEY.md §2.9 — gRPC transport, accumulators, queues); on TPU the XLA/PJRT
// runtime owns the device side, so the native layer that actually matters is
// the HOST side of the input pipeline: assembling the next batch while the
// current step runs on the chip.  This library provides:
//
//   * an aligned buffer pool (staging slabs for batch assembly),
//   * a multi-threaded prefetching batch loader: shuffle -> gather rows from
//     user arrays into contiguous staging buffers -> optional fp32->bf16
//     cast (halves host->HBM transfer bytes) -> bounded ready queue,
//   * a parallel fp32->bf16 conversion entry point usable standalone.
//
// Pure C ABI so Python binds with ctypes (no pybind11 in the image).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Aligned buffer helpers
// ---------------------------------------------------------------------------

void* ad_buffer_alloc(size_t bytes, size_t alignment) {
  if (alignment < sizeof(void*)) alignment = sizeof(void*);
  void* p = nullptr;
  if (posix_memalign(&p, alignment, bytes) != 0) return nullptr;
  return p;
}

void ad_buffer_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// fp32 -> bf16 (round-to-nearest-even), multi-threaded
// ---------------------------------------------------------------------------

static inline uint16_t fp32_to_bf16_rne(uint32_t bits) {
  // NaN-safe round-to-nearest-even truncation to the top 16 bits.
  if ((bits & 0x7fffffffu) > 0x7f800000u) {  // NaN: keep payload bit set
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  }
  uint32_t rounding_bias = 0x7fffu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>((bits + rounding_bias) >> 16);
}

static void cast_range(const float* src, uint16_t* dst, size_t n) {
  const uint32_t* s = reinterpret_cast<const uint32_t*>(src);
  for (size_t i = 0; i < n; ++i) dst[i] = fp32_to_bf16_rne(s[i]);
}

void ad_fp32_to_bf16(const float* src, uint16_t* dst, size_t n,
                     int num_threads) {
  if (num_threads <= 1 || n < (1u << 16)) {
    cast_range(src, dst, n);
    return;
  }
  std::vector<std::thread> ts;
  size_t chunk = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    size_t lo = t * chunk;
    if (lo >= n) break;
    size_t hi = lo + chunk < n ? lo + chunk : n;
    ts.emplace_back([=] { cast_range(src + lo, dst + lo, hi - lo); });
  }
  for (auto& t : ts) t.join();
}

// ---------------------------------------------------------------------------
// Prefetching batch loader
// ---------------------------------------------------------------------------

struct AdArraySpec {
  const uint8_t* data;   // base pointer of the source array
  size_t row_bytes;      // bytes per row in the source
  int cast_bf16;         // nonzero: source rows are fp32, emit bf16
};

struct AdBatch {
  std::vector<uint8_t*> arrays;  // one staging buffer per source array
  size_t rows;                   // rows actually gathered (last batch may be short)
  size_t index;                  // batch ordinal within the epoch
};

struct AdLoader {
  std::vector<AdArraySpec> specs;
  size_t num_rows = 0;
  size_t batch_size = 0;
  int drop_last = 0;
  int shuffle = 0;

  std::vector<uint32_t> perm;          // row permutation for this epoch
  size_t num_batches = 0;
  std::atomic<size_t> next_batch{0};   // producer cursor

  // buffer pool: each entry is one buffer-set (one buffer per array)
  std::deque<std::vector<uint8_t*>> free_pool;
  std::deque<AdBatch> ready;           // filled batches awaiting consumption
  size_t ready_expect = 0;             // next ordinal handed to the consumer
  std::deque<AdBatch> out_of_order;    // filled early by a faster thread

  std::mutex mu;
  std::condition_variable cv_free;     // producers wait for a free buffer-set
  std::condition_variable cv_ready;    // consumer waits for the next batch
  std::vector<std::thread> workers;
  std::atomic<int> stopping{0};

  size_t out_row_bytes(size_t i) const {
    const AdArraySpec& s = specs[i];
    return s.cast_bf16 ? s.row_bytes / 2 : s.row_bytes;
  }
};

static void fill_batch(AdLoader* L, AdBatch* b) {
  size_t start = b->index * L->batch_size;
  size_t rows = b->rows;
  for (size_t a = 0; a < L->specs.size(); ++a) {
    const AdArraySpec& s = L->specs[a];
    uint8_t* out = b->arrays[a];
    if (!s.cast_bf16) {
      for (size_t r = 0; r < rows; ++r) {
        uint32_t src_row = L->perm[start + r];
        memcpy(out + r * s.row_bytes, s.data + (size_t)src_row * s.row_bytes,
               s.row_bytes);
      }
    } else {
      size_t floats = s.row_bytes / 4;
      for (size_t r = 0; r < rows; ++r) {
        uint32_t src_row = L->perm[start + r];
        cast_range(
            reinterpret_cast<const float*>(s.data + (size_t)src_row * s.row_bytes),
            reinterpret_cast<uint16_t*>(out + r * (s.row_bytes / 2)), floats);
      }
    }
  }
}

static void worker_loop(AdLoader* L) {
  while (!L->stopping.load()) {
    // Acquire the staging buffer BEFORE claiming a batch index.  The other
    // order deadlocks: a worker holding the lowest unfilled index can starve
    // on the free pool while faster workers park every buffer in the
    // out-of-order queue, which only drains once that lowest index arrives.
    // Buffer-first guarantees every claimed index completes, so the in-order
    // drain always advances.
    std::vector<uint8_t*> bufs;
    {
      std::unique_lock<std::mutex> lk(L->mu);
      L->cv_free.wait(lk, [&] { return L->stopping.load() || !L->free_pool.empty(); });
      if (L->stopping.load()) return;
      bufs = std::move(L->free_pool.front());
      L->free_pool.pop_front();
    }

    size_t idx = L->next_batch.fetch_add(1);
    if (idx >= L->num_batches) {
      std::lock_guard<std::mutex> lk(L->mu);
      L->free_pool.push_back(std::move(bufs));
      return;
    }

    AdBatch b;
    b.arrays = std::move(bufs);
    b.index = idx;
    size_t start = idx * L->batch_size;
    size_t remaining = L->num_rows - start;
    b.rows = remaining < L->batch_size ? remaining : L->batch_size;
    fill_batch(L, &b);

    {
      std::unique_lock<std::mutex> lk(L->mu);
      // Deliver in order so shuffled epochs are reproducible from the seed.
      L->out_of_order.push_back(std::move(b));
      for (;;) {
        bool advanced = false;
        for (auto it = L->out_of_order.begin(); it != L->out_of_order.end(); ++it) {
          if (it->index == L->ready_expect) {
            L->ready.push_back(std::move(*it));
            L->out_of_order.erase(it);
            ++L->ready_expect;
            advanced = true;
            break;
          }
        }
        if (!advanced) break;
      }
      L->cv_ready.notify_all();
    }
  }
}

AdLoader* ad_loader_create(const void** arrays, const size_t* row_bytes,
                           const int* cast_bf16, int num_arrays,
                           size_t num_rows, size_t batch_size, int drop_last,
                           int shuffle, uint64_t seed, int num_threads,
                           int prefetch_depth) {
  if (num_arrays <= 0 || num_rows == 0 || batch_size == 0) return nullptr;
  AdLoader* L = new AdLoader();
  for (int i = 0; i < num_arrays; ++i) {
    AdArraySpec s;
    s.data = static_cast<const uint8_t*>(arrays[i]);
    s.row_bytes = row_bytes[i];
    s.cast_bf16 = cast_bf16 ? cast_bf16[i] : 0;
    if (s.cast_bf16 && (s.row_bytes % 4) != 0) { delete L; return nullptr; }
    L->specs.push_back(s);
  }
  L->num_rows = num_rows;
  L->batch_size = batch_size;
  L->drop_last = drop_last;
  L->shuffle = shuffle;

  L->perm.resize(num_rows);
  for (size_t i = 0; i < num_rows; ++i) L->perm[i] = (uint32_t)i;
  if (shuffle) {
    std::mt19937_64 rng(seed);
    for (size_t i = num_rows - 1; i > 0; --i) {
      size_t j = rng() % (i + 1);
      std::swap(L->perm[i], L->perm[j]);
    }
  }
  L->num_batches = drop_last ? num_rows / batch_size
                             : (num_rows + batch_size - 1) / batch_size;

  if (num_threads < 1) num_threads = 1;
  if (prefetch_depth < 1) prefetch_depth = 1;
  int pool_size = prefetch_depth + num_threads;
  for (int p = 0; p < pool_size; ++p) {
    std::vector<uint8_t*> bufs;
    for (size_t a = 0; a < L->specs.size(); ++a) {
      bufs.push_back(static_cast<uint8_t*>(
          ad_buffer_alloc(batch_size * L->out_row_bytes(a), 64)));
    }
    L->free_pool.push_back(std::move(bufs));
  }
  for (int t = 0; t < num_threads; ++t) L->workers.emplace_back(worker_loop, L);
  return L;
}

// Blocks until the next in-order batch is ready.  Fills out_ptrs (one pointer
// per array; owned by the loader until ad_loader_release) and returns the row
// count, or 0 at end of epoch.
size_t ad_loader_next(AdLoader* L, void** out_ptrs) {
  std::unique_lock<std::mutex> lk(L->mu);
  size_t want = 0;
  // The batch the consumer wants is ready_expect - ready.size() ... compute
  // from the front of the ready queue instead: batches are pushed in order.
  for (;;) {
    if (!L->ready.empty()) break;
    if (L->ready_expect >= L->num_batches) return 0;  // epoch drained
    L->cv_ready.wait(lk);
  }
  AdBatch b = std::move(L->ready.front());
  L->ready.pop_front();
  want = b.rows;
  for (size_t a = 0; a < b.arrays.size(); ++a) out_ptrs[a] = b.arrays[a];
  // Ownership of the buffers passes to the consumer; remember nothing.
  return want;
}

// Returns a consumed buffer-set to the pool.
void ad_loader_release(AdLoader* L, void** ptrs, int num_arrays) {
  std::vector<uint8_t*> bufs;
  for (int a = 0; a < num_arrays; ++a)
    bufs.push_back(static_cast<uint8_t*>(ptrs[a]));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_pool.push_back(std::move(bufs));
  }
  L->cv_free.notify_one();
}

size_t ad_loader_num_batches(AdLoader* L) { return L->num_batches; }

void ad_loader_destroy(AdLoader* L) {
  L->stopping.store(1);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  std::lock_guard<std::mutex> lk(L->mu);
  for (auto& bufs : L->free_pool)
    for (auto* p : bufs) ad_buffer_free(p);
  for (auto& b : L->ready)
    for (auto* p : b.arrays) ad_buffer_free(p);
  for (auto& b : L->out_of_order)
    for (auto* p : b.arrays) ad_buffer_free(p);
  delete L;
}

}  // extern "C"
