"""Benchmark: ResNet-50 training throughput (images/sec) on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline note: the reference publishes charts, not numbers
(docs/usage/performance.md; BASELINE.json.published is empty).  Until a
published number exists, ``vs_baseline`` is the measured value normalized by
``BASELINE_IMAGES_PER_SEC`` below — the round-1 recorded value on one
v5e chip, so later rounds report their speedup against round 1.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Round-1 measured reference point (one TPU v5e chip, bf16, batch 128):
# ~2240 images/sec. vs_baseline therefore reports speedup relative to the
# round-1 build.
BASELINE_IMAGES_PER_SEC = 2240.0

WARMUP_STEPS = 3
MEASURE_STEPS = 20


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.resnet import resnet50
    from autodist_tpu.strategy import AllReduce

    on_tpu = jax.devices()[0].platform == "tpu"
    batch_size = 128 if on_tpu else 16
    image_size = 224 if on_tpu else 64
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    spec = resnet50(num_classes=1000, image_size=image_size)
    params = spec.init(jax.random.PRNGKey(0))
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if x.dtype == jnp.float32 else x, params)
    batch = spec.sample_batch(batch_size)
    if on_tpu:
        batch = {"images": batch["images"].astype(np.float32).astype(
            jnp.bfloat16), "labels": batch["labels"]}

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params,
                   optimizer=optax.sgd(0.1, momentum=0.9),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()

    # Pre-place the batch (an input pipeline would prefetch like this);
    # async metrics so steps dispatch back-to-back.  The final step fetches
    # its loss to host — a hard sync that (unlike block_until_ready over the
    # remote-TPU tunnel) reliably waits for the whole chain.
    batch = sess.place_batch(batch)
    for _ in range(WARMUP_STEPS):
        sess.run(batch, sync=False)
    sess.run(batch)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS - 1):
        sess.run(batch, sync=False)
    sess.run(batch)
    dt = time.perf_counter() - t0

    images_per_sec = batch_size * MEASURE_STEPS / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
