"""Benchmark: the full BASELINE.json parity matrix, framework path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The primary metric stays ResNet-50 training throughput; enrichment sections
measure every other BASELINE.json parity config — flagship TransformerLM
(flash attention), BERT-base + PartitionedAR, VGG16 + PartitionedPS,
NCF + PSLoadBalancing, lm1b + Parallax (chunked-vocab exact loss) — each
through the framework's own ``AutoDist → DistributedSession`` path (matching
how the reference benchmarked through ``ad.scope()``,
``/root/reference/examples/benchmark/imagenet.py:85-120``).

Robustness (the TPU tunnel in this image can hang for hours — see
``__graft_entry__.py`` for the steering trick):

* The actual measurement runs in a **child process** (``--child``) so a hung
  PJRT tunnel can never hang the benchmark: the parent enforces timeouts and
  always prints a parseable JSON line (rc=0 when a metric was measured, even
  on the CPU fallback; rc=1 only when no measurement succeeded anywhere).
* A cheap probe child (``--probe``) verifies the TPU does a real matmul
  before the parent commits to the expensive run; while the tunnel is down
  the parent keeps re-probing (every ``AUTODIST_BENCH_PROBE_INTERVAL_S``,
  default 120s) until ``AUTODIST_BENCH_PROBE_DEADLINE_S`` (default 7200s
  — a late revival is cheap thanks to the compile cache, and a short fuse
  burned round 3's artifact on a CPU number), then falls back to CPU with
  a self-describing artifact (``tpu_unavailable: true``,
  ``vs_baseline: null``).  Set the deadline low for interactive runs.

MFU: model FLOPs per step are taken from XLA's compiled cost analysis
(exact for the program that ran) with an analytic ResNet-50 fallback
(~8.2 GFLOP fwd/image at 224**2, x3 for the backward pass), divided by the
chip's peak bf16 FLOP/s.

Baseline note: the reference publishes charts, not numbers
(docs/usage/performance.md; BASELINE.json.published is empty), so
``vs_baseline`` normalizes by the BEST PRIOR VERIFIED round's driver-captured
single-chip value (round 2: 2,468.8 images/sec, BENCH_r02.json): each round
reports its speedup against the best number already on record, keeping the
ratio meaningful instead of inflating forever against round 1.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Best prior verified round (round 2, BENCH_r02.json: one TPU v5e chip,
# bf16, batch 128).  Round 1's 2240.0 is superseded.
BASELINE_IMAGES_PER_SEC = 2468.8

WARMUP_STEPS = 3
MEASURE_STEPS = 20

PROBE_TIMEOUT_S = float(os.environ.get(
    "AUTODIST_BENCH_PROBE_TIMEOUT_S", 150))
# First TPU attempt gets the full budget (the parity matrix is ~8-10
# tunnel compiles at 1-4 min each); the retry is shorter (its value is
# recovering the PRIMARY metric after a flaky first attempt — the parent
# keeps whatever the timed-out child already printed), and the CPU
# fallback is quick.
TPU_ATTEMPTS = (("tpu", 3300), ("tpu", 1800), ("cpu", 1200))
CPU_ATTEMPTS = (("cpu", 1200),)
# Tunnel-outage lessons.  BENCH_r03 burned the artifact on a 135s probe
# budget; the r4 overcorrection (7200s) burned it the OTHER way — the
# driver killed the parent after ~27 min of silent probing, so the fix is
# not a longer fuse but (a) a self-describing JSON line printed BEFORE any
# probing, (b) child output streamed through live so a driver kill at any
# moment leaves the best-so-far line on stdout, (c) a CPU fallback
# measured EARLY when the first probe fails, and (d) a probe deadline
# comfortably inside the driver budget.  Env-tunable for interactive runs.
PROBE_DEADLINE_S = float(os.environ.get(
    "AUTODIST_BENCH_PROBE_DEADLINE_S", 900))
PROBE_RETRY_INTERVAL_S = float(os.environ.get(
    "AUTODIST_BENCH_PROBE_INTERVAL_S", 60))


def _steer(platform: str) -> None:
    """Steer JAX to ``platform`` before first backend use.  The image's
    sitecustomize registers a remote-TPU backend that env vars alone don't
    override — jax.config.update is required (see __graft_entry__.py).
    A failure here must propagate: silently proceeding would route the CPU
    fallback to the dead TPU tunnel and hang until the parent's timeout."""
    import jax
    os.environ["JAX_PLATFORMS"] = platform
    jax.config.update("jax_platforms", platform)


def _peak_flops(device) -> float:
    from autodist_tpu.utils.metrics import peak_flops_per_chip

    return peak_flops_per_chip(device)


def _analytic_step_flops(batch_size: int, image_size: int) -> float:
    """ResNet-50 fwd ~= 8.2 GFLOP/image at 224**2 (conv FLOPs scale with
    spatial area); training step ~= 3x forward."""
    fwd = 8.2e9 * (image_size / 224.0) ** 2
    return 3.0 * fwd * batch_size


def run_child(platform: str) -> None:
    """The measurement.  Prints one JSON line on success, exits nonzero on
    failure (parent handles fallback + failure JSON)."""
    if platform == "cpu":
        _steer("cpu")
    import jax

    # Persistent compilation cache: the parity matrix is ~8-10 programs at
    # 1-4 min of (remote) compile each — cached, a re-run (or the retry
    # attempt after a flaky tunnel drop) skips straight to measurement.
    # (config.update, not env vars: this jax build ignores the env names.)
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/autodist_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception as e:  # pragma: no cover - version-dependent knob
        print(f"bench: compilation cache unavailable ({e!r})",
              file=sys.stderr, flush=True)
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.resnet import resnet50
    from autodist_tpu.strategy import AllReduce

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    batch_size = int(os.environ.get("AUTODIST_BENCH_BATCH",
                                    128 if on_tpu else 16))
    image_size = 224 if on_tpu else 64
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    spec = resnet50(num_classes=1000, image_size=image_size)
    params = spec.init(jax.random.PRNGKey(0))
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype) if x.dtype == jnp.float32 else x, params)
    batch = spec.sample_batch(batch_size)
    if on_tpu:
        batch = {"images": batch["images"].astype(np.float32).astype(
            jnp.bfloat16), "labels": batch["labels"]}

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params,
                   optimizer=optax.sgd(0.1, momentum=0.9),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()

    # Pre-place the batch (an input pipeline would prefetch like this);
    # async metrics so steps dispatch back-to-back.  The final step fetches
    # its loss to host — a hard sync that (unlike block_until_ready over the
    # remote-TPU tunnel) reliably waits for the whole chain.
    batch = sess.place_batch(batch)
    dt = _measure_session(sess, batch, WARMUP_STEPS, MEASURE_STEPS)

    images_per_sec = batch_size * MEASURE_STEPS / dt
    # vs_baseline only means something against the TPU baseline when the
    # measurement itself ran on TPU: an outage round's CPU fallback must be
    # self-describing (tpu_unavailable) instead of reading as a 400x
    # "regression" against 2,468.8 img/s.
    result = {
        "metric": "resnet50_train_throughput",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 4)
        if on_tpu else None,
        "mfu": None,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "batch_size": batch_size,
        "image_size": image_size,
        "step_time_ms": round(1e3 * dt / MEASURE_STEPS, 2),
        "flops_per_step": _analytic_step_flops(batch_size, image_size),
        "flops_source": "analytic",
        "sections": {},
    }

    def mark(name):
        """Per-section provenance: a mid-run outage yields a partial
        artifact whose sections each say where and when they ran."""
        result["sections"][name] = {
            "platform": dev.platform, "t_unix": round(time.time(), 1)}
        print(json.dumps(result), flush=True)

    # The throughput number is safe NOW — print it before any optional
    # cost-analysis recompile so a hang there can't lose the metric; the
    # parent takes the LAST valid JSON line.
    mark("resnet50")
    # Bucketed gradient sync (all_reduce vs reduce_scatter/ZeRO-1): its
    # own child process with 8 simulated replicas, so it runs — and means
    # the same thing — on both the TPU path and the CPU fallback.
    _fill_grad_sync(result)
    _fill_quant(result)
    _fill_flightrec(result)
    _fill_profiler(result)
    _fill_search(result)
    _fill_moe(result)
    _fill_hier(result)
    _fill_mpmd(result)
    _fill_kernels(result)
    mark("grad_sync")
    # Serving scale-out (paged KV + continuous batching): its own CPU
    # child; the numbers compare scheduler modes against each other.
    _fill_serving(result)
    # Speculative serving rides the same CPU-child pattern; it reads
    # the committed BENCH_serving baseline, so it runs after it.
    _fill_spec(result)
    # Serving fault tolerance: recovery/hedging goodput under
    # deterministic mid-stream faults, its own CPU child.
    _fill_serving_resilience(result)
    mark("serving")
    # Fast-recovery checkpoint tiers: its own CPU child (host-side
    # mechanics); per-tier time-to-recover + goodput under preemption.
    _fill_recovery(result)
    mark("recovery")
    _fill_mfu(result, dev, on_tpu, dt, sess, batch)
    if on_tpu:
        # TPU-only like the other enrichments: a projection built on a
        # CPU-fallback step time would be a fabricated pod number.
        _fill_scaling_projection(result, sess)
    mark("mfu")
    if on_tpu:
        # Each enrichment prints the running result line when done, so a
        # parent timeout mid-enrichment keeps everything measured so far
        # (the parent takes the LAST valid JSON line).  Ordered by value:
        # the dense-attention comparison (extra compiles) goes last.
        _fill_input_pipeline(result, sess, batch_size, image_size)
        mark("input_pipeline")
        del sess, ad  # free the ResNet session before the LM sections
        _reset_default_autodist_for_testing()
        _fill_s2d_stem(result, batch_size, image_size)
        mark("s2d_stem")
        _reset_default_autodist_for_testing()
        flash_ok = _check_flash_numerics(result)  # on-chip kernel check
        mark("flash_numerics")
        if flash_ok:
            lm_cmp = _fill_lm(result)  # flagship tokens/sec (flash, session)
            mark("lm")
            _fill_lm_levers(result)    # remat/batch MFU sweep
            mark("lm_levers")
        else:
            lm_cmp = None
            print("bench: flash numerics failed; LM section blocked",
                  file=sys.stderr, flush=True)
            mark("lm")
        _fill_decode(result)           # serving decode tokens/sec
        mark("decode")
        _fill_engine(result)           # continuous-batching engine
        mark("engine")
        for fill in (_fill_bert, _fill_vgg, _fill_ncf, _fill_lm1b,
                     _fill_linreg, _fill_auto_strategy):
            fill(result)   # remaining BASELINE.json parity configs
            mark(fill.__name__.replace("_fill_", ""))
        if lm_cmp is not None:
            lm_cmp()       # flash-vs-dense speedup ratio
            mark("flash_vs_dense")


def _transformer_mfu(tokens_per_sec: float, n_params: float, seq: int,
                     n_layers: int, d_model: int, peak: float,
                     causal: bool = True) -> float:
    """Model-FLOPs utilization for a transformer train step: 6·N per
    token (fwd+bwd matmuls) + 12·L·d·T attention term, halved for causal
    masking (PaLM appendix-B accounting)."""
    attn = 12.0 * n_layers * d_model * seq * (0.5 if causal else 1.0)
    return tokens_per_sec * (6.0 * n_params + attn) / peak


def _session_throughput(spec, builder, optimizer, batch_size, steps, *,
                        warmup=3, bf16_params=False, batch_cast=None):
    """Measure one parity config through the framework's own path:
    ``AutoDist(builder) → capture → create_distributed_session →
    place_batch → run`` (matching how the reference benchmarked through
    ``ad.scope()``, /root/reference/examples/benchmark/imagenet.py:85-120).
    Returns ``(items_per_sec, dt, mesh_peak_flops)`` and frees the session
    state before returning so sections don't accumulate HBM."""
    import jax
    import jax.numpy as jnp

    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing

    params = spec.init(jax.random.PRNGKey(0))
    if bf16_params:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x, params)
    batch = spec.sample_batch(batch_size)
    if batch_cast is not None:
        batch = batch_cast(batch)
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optimizer,
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    placed = sess.place_batch(batch)
    dt = _measure_session(sess, placed, warmup, steps)
    peak = sum(_peak_flops(d) for d in sess.mesh.devices.flat)
    del sess, ad, params, batch, placed
    _reset_default_autodist_for_testing()
    return batch_size * steps / dt, dt, peak


def _check_flash_numerics(result) -> bool:
    """VERDICT r3 #2: assert the COMPILED Pallas flash-attention kernels —
    the real TPU lowering (block padding, VMEM tiling, custom-VJP bwd),
    not interpret mode — against dense attention, fwd + bwd, causal and
    full.  The suite's interpret-mode tests validate the algebra only;
    this is the on-chip check.  Records ``flash_numerics_ok``; a failure
    blocks the LM section (its throughput would be a number for a broken
    kernel).  Tolerances allow the MXU's mixed-precision f32 matmul paths
    (both sides run through the same hardware, but reduction orders
    differ)."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from autodist_tpu.models.transformer import dense_attention
        from autodist_tpu.ops.flash_attention import make_flash_attention

        flash = make_flash_attention()
        rng = np.random.RandomState(0)
        b, t, h, d = 2, 512, 4, 64
        q, k, v = (jnp.asarray(rng.randn(b, t, h, d) * 0.5, jnp.float32)
                   for _ in range(3))
        w = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)  # fixed cotangent

        ok = True
        for causal in (True, False):
            f_out = jax.jit(
                lambda q, k, v, c=causal: flash(q, k, v, c))(q, k, v)
            d_out = jax.jit(
                lambda q, k, v, c=causal: dense_attention(q, k, v, c))(
                    q, k, v)
            fwd_ok = np.allclose(np.asarray(f_out), np.asarray(d_out),
                                 rtol=2e-2, atol=2e-2)
            gf = jax.jit(jax.grad(
                lambda q, k, v, c=causal: jnp.sum(flash(q, k, v, c) * w),
                argnums=(0, 1, 2)))(q, k, v)
            gd = jax.jit(jax.grad(
                lambda q, k, v, c=causal: jnp.sum(
                    dense_attention(q, k, v, c) * w),
                argnums=(0, 1, 2)))(q, k, v)
            bwd_ok = all(np.allclose(np.asarray(a), np.asarray(bb),
                                     rtol=3e-2, atol=3e-2)
                         for a, bb in zip(gf, gd))
            if not (fwd_ok and bwd_ok):
                print(f"bench: flash numerics MISMATCH causal={causal} "
                      f"fwd_ok={fwd_ok} bwd_ok={bwd_ok}",
                      file=sys.stderr, flush=True)
            ok = ok and fwd_ok and bwd_ok
        result["flash_numerics_ok"] = bool(ok)
        return bool(ok)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: flash numerics check errored ({e!r})",
              file=sys.stderr, flush=True)
        result["flash_numerics_ok"] = False
        return False


def _fill_decode(result) -> None:
    """VERDICT r3 #4: measure serving decode — KV-cache autoregressive
    generation (``models/generate.py``) on the flagship LM at batch 8.
    Records ``decode_tokens_per_sec`` (greedy, O(T)/token scan) and the
    measured speedup over re-forward decode (argmax over a full causal
    forward per emitted token — the O(T^2) baseline a framework without
    KV caching pays), plus greedy token agreement between the two as an
    on-chip correctness signal.  Best-effort."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from autodist_tpu.models.generate import make_generator
        from autodist_tpu.models.transformer_lm import transformer_lm

        batch, p_len, n_new = 8, 32, 128
        total = p_len + n_new
        # max_len carries 8 slack positions for the speculative section
        # below (its proposals can overshoot the requested length by
        # gamma before trimming).
        spec = transformer_lm(num_layers=12, num_heads=12, head_dim=64,
                              d_ff=3072, max_len=total + 8, seq_len=total,
                              dtype=jnp.bfloat16)
        params = spec.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        prompt = jnp.asarray(rng.randint(
            0, spec.config["vocab_size"], (batch, p_len)), jnp.int32)

        gen = make_generator(spec)
        tok_kv = gen(params, prompt, n_new)       # compile
        tok_kv.block_until_ready()
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            tok_kv = gen(params, prompt, n_new)
        int(np.asarray(tok_kv[0, -1]))            # host fetch = hard sync
        dt_kv = (time.perf_counter() - t0) / reps
        result["decode_tokens_per_sec"] = round(batch * n_new / dt_kv, 1)
        result["decode_batch"] = batch
        result["decode_new_tokens"] = n_new
        print(json.dumps(result), flush=True)

        # Serving throughput at batch 64: decode is bandwidth-bound
        # (every tick re-reads all weights), so batching amortizes the
        # weight traffic — the number a serving deployment cares about.
        try:
            b64 = 64
            prompt64 = jnp.asarray(rng.randint(
                0, spec.config["vocab_size"], (b64, p_len)), jnp.int32)
            tok64 = gen(params, prompt64, n_new)
            tok64.block_until_ready()
            t0 = time.perf_counter()
            for _ in range(reps):
                tok64 = gen(params, prompt64, n_new)
            int(np.asarray(tok64[0, -1]))
            dt64 = (time.perf_counter() - t0) / reps
            result["decode_tokens_per_sec_b64"] = round(
                b64 * n_new / dt64, 1)
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"bench: b64 decode unavailable ({e!r})",
                  file=sys.stderr, flush=True)

        # Weight-only int8 decode (ops/quant.py Pallas kernel): decode
        # re-reads every weight per tick, so int8-resident weights halve
        # the bound traffic.  The on-chip correctness signal is greedy
        # agreement vs the SAME dequantized weights through the normal
        # decode (kernel-only difference — quantization itself changes
        # the model, so comparing against bf16 weights would mostly
        # measure int8 noise on random bench weights).
        try:
            from autodist_tpu.models.quantize import (
                dequantize_lm_params, quantize_lm_params)

            qp = quantize_lm_params(params)
            tok_q = gen(qp, prompt, n_new)
            tok_q.block_until_ready()
            t0 = time.perf_counter()
            for _ in range(reps):
                tok_q = gen(qp, prompt, n_new)
            int(np.asarray(tok_q[0, -1]))
            dt_q = (time.perf_counter() - t0) / reps
            result["decode_int8_tokens_per_sec"] = round(
                batch * n_new / dt_q, 1)
            # Cast the dequantized tree to the bench model's dtypes:
            # avals then match `params`, so gen's compile is reused, and
            # both paths run bf16 activations.  The agreement therefore
            # includes bf16 weight rounding (w cast before the dot here,
            # column-scaled after the dot in the kernel) on top of the
            # kernel arithmetic — a sanity signal, not an exactness
            # claim (the exact f32 oracle is tests/test_quant.py).
            dq = jax.tree_util.tree_map(
                lambda a, b: a.astype(b.dtype),
                dequantize_lm_params(qp, spec), params)
            tok_dq = gen(dq, prompt, n_new)
            result["decode_int8_oracle_agreement"] = round(float(np.mean(
                np.asarray(tok_q[:, p_len:])
                == np.asarray(tok_dq[:, p_len:]))), 4)
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"bench: int8 decode unavailable ({e!r})",
                  file=sys.stderr, flush=True)

        # Re-forward baseline: fixed [B, total] buffer, one compiled
        # program (pos is a traced scalar), full causal forward per token.
        @jax.jit
        def refwd_one(params, buf, pos):
            logits = spec.apply_fn(params, buf)          # [B, total, V]
            prev = lax.dynamic_index_in_dim(logits, pos - 1, 1,
                                            keepdims=False)
            nxt = jnp.argmax(prev, axis=-1).astype(buf.dtype)
            return lax.dynamic_update_index_in_dim(buf, nxt, pos, 1)

        def refwd_decode():
            buf = jnp.concatenate(
                [prompt, jnp.zeros((batch, n_new), prompt.dtype)], axis=1)
            for pos in range(p_len, total):
                buf = refwd_one(params, buf, jnp.int32(pos))
            return buf

        tok_rf = refwd_decode()                   # compile
        tok_rf.block_until_ready()
        t0 = time.perf_counter()
        tok_rf = refwd_decode()
        int(np.asarray(tok_rf[0, -1]))
        dt_rf = time.perf_counter() - t0
        result["decode_kv_speedup_vs_reforward"] = round(dt_rf / dt_kv, 2)
        # Greedy agreement (argmax ties under different reduction orders
        # can diverge a few positions in; report, don't assert).
        agree = float(np.mean(np.asarray(tok_kv[:, p_len:])
                              == np.asarray(tok_rf[:, p_len:])))
        result["decode_greedy_agreement"] = round(agree, 4)
        print(json.dumps(result), flush=True)

        # Speculative decoding (models/speculative.py), draft == target:
        # every proposal is accepted, so this is the MECHANICAL upper
        # bound of the draft-and-verify pipeline (gamma+1 tokens per
        # batched verify pass) — labeled as such; real speedup depends
        # on a trained draft's acceptance rate, which untrained bench
        # weights cannot exhibit.
        from autodist_tpu.models.speculative import \
            make_speculative_generator

        sg = make_speculative_generator(spec, spec)
        gamma = 4
        tok_sp, stats = sg(params, params, prompt, n_new, gamma)
        tok_sp.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            tok_sp, stats = sg(params, params, prompt, n_new, gamma)
        int(np.asarray(tok_sp[0, -1]))
        dt_sp = (time.perf_counter() - t0) / reps
        result["decode_speculative_tokens_per_sec"] = round(
            batch * n_new / dt_sp, 1)
        result["decode_speculative_note"] = \
            f"draft=target upper bound, gamma={gamma}"
        prop = int(np.asarray(stats["proposed"]).sum())
        result["decode_speculative_acceptance"] = round(
            int(np.asarray(stats["accepted"]).sum()) / max(prop, 1), 4)
        spec_agree = float(np.mean(np.asarray(tok_sp[:, p_len:])
                                   == np.asarray(tok_kv[:, p_len:])))
        result["decode_speculative_greedy_agreement"] = round(
            spec_agree, 4)
        print(json.dumps(result), flush=True)
        _fill_speculative_trained(result)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: decode metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_speculative_trained(result) -> None:
    """The REAL speculative number (VERDICT r4 weak #3): a trained
    target + a ~20x-smaller trained draft (the examples/
    speculative_draft.py pipeline, abbreviated), measured with-vs-
    without speculation at the same config.  Random bench weights can't
    exhibit acceptance, so both models train briefly on a learnable
    unigram stream (next = (3*prev + 7) % vocab); the recorded speedup —
    or honest lack of one — is the point.  Best-effort."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.autodist import AutoDist, \
            _reset_default_autodist_for_testing
        from autodist_tpu.models.generate import make_generator
        from autodist_tpu.models.speculative import \
            make_speculative_generator
        from autodist_tpu.models.transformer_lm import transformer_lm
        from autodist_tpu.strategy import AllReduce

        # Smoke knobs (CPU verification of the section; TPU uses the
        # full config): layer counts and train steps.
        t_layers = int(os.environ.get("AUTODIST_BENCH_SPEC_LAYERS", 6))
        t_steps = int(os.environ.get("AUTODIST_BENCH_SPEC_STEPS", 600))
        # Unigram stream: next = (3*prev + 7) % 97 — only 97 transitions
        # (and 97 deterministic trajectories, so most eval prompts recur
        # from training), which BOTH models learn as an exact transition
        # lookup: the draft tracks the target and the measurement shows
        # what speculation delivers WITH a competent draft.  A richer
        # two-token rule measurably fails here — the models minimize
        # teacher-forced loss by memorizing the rotating batches and
        # autoregressive accuracy collapses to ~0.3 (measured), so the
        # acceptance number reflects model quality, not the pipeline.
        # Acceptance is reported so the regime stays transparent.
        vocab, seq = 97, 128
        rng = np.random.RandomState(1)

        def make_batch(n):
            toks = np.zeros((n, seq), np.int64)
            toks[:, 0] = rng.randint(0, vocab, n)
            for t in range(1, seq):
                toks[:, t] = (3 * toks[:, t - 1] + 7) % vocab
            return {"tokens": toks.astype(np.int32)}

        t_spec = transformer_lm(vocab_size=vocab, num_layers=t_layers,
                                num_heads=8, head_dim=64, d_ff=2048,
                                max_len=2 * seq + 8, seq_len=seq,
                                dtype=jnp.bfloat16)
        d_spec = transformer_lm(vocab_size=vocab, num_layers=2,
                                num_heads=4, head_dim=32, d_ff=256,
                                max_len=2 * seq + 8, seq_len=seq,
                                dtype=jnp.bfloat16)

        def train(spec, steps, lr):
            _reset_default_autodist_for_testing()
            ad = AutoDist(strategy_builder=AllReduce())
            with ad.scope():
                ad.capture(params=spec.init(jax.random.PRNGKey(0)),
                           optimizer=optax.adam(lr),
                           loss_fn=spec.loss_fn)
            sess = ad.create_distributed_session()
            # Rotating batches: training on one fixed batch memorizes it
            # and generalizes nowhere (see vocab note above).
            placed = [sess.place_batch(make_batch(32)) for _ in range(8)]
            for i in range(steps):
                sess.run(placed[i % len(placed)], sync=False)
            loss = float(sess.run(placed[0])["loss"])
            params = sess.params
            del sess
            _reset_default_autodist_for_testing()
            return params, loss

        tp, t_loss = train(t_spec, t_steps, 2e-3)
        dp, d_loss = train(d_spec, t_steps, 3e-3)

        batch, p_len, n_new, gamma = 8, 32, 128, 4
        prompt = np.asarray(make_batch(batch)["tokens"][:, :p_len],
                            np.int32)
        gen = make_generator(t_spec)
        base = gen(tp, prompt, n_new)
        base.block_until_ready()
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            base = gen(tp, prompt, n_new)
        int(np.asarray(base[0, -1]))
        dt_base = (time.perf_counter() - t0) / reps

        sg = make_speculative_generator(t_spec, d_spec)
        tok, stats = sg(tp, dp, prompt, n_new, gamma)
        tok.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            tok, stats = sg(tp, dp, prompt, n_new, gamma)
        int(np.asarray(tok[0, -1]))
        dt_sp = (time.perf_counter() - t0) / reps

        prop = int(np.asarray(stats["proposed"]).sum())
        result["decode_speculative_trained_tokens_per_sec"] = round(
            batch * n_new / dt_sp, 1)
        result["decode_speculative_trained_speedup"] = round(
            dt_base / dt_sp, 3)
        result["decode_speculative_trained_acceptance"] = round(
            int(np.asarray(stats["accepted"]).sum()) / max(prop, 1), 4)
        result["decode_speculative_trained_note"] = (
            f"{t_layers}L target (loss {t_loss:.3f}) + 2L draft (loss "
            f"{d_loss:.3f}), gamma={gamma}, learnable synthetic stream")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: trained-draft speculative unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_s2d_stem(result, batch_size, image_size) -> None:
    """A/B the space-to-depth ResNet stem (models/resnet.py
    convert_stem_params — exactly the 7×7/s2 function, MXU-shaped):
    same session path, same batch, records the s2d throughput and the
    ratio over the main conv7 number measured above.  Best-effort."""
    try:
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.models.resnet import resnet50
        from autodist_tpu.strategy import AllReduce

        spec = resnet50(num_classes=1000, image_size=image_size,
                        stem="s2d")

        def cast(batch):
            return {"images": batch["images"].astype(np.float32).astype(
                jnp.bfloat16), "labels": batch["labels"]}

        s2d, _, _ = _session_throughput(
            spec, AllReduce(), optax.sgd(0.1, momentum=0.9), batch_size,
            MEASURE_STEPS, warmup=WARMUP_STEPS, bf16_params=True,
            batch_cast=cast)
        result["resnet50_s2d_images_per_sec"] = round(s2d, 2)
        if result.get("value"):
            result["resnet50_s2d_speedup"] = round(
                s2d / result["value"], 3)
    except Exception as e:
        print(f"bench: s2d stem section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_engine(result) -> None:
    """Continuous batching (serving/engine.py) on the flagship-LM-sized
    decoder: a mixed-completion-length workload through 8 slots, against
    the static-batching baseline (one compiled [8, max] program where
    every batch runs to the longest completion — what a naive server
    pays).  The engine wins by harvesting finished slots and admitting
    queued work (parallel prefill) without stopping the batch.
    Best-effort."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from autodist_tpu.models.generate import make_generator
        from autodist_tpu.models.transformer_lm import transformer_lm
        from autodist_tpu.serving import DecodeEngine

        slots, p_len, n_max, n_reqs = 8, 32, 128, 32
        window = 512
        # Env knob so an off-TPU smoke can exercise the exact code path
        # at a depth CPU can finish (the TPU bench keeps the default 12).
        n_layers = int(os.environ.get("AUTODIST_BENCH_ENGINE_LAYERS", 12))
        spec = transformer_lm(num_layers=n_layers, num_heads=12,
                              head_dim=64, d_ff=3072, max_len=window,
                              seq_len=p_len + n_max, dtype=jnp.bfloat16)
        params = spec.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        vocab = spec.config["vocab_size"]
        # Long-tailed completion lengths (decode traffic is famously
        # long-tailed — most requests stop early, a few run to the cap):
        # the regime continuous batching exists for.  Same prompt length
        # so the static baseline needs exactly one program.
        lens = np.minimum(rng.exponential(scale=n_max / 3, size=n_reqs)
                          .astype(np.int64) + 8, n_max)
        prompts = [rng.randint(0, vocab, p_len).astype(np.int32)
                   for _ in range(n_reqs)]

        def build_engine(param_tree=params):
            # chunk=32: admission latency is irrelevant for a throughput
            # benchmark, and fewer boundaries = fewer host round-trips.
            # One definition for both the fp and int8 rows so they can
            # never drift onto different engine configs.
            eng = DecodeEngine(spec, param_tree, slots=slots,
                               window=window, chunk=32)
            for p, n in zip(prompts, lens):
                eng.submit(p, int(n))
            return eng

        build_engine().run()                      # compile warm-up
        # Construction + submits stay OUTSIDE the timed region, matching
        # the static baseline (whose generator setup/compile is also
        # excluded) — dt_eng is the decode loop only.
        eng = build_engine()
        t0 = time.perf_counter()
        eng.run()
        dt_eng = time.perf_counter() - t0
        gen_tokens = int(lens.sum())
        result["engine_tokens_per_sec"] = round(gen_tokens / dt_eng, 1)
        result["engine_slot_utilization"] = round(
            eng.stats.slot_utilization, 3)
        result["engine_prefill_admissions"] = eng.stats.prefill_admissions
        print(json.dumps(result), flush=True)

        # Static baseline: batches of `slots` in submission order, every
        # batch decoded to n_max by ONE compiled program (a fixed-shape
        # server loop), surplus tokens discarded.
        gen = make_generator(spec)
        batches = [np.stack(prompts[i:i + slots])
                   for i in range(0, n_reqs, slots)]
        out = gen(params, jnp.asarray(batches[0]), n_max)  # compile
        out.block_until_ready()
        t0 = time.perf_counter()
        for b in batches:
            out = gen(params, jnp.asarray(b), n_max)
        int(np.asarray(out[0, -1]))               # hard sync
        dt_static = time.perf_counter() - t0
        result["engine_vs_static_speedup"] = round(dt_static / dt_eng, 2)
        print(json.dumps(result), flush=True)

        # The deployment config: continuous batching over weight-only
        # int8 (decode is weight-bandwidth-bound; int8 halves it).
        try:
            from autodist_tpu.models.quantize import quantize_lm_params

            qp = quantize_lm_params(params)
            build_engine(qp).run()            # compile warm-up
            eng_q = build_engine(qp)
            t0 = time.perf_counter()
            eng_q.run()
            dt_q = time.perf_counter() - t0
            result["engine_int8_tokens_per_sec"] = round(
                gen_tokens / dt_q, 1)
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"bench: int8 engine row unavailable ({e!r})",
                  file=sys.stderr, flush=True)

        # Prefix cache: the system-prompt workload — every request
        # shares a 256-token prefix.  Plain serving re-prefills it per
        # admission (prompt = prefix + user text); the prefix cache
        # computes its K/V once (set_prefix) and admissions prefill only
        # the user text.  Same requests, same completion lengths.
        try:
            pfx_len = 256
            pfx = rng.randint(0, vocab, pfx_len).astype(np.int32)

            def run_prefix_case(shared: bool):
                eng_p = DecodeEngine(spec, params, slots=slots,
                                     window=window, chunk=32)
                if shared:
                    eng_p.set_prefix(pfx)
                for p, n in zip(prompts, lens):
                    if shared:
                        eng_p.submit(p, int(n), use_prefix=True)
                    else:
                        eng_p.submit(np.concatenate([pfx, p]), int(n))
                t0 = time.perf_counter()
                eng_p.run()
                return time.perf_counter() - t0

            run_prefix_case(True)             # compile warm-up
            run_prefix_case(False)
            dt_shared = run_prefix_case(True)
            dt_plain = run_prefix_case(False)
            result["engine_prefix_tokens_per_sec"] = round(
                gen_tokens / dt_shared, 1)
            result["engine_prefix_speedup"] = round(
                dt_plain / dt_shared, 2)
            result["engine_prefix_len"] = pfx_len
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"bench: prefix engine row unavailable ({e!r})",
                  file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: engine section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_lm(result):
    """Secondary metric: flagship TransformerLM training throughput with
    the Pallas flash-attention kernel (the TPU default), measured through
    the framework session path like every other section.  Returns a
    thunk that fills the dense-attention comparison (so the caller can
    defer those extra compiles), or None on failure.
    Best-effort — a failure here never loses the primary metric."""
    try:
        import jax.numpy as jnp
        import optax

        from autodist_tpu.models.transformer import dense_attention
        from autodist_tpu.models.transformer_lm import transformer_lm
        from autodist_tpu.ops.flash_attention import make_flash_attention
        from autodist_tpu.strategy import AllReduce

        batch_size, seq = 8, 2048
        steps = 8

        mesh_peak = [0.0]

        def measure(attn_fn, bs):
            spec = transformer_lm(num_layers=12, num_heads=12, head_dim=64,
                                  d_ff=3072, max_len=seq, seq_len=seq,
                                  attn_fn=attn_fn, dtype=jnp.bfloat16)
            samples_per_sec, _, peak = _session_throughput(
                spec, AllReduce(), optax.sgd(1e-3), bs, steps)
            mesh_peak[0] = peak
            return samples_per_sec * seq

        flash_tps = measure(make_flash_attention(), batch_size)
        result["lm_tokens_per_sec"] = round(flash_tps, 1)
        result["lm_seq_len"] = seq
        result["lm_path"] = "session"
        # Session throughput is AGGREGATE over the mesh: divide by the
        # whole mesh's peak, not one chip's.
        peak = mesh_peak[0]
        if peak:
            # 12L x d768: ~124M params (incl. 32128-vocab tied embedding).
            result["lm_mfu"] = round(_transformer_mfu(
                flash_tps, 124e6, seq, 12, 768, peak), 4)

        def compare_dense():
            # Dense attention materializes f32[B,H,T,T] score tensors
            # (1.5 GB per layer at B=8, T=2048) and can OOM where flash
            # runs — itself the headline.  Fall back to smaller dense
            # batches; the ratio is apples-to-apples because flash is
            # re-measured at the SAME batch.
            for dense_bs in (batch_size, 2, 1):
                try:
                    dense_tps = measure(dense_attention, dense_bs)
                    flash_at_bs = flash_tps if dense_bs == batch_size \
                        else measure(make_flash_attention(), dense_bs)
                    result["lm_flash_speedup_vs_dense"] = round(
                        flash_at_bs / dense_tps, 3)
                    result["lm_dense_batch"] = dense_bs
                    return
                except Exception as de:
                    result["lm_dense_oom_at_batch"] = dense_bs
                    print(f"bench: dense attention failed at batch "
                          f"{dense_bs} ({type(de).__name__}); flash ran "
                          f"at {batch_size}", file=sys.stderr, flush=True)

        return compare_dense
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: LM secondary metric unavailable ({e!r})",
              file=sys.stderr, flush=True)
        return None


def _fill_lm_levers(result):
    """MFU lever sweep on the flagship LM (VERDICT r4 #5): per-layer
    remat ("dots" policy) frees activation HBM, which the batch then
    grows into — the standard route past the ~43% plateau.  Each lever
    is measured at the same 12-layer flash config as ``_fill_lm`` and
    recorded separately so the per-lever delta is explicit."""
    try:
        import jax.numpy as jnp
        import optax

        from autodist_tpu.models.transformer_lm import transformer_lm
        from autodist_tpu.ops.flash_attention import make_flash_attention
        from autodist_tpu.strategy import AllReduce

        seq, steps = 2048, 8

        def measure(bs, remat):
            spec = transformer_lm(num_layers=12, num_heads=12, head_dim=64,
                                  d_ff=3072, max_len=seq, seq_len=seq,
                                  attn_fn=make_flash_attention(),
                                  dtype=jnp.bfloat16, remat=remat)
            sps, _, peak = _session_throughput(
                spec, AllReduce(), optax.sgd(1e-3), bs, steps)
            tps = sps * seq
            mfu = _transformer_mfu(tps, 124e6, seq, 12, 768, peak) \
                if peak else None
            return tps, mfu

        for key, bs, remat in (("remat_dots_b8", 8, "dots"),
                               ("remat_dots_b16", 16, "dots"),
                               ("b16", 16, "none"),
                               ("remat_dots_b32", 32, "dots")):
            try:
                tps, mfu = measure(bs, remat)
                result[f"lm_tokens_per_sec_{key}"] = round(tps, 1)
                if mfu is not None:
                    result[f"lm_mfu_{key}"] = round(mfu, 4)
                print(json.dumps(result), flush=True)
            except Exception as le:
                result[f"lm_lever_{key}_failed"] = type(le).__name__
                print(f"bench: LM lever {key} failed ({le!r})",
                      file=sys.stderr, flush=True)
        best = max((v for k, v in result.items()
                    if k.startswith("lm_mfu")), default=None)
        if best is not None:
            result["lm_mfu_best"] = best
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: LM lever sweep unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_scaling_projection(result, sess) -> None:
    """Model-based multi-chip scaling projection (clearly labeled as a
    projection — one chip is all this environment can attach).  Uses the
    analytic cost model (strategy/cost_model.py) on a hypothetical
    64-chip v5e pod: projected efficiency = t_compute / (t_compute +
    t_sync) with the MEASURED single-chip step time as t_compute and the
    ring-allreduce wire estimate as unoverlapped worst-case t_sync.  XLA
    overlaps collectives with backward compute, so the true number lands
    between this floor and 1.0; BASELINE.json's north star is >=90%."""
    try:
        from autodist_tpu.resource_spec import ResourceSpec
        from autodist_tpu.strategy.cost_model import estimate_cost

        spec64 = ResourceSpec(resource_info={
            "nodes": [{"address": f"10.0.0.{i}", "chips": 4,
                       **({"chief": True} if i == 0 else {})}
                      for i in range(16)],
            "ici_connected": True,    # one v5e-64 pod slice: ICI domain
            "network_bandwidth": 200})
        gi = sess._gi
        report = estimate_cost(sess._step.compiled_strategy.strategy, gi,
                               spec64)
        t_compute = result["step_time_ms"] / 1e3
        eff = t_compute / (t_compute + report.time_s)
        result["projected_scaling_efficiency_64chip"] = round(eff, 4)
        result["projected_sync_ms_64chip"] = round(report.time_s * 1e3, 3)
        result["scaling_projection_basis"] = "analytic-cost-model"
        # Calibration status (tests/test_cost_model_calibration.py): the
        # model's strategy RANKING is validated against measured step
        # times on the 8-device CPU mesh; absolute times are hardware-
        # uncalibrated (one chip cannot measure a cross-chip collective).
        result["scaling_projection_calibration"] = \
            "rank-validated-cpu-mesh; absolute-times-uncalibrated"
    except Exception as e:  # pragma: no cover - advisory only
        print(f"bench: scaling projection unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _measure_session(sess, placed_batch, warmup: int, steps: int) -> float:
    """Warmup + async-dispatch timing over a pre-placed batch; the final
    step's host fetch is the hard sync closing the window (reliable over
    the remote-TPU tunnel where block_until_ready is not).  Returns
    elapsed seconds for ``steps`` steps."""
    for _ in range(warmup):
        sess.run(placed_batch, sync=False)
    sess.run(placed_batch)
    t0 = time.perf_counter()
    for _ in range(steps - 1):
        sess.run(placed_batch, sync=False)
    sess.run(placed_batch)
    return time.perf_counter() - t0


def _fill_bert(result) -> None:
    """Secondary metric: BERT-base MLM pre-training samples/sec through the
    full AutoDist path with the PartitionedAR strategy — the BASELINE.json
    parity config ('BERT-base — PartitionedAR').  Best-effort."""
    try:
        import jax.numpy as jnp
        import optax

        from autodist_tpu.models.bert import bert_base
        from autodist_tpu.strategy import PartitionedAR

        batch_size, seq, steps = 64, 128, 10
        spec = bert_base(seq_len=seq, dtype=jnp.bfloat16)
        sps, dt, peak = _session_throughput(
            spec, PartitionedAR(), optax.adamw(1e-4), batch_size, steps,
            bf16_params=True)
        result["bert_samples_per_sec"] = round(sps, 1)
        result["bert_seq_len"] = seq
        result["bert_batch_size"] = batch_size
        if peak:
            result["bert_mfu"] = round(_transformer_mfu(
                sps * seq, 110e6, seq, 12, 768, peak, causal=False), 4)
        # Optimizer-state-width lever.  The baseline's bf16 params ALREADY
        # imply bf16 adamw moments (optax zeros_like inherits the param
        # dtype), so the control arm is FORCED-f32 moments at the same
        # config: the delta baseline-vs-f32state is what narrow optimizer
        # state buys (ops/opt_state_dtype.py).
        from autodist_tpu.ops.opt_state_dtype import cast_opt_state

        sps2, _, _ = _session_throughput(
            spec, PartitionedAR(),
            cast_opt_state(optax.adamw(1e-4), jnp.float32),
            batch_size, steps, bf16_params=True)
        result["bert_samples_per_sec_f32state"] = round(sps2, 1)
        if peak:
            result["bert_mfu_f32state"] = round(_transformer_mfu(
                sps2 * seq, 110e6, seq, 12, 768, peak, causal=False), 4)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: BERT secondary metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_input_pipeline(result, sess, batch_size, image_size) -> None:
    """VERDICT r2 #5: prove the input pipeline end-to-end instead of
    arguing from design.  Three numbers:

    * ``loader_images_per_sec`` — the native threaded DataLoader alone
      (shuffle + gather + fp32→bf16 cast into pooled staging buffers);
      it must sustain the step rate for the C++ layer's existence claim.
    * ``input_pipeline_images_per_sec`` — fresh loader batch placed and
      trained every step (loader → place_batch → session.run).
    * ``input_pipeline_overhead_pct`` — end-to-end vs the pre-placed
      number already measured.

    Honesty label: over THIS image's remote-TPU tunnel, host→device
    transfers serialize with compute (measured r2: interleaving fresh
    batches collapses ResNet to ~150 img/s while the loader alone does
    >5k and a lone transfer ~600 MB/s), so the overhead number here
    reflects the tunnel, not the loader; the basis field says which side
    the bottleneck is on.  Best-effort."""
    try:
        import numpy as np

        from autodist_tpu.runtime.data_loader import DataLoader

        n = 512
        rng = np.random.RandomState(0)
        images = rng.rand(n, image_size, image_size, 3).astype(np.float32)
        labels = rng.randint(0, 1000, (n,)).astype(np.int32)
        loader = DataLoader({"images": images, "labels": labels},
                            batch_size=batch_size, shuffle=True,
                            to_bf16=("images",), num_threads=4,
                            prefetch_depth=4)
        # Loader standalone throughput (3 epochs, host only).
        for _ in loader:      # warm the thread pool / staging buffers
            pass
        t0 = time.perf_counter()
        epochs, count = 3, 0
        for _ in range(epochs):
            for _ in loader:
                count += 1
        loader_ips = count * batch_size / (time.perf_counter() - t0)
        result["loader_images_per_sec"] = round(loader_ips, 1)
        result["loader_native"] = bool(loader._use_native)
        print(json.dumps(result), flush=True)

        # End-to-end: a fresh loader batch through place_batch + run each
        # step (async dispatch; final host fetch closes the window).
        it = iter(loader)
        steps = 8

        def fresh():
            nonlocal it
            try:
                return next(it)
            except StopIteration:
                it = iter(loader)
                return next(it)

        sess.run(sess.place_batch(fresh()))  # sync start point
        t0 = time.perf_counter()
        for _ in range(steps - 1):
            sess.run(sess.place_batch(fresh()), sync=False)
        sess.run(sess.place_batch(fresh()))
        e2e_ips = steps * batch_size / (time.perf_counter() - t0)
        pre_ips = result["value"]
        result["input_pipeline_images_per_sec"] = round(e2e_ips, 1)
        result["input_pipeline_overhead_pct"] = round(
            100.0 * (1.0 - e2e_ips / pre_ips), 1)
        if e2e_ips < 0.5 * min(loader_ips, pre_ips):
            # End-to-end collapsed far below BOTH the loader (host-only)
            # and the pre-placed step rate (device-only): the bottleneck
            # is the transfer path between them — on this image the
            # tunnel's serialized H2D (r2 measurement in BASELINE.md).
            # Labeling this "loader-bound" would wrongly indict the
            # native loader.
            result["input_pipeline_basis"] = (
                "h2d-serialized-over-tunnel; loader "
                f"{round(loader_ips)} img/s standalone")
        elif loader_ips >= pre_ips:
            result["input_pipeline_basis"] = "loader-sustains-step-rate"
        else:
            result["input_pipeline_basis"] = "loader-bound"
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: input pipeline metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_linreg(result) -> None:
    """BASELINE.json parity config #1: linear_regression + PS (the
    reference's single-node smoke workload).  Steps/sec through the full
    session path — trivial compute, so this measures the framework's
    per-step dispatch floor.  Best-effort."""
    try:
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.models.base import ModelSpec
        from autodist_tpu.strategy import PS

        rng = np.random.RandomState(0)
        w_true = rng.randn(8, 1).astype(np.float32)

        def loss_fn(p, batch):
            return jnp.mean((batch["x"] @ p["w"] + p["b"]
                             - batch["y"]) ** 2)

        def make_batch(r, n):
            x = r.randn(n, 8).astype(np.float32)
            return {"x": x, "y": x @ w_true + 0.01
                    * r.randn(n, 1).astype(np.float32)}

        spec = ModelSpec(
            name="linear_regression",
            init=lambda _: {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))},
            loss_fn=loss_fn, apply_fn=None, make_batch=make_batch)
        batch_size, steps = 256, 100
        _, dt, _ = _session_throughput(spec, PS(), optax.sgd(0.1),
                                       batch_size, steps, warmup=5)
        result["linreg_steps_per_sec"] = round(steps / dt, 1)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: linear-regression metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_vgg(result) -> None:
    """BASELINE.json parity config: VGG16 + PartitionedPS (the variable-
    partitioner showcase — its 4096-wide fc layers are what partitioning
    was built for).  Best-effort."""
    try:
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.models.vgg import vgg16
        from autodist_tpu.strategy import PartitionedPS

        batch_size, steps = 128, 10
        spec = vgg16(num_classes=1000, image_size=224)

        def cast(batch):
            return {"images": batch["images"].astype(np.float32).astype(
                jnp.bfloat16), "labels": batch["labels"]}

        ips, dt, peak = _session_throughput(
            spec, PartitionedPS(), optax.sgd(0.1, momentum=0.9),
            batch_size, steps, bf16_params=True, batch_cast=cast)
        result["vgg16_images_per_sec"] = round(ips, 1)
        result["vgg16_batch_size"] = batch_size
        if peak:
            # VGG16 fwd ~= 15.5 GFLOP/image at 224**2; train ~= 3x fwd.
            result["vgg16_mfu"] = round(
                ips * 3.0 * 15.5e9 / peak, 4)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: VGG16 metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_ncf(result) -> None:
    """BASELINE.json parity config: NCF (MovieLens-scale) + PSLoadBalancing
    — embedding-dominated, the byte-balanced PS showcase.  Best-effort."""
    try:
        import optax

        from autodist_tpu.models.ncf import ncf
        from autodist_tpu.strategy import PSLoadBalancing

        batch_size, steps = 4096, 20
        spec = ncf()
        sps, dt, _ = _session_throughput(
            spec, PSLoadBalancing(), optax.adam(1e-3), batch_size, steps)
        result["ncf_samples_per_sec"] = round(sps, 0)
        result["ncf_batch_size"] = batch_size
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: NCF metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_lm1b(result) -> None:
    """BASELINE.json parity config: lm1b LSTM LM (793k vocab) + Parallax
    hybrid — sparse embedding/softmax to sharded PS, dense LSTM weights to
    AllReduce.  Uses the chunked-vocab EXACT cross entropy (the default,
    ops/chunked_xent.py) at batch 256: the framework's best configuration —
    the dense-logits loss OOMs there ([256, 19, 793k] f32 = 15.5 GB), and
    chunking measured 28.3k vs 16.1k wps for dense at its best batch (r2).
    Best-effort."""
    try:
        import optax

        from autodist_tpu.models.lm1b import lm1b
        from autodist_tpu.strategy import Parallax

        batch_size, steps = 256, 10
        spec = lm1b()          # default = chunked exact loss, 8192 chunks
        seq = spec.config["seq_len"]
        sps, dt, _ = _session_throughput(
            spec, Parallax(), optax.adagrad(0.1), batch_size, steps)
        result["lm1b_words_per_sec"] = round(sps * seq, 0)
        result["lm1b_batch_size"] = batch_size
        result["lm1b_loss"] = "chunked_xent_exact"
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: lm1b metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_auto_strategy(result) -> None:
    """VERDICT r3 #5: AutoStrategy's END-TO-END claim measured on TPU —
    for two contrasting workloads (embedding-heavy, dense MLP) the auto
    choice's step time vs the best fixed builder's.  Records
    ``auto_vs_best_pct`` = worst-case percentage overhead of auto over
    the measured-best fixed builder (negative = auto was fastest).
    Best-effort."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.autodist import AutoDist, \
            _reset_default_autodist_for_testing
        from autodist_tpu.strategy import (AllReduce, AutoStrategy,
                                           Parallax, PSLoadBalancing)

        def measure(builder, params, loss_fn, batch, sparse_vars=()):
            _reset_default_autodist_for_testing()
            ad = AutoDist(strategy_builder=builder)
            with ad.scope():
                ad.capture(params=params, optimizer=optax.sgd(0.1),
                           loss_fn=loss_fn, sparse_vars=sparse_vars)
            sess = ad.create_distributed_session()
            placed = sess.place_batch(batch)
            dt = _measure_session(sess, placed, 3, 15)
            del sess, ad
            _reset_default_autodist_for_testing()
            return dt / 15

        rng = np.random.RandomState(0)
        vocab, dim = 200_000, 64
        emb_params = {
            "emb": {"table": jnp.asarray(rng.randn(vocab, dim) * 0.01,
                                         jnp.float32)},
            "head": {"w": jnp.asarray(rng.randn(dim, 1) * 0.1,
                                      jnp.float32)}}
        emb_batch = {"ids": rng.randint(0, vocab, (4096,)).astype(np.int32),
                     "y": rng.randn(4096).astype(np.float32)}

        def emb_loss(p, b):
            rows = jnp.take(p["emb"]["table"], b["ids"], axis=0)
            return jnp.mean(((rows @ p["head"]["w"])[:, 0] - b["y"]) ** 2)

        dense_params = {
            "l1": {"w": jnp.asarray(rng.randn(1024, 1024) * 0.03,
                                    jnp.float32)},
            "l2": {"w": jnp.asarray(rng.randn(1024, 1024) * 0.03,
                                    jnp.float32)},
            "out": {"w": jnp.asarray(rng.randn(1024, 1) * 0.1,
                                     jnp.float32)}}
        dense_batch = {"x": rng.randn(512, 1024).astype(np.float32),
                       "y": rng.randn(512).astype(np.float32)}

        def dense_loss(p, b):
            h = jnp.tanh(b["x"] @ p["l1"]["w"])
            h = jnp.tanh(h @ p["l2"]["w"])
            return jnp.mean(((h @ p["out"]["w"])[:, 0] - b["y"]) ** 2)

        worst_pct = worst_search_pct = None
        for name, params, loss_fn, batch, sparse, fixed in (
                ("sparse", emb_params, emb_loss, emb_batch, ("emb/table",),
                 (AllReduce(), Parallax(), PSLoadBalancing())),
                ("dense", dense_params, dense_loss, dense_batch, (),
                 (AllReduce(), PSLoadBalancing()))):
            best = min(measure(b, params, loss_fn, batch, sparse)
                       for b in fixed)
            auto = measure(AutoStrategy(), params, loss_fn, batch, sparse)
            pct = 100.0 * (auto / best - 1.0)
            result[f"auto_vs_best_pct_{name}"] = round(pct, 1)
            worst_pct = pct if worst_pct is None else max(worst_pct, pct)
            # Cost-model search mode: the searched candidate usually IS
            # one of the fixed builders, so its program hits the
            # compile cache.
            searcher = AutoStrategy(search=True)
            s_auto = measure(searcher, params, loss_fn, batch, sparse)
            s_pct = 100.0 * (s_auto / best - 1.0)
            result[f"auto_search_vs_best_pct_{name}"] = round(s_pct, 1)
            result[f"auto_search_choice_{name}"] = searcher.last_choice
            worst_search_pct = s_pct if worst_search_pct is None \
                else max(worst_search_pct, s_pct)
        result["auto_vs_best_pct"] = round(worst_pct, 1)
        result["auto_search_vs_best_pct"] = round(worst_search_pct, 1)
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: auto-strategy metric unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_mfu(result, dev, on_tpu, dt, sess, batch) -> None:
    """MFU = model FLOPs/s ÷ chip peak, from analytic ResNet-50 FLOPs (the
    cheap, always-available estimate).  XLA's compiled cost analysis is
    exact but AOT lower().compile() is not guaranteed to hit jit's cache —
    a second compile this benchmark only pays when asked
    (AUTODIST_BENCH_XLA_FLOPS=1)."""
    peak = _peak_flops(dev) if on_tpu else 0.0
    if peak:
        result["mfu"] = round(
            result["flops_per_step"] * MEASURE_STEPS / dt / peak, 4)
    if not os.environ.get("AUTODIST_BENCH_XLA_FLOPS"):
        return
    print(json.dumps(result), flush=True)  # safety line before recompile
    try:
        lowered = sess._step.step_fn.lower(
            sess.sharded_params, sess.opt_state, sess.sync_state, batch)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        xla_flops = float(cost.get("flops", 0.0))
        if xla_flops > 0:
            result["flops_per_step"] = xla_flops
            result["flops_source"] = "xla_cost_analysis"
            if peak:
                result["mfu"] = round(
                    xla_flops * MEASURE_STEPS / dt / peak, 4)
    except Exception as e:  # pragma: no cover - backend-dependent
        print(f"bench: cost_analysis unavailable ({e!r}); "
              f"keeping analytic FLOPs", file=sys.stderr, flush=True)


def _fill_grad_sync(result) -> None:
    """Bucketed gradient sync: per-mode (all_reduce vs reduce_scatter)
    wire bytes, bucket count, optimizer-state bytes/device, and measured
    step time, on an 8-way SIMULATED replica mesh (virtual CPU devices —
    collective byte counts are platform-independent facts of the
    program; step times compare the modes against each other).  Runs in
    its own child process so the device-count flag cannot disturb the
    parent's backend."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--grad-sync-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=600)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from grad-sync child "
                               f"(rc={proc.returncode})")
        result["grad_sync"] = payload
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: grad_sync section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_quant(result) -> None:
    """Quantized ring collectives (docs/overlap.md, BENCH_quant.json):
    int8/fp8 x pipeline on/off against the f32 ZeRO-1 baseline on the
    grad_sync model — wire bytes per step from the verified schedule IR
    (platform-independent facts; the verifier gates every mode before it
    is timed), measured step times, and the guard's post-quantization
    saturation counters.  Runs in its own 8-virtual-device child like
    grad_sync; the payload lands under ``grad_sync.quant`` AND is
    committed standalone as BENCH_quant.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__), "--quant-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=600)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from quant child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["quant"] = payload
        with open(os.path.join(REPO, "BENCH_quant.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: quant section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_flightrec(result) -> None:
    """Flight-recorder overhead (docs/observability.md "Flight
    recorder", BENCH_flightrec.json): recorder off vs the default
    host-phase granularity (interleaved minima, <1% bar) plus the
    honest legs-mode (host-callback) datapoint.  Runs in its own
    8-virtual-device child like grad_sync; the payload lands under
    ``grad_sync.flightrec`` AND is committed standalone as
    BENCH_flightrec.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--flightrec-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=600)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from flightrec child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["flightrec"] = \
            payload.get("flightrec")
        with open(os.path.join(REPO, "BENCH_flightrec.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: flightrec section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_profiler(result) -> None:
    """Schedule-aware profiler (docs/observability.md,
    BENCH_profiler.json): per-leg-kind measured vs leg-priced predicted
    time for every grad_sync mode (incl. the guard legs — attributing
    BENCH_guard's 5-7% overhead), the fitted calibration.json the cost
    model and AutoStrategy(search=True) consume, and the profiler
    off-vs-on overhead check.  Runs in its own 8-virtual-device child;
    the child also commits BENCH_leg_samples.jsonl + calibration.json
    at the repo root."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--profiler-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from profiler child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["profiler"] = payload
        with open(os.path.join(REPO, "BENCH_profiler.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: profiler section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_search(result) -> None:
    """Leg-calibrated strategy search (docs/strategies.md "Search",
    BENCH_search.json): on the comm-bound accum fixture, calibrate from
    leg micro-runs, run the beam search, and compare the searched
    schedule's ESTIMATED and MEASURED step time against every fixed
    candidate — the searched estimate must be <= all fixed estimates
    and the search must fit its 30 s wall budget.  Runs in its own
    8-virtual-device child; committed standalone as BENCH_search.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--search-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from search child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["search"] = payload
        with open(os.path.join(REPO, "BENCH_search.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: search section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_moe(result) -> None:
    """Expert-parallel MoE (docs/strategies.md "The expert axis",
    BENCH_moe.json): the MoE decoder LM measured dense (experts
    replicated, pure data parallel) vs expert-parallel (dispatch/combine
    a2a pairs over the ``expert`` axis) vs expert-parallel with the int8
    a2a wire — step time, honest a2a wire bytes from the schedule IR,
    per-leg predicted-vs-measured a2a cost from the leg profiler, and
    the liveness watermark peak (capacity transients included).  The IR
    verifier gates every mode.  Runs in its own 8-virtual-device child;
    committed standalone as BENCH_moe.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__), "--moe-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from moe child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["moe"] = payload
        with open(os.path.join(REPO, "BENCH_moe.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: moe section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_hier(result) -> None:
    """Hierarchical ICI+DCN grad sync (docs/strategies.md "Two-tier
    sync and --simulate", BENCH_hier.json): the comm-bound dense model
    on a simulated 2-slice mesh measured flat (single ring over the
    whole data axis) vs hierarchical (within-slice reduce-scatter →
    cross-slice DCN all-reduce → within-slice all-gather) vs
    hierarchical with the int8 DCN wire — step time, honest per-tier
    wire bytes from the schedule IR, per-tier predicted-vs-measured
    cost from the leg profiler (distinct fitted ICI and DCN constants),
    and loss parity against flat.  ``assert_verified`` gates every
    mode.  Runs in its own 8-virtual-device child; committed standalone
    as BENCH_hier.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__), "--hier-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from hier child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["hier"] = payload
        with open(os.path.join(REPO, "BENCH_hier.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: hier section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_mpmd(result) -> None:
    """MPMD pipeline runtime (docs/pipeline.md, BENCH_mpmd.json): the
    same 4-layer model as 1, 2, and 4 per-stage programs coupled only
    by the activation transport — step time, exposed DCN activation
    bytes per microbatch, and the 1F1B bubble predicted
    (``bubble_fraction_1f1b``) vs measured (``1 - t1/(S*tS)``).
    ``assert_verified`` gates every mode and each mode asserts its
    runtime fingerprint equals an independent ``ir_from_facts``
    rebuild.  Runs in its own CPU child; committed standalone as
    BENCH_mpmd.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-u", os.path.abspath(__file__), "--mpmd-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None or proc.returncode != 0:
            raise RuntimeError(f"no JSON from mpmd child "
                               f"(rc={proc.returncode})")
        result["mpmd"] = payload
        with open(os.path.join(REPO, "BENCH_mpmd.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: mpmd section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_serving(result) -> None:
    """Serving scale-out (docs/serving.md, BENCH_serving.json): the
    paged-KV continuous-batching engine under a synthetic open-loop
    load — tokens/s, p50/p99 time-to-first-token and per-token latency,
    continuous batching on vs off (slots=1), and a shared-prefix
    workload warm vs cold (prefix hit rate + TTFT delta).  Block-pool
    leak checks gate every mode like the IR verifier gates the sync
    benches: a leaked block fails the child, not just a counter.  Runs
    in its own CPU child; numbers compare modes against each other."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--serving-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None or proc.returncode != 0:
            raise RuntimeError(f"no JSON from serving child "
                               f"(rc={proc.returncode})")
        result["serving"] = payload
        with open(os.path.join(REPO, "BENCH_serving.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: serving section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_spec(result) -> None:
    """Speculative serving (docs/serving.md, BENCH_spec.json): the
    paged engine's draft-and-verify mode on the BENCH_serving burst
    workload — per-token p50/p99 vs the committed batching-on decode
    baseline, acceptance-length and gamma histograms, draft-vs-target
    block occupancy peaks, and the load-spike gamma-adaptation drill.
    Token-exactness against the target-only oracle and the block-leak
    invariant gate every mode inside the child (an assert fails the
    child, not just a counter).  Runs in its own CPU child."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--spec-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None or proc.returncode != 0:
            raise RuntimeError(f"no JSON from spec child "
                               f"(rc={proc.returncode})")
        result["spec_serving"] = payload
        with open(os.path.join(REPO, "BENCH_spec.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: speculative serving section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_serving_resilience(result) -> None:
    """Serving-plane fault tolerance (docs/serving.md "Fault
    tolerance", BENCH_serving_resilience.json): a two-replica pool
    under deterministic mid-stream faults — deadline goodput and
    re-decoded token waste with token-exact recovery on vs off, and a
    straggler scenario with hedged requests on vs off.  Token-exactness
    against the greedy oracle and the block-leak invariant gate every
    mode inside the child.  Runs in its own CPU child."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--serving-chaos-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None or proc.returncode != 0:
            raise RuntimeError(f"no JSON from serving-chaos child "
                               f"(rc={proc.returncode})")
        result["serving_resilience"] = payload
        with open(os.path.join(REPO, "BENCH_serving_resilience.json"),
                  "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: serving resilience section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_kernels(result) -> None:
    """Fused Pallas kernel suite (docs/kernels.md, BENCH_kernels.json):
    every fused kernel measured against its unfused reference on the
    same program — step times, per-leg LegProfiler attribution for each
    fusion (the BENCH_guard detect overhead finally has a leg to point
    at), exactness gates (fused-vs-unfused parity, paged decode
    token-exact), and the verified fused schedule IRs.  Runs in its own
    8-virtual-device child; committed standalone as
    BENCH_kernels.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--kernels-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=900)
        payload = _extract_json(proc.stdout.decode())
        if payload is None:
            raise RuntimeError(f"no JSON from kernels child "
                               f"(rc={proc.returncode})")
        result.setdefault("grad_sync", {})["kernels"] = payload
        with open(os.path.join(REPO, "BENCH_kernels.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: kernels section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def _fill_recovery(result) -> None:
    """Fast-recovery checkpoint tiers (docs/resilience.md,
    BENCH_recovery.json): time-to-recover per tier (RAM-local ring /
    peer mirror fetch / persistent Orbax), the sync-vs-async checkpoint
    stall a training loop actually pays, and end-to-end goodput under
    an injected preemption schedule — gated on the no-litter invariant
    (no drill may leave snapshot/marker files behind).  Runs in its own
    CPU child; committed standalone as BENCH_recovery.json."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-u", os.path.abspath(__file__),
           "--recovery-child"]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, env=env,
                              timeout=600)
        payload = _extract_json(proc.stdout.decode())
        if payload is None or proc.returncode != 0:
            raise RuntimeError(f"no JSON from recovery child "
                               f"(rc={proc.returncode})")
        result["recovery"] = payload
        with open(os.path.join(REPO, "BENCH_recovery.json"), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    except Exception as e:  # pragma: no cover - best-effort enrichment
        print(f"bench: recovery section unavailable ({e!r})",
              file=sys.stderr, flush=True)


def run_recovery_child() -> None:
    """The recovery-tier measurement (CPU child — recovery mechanics
    are host-side: device→host snapshot, file mirror, Orbax I/O; tier
    ratios mean the same thing on any backend).

    Sections: (1) time-to-recover per tier on the same trained state —
    RAM ring restore vs peer-mirror fetch+restore vs persistent Orbax
    restore; (2) checkpoint stall per save, sync vs async, with the
    RAM-snapshot capture cost alongside; (3) a live two-attempt
    preemption drill — chaos ``preempt@...,grace=...`` forces the
    emergency state onto the peer tier, the second attempt resumes from
    it, and goodput is decomposed over the journaled events.  The child
    FAILS (nonzero) if any drill leaves snapshot/marker litter."""
    _steer("cpu")
    import shutil
    import signal as _signal
    import tempfile

    import numpy as np

    os.environ["AUTODIST_IS_TESTING"] = "True"
    import jax.numpy as jnp
    import optax

    from autodist_tpu.autodist import (
        AutoDist, _reset_default_autodist_for_testing)
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.checkpoint.tiers import (
        CheckpointTiers, load_snapshot, route_restore)
    from autodist_tpu.resilience import ChaosCallback, ChaosMonkey
    from autodist_tpu.resilience.chaos import parse_chaos
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import get_journal
    from autodist_tpu.telemetry.goodput import goodput_from_run

    work = tempfile.mkdtemp(prefix="bench_recovery_")

    def session(dim=256):
        _reset_default_autodist_for_testing()
        rng = np.random.RandomState(0)
        x = rng.randn(64, dim).astype(np.float32)
        params = {"w": jnp.zeros((dim, dim), jnp.float32),
                  "b": jnp.zeros((dim,), jnp.float32)}

        def loss_fn(p, b):
            pred = b["x"] @ p["w"] + p["b"]
            return jnp.mean((pred - b["y"]) ** 2)

        ad = AutoDist(strategy_builder=AllReduce())
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn)
        batch = {"x": x,
                 "y": rng.randn(64, dim).astype(np.float32)}
        return ad.create_distributed_session(), batch

    payload = {"work_model": "adam linear 256x256 (~0.5 MB params + "
                             "1 MB opt state)", "platform": "cpu"}

    # -- 1) time-to-recover per tier --------------------------------------
    sess, batch = session()
    ckpt = os.path.join(work, "ck")
    peer = os.path.join(work, "peer")
    tiers = CheckpointTiers(sess, snapshot_every=1, keep=2, peer_dir=peer)
    for _ in range(3):
        sess.run(batch)
    saver = Saver(sess)
    saver.save(ckpt)
    tiers.snapshot()
    w_ref = np.asarray(sess.params["w"]).copy()

    t2r = {}
    # ram: the surviving-process path (ring already in memory)
    fresh, _ = session()
    snap = tiers.ring.latest()
    t0 = time.perf_counter()
    load_snapshot(fresh, snap)
    t2r["ram"] = round(time.perf_counter() - t0, 6)
    # peer: fresh process, mirror fetch + restore
    fresh, _ = session()
    t0 = time.perf_counter()
    step, tier, _meta = route_restore(
        fresh, None, tiers=CheckpointTiers(fresh, peer_dir=peer))
    t2r["peer"] = round(time.perf_counter() - t0, 6)
    assert tier == "peer", tier
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), w_ref,
                               rtol=1e-6, atol=1e-7)
    # persistent: Orbax restore of the same state
    fresh, _ = session()
    t0 = time.perf_counter()
    Saver(fresh).restore(os.path.join(ckpt, f"step_{step}"))
    t2r["persistent"] = round(time.perf_counter() - t0, 6)
    payload["time_to_recover_s"] = t2r
    payload["snapshot_capture_s"] = round(tiers.last_snapshot_s, 6)
    print(json.dumps(payload), flush=True)

    # -- 2) checkpoint stall: sync vs async saves -------------------------
    stalls = {}
    for mode, async_save in (("sync", False), ("async", True)):
        s2, b2 = session()
        sv = Saver(s2, async_save=async_save)
        d = os.path.join(work, f"stall_{mode}")
        s2.run(b2)
        total = 0.0
        for i in range(4):
            s2.run(b2)
            t0 = time.perf_counter()
            sv.save(d, step=s2.step_count)
            total += time.perf_counter() - t0
        sv.wait()
        stalls[f"{mode}_per_save_s"] = round(total / 4, 6)
    stalls["async_stall_reduction"] = round(
        stalls["sync_per_save_s"]
        / max(stalls["async_per_save_s"], 1e-9), 2)
    payload["checkpoint_stall"] = stalls
    print(json.dumps(payload), flush=True)

    # -- 3) goodput under an injected preemption schedule -----------------
    gp_peer = os.path.join(work, "gp_peer")
    events_before = len(get_journal().events)   # drill events only
    os.environ["AUTODIST_PREEMPT_GRACE_S"] = "0.001"   # forces peer tier
    a, ab = session()
    monkey = ChaosMonkey(parse_chaos("preempt@step=6,signal=SIGUSR1"),
                         process_index=0)
    hist_a = a.fit({"x": ab["x"], "y": ab["y"]}, epochs=2,
                   steps_per_epoch=8, snapshot_every=2,
                   snapshot_dir=gp_peer,
                   callbacks=[ChaosCallback(monkey)],
                   preemption_signals=(_signal.SIGUSR1,))
    assert hist_a.preempted and hist_a.preempt_tier == "peer", \
        (hist_a.preempted, hist_a.preempt_tier)
    records = list(a.telemetry.records) if a.telemetry else []
    b_sess, bb = session()
    hist_b = b_sess.fit({"x": ab["x"], "y": ab["y"]}, epochs=2,
                        steps_per_epoch=8, snapshot_every=2,
                        snapshot_dir=gp_peer)
    assert hist_b.resume_tier == "peer", hist_b.resume_tier
    # dict data resumes at epoch granularity: the partial epoch re-runs
    # (8 steps) then epoch 1 — 6 + 8 + 8
    assert b_sess.step_count == 22, b_sess.step_count
    if b_sess.telemetry:
        records += list(b_sess.telemetry.records)
    gp = goodput_from_run(records, get_journal().events[events_before:])
    payload["goodput_under_preemption"] = {
        "kill_schedule": "preempt@step=6,grace=0.001 (emergency -> peer)",
        "attempt_a": hist_a.goodput, "attempt_b": hist_b.goodput,
        "run": gp,
    }
    del os.environ["AUTODIST_PREEMPT_GRACE_S"]
    print(json.dumps(payload), flush=True)

    # -- 4) no-litter invariant -------------------------------------------
    tiers.cleanup()
    for t in (CheckpointTiers(None, peer_dir=peer),
              CheckpointTiers(None, peer_dir=gp_peer)):
        t.mirror.clear()
    litter = []
    for root_dir in (peer, gp_peer):
        if os.path.isdir(root_dir):
            for r, _dirs, files in os.walk(root_dir):
                litter += [os.path.join(r, f) for f in files]
    if litter:
        payload["litter"] = litter
        print(json.dumps(payload), flush=True)
        sys.exit(1)
    payload["no_litter"] = True
    shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(payload), flush=True)


def run_kernels_child() -> None:
    """The fused-kernel measurement (child process, 8 virtual CPU
    devices — docs/kernels.md).

    Off-TPU the kernels run in Pallas INTERPRET mode (the
    AUTODIST_FUSED_INTERPRET escape hatch): the exact kernel bodies
    execute, so parity gates and per-leg attribution are real, but the
    interpreter is slower than XLA — fused-vs-unfused STEP-TIME deltas
    on this path are structural documentation, not the TPU win (the
    note field says which regime produced the artifact).  What this
    child pins regardless of platform: (1) fused programs verify and
    fingerprint distinctly, (2) fused == unfused numerics (params at
    1e-5 over 3 steps; guard skip decision identical; paged decode
    token-exact vs the oracle), (3) per-leg-kind LegProfiler
    attribution before/after each fusion — the detect arithmetic
    BENCH_guard.json could only see as a whole-step 5-7% now has its
    own fused_detect legs with measured time."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    os.environ["AUTODIST_IS_TESTING"] = "True"
    os.environ["AUTODIST_FUSED_INTERPRET"] = "1"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.ops import fused_kernels as fk
    from autodist_tpu.strategy import Zero1
    from autodist_tpu.telemetry.profiler import LegProfiler

    d = jax.device_count()
    on_tpu = jax.devices()[0].platform == "tpu"
    bucket_bytes = 1 << 20
    rng = np.random.RandomState(0)
    layers = 3
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(288, 288) * 0.05,
                                         jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(16, 288).astype(np.float32),
             "y": rng.randn(16, 288).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"])
        return jnp.mean((h - b["y"]) ** 2)

    guard = {"clip_norm": 1.0, "loss_scale": None}

    def build(kernels, compressor, overlap, numerics):
        _reset_default_autodist_for_testing()
        if kernels:
            os.environ["AUTODIST_FUSED_KERNELS"] = kernels
        else:
            os.environ.pop("AUTODIST_FUSED_KERNELS", None)
        ad = AutoDist(strategy_builder=Zero1(
            bucket_bytes=bucket_bytes, compressor=compressor,
            overlap=overlap))
        with ad.scope():
            ad.capture(params=params, optimizer=fk.fusable_adam(1e-3),
                       loss_fn=loss_fn, numerics=numerics)
        return ad, ad.create_distributed_session()

    # (name, AUTODIST_FUSED_KERNELS, compressor, overlap, numerics):
    # each fused mode directly follows its unfused reference, and the
    # no-guard baseline anchors the detect-overhead attribution.
    modes = (
        ("zero1_baseline", "", "NoneCompressor", "auto", None),
        ("zero1_guard", "", "NoneCompressor", "auto", guard),
        ("zero1_guard_fused", "guard", "NoneCompressor", "auto", guard),
        ("zero1_update", "", "NoneCompressor", "auto", None),
        ("zero1_update_fused", "update", "NoneCompressor", "auto", None),
        ("int8_ring", "", "Int8Compressor", "ring", guard),
        ("int8_ring_fused", "quant_hop", "Int8Compressor", "ring", guard),
    )
    out = {"dp": d, "bucket_bytes": bucket_bytes,
           "platform": jax.devices()[0].platform,
           "interpret_mode": not on_tpu,
           "note": (
               "Fused Pallas kernels vs their unfused references on one "
               "ZeRO-1 program. Off-TPU the kernels execute in the "
               "Pallas interpreter (AUTODIST_FUSED_INTERPRET=1): parity "
               "gates and per-leg attribution are real, but interpreter "
               "step times overstate fused cost by orders of magnitude "
               "— on this path compare leg_kinds attribution, not "
               "step_time_ms. The committed baseline for the guard "
               "overhead is BENCH_guard.json (5.1% detect overhead at "
               "whole-step granularity)."),
           "modes": {}}
    steps = 10
    for name, kernels, compressor, overlap, numerics in modes:
        ad, sess = build(kernels, compressor, overlap, numerics)
        ir = sess.schedule_ir
        sir.assert_verified(ir, f"bench kernels [{name}]")
        prof = LegProfiler(mesh=sess.mesh, warmup=1, repeats=3)
        samples = prof.profile_ir(ir)
        placed = sess.place_batch(batch)
        dt = _measure_session(sess, placed, 2, steps)
        kinds: dict = {}
        for s in samples:
            row = kinds.setdefault(s.kind, {
                "measured_ms": 0.0, "predicted_ms": 0.0, "n_legs": 0})
            row["n_legs"] += 1
            row["measured_ms"] = round(
                row["measured_ms"] + s.measured_s * 1e3, 4)
            if s.predicted_s:
                row["predicted_ms"] = round(
                    row["predicted_ms"] + s.predicted_s * 1e3, 4)
        out["modes"][name] = {
            "schedule_fingerprint": ir.fingerprint(),
            "fused_kernels": list(ir.fused_kernels),
            "leg_count": len(ir.legs),
            "step_time_ms": round(dt / steps * 1e3, 3),
            "leg_kinds": kinds,
        }
        del sess, ad
        _reset_default_autodist_for_testing()

    # Detect-overhead attribution: guard-on minus no-guard step time,
    # unfused vs fused, next to the fused_detect legs' own measured
    # time — the per-leg answer to BENCH_guard's whole-step 5-7%.
    m = out["modes"]
    base = m["zero1_baseline"]["step_time_ms"]
    out["guard_detect_overhead"] = {
        "baseline_step_ms": base,
        "unfused_overhead_ms": round(
            m["zero1_guard"]["step_time_ms"] - base, 3),
        "fused_overhead_ms": round(
            m["zero1_guard_fused"]["step_time_ms"] - base, 3),
        "fused_detect_legs_measured_ms":
            m["zero1_guard_fused"]["leg_kinds"].get(
                "fused_detect", {}).get("measured_ms"),
        "bench_guard_baseline_overhead_fraction": 0.0514,
    }

    # Parity gate: every kernel on at once vs everything off — params
    # must agree after 3 steps.  Session-level tolerance is 1e-4, looser
    # than the per-kernel 1e-6 (tests/test_fused_kernels.py): the fused
    # norm partial sums in block order, and that ~1e-8-relative
    # difference compounds through the clip multiplier and the int8
    # error-feedback chain across steps.
    def run3(kernels):
        ad, sess = build(kernels, "Int8Compressor", "ring", guard)
        placed = sess.place_batch(batch)
        for _ in range(3):
            sess.run(placed)
        jax.block_until_ready(sess.params)
        p = jax.tree_util.tree_map(np.asarray, sess.params)
        del sess, ad
        _reset_default_autodist_for_testing()
        return p

    p_u, p_f = run3(""), run3("guard,update,quant_hop")
    diff = max(float(np.max(np.abs(a - b))) for a, b in zip(
        jax.tree_util.tree_leaves(p_u), jax.tree_util.tree_leaves(p_f)))
    if diff > 1e-4:
        raise RuntimeError(
            f"fused/unfused parity gate failed: max param diff {diff}")
    out["parity"] = {"max_param_diff_after_3_steps": diff,
                     "gate": 1e-4}

    out["paged_attention"] = _kernels_paged_section()
    print(json.dumps(out), flush=True)


def _kernels_paged_section() -> dict:
    """Paged decode, gather program vs fused paged-attention kernel:
    token-exact vs the per-request oracle (gate), plus wall-clock
    tokens/s for both (interpret-mode caveat as above).  The paged jit
    cache is cleared between modes — the fused decision is pinned per
    trace, and reusing the gather trace would silently measure the
    wrong program."""
    import jax
    import numpy as np

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import PagedDecodeEngine
    from autodist_tpu.serving import paged_kv

    vocab = 61
    spec = transformer_lm(vocab_size=vocab, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(0, vocab, p).astype(np.int32), n)
            for p, n in [(3, 6), (5, 8), (2, 5), (6, 7)]]
    gen = make_generator(spec)
    oracle = {i: np.asarray(gen(params, p[None, :], n))[0]
              for i, (p, n) in enumerate(reqs)}

    section = {}
    for label, kernels in (("gather", ""), ("fused_kernel",
                                            "paged_attention")):
        if kernels:
            os.environ["AUTODIST_FUSED_KERNELS"] = kernels
        else:
            os.environ.pop("AUTODIST_FUSED_KERNELS", None)
        paged_kv._paged_chunk_program.clear_cache()
        paged_kv._paged_prefill_program.clear_cache()
        eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                                block_size=8, num_blocks=24, chunk=4)
        ids = [eng.submit(p, n) for p, n in reqs]
        t0 = time.perf_counter()
        results = eng.run()
        dt = time.perf_counter() - t0
        for i, rid in enumerate(ids):
            if not np.array_equal(results[rid], oracle[i]):
                raise RuntimeError(
                    f"paged {label}: request {rid} diverged from the "
                    "oracle")
        eng.assert_no_leaks()
        tokens = sum(n for _, n in reqs)
        section[label] = {
            "tokens_per_sec": round(tokens / dt, 2),
            "wall_s": round(dt, 3),
            "token_exact_vs_oracle": True,
        }
    os.environ.pop("AUTODIST_FUSED_KERNELS", None)
    return section


def run_serving_child() -> None:
    """The serving measurement (child process, CPU): a small LM through
    the paged engine under deterministic synthetic load."""
    _steer("cpu")
    import jax
    import numpy as np

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving.scheduler import PagedDecodeEngine

    spec = transformer_lm(vocab_size=128, num_layers=3, num_heads=4,
                          head_dim=16, d_ff=256, max_len=128, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(7)
    geom = dict(window=64, block_size=8, num_blocks=160, chunk=8)

    # deterministic mixed workload: 24 requests, varied prompts/outputs
    plain = [(rng.randint(0, 128, int(rng.randint(4, 25))).astype(np.int32),
              int(rng.randint(8, 17))) for _ in range(24)]
    # shared-prefix workload: 12 requests behind one 48-token (6-block)
    # system prefix with a 4-token per-request tail — the production
    # system-prompt shape
    shared = rng.randint(0, 128, 48).astype(np.int32)
    prefixed = [(np.concatenate([shared,
                                 rng.randint(0, 128, 4).astype(np.int32)]),
                 8) for _ in range(12)]

    def drive(eng, reqs):
        """Open-loop drive: arrivals land between scheduler boundaries
        (4 per boundary) independent of service progress."""
        pending = list(reqs)
        t0 = time.perf_counter()
        while pending:
            for p, n in pending[:4]:
                eng.submit(p, n)
            pending = pending[4:]
            eng.step()
        while eng.step():
            pass
        eng.results()
        wall = time.perf_counter() - t0
        timings = list(eng.pop_timings().values())
        eng.assert_no_leaks()   # the gate: a leaked block fails the run
        ttft = sorted(t["ttft_s"] for t in timings)
        itl = sorted(t["per_token_s"] for t in timings
                     if t["per_token_s"] > 0)
        gen = sum(t["generated"] for t in timings)

        def pct(xs, q):
            return round(xs[min(int(q * len(xs)), len(xs) - 1)] * 1e3, 3) \
                if xs else None
        return {
            "requests": len(timings),
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(gen / wall, 2),
            "ttft_p50_ms": pct(ttft, 0.5),
            "ttft_p99_ms": pct(ttft, 0.99),
            "per_token_p50_ms": pct(itl, 0.5),
            "per_token_p99_ms": pct(itl, 0.99),
            "prefix_hit_rate": round(eng.stats.prefix_hit_rate, 4),
            "slot_utilization": round(eng.stats.slot_utilization, 4),
            "block_high_water": eng.pool.stats.high_water,
            "block_leak_check": "ok",
        }

    payload = {"model": "transformer_lm L3 d64 vocab128",
               "geometry": dict(geom), "modes": {}}
    # Warm-up discipline: every measured pass runs its FULL workload
    # once first (same prompt buckets, same pow-2 batch sizes), so xla
    # compiles land in the warm-up and the measured TTFT is scheduling
    # + compute, not compile time.
    # -- continuous batching ON vs OFF on the same arrival schedule
    eng = PagedDecodeEngine(spec, params, slots=8, **geom)
    drive(eng, plain)                           # warm the jit caches
    eng.reset()
    payload["modes"]["batching_on"] = drive(eng, plain)
    eng1 = PagedDecodeEngine(spec, params, slots=1, **geom)
    drive(eng1, plain)
    eng1.reset()
    payload["modes"]["batching_off"] = drive(eng1, plain)
    # -- shared-prefix workload, cold (no trie) vs warm (trie primed) —
    # the acceptance criterion: hit rate > 0 and lower TTFT than cold
    engc = PagedDecodeEngine(spec, params, slots=8, cache_prefixes=False,
                             **geom)
    drive(engc, prefixed)
    engc.reset()
    payload["modes"]["prefix_cold"] = drive(engc, prefixed)
    engw = PagedDecodeEngine(spec, params, slots=8, **geom)
    drive(engw, prefixed[:1])                   # pass A: primes the trie
    drive(engw, prefixed)                       # pass B: warm-path compiles
    payload["modes"]["prefix_warm"] = drive(engw, prefixed)
    on, off = (payload["modes"]["batching_on"],
               payload["modes"]["batching_off"])
    payload["batching_tokens_per_sec_speedup"] = round(
        on["tokens_per_sec"] / off["tokens_per_sec"], 3)
    # On CPU the per-tick compute scales with the slot count (no MXU
    # batching), so the throughput ratio undersells continuous
    # batching; the latency win is the honest CPU-visible signal.
    payload["batching_ttft_p50_speedup"] = round(
        off["ttft_p50_ms"] / on["ttft_p50_ms"], 3)
    warm, cold = (payload["modes"]["prefix_warm"],
                  payload["modes"]["prefix_cold"])
    payload["prefix_ttft_p50_speedup"] = round(
        cold["ttft_p50_ms"] / warm["ttft_p50_ms"], 3)
    print(json.dumps(payload), flush=True)


def run_spec_child() -> None:
    """The speculative-serving measurement (child process, CPU): the
    paged engine's draft-and-verify mode on the SAME 24-request burst
    workload as ``run_serving_child``, gated on token-exactness against
    the target-only greedy oracle and on the block-leak invariant —
    a mismatched token or a leaked block fails the child, not just a
    counter.

    Fixture disclosure: the target is the L3 serving model with layers
    1-2 residual writes (attn.out / mlp.wo kernels) damped by
    ``EPS=0.005``, and the draft is an L1 model SHARING the target's
    embedding, positions, layer 0 and final norm.  That is the honest
    way to get a draft that agrees with an untrained target often
    (~0.9 acceptance) without training either model — the acceptance
    rate is real model agreement, not a draft==target shortcut.  On
    CPU a parallel verify pass costs nearly as much as the chunked
    scan it replaces (no MXU to batch the gamma+1 positions), so the
    speculative win shows against the committed batching-on decode
    baseline, not against a same-geometry target-only run."""
    _steer("cpu")
    import jax
    import numpy as np

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving.scheduler import PagedDecodeEngine

    EPS = 0.005

    def _mk(layers):
        return transformer_lm(vocab_size=128, num_layers=layers,
                              num_heads=4, head_dim=16, d_ff=256,
                              max_len=128, seq_len=16,
                              attn_fn=dense_attention)

    tspec, dspec = _mk(3), _mk(1)
    base = tspec.init(jax.random.PRNGKey(0))
    # Damp layers 1-2 so layer 0 dominates the target's logits.
    tparams = dict(base)
    dec = dict(tparams["decoder"])
    for li in (1, 2):
        lay = {k: dict(v) if isinstance(v, dict) else v
               for k, v in dec[f"layers_{li}"].items()}
        lay["attn"] = dict(lay["attn"])
        lay["attn"]["out"] = {"kernel": lay["attn"]["out"]["kernel"] * EPS}
        lay["mlp"] = dict(lay["mlp"])
        lay["mlp"]["wo"] = {"kernel": lay["mlp"]["wo"]["kernel"] * EPS}
        dec[f"layers_{li}"] = lay
    tparams["decoder"] = dec
    dparams = {"embed": tparams["embed"],
               "pos_embed": tparams["pos_embed"],
               "decoder": {"layers_0": tparams["decoder"]["layers_0"],
                           "ln_final": tparams["decoder"]["ln_final"]}}

    geom = dict(window=64, block_size=8, num_blocks=160, chunk=8)
    rng = np.random.RandomState(7)
    plain = [(rng.randint(0, 128, int(rng.randint(4, 25))).astype(np.int32),
              int(rng.randint(8, 17))) for _ in range(24)]
    # The token-exact oracle: plain greedy decode of the (damped)
    # target, one request at a time — no paging, no speculation.
    gen = make_generator(tspec)
    oracle = [np.asarray(gen(tparams, p[None], n))[0] for p, n in plain]

    def drive(eng, reqs, oracles):
        """Open-loop drive (4 arrivals per boundary) with per-boundary
        occupancy/gamma sampling; token-exactness and the leak
        invariant gate the pass."""
        ids, occ_t, occ_d, gtrace = [], [], [], []
        pending = list(reqs)

        def sample():
            st = eng.scheduler_stats()
            occ_t.append(st["block_occupancy_target"])
            occ_d.append(st["block_occupancy_draft"])
            if "speculative" in st:
                gtrace.append(st["speculative"]["gamma"])

        t0 = time.perf_counter()
        while pending:
            for p, n in pending[:4]:
                ids.append(eng.submit(p, n))
            pending = pending[4:]
            eng.step()
            sample()
        while eng.step():
            sample()
        res = eng.results()
        wall = time.perf_counter() - t0
        timings = list(eng.pop_timings().values())
        sstats = eng.scheduler_stats()
        eng.assert_no_leaks()              # gate 1: no leaked blocks
        for i, rid in enumerate(ids):      # gate 2: token-exact output
            np.testing.assert_array_equal(
                np.asarray(res[rid]), oracles[i],
                err_msg=f"request {i} diverged from the target oracle")
        ttft = sorted(t["ttft_s"] for t in timings)
        itl = sorted(t["per_token_s"] for t in timings
                     if t["per_token_s"] > 0)
        gen_tokens = sum(t["generated"] for t in timings)

        def pct(xs, q):
            return round(xs[min(int(q * len(xs)), len(xs) - 1)] * 1e3, 3) \
                if xs else None

        out = {
            "requests": len(timings),
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(gen_tokens / wall, 2),
            "ttft_p50_ms": pct(ttft, 0.5),
            "ttft_p99_ms": pct(ttft, 0.99),
            "per_token_p50_ms": pct(itl, 0.5),
            "per_token_p99_ms": pct(itl, 0.99),
            "block_high_water": eng.pool.stats.high_water,
            "block_occupancy_target_peak": max(occ_t),
            "block_occupancy_draft_peak": max(occ_d),
            "block_leak_check": "ok",
        }
        if "speculative" in sstats:
            sp = sstats["speculative"]
            out["acceptance_rate"] = sp["acceptance_rate"]
            out["mean_accept_len"] = sp["mean_accept_len"]
            out["rounds"] = sp["rounds"]
            out["bonus_tokens"] = sp["bonus"]
            out["gamma_hist"] = {str(k): v
                                 for k, v in sp["gamma_hist"].items()}
            # Acceptance-length histogram over per-request means, the
            # same fixed bounds the server exports for
            # autodist_serving_spec_accept_len.
            bounds = [1, 2, 4, 6, 8, 12, 16]
            hist = {f"le_{b}": 0 for b in bounds}
            hist["gt_16"] = 0
            for t in timings:
                v = t.get("accept_len_mean", 0.0)
                for b in bounds:
                    if v <= b:
                        hist[f"le_{b}"] += 1
                        break
                else:
                    hist["gt_16"] += 1
            out["accept_len_hist"] = hist
            if gtrace:
                out["gamma_trace"] = gtrace
        return out

    payload = {
        "model": "transformer_lm L3 d64 vocab128 target, L1 shared-"
                 "layer-0 draft",
        "fixture": {
            "eps": EPS,
            "note": "target layers 1-2 residual writes damped by eps; "
                    "draft shares embed/pos/layer0/ln_final — real "
                    "model agreement, not draft==target",
        },
        "geometry": dict(geom),
        "workload": "BENCH_serving 24-request open-loop burst "
                    "(RandomState(7))",
        "cpu_note": "on CPU a parallel verify costs nearly as much as "
                    "the chunked scan it replaces, so speculation is "
                    "measured against the committed batching-on "
                    "baseline, not the same-slots target_only mode",
        "modes": {},
    }

    # Warm-up discipline matches run_serving_child: each engine drives
    # its full workload once first so XLA compiles (one draft-scan
    # program per distinct proposal depth) land outside the timing.
    te = PagedDecodeEngine(tspec, tparams, slots=1, **geom)
    drive(te, plain, oracle)
    te.reset()
    payload["modes"]["target_only"] = drive(te, plain, oracle)

    se = PagedDecodeEngine(tspec, tparams, slots=1, gamma=16,
                           adapt_gamma=False, draft_spec=dspec,
                           draft_params=dparams, **geom)
    drive(se, plain, oracle)
    se.reset()
    payload["modes"]["speculative"] = drive(se, plain, oracle)

    ae = PagedDecodeEngine(tspec, tparams, slots=4, gamma=16,
                           adapt_gamma=True, draft_spec=dspec,
                           draft_params=dparams, **geom)
    drive(ae, plain, oracle)
    ae.reset()
    payload["modes"]["spec_adaptive"] = drive(ae, plain, oracle)

    # Load-spike gamma drill: a 12-request burst into 2 slots backs up
    # the latency queue, which must shrink gamma toward 1; the drained
    # tail (idle slot, empty queue) must grow it back — all while the
    # output stays token-exact (the drive() gates run unchanged).
    de = PagedDecodeEngine(tspec, tparams, slots=2, gamma=12,
                           adapt_gamma=True, draft_spec=dspec,
                           draft_params=dparams, **geom)

    def spike(eng):
        ids, gtrace = [], []
        for p, n in plain[:12]:
            ids.append(eng.submit(p, n))
        while eng.step():
            gtrace.append(
                eng.scheduler_stats()["speculative"]["gamma"])
        res = eng.results()
        eng.assert_no_leaks()
        for i, rid in enumerate(ids):
            np.testing.assert_array_equal(
                np.asarray(res[rid]), oracle[i],
                err_msg=f"drill request {i} diverged under adaptation")
        return gtrace

    spike(de)
    de.reset()
    gtrace = spike(de)
    floor, tail = min(gtrace), gtrace[-1]
    assert floor < 12, f"gamma never shrank under the spike: {gtrace}"
    assert tail > floor, f"gamma never regrew after drain: {gtrace}"
    payload["gamma_drill"] = {
        "slots": 2, "burst": 12, "gamma_max": 12,
        "gamma_floor": floor, "gamma_tail": tail,
        "gamma_trace": gtrace, "token_exact": "ok",
    }

    # The acceptance bar: the committed batching-on decode p50 from
    # BENCH_serving.json (recorded, not asserted — the hard gates are
    # exactness and leaks; the bar moves with the committed baseline).
    ref = None
    try:
        with open(os.path.join(REPO, "BENCH_serving.json"),
                  encoding="utf-8") as f:
            ref = json.load(f)["modes"]["batching_on"]["per_token_p50_ms"]
    except Exception:
        pass
    payload["committed_batching_on_p50_ms"] = ref
    spec_p50 = payload["modes"]["speculative"]["per_token_p50_ms"]
    payload["speculative_beats_committed_baseline"] = (
        ref is not None and spec_p50 < ref)
    print(json.dumps(payload), flush=True)


def run_serving_chaos_child() -> None:
    """The serving-resilience measurement (child process, CPU): two
    paged engines behind real EngineServers with a Router in front,
    under deterministic mid-stream faults (docs/serving.md, "Fault
    tolerance").

    A fault wrapper severs the SSE stream of designated requests after
    the first chunk-boundary delta — once per trace, so the retry
    lands clean — which is exactly what a chaos ``kill_replica`` looks
    like from the router's side.  Modes:

    * ``baseline_no_faults`` — recovery on, no faults;
    * ``faults_recovery_on`` — the router carries the streamed partial
      to the survivor (prefill-and-continue);
    * ``faults_recovery_off`` — same faults, but the wrapper withholds
      the deltas so the retry restarts the decode from scratch (the
      pre-recovery behavior, isolated from transport differences);
    * ``straggler_hedging_off`` / ``straggler_hedging_on`` — a slow
      primary with and without first-wins hedged requests.

    Deadline goodput (fraction of requests finishing inside the
    baseline-derived deadline) and re-decoded token waste compare the
    modes; token-exactness against the single-engine greedy oracle and
    ``assert_no_leaks`` on every engine gate every mode — a diverged
    token or a leaked block fails the child, not just a counter."""
    _steer("cpu")
    import queue as queue_mod
    import threading

    import jax
    import numpy as np

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import EngineServer, PagedDecodeEngine, Router
    from autodist_tpu.serving.router import HTTPReplicaClient

    spec = transformer_lm(vocab_size=128, num_layers=3, num_heads=4,
                          head_dim=16, d_ff=256, max_len=128, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    geom = dict(window=64, block_size=8, num_blocks=160, chunk=8)
    rng = np.random.RandomState(11)
    reqs = [(rng.randint(0, 128, int(rng.randint(4, 25))).astype(np.int32),
             int(rng.randint(12, 21))) for _ in range(24)]
    gen = make_generator(spec)
    oracle = {i: [int(t) for t in np.asarray(gen(params, p[None, :], n))[0]]
              for i, (p, n) in enumerate(reqs)}
    # every 3rd request dies mid-stream in the fault modes, keyed by its
    # (unique-per-workload) prompt so the schedule survives re-routing
    faulted = {tuple(int(t) for t in reqs[i][0]): i
               for i in range(0, len(reqs), 3)}

    class _Ep:
        """Router endpoint over a live EngineServer, with deterministic
        mid-stream fault injection: designated requests lose their
        connection right after the first streamed delta (once per
        trace).  ``forward_partials=False`` additionally withholds the
        deltas from the router's recovery ledger — same fault, but the
        retry can only restart from scratch."""

        def __init__(self, name, server, *, fault=False,
                     forward_partials=True, delay_s=0.0, severed=None):
            self.name = name
            self._cli = HTTPReplicaClient(*server.address)
            self.fault = fault
            self.forward_partials = forward_partials
            self.delay_s = delay_s
            # trace ids already faulted — SHARED across the pool's
            # endpoints so each request dies at most once wherever the
            # router places it (the re-route must land clean)
            self.severed = set() if severed is None else severed

        def probe(self, timeout=2.0):
            return self._cli.healthz(timeout=timeout)

        def fetch_stats(self):
            try:
                return self._cli.stats()
            except OSError:
                return None

        def post(self, body, timeout, trace_id=""):
            return self._cli.post_completion(body, timeout=timeout,
                                             trace_id=trace_id)

        def cancel(self, request_id):
            return self._cli.cancel(request_id)

        def post_stream(self, body, timeout, trace_id="", on_event=None):
            if self.delay_s:
                time.sleep(self.delay_s)     # the straggler scenario
            key = tuple(body.get("prompt_tokens") or ())
            sever = (self.fault and key in faulted
                     and trace_id not in self.severed)
            streamed = 0

            def tap(ev):
                nonlocal streamed
                new = ev.get("new_tokens") or []
                if ev.get("done") or not new:   # announce / terminal
                    if on_event is not None:
                        on_event(ev)
                    return
                streamed += len(new)
                if on_event is not None and (not sever
                                             or self.forward_partials):
                    on_event(ev)
                if sever and streamed >= 1:
                    # conn.close() in the client's finally frees the
                    # replica side (its next write cancels the request)
                    self.severed.add(trace_id)
                    raise OSError("bench fault: stream severed "
                                  "mid-decode")

            return self._cli.post_completion_stream(
                body, timeout=timeout, trace_id=trace_id, on_event=tap)

    def run_mode(eps, *, recover, hedge_after_s=None, deadline_s=None,
                 workers=4):
        engines = [PagedDecodeEngine(spec, params, slots=4, **geom)
                   for _ in range(2)]
        for eng in engines:
            # pace the tick (every mode equally) so chunk-boundary
            # deltas actually stream before a request finishes — the
            # mid-decode window the fault injection needs to exist
            orig = eng.step
            eng.step = (lambda orig=orig:
                        (time.sleep(0.02), orig())[1])
        servers = [EngineServer(eng, port=0,
                                request_timeout_s=120).start()
                   for eng in engines]
        endpoints = [mk(srv) for mk, srv in zip(eps, servers)]
        # retry_wait × max_attempts must outlive the 2 s mark-down hold
        # a severed stream puts on a replica, or a burst of faults
        # exhausts its attempts before anything comes back up
        router = Router(endpoints, probe_ttl_s=0.5, stats_ttl_s=0.05,
                        retry_wait_s=0.25, max_attempts=24,
                        breaker_threshold=8, recover=recover,
                        hedge_after_s=hedge_after_s)
        lat = {}
        failures = []
        work = queue_mod.Queue()
        for i, (p, n) in enumerate(reqs):
            work.put((i, p, n))

        def worker():
            while True:
                try:
                    i, p, n = work.get_nowait()
                except queue_mod.Empty:
                    return
                t0 = time.perf_counter()
                try:
                    out = router.complete(
                        {"prompt_tokens": [int(t) for t in p],
                         "max_new_tokens": n}, timeout_s=120)
                    lat[i] = (time.perf_counter() - t0, out)
                except Exception as e:  # noqa: BLE001 - gates the child
                    failures.append((i, repr(e)))

        threads = [threading.Thread(target=worker)
                   for _ in range(workers)]
        t_wall = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_wall
        for srv in servers:
            srv.close()
        assert not failures, f"requests failed: {failures}"
        # the hard gates: greedy token-exactness for every request
        # (including the recovered ones), and zero leaked blocks
        for i, (_, out) in lat.items():
            assert out["tokens"] == oracle[i], \
                f"request {i} diverged from the greedy oracle"
        for eng in engines:
            # a hedged loser's cancel can still be settling at close;
            # finish any abandoned in-flight decode, then hold the
            # no-leak gate
            while eng.step():
                pass
            eng.results()
            eng.assert_no_leaks()
        lats = sorted(v[0] for v in lat.values())

        def pct(q):
            return lats[min(int(q * len(lats)), len(lats) - 1)]

        reg = router.registry
        ideal = sum(n for _, n in reqs)
        generated = sum(int(eng.stats.generated_tokens)
                        for eng in engines)
        mode = {
            "requests": len(lats),
            "wall_s": round(wall, 3),
            "latency_p50_s": round(pct(0.5), 3),
            "latency_p99_s": round(pct(0.99), 3),
            "recovered_requests": int(reg.counter(
                "autodist_router_recovered_total").value),
            "recovered_tokens": int(reg.counter(
                "autodist_router_recovered_tokens_total").value),
            "hedged_requests": int(reg.counter(
                "autodist_router_hedged_total").value),
            "hedge_wins": int(reg.counter(
                "autodist_router_hedge_wins_total").value),
            "generated_tokens": generated,
            "redecoded_tokens": generated - ideal,
            "token_exact_check": "ok",
            "block_leak_check": "ok",
        }
        if deadline_s is not None:
            mode["deadline_s"] = round(deadline_s, 3)
            mode["deadline_goodput"] = round(
                sum(1 for v in lats if v <= deadline_s) / len(lats), 4)
        return mode

    def pool(**kw):
        shared = set()
        return [lambda srv, i=i: _Ep(f"replica-{i}", srv,
                                     severed=shared, **kw)
                for i in range(2)]

    def straggler():                        # slow primary, fast peer
        return [lambda srv: _Ep("replica-0", srv, delay_s=0.4),
                lambda srv: _Ep("replica-1", srv)]

    payload = {"model": "transformer_lm L3 d64 vocab128",
               "geometry": dict(geom),
               "workload": "24 greedy requests, prompts 4-24, "
                           "max_new 12-20, 4 client threads; every 3rd "
                           "request severed mid-stream in fault modes",
               "modes": {}}
    run_mode(pool(), recover=True)          # warm the jit caches
    base = run_mode(pool(), recover=True)
    # the goodput bar: fault-free p50 plus one failover allowance —
    # the 2 s mark-down hold + the 0.25 s retry wait + ~0.5 s to
    # prefill-and-finish the resumed continuation.  An SLO that
    # tolerates single faults promises exactly this; a restarted
    # decode (recovery off) blows it, a resumed one does not.  (p50,
    # not p99: the fault-free tail is CPU-noise-dominated and would
    # make the bar jitter run to run.)
    deadline = base["latency_p50_s"] + 2.75
    base["deadline_s"] = round(deadline, 3)
    base["deadline_goodput"] = 1.0
    payload["modes"]["baseline_no_faults"] = base
    payload["modes"]["faults_recovery_on"] = run_mode(
        pool(fault=True), recover=True, deadline_s=deadline)
    payload["modes"]["faults_recovery_off"] = run_mode(
        pool(fault=True, forward_partials=False), recover=True,
        deadline_s=deadline)
    payload["modes"]["straggler_hedging_off"] = run_mode(
        straggler(), recover=True, deadline_s=deadline)
    payload["modes"]["straggler_hedging_on"] = run_mode(
        straggler(), recover=True, hedge_after_s=0.1,
        deadline_s=deadline)
    on = payload["modes"]["faults_recovery_on"]
    off = payload["modes"]["faults_recovery_off"]
    payload["recovery_redecode_savings_tokens"] = (
        off["redecoded_tokens"] - on["redecoded_tokens"])
    payload["recovery_goodput_delta"] = round(
        on["deadline_goodput"] - off["deadline_goodput"], 4)
    payload["hedging_p99_speedup"] = round(
        payload["modes"]["straggler_hedging_off"]["latency_p99_s"]
        / payload["modes"]["straggler_hedging_on"]["latency_p99_s"], 3)
    print(json.dumps(payload), flush=True)


def run_quant_child() -> None:
    """The quantized-collective measurement (child process, 8 virtual
    CPU devices): int8/fp8 x pipeline off/on vs f32 under ZeRO-1 and
    gradient accumulation."""
    _steer("cpu")
    import logging as pylog

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy import Zero1
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    d = jax.device_count()
    accum = 4
    bucket_bytes = 256 << 10
    rng = np.random.RandomState(0)
    layers = 6
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                         jnp.float32),
                        "b": jnp.zeros(256, jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(64, 256).astype(np.float32),
             "y": rng.randn(64, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    # Count overlap-fallback WARNs: the acceptance criterion is that
    # quantized buckets PIPELINE under accum_steps=4 with no fallback.
    fallback_counts = []

    class _Counter(pylog.Handler):
        def emit(self, record):
            if "overlap scheduling skipped" in record.getMessage():
                fallback_counts.append(record.getMessage())

    def measure(compressor, overlap, numerics=None, steps=30):
        _reset_default_autodist_for_testing()
        counter = _Counter()
        logger = pylog.getLogger("autodist_tpu")
        n_before = len(fallback_counts)
        logger.addHandler(counter)
        try:
            ad = AutoDist(strategy_builder=Zero1(
                bucket_bytes=bucket_bytes, compressor=compressor,
                overlap=overlap))
            with ad.scope():
                ad.capture(params=params, optimizer=optax.adam(1e-3),
                           loss_fn=loss_fn, accum_steps=accum,
                           numerics=numerics)
            sess = ad.create_distributed_session()
        finally:
            logger.removeHandler(counter)
        ir = sess.schedule_ir
        if ir is None:
            raise RuntimeError("bench quant: session has no schedule IR")
        # Verifier gate: a rejected schedule fails the bench outright.
        sir.assert_verified(ir, f"bench quant [{compressor}/{overlap}]")
        cost = estimate_ir_cost(ir)
        reduce_bytes = sum(
            l.nbytes for l in ir.legs if l.kind in sir.COLLECTIVE_KINDS
            and "@gather" not in l.id and "@gather" not in l.chain)
        placed = sess.place_batch(batch)
        dt = _measure_session(sess, placed, 3, steps)
        sat = None
        if numerics is not None:
            h = sess.run(placed)["grad_health"]
            sat = round(sum(
                float(e["sat_count"]) for e in h.per_bucket.values()
                if "sat_count" in e), 1)
        info = {
            "step_time_ms": round(dt / steps * 1e3, 3),
            "schedule_fingerprint": ir.fingerprint(),
            "pipelined_bucket_count": len(ir.pipelined_keys()),
            "overlap_fallback_warns": len(fallback_counts) - n_before,
            # IR-priced wire, per chip per step (the verified program's
            # own leg bytes: quantized legs carry payload+scales)
            "ir_wire_bytes_per_step": round(cost.wire_bytes, 1),
            "ir_exposed_wire_bytes": round(cost.exposed_wire_bytes, 1),
            # the gradient-sync (reduce) leg alone: ZeRO-1's param
            # gather stays f32 by design, so THIS is the compressed wire
            "reduce_leg_wire_bytes": int(reduce_bytes),
            "saturation_count": sat,
        }
        del sess, ad
        _reset_default_autodist_for_testing()
        return info

    out = {"dp": d, "accum_steps": accum, "bucket_bytes": bucket_bytes,
           "modes": {}}
    guard = {"clip_norm": None, "loss_scale": None}
    for comp, key in (("NoneCompressor", "f32"),
                      ("Int8Compressor", "int8"),
                      ("Fp8Compressor", "fp8")):
        for overlap, pk in (("none", "pipeline_off"),
                            ("pipeline", "pipeline_on")):
            numerics = guard if comp != "NoneCompressor" else None
            out["modes"][f"{key}.{pk}"] = measure(comp, overlap,
                                                  numerics=numerics)
    # Wire reductions compare LIKE schedules: a pipelined step issues
    # one reduce per microbatch slot in both the f32 and quantized
    # programs, so the ratio isolates the wire format.
    for key in ("int8", "fp8"):
        for pk in ("pipeline_on", "pipeline_off"):
            f32 = out["modes"][f"f32.{pk}"]
            q = out["modes"][f"{key}.{pk}"]
            out[f"{key}_reduce_wire_reduction_vs_f32_{pk}"] = round(
                f32["reduce_leg_wire_bytes"] / q["reduce_leg_wire_bytes"],
                2)
        out[f"{key}_exposed_wire_reduction_vs_f32"] = round(
            out["modes"]["f32.pipeline_off"]["ir_exposed_wire_bytes"]
            / out["modes"][f"{key}.pipeline_on"]["ir_exposed_wire_bytes"],
            2)
    out["target_reduce_wire_reduction"] = 3.5
    # CPU-child caveat: step times compare modes against each other on 8
    # virtual CPU devices (quantize/dequantize is emulated arithmetic
    # there, not a TPU VPU fusion); wire-byte columns are
    # platform-independent facts of the verified schedule.
    out["step_time_platform"] = "cpu-virtual"

    # ZeRO-1 quantized-ring vs single-collective oracle parity on the
    # grid-exact fixture (the 1e-6 acceptance fact, recomputed here so
    # the artifact is self-contained; the full matrix lives in
    # tests/test_quant_ring.py).
    from jax.sharding import Mesh, PartitionSpec as P

    from autodist_tpu.kernel.synchronization import quant_ring as qr
    from autodist_tpu.utils import compat

    mesh = Mesh(np.array(jax.devices()).reshape(d), ("data",))
    chunk = 96
    v = rng.randint(-126, 127, d * chunk).astype(np.float32)
    v[::chunk] = 127.0
    c = (2.0 ** rng.randint(-2, 3, d)).astype(np.float32)
    x = c[:, None] * v[None, :]

    def parity(xs):
        xs = xs.reshape(-1)
        ring, _, _ = qr.quantized_ring_reduce_scatter(
            xs, "data", d, qr.WIRE_INT8)
        shot, _, _ = qr.quantized_all_to_all_reduce_scatter(
            xs, "data", d, qr.WIRE_INT8)
        return ring / d, shot / d

    ring, shot = jax.jit(compat.shard_map(
        parity, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P("data")), check_vma=False))(x)
    true_mean = x.mean(0)
    out["zero1_ring_vs_oracle_max_abs_err"] = float(
        np.abs(np.asarray(ring).ravel() - np.asarray(shot).ravel()).max())
    out["zero1_vs_f32_mean_max_abs_err"] = float(
        np.abs(np.asarray(shot).ravel() - true_mean).max())

    # AutoStrategy(search=True) on the comm-bound accum fixture with the
    # quantized opt-in: the searched plan itself.
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AutoStrategy

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": d, "chief": True}]})
    gi = GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32)},
                   accum_steps=accum)
    searcher = AutoStrategy(search=True, compressor="Int8Compressor")
    sync = searcher.build(gi, spec).node_for("w").synchronizer
    out["auto_search"] = {
        "choice": searcher.last_choice, "sync": sync.sync,
        "compressor": sync.compressor, "overlap": sync.overlap,
    }
    print(json.dumps(out), flush=True)


def run_flightrec_child() -> None:
    """Flight-recorder overhead (child process, 8 virtual CPU devices;
    docs/observability.md "Flight recorder", BENCH_flightrec.json).

    The ZeRO-1 grad_sync program with ``AUTODIST_FLIGHTREC=0`` vs the
    recorder ON at its default (host-phase) granularity — interleaved
    minima over 4x50-step trials, the BENCH_telemetry.json protocol,
    against the <1% step-time bar — plus an HONEST ``legs`` datapoint:
    leg-granularity host callbacks are the ``AUTODIST_FLIGHTREC=legs``
    opt-in, automatic only on TPU backends where the callback rides
    async dispatch; on CPU each callback serializes the step, which is
    exactly why ``auto`` resolves to host granularity off-TPU (the
    measured legs-mode overhead documents that decision)."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1
    from autodist_tpu.telemetry import flightrec

    d = jax.device_count()
    bucket_bytes = 256 << 10
    rng = np.random.RandomState(0)
    layers = 6
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                         jnp.float32),
                        "b": jnp.zeros(256, jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(64, 256).astype(np.float32),
             "y": rng.randn(64, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    def measure(mode, steps=50):
        """One session under AUTODIST_FLIGHTREC=<mode>; returns
        (per-step seconds, cursors stamped per step, leg ids seen)."""
        os.environ["AUTODIST_FLIGHTREC"] = mode
        flightrec.reset_for_testing()
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=Zero1(bucket_bytes=bucket_bytes))
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn)
        sess = ad.create_distributed_session()
        placed = sess.place_batch(batch)
        seq0 = flightrec.ring().seq
        dt = _measure_session(sess, placed, 3, steps)
        stamped = flightrec.ring().seq - seq0
        legs = sorted({c.leg for c in flightrec.ring().cursors()
                       if c.kind == "leg"})
        del sess, ad
        _reset_default_autodist_for_testing()
        return dt / steps, stamped / steps, legs

    prev = os.environ.get("AUTODIST_FLIGHTREC")
    ts = {"0": [], "host": []}
    cursors_per_step = 0.0
    for trial in range(4):
        order = ("0", "host") if trial % 2 == 0 else ("host", "0")
        for mode in order:
            t, per_step, _ = measure(mode)
            ts[mode].append(t)
            if mode == "host":
                cursors_per_step = per_step
    t_off, t_on = min(ts["0"]), min(ts["host"])
    # The legs-mode datapoint (2 interleaved-with-nothing trials is
    # enough: the delta here is large and one-sided by design on CPU).
    legs_ts, legs_cursors, leg_ids = [], 0.0, []
    for _ in range(2):
        t, per_step, legs = measure("legs")
        legs_ts.append(t)
        legs_cursors, leg_ids = per_step, legs
    if prev is None:
        os.environ.pop("AUTODIST_FLIGHTREC", None)
    else:
        os.environ["AUTODIST_FLIGHTREC"] = prev
    t_legs = min(legs_ts)
    out = {
        "section": "grad_sync.flightrec",
        "note": (
            "flight-recorder overhead on the ZeRO-1 grad_sync bench "
            "program: AUTODIST_FLIGHTREC=0 vs the default host-phase "
            "recorder (cursor ring + beacon piggyback), interleaved "
            "minima over 4x50-step trials on 8 virtual CPU devices — "
            "the BENCH_telemetry.json protocol, <1% target.  "
            "legs-mode rows measure the AUTODIST_FLIGHTREC=legs "
            "opt-in (per-leg-group jax.debug.callback stamps): on CPU "
            "each callback serializes the step, which is why 'auto' "
            "resolves legs-granularity ON only for TPU backends, "
            "where callbacks ride async dispatch."),
        "date": time.strftime("%Y-%m-%d"),
        "dp": d,
        "bucket_bytes": bucket_bytes,
        "flightrec": {
            "mode": "reduce_scatter",
            "step_time_ms_recorder_off": round(t_off * 1e3, 3),
            "step_time_ms_recorder_on": round(t_on * 1e3, 3),
            "overhead_fraction": round((t_on - t_off) / t_off, 4),
            "target_overhead_fraction": 0.01,
            "cursors_per_step": round(cursors_per_step, 2),
            "legs_mode": {
                "step_time_ms": round(t_legs * 1e3, 3),
                "overhead_fraction": round((t_legs - t_off) / t_off, 4),
                "cursors_per_step": round(legs_cursors, 2),
                "leg_ids_stamped": leg_ids,
                "default_on_tpu_only": True,
            },
        },
    }
    print(json.dumps(out), flush=True)


def run_grad_sync_child() -> None:
    """The grad_sync measurement (child process, 8 virtual CPU devices)."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization.explicit_sync import \
        plan_step_buckets
    from autodist_tpu.strategy import AllReduce, Zero1
    from autodist_tpu.strategy.cost_model import (
        all_gather_bytes,
        allreduce_bytes,
        reduce_scatter_bytes,
    )

    d = jax.device_count()
    bucket_bytes = 256 << 10
    rng = np.random.RandomState(0)
    layers = 6
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                         jnp.float32),
                        "b": jnp.zeros(256, jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(64, 256).astype(np.float32),
             "y": rng.randn(64, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    def measure(builder, accum=1, numerics=None, steps=20):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=builder)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn, accum_steps=accum,
                       numerics=numerics)
        sess = ad.create_distributed_session()
        # Schedule-verifier gate (docs/schedule-ir.md): every mode's
        # sync program must pass the static verifier BEFORE it is
        # timed — a verifier failure fails the bench run outright, not
        # just a lint.  The fingerprint and verify wall time ride the
        # per-mode payload (the <1s pre-trace-gate budget is asserted
        # in tests/test_schedule_ir.py on the largest fixture).
        from autodist_tpu.kernel.synchronization import schedule_ir as sir
        ir = sess.schedule_ir
        if ir is None:
            raise RuntimeError("bench: session has no schedule IR")
        t_v = time.perf_counter()
        sir.assert_verified(ir, f"bench grad_sync [{type(builder).__name__}]")
        verify_ms = (time.perf_counter() - t_v) * 1e3
        from autodist_tpu.analysis import dataflow
        from autodist_tpu.strategy.cost_model import estimate_ir_cost
        ir_cost = estimate_ir_cost(ir)
        # Liveness watermark of the schedule's transient buffers
        # (analysis/dataflow.py, base 0: schedule component only) —
        # rides the per-mode payload next to the verifier wall time so
        # verifier-cost and watermark regressions both show up in
        # BENCH artifacts.
        wm = dataflow.watermark(ir)
        measure.last_ir = {
            "schedule_fingerprint": ir.fingerprint(),
            "ir_leg_count": len(ir.legs),
            "ir_verify_ms": round(verify_ms, 3),
            "ir_watermark_peak_bytes": int(wm.peak_bytes)
            if wm is not None else None,
            "ir_watermark_peak_leg": wm.peak_leg if wm is not None else "",
            # leg-priced estimate (estimate_ir_cost): exposed wire after
            # the IR's own slot/prefetch accounting, per chip per step
            "ir_exposed_wire_bytes": round(ir_cost.exposed_wire_bytes, 1),
        }
        placed = sess.place_batch(batch)
        dt = _measure_session(sess, placed, 3, steps)
        opt_dev_bytes = 0
        for leaf in jax.tree_util.tree_leaves(sess.opt_state):
            sh = leaf.addressable_shards[0]
            opt_dev_bytes += sh.data.size * sh.data.dtype.itemsize
        compiled = sess._step.compiled_strategy
        buckets = plan_step_buckets(sess._gi, compiled, {}, d)
        gi = sess._gi
        # Stash the session's StepRecords (telemetry, when enabled) so
        # the bench can emit them as JSONL — bench runs and real runs
        # feed the same calibration path (telemetry/calibration.py).
        measure.last_records = list(sess.telemetry.records) \
            if sess.telemetry is not None else []
        del sess, ad
        _reset_default_autodist_for_testing()
        return dt / steps, opt_dev_bytes, buckets, gi, compiled

    grad_bytes = float(sum(np.asarray(leaf).nbytes
                           for lp in params.values()
                           for leaf in lp.values()))

    out = {"dp": d, "bucket_bytes": bucket_bytes, "modes": {}}
    # Analysis memory report: the static per-device optimizer bytes.
    from autodist_tpu.analysis import analyzer as _an
    _an._load_passes()   # BEFORE importing memory: a partial registry
    from autodist_tpu.analysis import memory as _mem                # noqa: E402

    for mode, builder in (
            ("all_reduce", AllReduce(bucket_bytes=bucket_bytes)),
            ("reduce_scatter", Zero1(bucket_bytes=bucket_bytes))):
        step_s, opt_dev, buckets, gi, compiled = measure(builder)
        if mode == "all_reduce":
            reduce_leg = allreduce_bytes(grad_bytes, d)
            gather_leg = 0.0
        else:
            reduce_leg = reduce_scatter_bytes(grad_bytes, d)
            gather_leg = all_gather_bytes(grad_bytes, d)
        ctx = _an.AnalysisContext(strategy=compiled.strategy,
                                  graph_item=gi, axes={"data": d})
        _an.PASS_REGISTRY["legality"](ctx)
        opt_analysis = _mem._opt_state_bytes(ctx)
        out["modes"][mode] = {
            # reduce-path bytes per device per step: the gradient-sync
            # cost proper (all-reduce = RS+AG of GRADIENTS; ZeRO-1 pays
            # only the RS leg here and gathers PARAMS instead)
            "sync_bytes_per_step": round(reduce_leg, 1),
            "param_gather_bytes_per_step": round(gather_leg, 1),
            "total_collective_bytes_per_step": round(
                reduce_leg + gather_leg, 1),
            "bucket_count": len(buckets),
            "step_time_ms": round(step_s * 1e3, 3),
            "opt_state_bytes_per_device": opt_dev,
            "opt_state_bytes_analysis": round(opt_analysis, 1)
            if opt_analysis is not None else None,
            # The verified sync-schedule program this mode executed
            # (docs/schedule-ir.md): fingerprint + verifier gate time.
            **(getattr(measure, "last_ir", None) or {}),
        }
    ar, rs = out["modes"]["all_reduce"], out["modes"]["reduce_scatter"]
    out["sync_bytes_ratio"] = round(
        rs["sync_bytes_per_step"] / ar["sync_bytes_per_step"], 4)
    out["opt_state_ratio"] = round(
        rs["opt_state_bytes_per_device"] / ar["opt_state_bytes_per_device"],
        4)

    # -- overlap schedule: accumulation-pipelined bucket collectives ------
    # Same model under gradient accumulation (4 microbatches/step), with
    # the overlap scheduler off vs on.  Step-time deltas are measured on
    # this mesh (CPU replicas: relative, not absolute, evidence);
    # exposed_comm_ms and the overlap fraction come from the cost model's
    # ICI clock — the quantity AutoStrategy(search=True) ranks on.
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.cost_model import ICI_BANDWIDTH, estimate_cost

    accum = 4
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": d, "chief": True}]})
    for mode in out["modes"]:
        if mode == "all_reduce":
            mk = lambda ov: AllReduce(bucket_bytes=bucket_bytes, overlap=ov)
        else:
            mk = lambda ov: Zero1(bucket_bytes=bucket_bytes, overlap=ov)
        t_off, _, _, gi_off, c_off = measure(mk("none"), accum=accum)
        t_on, _, _, gi_on, c_on = measure(mk("auto"), accum=accum)
        cost_off = estimate_cost(c_off.strategy, gi_off, spec)
        cost_on = estimate_cost(c_on.strategy, gi_on, spec)
        out["modes"][mode]["overlap"] = {
            "accum_steps": accum,
            "step_time_ms_overlap_off": round(t_off * 1e3, 3),
            "step_time_ms_overlap_on": round(t_on * 1e3, 3),
            "step_time_delta_ms": round((t_off - t_on) * 1e3, 3),
            "wire_comm_ms": round(
                cost_on.wire_bytes / ICI_BANDWIDTH * 1e3, 4),
            "exposed_comm_ms": round(
                cost_on.exposed_wire_bytes / ICI_BANDWIDTH * 1e3, 4),
            "exposed_comm_ms_overlap_off": round(
                cost_off.exposed_wire_bytes / ICI_BANDWIDTH * 1e3, 4),
            "overlap_fraction": round(cost_on.overlap_fraction, 4),
        }

    # -- numerics guard overhead (docs/numerics.md) -----------------------
    # Same ZeRO-1 pipelined-accum program with the fused guard off vs on
    # (detection + skip gate: finiteness bits as a pack byproduct, norm
    # partials from the reduce-scattered shards, one small psum), and
    # additionally with exact global-norm clipping — the clip factor
    # JOINS every bucket's norm partial before the shard updates, so its
    # cost is reported separately from the guard proper.  Runs are
    # INTERLEAVED and minima compared: host-load drift between serial
    # measurement blocks otherwise dwarfs a percent-level delta on a
    # shared CPU host (whose 8 "devices" also share one memory bus —
    # the absolute overheads here are an upper bound on the TPU regime).
    accum = 4
    cfgs = (("off", None),
            ("detect", {"clip_norm": None, "loss_scale": None}),
            ("clip", {"clip_norm": 1.0, "loss_scale": None}))
    ts = {k: [] for k, _ in cfgs}
    for trial in range(4):
        order = cfgs if trial % 2 == 0 else tuple(reversed(cfgs))
        for key, numerics in order:
            t, _, _, _, _ = measure(Zero1(bucket_bytes=bucket_bytes),
                                    accum=accum, numerics=numerics,
                                    steps=50)
            ts[key].append(t)
    t_off = min(ts["off"])
    t_detect, t_clip = min(ts["detect"]), min(ts["clip"])
    out["guard"] = {
        "accum_steps": accum,
        "mode": "reduce_scatter",
        "step_time_ms_guard_off": round(t_off * 1e3, 3),
        "step_time_ms_guard_on": round(t_detect * 1e3, 3),
        "step_time_ms_guard_clip": round(t_clip * 1e3, 3),
        "overhead_fraction": round((t_detect - t_off) / t_off, 4),
        "overhead_fraction_with_clip": round((t_clip - t_off) / t_off, 4),
        "target_overhead_fraction": 0.02,
    }

    # -- telemetry overhead + StepRecord emission (docs/observability.md)
    # Same ZeRO-1 program with AUTODIST_TELEMETRY off vs on (interleaved
    # minima, like the guard block: percent-level deltas drown in host
    # drift otherwise).  The enabled runs' StepRecords are written as
    # JSONL next to the BENCH_*.json artifacts so bench measurements
    # feed the same calibration path as real runs
    # (telemetry.calibration.fit_constants).
    tel_env = os.environ.get("AUTODIST_TELEMETRY")
    ts = {"off": [], "on": []}
    tel_records = []
    for trial in range(4):
        order = ("off", "on") if trial % 2 == 0 else ("on", "off")
        for key in order:
            os.environ["AUTODIST_TELEMETRY"] = \
                "0" if key == "off" else "1"
            t, _, _, _, _ = measure(Zero1(bucket_bytes=bucket_bytes),
                                    steps=50)
            ts[key].append(t)
            if key == "on":
                tel_records = measure.last_records or tel_records
    if tel_env is None:
        os.environ.pop("AUTODIST_TELEMETRY", None)
    else:
        os.environ["AUTODIST_TELEMETRY"] = tel_env
    t_tel_off, t_tel_on = min(ts["off"]), min(ts["on"])
    records_path = None
    if tel_records:
        records_path = os.path.join(REPO, "BENCH_telemetry_steps.jsonl")
        with open(records_path, "w", encoding="utf-8") as f:
            for r in tel_records:
                f.write(r.to_json() + "\n")
    calibration = None
    if tel_records:
        from autodist_tpu.telemetry.calibration import fit_constants
        fc = fit_constants(tel_records)
        if fc is not None:
            calibration = {
                "ici_bandwidth": fc.ici_bandwidth,
                "alpha": fc.alpha,
                "n_records": fc.n_records,
                "mean_abs_error_ms": round(fc.mean_abs_error_s * 1e3, 4),
                "baseline_mean_abs_error_ms": round(
                    fc.baseline_mean_abs_error_s * 1e3, 4),
                "improved": fc.improved,
            }
    out["telemetry"] = {
        "mode": "reduce_scatter",
        "step_time_ms_telemetry_off": round(t_tel_off * 1e3, 3),
        "step_time_ms_telemetry_on": round(t_tel_on * 1e3, 3),
        "overhead_fraction": round((t_tel_on - t_tel_off) / t_tel_off, 4),
        "target_overhead_fraction": 0.01,
        "step_records_path": records_path,
        "calibration": calibration,
    }
    print(json.dumps(out), flush=True)


def run_profiler_child() -> None:
    """Schedule-aware profiler measurement (child process, 8 virtual
    CPU devices — docs/observability.md "Profiling & Tracing").

    For every grad_sync mode (all_reduce, ZeRO-1, ZeRO-1+guard,
    int8-pipelined+guard) this: (1) verifies the schedule IR, (2)
    micro-runs every leg group on the session mesh (LegProfiler) into
    per-leg samples, (3) tabulates per-leg-kind measured vs
    ``estimate_ir_cost``-predicted time — including the guard legs, so
    the 5-7% overhead BENCH_guard.json reports is finally attributed to
    a kind instead of the whole step, (4) records telemetry StepRecords.
    Then it fits ``fit_leg_constants`` over all samples + records,
    writes the committed artifacts (BENCH_leg_samples.jsonl +
    calibration.json at the repo root), scores the leg-calibrated
    step-time error against the whole-step ``fit_constants`` error (the
    acceptance comparison), and measures profiler overhead off-vs-on
    (interleaved minima, same bar as the telemetry bench: <1%)."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy import AllReduce, Zero1
    from autodist_tpu.telemetry.calibration import (
        fit_constants,
        fit_leg_constants,
        save_calibration,
    )
    from autodist_tpu.telemetry.profiler import LegProfiler

    d = jax.device_count()
    bucket_bytes = 256 << 10
    rng = np.random.RandomState(0)
    layers = 6
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                         jnp.float32),
                        "b": jnp.zeros(256, jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(64, 256).astype(np.float32),
             "y": rng.randn(64, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    guard = {"clip_norm": None, "loss_scale": None}
    modes = (
        ("all_reduce", AllReduce(bucket_bytes=bucket_bytes), 1, None),
        ("zero1", Zero1(bucket_bytes=bucket_bytes), 1, None),
        ("zero1_guard", Zero1(bucket_bytes=bucket_bytes), 1, guard),
        ("int8_pipeline", Zero1(bucket_bytes=bucket_bytes,
                                compressor="Int8Compressor",
                                overlap="pipeline"), 4, guard),
    )
    all_samples = []
    all_records = []
    out = {"dp": d, "bucket_bytes": bucket_bytes, "modes": {}}

    def build(builder, accum, numerics):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=builder)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn, accum_steps=accum,
                       numerics=numerics)
        return ad, ad.create_distributed_session()

    samples_by_mode = {}
    for name, builder, accum, numerics in modes:
        ad, sess = build(builder, accum, numerics)
        ir = sess.schedule_ir
        if ir is None:
            raise RuntimeError(f"profiler bench: {name} has no IR")
        sir.assert_verified(ir, f"bench profiler [{name}]")
        prof = LegProfiler(mesh=sess.mesh)
        samples = prof.profile_ir(ir)
        samples_by_mode[name] = samples
        all_samples.extend(samples)
        placed = sess.place_batch(batch)
        steps = 30
        dt = _measure_session(sess, placed, 3, steps)
        if sess.telemetry is not None:
            all_records.extend(sess.telemetry.records)
        # Per-leg-kind measured vs leg-priced prediction (exposed legs:
        # slotted legs before the FINAL microbatch ride behind the next
        # backward — the cost model's own rule).
        kinds: dict = {}
        for s in samples:
            row = kinds.setdefault(s.kind, {
                "measured_ms": 0.0, "predicted_ms": 0.0, "n_legs": 0})
            row["n_legs"] += 1
            if s.slot is not None and 0 <= s.slot < accum - 1:
                continue           # hidden behind the accum pipeline
            row["measured_ms"] += s.measured_s * 1e3
            if s.predicted_s:
                row["predicted_ms"] += s.predicted_s * 1e3
        for row in kinds.values():
            row["measured_ms"] = round(row["measured_ms"], 4)
            row["predicted_ms"] = round(row["predicted_ms"], 4)
        out["modes"][name] = {
            "schedule_fingerprint": ir.fingerprint(),
            "leg_count": len(ir.legs),
            "leg_samples": len(samples),
            "accum_steps": accum,
            "step_time_ms": round(dt / steps * 1e3, 3),
            "leg_kinds": kinds,
        }
        del sess, ad
        _reset_default_autodist_for_testing()

    # Guard attribution: the measured time of exactly the legs the
    # guard ADDS to the ZeRO-1 schedule (leg ids present in zero1_guard
    # but not zero1 — the psum rollup), per kind.  This is the
    # attribution BENCH_guard could not make at whole-step granularity:
    # the guard's own collective is microseconds, so the rest of the
    # measured 5-7% lives in the detection arithmetic fused into
    # existing legs, not in extra wire.
    base_ids = {s.leg_id for s in samples_by_mode["zero1"]}
    extra = [s for s in samples_by_mode["zero1_guard"]
             if s.leg_id not in base_ids]
    attribution: dict = {}
    for s in extra:
        attribution[s.kind] = round(
            attribution.get(s.kind, 0.0) + s.measured_s * 1e3, 4)
    out["guard_attribution_ms"] = {
        "added_legs": sorted(s.leg_id for s in extra),
        "per_kind": attribution,
        "step_time_delta_ms": round(
            out["modes"]["zero1_guard"]["step_time_ms"]
            - out["modes"]["zero1"]["step_time_ms"], 3),
    }

    # Committed artifacts: every sample + the fitted calibration.
    samples_path = os.path.join(REPO, "BENCH_leg_samples.jsonl")
    with open(samples_path, "w", encoding="utf-8") as f:
        for s in all_samples:
            f.write(s.to_json() + "\n")
    cal = fit_leg_constants(all_samples, all_records)
    cal_path = None
    if cal is not None:
        cal_path = save_calibration(
            cal, os.path.join(REPO, "calibration.json"))
    step_fit = fit_constants(all_records) if all_records else None
    out["calibration"] = {
        "path": cal_path,
        "samples_path": samples_path,
        "n_samples": cal.n_samples if cal else 0,
        "n_records": cal.n_records if cal else 0,
        "kinds": sorted(cal.bandwidths) if cal else [],
        "quant_overhead_per_byte":
            cal.quant_overhead_per_byte if cal else None,
        "scale": cal.scale if cal else None,
        # The acceptance pair: leg-calibrated estimate error on the
        # recorded runs vs the whole-step fit_constants error.
        "leg_mean_abs_error_ms": round(cal.mean_abs_error_s * 1e3, 4)
        if cal and cal.mean_abs_error_s is not None else None,
        "step_fit_mean_abs_error_ms": round(
            step_fit.mean_abs_error_s * 1e3, 4) if step_fit else None,
        "leg_fit_improved": cal.improved if cal else None,
    }

    # Profiler overhead: step time with the profiler plane active (leg
    # micro-runs just executed in-process, samples emitted) vs without.
    # The profiler adds NO per-step hooks by design, so this verifies
    # the design held.  One shared session, interleaved windows, minima
    # compared — separate sessions would measure compile/host drift,
    # not the profiler (the guard/telemetry bench discipline).
    ad, sess = build(Zero1(bucket_bytes=bucket_bytes), 1, None)
    placed = sess.place_batch(batch)
    _measure_session(sess, placed, 5, 10)          # warm the dispatch path
    prof_on = LegProfiler(mesh=sess.mesh, warmup=1, repeats=2)
    ts = {"off": [], "on": []}
    for trial in range(6):
        order = ("off", "on") if trial % 2 == 0 else ("on", "off")
        for key in order:
            if key == "on":
                prof_on.profile_ir(sess.schedule_ir)
            t = _measure_session(sess, placed, 2, 50)
            ts[key].append(t / 50)
    del sess, ad
    _reset_default_autodist_for_testing()
    t_off, t_on = min(ts["off"]), min(ts["on"])
    out["overhead"] = {
        "step_time_ms_profiler_off": round(t_off * 1e3, 3),
        "step_time_ms_profiler_on": round(t_on * 1e3, 3),
        "overhead_fraction": round((t_on - t_off) / t_off, 4),
        "target_overhead_fraction": 0.01,
    }
    print(json.dumps(out), flush=True)


def run_search_child() -> None:
    """Leg-calibrated strategy search measurement (child process, 8
    virtual CPU devices — docs/strategies.md "Search").

    The comm-bound accum fixture (the profiler child's MLP under
    accum=4, small batch so sync dominates compute): (1) every fixed
    candidate builder is built, leg-profiled, and measured end-to-end;
    (2) ``fit_leg_constants`` regresses this host's per-kind constants
    from the collected samples + records; (3) the beam search runs on
    those constants (Int8 wire admitted — the fixture's accuracy
    opt-in), with every priced candidate IR-verified inside the search;
    (4) the Automap-style refinement: the search's top-K (plus the
    fixed candidates' gene projections, which are search states too)
    form a measured shortlist — each distinct schedule lowers to a real
    session (verifier gates it again pre-trace) and the measured-best
    is THE searched schedule.  Measurement disambiguates what a
    wire-level calibration cannot see (a synchronous CPU backend hides
    nothing behind compute, quantize arithmetic rides outside the
    collective micro-run), which is exactly why the search keeps a
    shortlist instead of trusting rank 1.  Asserted in-child: searched
    estimate <= every fixed candidate's estimate under the same
    constants, search wall time < 30 s on the fixture, and the searched
    schedule's measured step time no worse than the best fixed
    candidate's (the shortlist contains the fixed candidates' plans, so
    the search can tie but never lose)."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AllReduce, Strategy, StrategyBuilder, \
        Zero1
    from autodist_tpu.strategy.search import (
        SearchSpace,
        beam_search,
        evaluate_candidate,
        genes_from_strategy,
        resolve_axes,
        strategy_from_genes,
    )
    from autodist_tpu.telemetry.calibration import fit_leg_constants
    from autodist_tpu.telemetry.profiler import LegProfiler

    d = jax.device_count()
    bucket_bytes = 256 << 10
    accum = 4
    rng = np.random.RandomState(0)
    layers = 6
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                         jnp.float32),
                        "b": jnp.zeros(256, jnp.float32)}
              for i in range(layers)}
    batch = {"x": rng.randn(64, 256).astype(np.float32),
             "y": rng.randn(64, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    class _Fixed(StrategyBuilder):
        def __init__(self, strategy: Strategy):
            self._s = strategy

        def build(self, graph_item, resource_spec):
            return self._s

    def build(builder):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=builder)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn, accum_steps=accum)
        return ad, ad.create_distributed_session()

    from autodist_tpu.strategy import PSLoadBalancing
    fixed = (
        ("AllReduce", AllReduce(bucket_bytes=bucket_bytes)),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("Zero1_serial", Zero1(bucket_bytes=bucket_bytes,
                               overlap="none")),
        ("Zero1_auto", Zero1(bucket_bytes=bucket_bytes)),
        ("Zero1_int8_pipeline", Zero1(bucket_bytes=bucket_bytes,
                                      compressor="Int8Compressor",
                                      overlap="pipeline")),
    )
    spec = ResourceSpec(resource_info={"nodes": [
        {"address": "localhost", "chips": d, "chief": True}]})
    out = {"dp": d, "accum_steps": accum, "bucket_bytes": bucket_bytes,
           "fixed": {}}

    # Phase 1: measure every fixed candidate + collect leg samples and
    # step records for calibration.
    steps = 30
    all_samples, all_records = [], []
    gi = None
    strategies = {}
    for name, builder in fixed:
        ad, sess = build(builder)
        gi = ad.graph_item
        strategies[name] = ad._strategy
        ir = sess.schedule_ir
        if ir is None:
            raise RuntimeError(f"search bench: {name} has no IR")
        sir.assert_verified(ir, f"bench search [{name}]")
        all_samples.extend(LegProfiler(mesh=sess.mesh).profile_ir(ir))
        placed = sess.place_batch(batch)
        dt = _measure_session(sess, placed, 3, steps)
        if sess.telemetry is not None:
            all_records.extend(sess.telemetry.records)
        out["fixed"][name] = {
            "schedule_fingerprint": ir.fingerprint(),
            "step_time_ms": round(dt / steps * 1e3, 3),
        }
        del sess, ad
        _reset_default_autodist_for_testing()

    # Phase 2: fit this host's per-kind constants (the search's prices).
    cal = fit_leg_constants(all_samples, all_records)
    if cal is None:
        raise RuntimeError("search bench: calibration fit produced "
                           "nothing — no samples?")
    out["calibration"] = {"n_samples": cal.n_samples,
                          "kinds": sorted(cal.bandwidths),
                          "scale": cal.scale}

    # Phase 3: estimate each fixed candidate + run the search on the
    # SAME constants; the searched estimate must be <= all of them.
    axes = resolve_axes(gi, spec)
    fixed_evals = {}
    for name, _ in fixed:
        ev, strat = evaluate_candidate(
            name, genes_from_strategy(strategies[name], gi), gi, spec,
            axes, cal)
        fixed_evals[name] = (ev, strat)
        out["fixed"][name]["estimated_ms"] = \
            round(ev.cost_s * 1e3, 4) if ev and ev.cost_s else None
    space = SearchSpace(
        compressors=("NoneCompressor", "Int8Compressor"),
        wall_budget_s=25.0)
    result = beam_search(gi, spec, axes=axes, space=space, constants=cal)
    assert result.wall_time_s < 30.0, (
        f"search wall time {result.wall_time_s:.1f}s blew the 30s "
        "fixture budget")
    top1 = result.best
    out["search"] = {
        "rank1": top1.name,
        "rank1_fingerprint": top1.fingerprint,
        "rank1_estimated_ms": round(top1.cost_s * 1e3, 4),
        "n_evals": result.n_evals,
        "n_pruned": len(result.pruned),
        "rounds": result.rounds,
        "wall_time_s": round(result.wall_time_s, 2),
    }
    for name, row in out["fixed"].items():
        est = row.get("estimated_ms")
        assert est is None or top1.cost_s * 1e3 <= est + 1e-9, (
            f"searched estimate {top1.cost_s * 1e3:.4f} ms worse "
            f"than fixed {name} at {est} ms")

    # Phase 4: measured shortlist.  The top-K estimated candidates plus
    # the fixed candidates' gene projections (search states themselves)
    # each lower and measure once per distinct fingerprint; the
    # measured-best is the searched schedule.
    shortlist = []       # (name, fingerprint, estimated_s, strategy|None)
    for ev in result.top(5):
        shortlist.append((ev.name, ev.fingerprint, ev.cost_s, None))
    for name, (ev, strat) in fixed_evals.items():
        if ev is not None and ev.cost_s is not None:
            shortlist.append((f"fixed:{name}", ev.fingerprint,
                              ev.cost_s, strat))
    measured = {}        # fingerprint -> (name, step_time_ms)
    # A shortlist entry whose plan IS a fixed candidate's (identical
    # fact fingerprint -> identical program) reuses the phase-1
    # measurement instead of paying a second, jittery pass.
    for name, (ev, _strat) in fixed_evals.items():
        if ev is not None and ev.fingerprint \
                and ev.fingerprint == out["fixed"][name].get(
                    "schedule_fingerprint"):
            measured[ev.fingerprint] = (
                f"fixed:{name}", out["fixed"][name]["step_time_ms"])
    by_fp = {}
    for ev in result.evaluated:
        by_fp[ev.fingerprint] = ev
    out["shortlist"] = []
    seen_short = set()
    for name, fp, est_s, strat in shortlist:
        if fp in seen_short:
            continue
        seen_short.add(fp)
        if fp in measured:
            out["shortlist"].append({
                "name": name, "fingerprint": fp,
                "estimated_ms": round(est_s * 1e3, 4),
                "step_time_ms": measured[fp][1],
                "reused_measurement": True,
            })
            continue
        if strat is None:
            ev = by_fp.get(fp)
            if ev is None:
                continue
            strat = strategy_from_genes(ev.genes, gi, spec)
        ad, sess = build(_Fixed(strat))
        sir.assert_verified(sess.schedule_ir, f"bench search [{name}]")
        placed = sess.place_batch(batch)
        dt = _measure_session(sess, placed, 3, steps)
        ms = round(dt / steps * 1e3, 3)
        measured[fp] = (name, ms)
        out["shortlist"].append({
            "name": name, "fingerprint": fp,
            "estimated_ms": round(est_s * 1e3, 4),
            "step_time_ms": ms,
            "session_fingerprint": sess.schedule_fingerprint,
        })
        del sess, ad
        _reset_default_autodist_for_testing()
    win_fp, (win_name, win_ms) = min(
        measured.items(), key=lambda kv: (kv[1][1], kv[1][0]))
    out["search"]["winner"] = win_name
    out["search"]["fingerprint"] = win_fp
    out["search"]["step_time_ms"] = win_ms
    best_fixed = min(out["fixed"].items(),
                     key=lambda kv: kv[1]["step_time_ms"])
    out["best_fixed"] = {"name": best_fixed[0], **best_fixed[1]}
    out["searched_vs_best_fixed_pct"] = round(
        (win_ms / best_fixed[1]["step_time_ms"] - 1.0) * 100.0, 2)
    # The no-worse guarantee: the shortlist contains every fixed plan,
    # measured through the same harness (min-of-shortlist <= each; a
    # 5% grace absorbs run-to-run host jitter between the two
    # measurement passes of the same schedule).
    assert win_ms <= best_fixed[1]["step_time_ms"] * 1.05, (
        f"searched schedule measured {win_ms} ms, worse than fixed "
        f"{best_fixed[0]} at {best_fixed[1]['step_time_ms']} ms")
    print(json.dumps(out), flush=True)


def run_moe_child() -> None:
    """Expert-parallel MoE measurement (child process, 8 virtual CPU
    devices — docs/strategies.md "The expert axis").

    One MoE decoder LM, three modes through the full AutoDist path:
    ``dense`` (mesh data=8, experts replicated — the moe/* vars sync
    like any other weight, zero a2a legs), ``expert`` (mesh data=2 x
    expert=4 — the graph transformer lowers dispatch/combine
    ``all_to_all`` pairs per MoE stack into the schedule IR), and
    ``expert_int8`` (the ``AUTODIST_MOE_WIRE=int8`` knob: the runtime
    a2a wire quantizes through ``quant_ring`` and the IR prices
    payload+scale bytes honestly).  Per mode: the verifier gates the
    IR (``assert_verified`` — a mutation in the lowering fails the
    bench, not just a counter), step time over the same batch, the
    IR's a2a wire bytes, and the liveness watermark peak with the
    capacity transients in flight.  The expert mode additionally
    leg-profiles its a2a pairs and reports predicted-vs-measured a2a
    cost from a fit on this host's samples (the constants the beam
    search prices expert-parallel candidates with).  Asserted
    in-child: int8 halves-or-better the a2a wire vs f32, and the
    expert watermark exceeds the dense one (the capacity buffers are
    real, not free)."""
    _steer("cpu")
    import jax
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.analysis import dataflow
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.models.moe_lm import moe_transformer_lm
    from autodist_tpu.strategy import Parallax
    from autodist_tpu.strategy.cost_model import leg_cost_s
    from autodist_tpu.telemetry.calibration import fit_leg_constants
    from autodist_tpu.telemetry.profiler import LegProfiler

    steps = 20
    out = {"devices": jax.device_count(), "modes": {}}

    def run_mode(name, axes, wire=None):
        if wire is None:
            os.environ.pop("AUTODIST_MOE_WIRE", None)
        else:
            os.environ["AUTODIST_MOE_WIRE"] = wire
        _reset_default_autodist_for_testing()
        mesh = build_mesh(axes)
        spec = moe_transformer_lm(
            mesh, vocab_size=256, num_layers=2, num_heads=4, head_dim=16,
            d_ff=128, num_experts=4, max_len=64, seq_len=64)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=Parallax(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                       expert_vars=spec.expert_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        ir = sess.schedule_ir
        sir.assert_verified(ir, f"bench moe [{name}]")
        a2a = [l for l in ir.legs if l.kind == sir.LEG_ALL_TO_ALL]
        wm = dataflow.watermark(ir)
        if wm is None:
            raise RuntimeError(f"moe bench [{name}]: unexecutable IR")
        batch = spec.sample_batch(8)
        dt = _measure_session(sess, batch, 3, steps)
        row = {
            "mesh": dict(axes),
            "schedule_fingerprint": ir.fingerprint(),
            "step_time_ms": round(dt / steps * 1e3, 3),
            "n_a2a_legs": len(a2a),
            "a2a_wire_bytes": int(sum(l.nbytes for l in a2a)),
            "watermark_peak_mib": round(wm.peak_bytes / (1 << 20), 3),
            "watermark_peak_leg": wm.peak_leg,
        }
        out["modes"][name] = row
        return sess, ir, a2a

    sess, _, _ = run_mode("dense", {"data": 8})
    del sess
    sess, ir_e, a2a_e = run_mode("expert", {"data": 2, "expert": 4})

    # Predicted-vs-measured a2a cost: leg-profile the expert schedule,
    # fit this host's per-kind constants, and price the a2a pair with
    # them — the same numbers the beam search sees.
    samples = LegProfiler(mesh=sess.mesh).profile_ir(ir_e)
    cal = fit_leg_constants(samples)
    if cal is None:
        raise RuntimeError("moe bench: leg calibration fit nothing")
    a2a_samples = [s for s in samples if s.kind == sir.LEG_ALL_TO_ALL]
    measured_ms = sum(s.measured_s for s in a2a_samples) \
        / max(1, len(a2a_samples)) * 1e3
    predicted_ms = sum(leg_cost_s(l, ir_e, constants=cal)
                       for l in a2a_e) / max(1, len(a2a_e)) * 1e3
    out["a2a_cost"] = {
        "fitted_kinds": sorted(cal.bandwidths),
        "n_a2a_samples": len(a2a_samples),
        "measured_ms_per_leg": round(measured_ms, 4),
        "predicted_ms_per_leg": round(predicted_ms, 4),
    }
    del sess
    sess, _, _ = run_mode("expert_int8", {"data": 2, "expert": 4},
                          wire="int8")
    del sess
    os.environ.pop("AUTODIST_MOE_WIRE", None)
    _reset_default_autodist_for_testing()

    modes = out["modes"]
    assert modes["dense"]["n_a2a_legs"] == 0
    assert modes["expert"]["n_a2a_legs"] > 0
    f32_wire = modes["expert"]["a2a_wire_bytes"]
    int8_wire = modes["expert_int8"]["a2a_wire_bytes"]
    assert 0 < int8_wire <= f32_wire // 2, (
        f"int8 a2a wire {int8_wire} not <= half of f32 {f32_wire}")
    assert modes["expert"]["watermark_peak_mib"] \
        > modes["dense"]["watermark_peak_mib"], (
        "expert watermark does not see the capacity transients")
    out["int8_wire_saving_pct"] = round(
        (1.0 - int8_wire / f32_wire) * 100.0, 1)
    print(json.dumps(out), flush=True)


def run_hier_child() -> None:
    """Hierarchical ICI+DCN measurement (child process, 8 virtual CPU
    devices — docs/strategies.md "Two-tier sync and --simulate").

    One comm-bound dense model on a simulated 2-slice topology
    (``num_slices=2`` over ``data=8`` — two 4-chip slices joined by a
    25 Gbit/s DCN), three modes through the full AutoDist path:
    ``flat`` (one ring over the whole data axis — every hop crosses
    the slice boundary), ``hier`` (the two-tier lowering:
    within-slice reduce-scatter → cross-slice DCN all-reduce →
    within-slice all-gather), and ``hier_int8`` (the
    ``AUTODIST_DCN_WIRE=int8`` knob: only the DCN leg quantizes
    through ``quant_ring``; the ICI legs stay f32).  Per mode: the
    verifier gates the IR (``assert_verified`` — a mutation in the
    two-level lowering fails the bench, not just a counter), step time
    over the same batch, the IR's wire bytes split per tier, and loss
    parity against the flat baseline.  The hier mode additionally
    leg-profiles its schedule and fits per-kind constants so the
    report carries predicted-vs-measured cost per tier — the distinct
    ICI and DCN constants ``--simulate`` extrapolates from.  Asserted
    in-child: the hier IR carries dcn-tier legs, hier moves fewer DCN
    bytes than flat's full-ring wire, and int8 shrinks the DCN wire
    further."""
    _steer("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    os.environ["AUTODIST_IS_TESTING"] = "True"
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.strategy.cost_model import leg_cost_s, leg_tier
    from autodist_tpu.telemetry.calibration import fit_leg_constants
    from autodist_tpu.telemetry.profiler import LegProfiler

    steps = 20
    out = {"devices": jax.device_count(), "modes": {}}

    rng = np.random.RandomState(0)
    dims = [(1024, 1024), (1024, 512), (512, 256)]
    params = {f"w{i}": jnp.asarray(rng.randn(*d) * 0.02, jnp.float32)
              for i, d in enumerate(dims)}
    batch = {"x": rng.randn(32, 1024).astype(np.float32),
             "y": rng.randn(32, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(len(dims)):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - b["y"]) ** 2)

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8, "chief": True}],
        "mesh": {"data": 8}, "num_slices": 2, "dcn_gbps": 25})

    def run_mode(name, hier, wire=None):
        if wire is None:
            os.environ.pop("AUTODIST_DCN_WIRE", None)
        else:
            os.environ["AUTODIST_DCN_WIRE"] = wire
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=AllReduce(bucket_bytes=1 << 22,
                                                 hier=hier),
                      resource_spec=spec)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-3),
                       loss_fn=loss_fn)
        sess = ad.create_distributed_session()
        ir = sess.schedule_ir
        sir.assert_verified(ir, f"bench hier [{name}]")
        wire_by_tier = {sir.TIER_ICI: 0, sir.TIER_DCN: 0}
        for l in ir.legs:
            wire_by_tier[leg_tier(l, ir)] += l.nbytes
        losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
        dt = _measure_session(sess, batch, 3, steps)
        out["modes"][name] = {
            "schedule_fingerprint": ir.fingerprint(),
            "step_time_ms": round(dt / steps * 1e3, 3),
            "n_legs": len(ir.legs),
            "n_dcn_legs": sum(1 for l in ir.legs
                              if leg_tier(l, ir) == sir.TIER_DCN),
            "ici_wire_bytes": int(wire_by_tier[sir.TIER_ICI]),
            "dcn_wire_bytes": int(wire_by_tier[sir.TIER_DCN]),
            "losses": [round(x, 6) for x in losses],
        }
        return sess, ir, losses

    sess, _, losses_flat = run_mode("flat", hier=False)
    del sess
    sess, ir_h, losses_h = run_mode("hier", hier=True)

    # Per-tier predicted-vs-measured: leg-profile the hier schedule,
    # fit this host's per-kind constants, and price each tier with
    # them — the ICI-vs-DCN split --simulate extrapolates to pods.
    samples = LegProfiler(mesh=sess.mesh).profile_ir(ir_h)
    cal = fit_leg_constants(samples)
    if cal is None:
        raise RuntimeError("hier bench: leg calibration fit nothing")
    dcn_kinds = set(sir.DCN_KINDS)
    tiers = {}
    for tier in (sir.TIER_ICI, sir.TIER_DCN):
        t_samples = [s for s in samples
                     if (s.kind in dcn_kinds) == (tier == sir.TIER_DCN)]
        t_legs = [l for l in ir_h.legs if leg_tier(l, ir_h) == tier]
        tiers[tier] = {
            "n_samples": len(t_samples),
            "measured_ms": round(
                sum(s.measured_s for s in t_samples) * 1e3, 4),
            "predicted_ms": round(
                sum(leg_cost_s(l, ir_h, constants=cal)
                    for l in t_legs) * 1e3, 4),
        }
    out["per_tier_cost"] = tiers
    out["fitted_bandwidths_gbps"] = {
        k: round(v * 8 / 1e9, 2) for k, v in sorted(cal.bandwidths.items())}
    del sess
    sess, _, losses_q = run_mode("hier_int8", hier=True, wire="int8")
    del sess
    os.environ.pop("AUTODIST_DCN_WIRE", None)
    _reset_default_autodist_for_testing()

    modes = out["modes"]
    assert modes["hier"]["n_dcn_legs"] > 0, "hier IR carries no DCN legs"
    assert modes["hier"]["dcn_wire_bytes"] \
        < modes["flat"]["dcn_wire_bytes"], (
        "hier does not shrink the DCN wire vs the flat ring")
    assert modes["hier_int8"]["dcn_wire_bytes"] \
        < modes["hier"]["dcn_wire_bytes"], (
        "int8 DCN wire not below f32 hier wire")
    np.testing.assert_allclose(losses_h, losses_flat, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(losses_q, losses_flat, rtol=2e-2, atol=2e-2)
    out["dcn_wire_saving_vs_flat_pct"] = round(
        (1.0 - modes["hier"]["dcn_wire_bytes"]
         / modes["flat"]["dcn_wire_bytes"]) * 100.0, 1)
    out["int8_dcn_wire_saving_pct"] = round(
        (1.0 - modes["hier_int8"]["dcn_wire_bytes"]
         / modes["hier"]["dcn_wire_bytes"]) * 100.0, 1)
    print(json.dumps(out), flush=True)


def run_mpmd_child() -> None:
    """MPMD pipeline measurement (child process, CPU — docs/pipeline.md).

    One 4-layer MLP trained three ways through the SAME
    :func:`~autodist_tpu.parallel.mpmd.partition.build_pipeline_ir`
    program: single-stage (no pipeline, the baseline ``t1``), 2-stage,
    and 4-stage MPMD — each stage its own
    :class:`~autodist_tpu.parallel.mpmd.runner.StageRunner` on its own
    thread, coupled only by the in-memory activation transport (the
    cross-slice DCN plane's fast path).  Per mode: ``assert_verified``
    gates the IR, the runtime fingerprint is asserted equal to an
    independent ``ir_from_facts`` rebuild (static == runtime), step
    time over the same batch, exposed DCN activation bytes per
    microbatch (``2*(S-1)*leg_nbytes`` — one forward + one backward
    boundary crossing), and the 1F1B bubble predicted
    (``bubble_fraction_1f1b(S, M)``) vs measured
    (``1 - t1/(S*tS)`` — with S stages the work is spread over S
    runners, so a bubble-free pipeline would step in ``t1/S``).
    Asserted in-child: every transport leg rides the dcn tier, the leg
    count is ``4*(S-1)*M``, and all three modes produce the same
    step-0 loss (they are the SAME model and the SAME f32 SGD)."""
    _steer("cpu")
    import threading
    import time as _time

    import jax  # noqa: F401
    import jax.numpy as jnp
    import numpy as np

    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.parallel import mpmd
    from autodist_tpu.parallel.mpmd import transport as tmod
    from autodist_tpu.strategy.cost_model import act_transport_bytes

    n_layers, width, m_n, batch = 4, 64, 8, 32
    steps, warmup = 6, 2
    rng = np.random.RandomState(0)
    layers = [{"w": (rng.randn(width, width) * 0.2).astype(np.float32),
               "b": np.zeros((width,), np.float32)}
              for _ in range(n_layers)]
    x = rng.randn(batch, width).astype(np.float32)
    tgt = rng.randn(batch, width).astype(np.float32)
    rows = batch // m_n
    x_mbs = [x[j * rows:(j + 1) * rows] for j in range(m_n)]
    t_mbs = [tgt[j * rows:(j + 1) * rows] for j in range(m_n)]

    def mse(y, t):
        return jnp.mean((y - t) ** 2)

    out = {"microbatches": m_n, "batch": batch, "layers": n_layers,
           "width": width, "modes": {}}

    for s_n in (1, 2, 4):
        part, stage_params = mpmd.partition_params(layers, s_n)
        prog = mpmd.build_pipeline_ir(
            layer_params=layers, num_stages=s_n, num_microbatches=m_n,
            act_nbytes=rows * width * 4)
        sir.assert_verified(prog.ir, f"bench mpmd [stages={s_n}]")
        rebuilt = sir.ir_from_facts(
            list(prog.facts), axes=dict(prog.axes),
            accum_steps=int(prog.ir.accum_steps),
            pipeline=list(prog.pipeline))
        assert rebuilt.fingerprint() == prog.ir.fingerprint(), \
            "static fingerprint diverges from the runtime IR"
        transport_legs = [l for l in prog.ir.legs
                          if l.kind in sir.TRANSPORT_KINDS]
        assert all(l.tier == sir.TIER_DCN for l in transport_legs), \
            "activation transport off the dcn tier"
        assert len(transport_legs) == 4 * (s_n - 1) * m_n, \
            (len(transport_legs), s_n)

        def stage_fn_for(si):
            def fn(p, h):
                for j in part.layers[si]:
                    pre = f"{sir.stage_name(si)}/l{j}"
                    h = jnp.tanh(h @ p[f"{pre}/w"] + p[f"{pre}/b"])
                return h
            return fn

        tmod.reset_registry()
        runners = [mpmd.StageRunner(
            prog, si, stage_fn=stage_fn_for(si),
            params=stage_params[si],
            transport=mpmd.ActivationTransport("", channel="dp0"),
            lr=0.1, loss_fn=mse if si == s_n - 1 else None)
            for si in range(s_n)]

        def one_step():
            res = [None] * s_n

            def run(si):
                res[si] = runners[si].run_step(
                    x_mbs if si == 0 else None,
                    t_mbs if si == s_n - 1 else None)

            ths = [threading.Thread(target=run, args=(si,))
                   for si in range(s_n)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            return float(res[s_n - 1])

        losses = [one_step() for _ in range(warmup)]
        t0 = _time.perf_counter()
        losses += [one_step() for _ in range(steps)]
        dt = (_time.perf_counter() - t0) / steps

        total_act, exposed_act = act_transport_bytes(prog.ir)
        pf = prog.pipeline[0] if prog.pipeline else None
        out["modes"][f"stages{s_n}"] = {
            "stages": s_n,
            "schedule_fingerprint": prog.ir.fingerprint(),
            "step_time_ms": round(dt * 1e3, 3),
            "losses": [round(v, 6) for v in losses],
            "n_transport_legs": len(transport_legs),
            "bubble_predicted": round(
                sir.bubble_fraction_1f1b(s_n, m_n), 4),
            "act_dcn_bytes": {"total": int(total_act),
                              "exposed": int(exposed_act)},
            "act_dcn_bytes_per_microbatch": int(
                2 * (s_n - 1) * (pf.leg_nbytes() if pf else 0)),
        }

    t1 = out["modes"]["stages1"]["step_time_ms"]
    for s_n in (2, 4):
        mode = out["modes"][f"stages{s_n}"]
        mode["bubble_measured"] = round(
            max(0.0, 1.0 - t1 / (s_n * mode["step_time_ms"])), 4)
    first = [m["losses"][0] for m in out["modes"].values()]
    assert max(first) - min(first) <= 1e-5, \
        f"pipelined modes diverge at step 0: {first}"
    print(json.dumps(out), flush=True)


def run_probe() -> None:
    """Cheap TPU liveness check: real matmul, real sync."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(f"probe: first device is {dev.platform}, not tpu",
              file=sys.stderr, flush=True)
        sys.exit(2)
    x = jnp.ones((512, 512), jnp.bfloat16)
    (x @ x).block_until_ready()
    print("probe: tpu matmul OK", flush=True)


def _spawn(args, timeout_s):
    """Run a child bench process; return (rc, stdout_text).  rc=124 on
    timeout.  Child stderr passes through for driver logs."""
    cmd = [sys.executable, "-u", os.path.abspath(__file__)] + args
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, timeout=timeout_s)
        return proc.returncode, proc.stdout.decode()
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if e.stdout else ""
        return 124, out


def _spawn_streaming(args, timeout_s):
    """Run a child bench process, ECHOING each stdout line to the parent's
    stdout as it arrives (the artifact the driver captures is the parent's
    stream — a driver kill at any moment must leave the child's best-so-far
    JSON line already printed, BENCH_r04's failure mode).  Returns
    (rc, last_valid_json_dict_or_None); rc=124 on timeout."""
    cmd = [sys.executable, "-u", os.path.abspath(__file__)] + args
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    deadline = time.monotonic() + timeout_s
    last = None
    # Line-by-line with a watchdog: readline blocks, so enforce the
    # deadline from a timer thread that kills the child.
    import threading

    def _watchdog():
        while proc.poll() is None:
            if time.monotonic() >= deadline:
                proc.kill()
                return
            time.sleep(1.0)

    t = threading.Thread(target=_watchdog, daemon=True)
    t.start()
    for raw in proc.stdout:
        line = raw.decode(errors="replace").rstrip("\n")
        print(line, flush=True)
        s = line.strip()
        if s.startswith("{"):
            try:
                last = json.loads(s)
            except json.JSONDecodeError:
                pass
    proc.wait()
    rc = 124 if time.monotonic() >= deadline and proc.returncode != 0 \
        else proc.returncode
    return rc, last


def _extract_json(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> int:
    errors = []
    t0 = time.time()

    # 0) Self-describing placeholder FIRST: whatever happens after this —
    #    dead tunnel, driver kill mid-probe — the artifact parses.
    best = {
        "metric": "resnet50_train_throughput",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "platform": None,
        "tpu_unavailable": True,
        "status": "no_measurement_yet",
        "sections": {},
        "t_start_unix": round(t0, 1),
    }
    print(json.dumps(best), flush=True)

    def consider(result, *, tpu_alive):
        """Adopt ``result`` as best-so-far if it measured something; a TPU
        result always beats a CPU one."""
        nonlocal best
        if result is None or result.get("value") is None:
            return False
        if result.get("platform") != "tpu":
            if tpu_alive:
                result["tpu_measurement_failed"] = True
            else:
                result["tpu_unavailable"] = True
        if best.get("value") is None or (result.get("platform") == "tpu"
                                         and best.get("platform") != "tpu"):
            best = result
        return True

    # 1) Probe the TPU tunnel.  If the FIRST probe fails, measure the CPU
    #    fallback immediately (a labeled CPU number beats silence — the r3
    #    vs r4 lesson), then keep probing until the deadline in case the
    #    tunnel revives.
    tpu_alive = False
    cpu_done = False
    probe_deadline = time.monotonic() + PROBE_DEADLINE_S
    n_probes = 0
    while True:
        rc, _ = _spawn(["--probe"], PROBE_TIMEOUT_S)
        n_probes += 1
        if rc == 0:
            tpu_alive = True
            break
        if rc == 2:  # backend up but routed to non-TPU: retries won't help
            errors.append(f"probe rc=2 after {n_probes} attempts")
            break
        if not cpu_done:
            print(f"bench: tunnel down (probe #1 rc={rc}); measuring CPU "
                  f"fallback now, will keep probing after", file=sys.stderr,
                  flush=True)
            crc, cres = _spawn_streaming(["--child", "cpu"],
                                         CPU_ATTEMPTS[0][1])
            if not consider(cres, tpu_alive=False):
                errors.append(f"bench[cpu] rc={crc}")
            cpu_done = True
        remaining = probe_deadline - time.monotonic()
        if remaining <= 0:
            errors.append(
                f"probe rc={rc}; tunnel down for the full "
                f"{PROBE_DEADLINE_S:.0f}s deadline ({n_probes} probes)")
            break
        wait = min(PROBE_RETRY_INTERVAL_S, remaining)
        print(f"bench: tunnel down (probe #{n_probes} rc={rc}), retrying "
              f"in {wait:.0f}s ({remaining / 60:.0f} min left in probe "
              f"deadline)", file=sys.stderr, flush=True)
        time.sleep(wait)

    # 2) Measure.  TPU attempts when the tunnel answered (one retry — the
    #    first compile over the tunnel is the slow part); the CPU fallback
    #    only if a CPU number isn't already on record.
    attempts = TPU_ATTEMPTS if tpu_alive else \
        (() if cpu_done else CPU_ATTEMPTS)
    for platform, timeout_s in attempts:
        if platform == "cpu" and best.get("value") is not None:
            continue   # a CPU re-run could only duplicate what we have
        rc, result = _spawn_streaming(["--child", platform], timeout_s)
        ok = consider(result, tpu_alive=tpu_alive)
        if ok and result.get("platform") == "tpu":
            break
        if not ok:
            errors.append(f"bench[{platform}] rc={rc}")

    # 3) Final line: best measurement anywhere, else parseable failure.
    #    Relabel with FINAL knowledge: a CPU result adopted while the
    #    tunnel looked dead must not say tpu_unavailable if the tunnel
    #    later answered (that's a measurement failure, a different bug).
    if best.get("platform") != "tpu":
        best.pop("tpu_unavailable", None)
        best.pop("tpu_measurement_failed", None)
        if tpu_alive:
            best["tpu_measurement_failed"] = True
        else:
            best["tpu_unavailable"] = True
    if best.get("value") is not None:
        print(json.dumps(best), flush=True)
        return 0
    best["error"] = "; ".join(errors)
    print(json.dumps(best), flush=True)
    return 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child(sys.argv[sys.argv.index("--child") + 1])
    elif "--grad-sync-child" in sys.argv:
        run_grad_sync_child()
    elif "--flightrec-child" in sys.argv:
        run_flightrec_child()
    elif "--quant-child" in sys.argv:
        run_quant_child()
    elif "--search-child" in sys.argv:
        run_search_child()
    elif "--moe-child" in sys.argv:
        run_moe_child()
    elif "--hier-child" in sys.argv:
        run_hier_child()
    elif "--mpmd-child" in sys.argv:
        run_mpmd_child()
    elif "--profiler-child" in sys.argv:
        run_profiler_child()
    elif "--kernels-child" in sys.argv:
        run_kernels_child()
    elif "--serving-child" in sys.argv:
        run_serving_child()
    elif "--spec-child" in sys.argv:
        run_spec_child()
    elif "--serving-chaos-child" in sys.argv:
        run_serving_chaos_child()
    elif "--recovery-child" in sys.argv:
        run_recovery_child()
    elif "--probe" in sys.argv:
        run_probe()
    else:
        sys.exit(main())
