"""Router + supervised replica pool.

Fast half: routing policy against in-process fake replicas — score-
based selection, reroute-on-failure, 429 route-elsewhere vs RouterBusy,
down-marking and recovery, non-retryable 4xx.

Slow half: the live drill the PR's acceptance criterion names — two
REAL replica subprocesses (paged engines behind HTTP, supervised with
heartbeat beacons), open-loop load, SIGKILL one replica mid-flight:
every request completes via re-routing with outputs equal to the
uninterrupted oracle, and the supervisor relaunches the dead replica
back into rotation.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from autodist_tpu.serving.router import (ReplicaEndpoint, Router,
                                         RouterBusy, RouterError,
                                         RouterRequestError)

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeReplica:
    """Duck-typed endpoint: a scripted replica the router can route to."""

    def __init__(self, name, queue_depth=0, occupancy=0.0,
                 healthy=True, mode="ok", retry_after=2.0):
        self.name = name
        self.queue_depth = queue_depth
        self.occupancy = occupancy
        self.healthy = healthy
        self.mode = mode
        self.retry_after = retry_after
        self.served = []
        self.posts = 0

    def probe(self, timeout=2.0):
        return self.healthy

    def fetch_stats(self):
        if not self.healthy:
            return None
        return {"outstanding": 0,
                "queue_depth_total": self.queue_depth,
                "block_occupancy": self.occupancy}

    def post(self, body, timeout):
        self.posts += 1
        if self.mode == "die":
            raise OSError("connection reset by peer")
        if self.mode == "busy":
            return 429, {"error": "queue full",
                         "retry_after_s": self.retry_after}
        if self.mode == "unavailable":
            return 503, {"error": "engine unavailable"}
        if self.mode == "bad":
            return 400, {"error": "prompt_tokens must be ints"}
        self.served.append(body)
        return 200, {"id": len(self.served), "tokens": [1, 2, 3]}


def _router(*eps, **kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("stats_ttl_s", 0.0)
    kw.setdefault("retry_wait_s", 0.01)
    return Router(eps, **kw)


def test_router_prefers_low_queue_and_headroom():
    a = FakeReplica("a", queue_depth=5, occupancy=0.9)
    b = FakeReplica("b", queue_depth=0, occupancy=0.1)
    r = _router(a, b)
    for _ in range(3):
        out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
        assert out["tokens"] == [1, 2, 3]
    assert len(b.served) == 3 and len(a.served) == 0


def test_router_reroutes_on_transport_failure():
    a = FakeReplica("a", mode="die")                  # best score, dies
    b = FakeReplica("b", queue_depth=3)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert out["tokens"] == [1, 2, 3]
    assert a.posts == 1 and len(b.served) == 1
    assert r.registry.counter(
        "autodist_router_reroutes_total").value == 1
    # a is held down: the next request goes straight to b
    r.complete({"prompt_tokens": [2], "max_new_tokens": 2})
    assert a.posts == 1 and len(b.served) == 2
    # a recovers: after the hold expires it re-enters rotation
    a.mode = "ok"
    r._down_until["a"] = 0.0
    r.complete({"prompt_tokens": [3], "max_new_tokens": 2})
    assert len(a.served) == 1


def test_router_busy_routes_elsewhere_then_raises():
    a = FakeReplica("a", mode="busy", retry_after=3.0)
    b = FakeReplica("b", queue_depth=9)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert out["tokens"] == [1, 2, 3] and len(b.served) == 1

    b.mode = "busy"
    b.retry_after = 7.0
    with pytest.raises(RouterBusy) as exc:
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert exc.value.retry_after_s == 7.0             # the largest hint
    assert r.registry.counter(
        "autodist_router_busy_rejects_total").value == 1


def test_router_503_reroutes_but_400_raises():
    a = FakeReplica("a", mode="unavailable")
    b = FakeReplica("b", queue_depth=3)
    r = _router(a, b)
    r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert len(b.served) == 1                         # rerouted off 503

    b.mode = "bad"
    with pytest.raises(RouterRequestError) as exc:
        r.complete({"prompt_tokens": ["x"], "max_new_tokens": 2})
    assert exc.value.status == 400
    # a bad request is NOT rerouted (it would fail identically)
    assert b.posts == 2 and a.posts == 1


def test_router_no_live_replica():
    a = FakeReplica("a", healthy=False)
    b = FakeReplica("b", healthy=False)
    r = _router(a, b, max_attempts=3)
    with pytest.raises(RouterError, match="no live replica"):
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2},
                   timeout_s=0.2)


def test_endpoint_rereads_address_file(tmp_path):
    """A relaunched replica publishes a fresh port; the endpoint picks
    it up from the address file's mtime without a router restart."""
    addr = tmp_path / "r.addr.json"
    ep = ReplicaEndpoint(name="r", address_file=str(addr))
    assert ep.client() is None                        # nothing published
    addr.write_text(json.dumps({"host": "127.0.0.1", "port": 1111}))
    assert ep.client().port == 1111
    time.sleep(0.01)
    addr.write_text(json.dumps({"host": "127.0.0.1", "port": 2222}))
    os.utime(addr, (time.time() + 5, time.time() + 5))
    assert ep.client().port == 2222


# ---------------------------------------------------------------------------
# the live drill
# ---------------------------------------------------------------------------

def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.5)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow
def test_kill_one_of_two_supervised_replicas_under_load(tmp_path):
    """Kill one of two supervised replicas under open-loop load: all
    in-flight requests complete via re-routing, outputs equal the
    uninterrupted oracle (greedy decode is deterministic and replica-
    independent), and the supervisor relaunches the dead replica back
    into rotation."""
    import jax

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.resilience.backoff import Backoff
    from autodist_tpu.resilience.supervisor import SupervisorPolicy
    from autodist_tpu.serving.router import SupervisedReplicaPool

    script = os.path.join(REPO, "tests", "integration",
                          "serving_replica.py")

    def launch(index, attempt):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "AUTODIST_REPLICA_ADDR_FILE":
                os.path.join(str(tmp_path), f"replica_{index}.addr.json"),
            "AUTODIST_REPLICA_HB_DIR": attempt.heartbeat_dir,
            "AUTODIST_REPLICA_NAME": f"replica-{index}",
            "AUTODIST_REPLICA_SEED": "0",
        })
        return subprocess.Popen([sys.executable, "-u", script], env=env,
                                start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)

    policy = SupervisorPolicy(
        max_restarts=6, heartbeat_timeout=8.0, poll_interval=0.2,
        backoff=Backoff(max_tries=8, base=0.5, cap=2.0), kill_grace=3.0)
    pool = SupervisedReplicaPool(2, launch, str(tmp_path / "pool"),
                                 policy=policy)
    # endpoints watch the addr files the launcher writes (stable across
    # relaunches) and the pool's per-replica beacon dirs
    eps = [ReplicaEndpoint(
               name=f"replica-{i}",
               address_file=os.path.join(str(tmp_path),
                                         f"replica_{i}.addr.json"),
               beacon_dir=pool.beacon_dir(i), beacon_timeout=8.0)
           for i in range(2)]
    router = Router(eps, probe_ttl_s=0.5, stats_ttl_s=0.2,
                    retry_wait_s=0.5, max_attempts=20)

    spec = transformer_lm(vocab_size=61, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    gen = make_generator(spec)
    rng = np.random.RandomState(42)
    reqs = [(rng.randint(0, 61, rng.randint(2, 6)).astype(np.int32),
             int(rng.randint(3, 8))) for _ in range(12)]
    oracle = {i: np.asarray(gen(params, p[None, :], n))[0]
              for i, (p, n) in enumerate(reqs)}

    with pool:
        _wait(lambda: all(ep.probe() for ep in eps), 180,
              "both replicas serving")
        results, errors = {}, []

        def issue(i, prompt, n):
            try:
                out = router.complete(
                    {"prompt_tokens": [int(t) for t in prompt],
                     "max_new_tokens": n}, timeout_s=240)
                results[i] = np.asarray(out["tokens"])
            except Exception as e:  # noqa: BLE001 - collected for assert
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=issue, args=(i, p, n))
                   for i, (p, n) in enumerate(reqs)]
        for t in threads:
            t.start()
        # let load land on both replicas, then kill replica 0 hard
        time.sleep(2.0)
        victim = pool.current_proc(0)
        assert victim is not None
        os.kill(victim.pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=300)
        assert not errors, f"requests failed: {errors}"
        assert sorted(results) == list(range(len(reqs)))
        for i in sorted(oracle):
            np.testing.assert_array_equal(
                results[i], oracle[i],
                err_msg=f"request {i} diverged after re-route")
        # the kill was a ROUTING event: the router re-routed in-flight
        # work off the dead replica...
        assert router.registry.counter(
            "autodist_router_reroutes_total").value >= 1
        # ...and the supervisor relaunched it back into rotation
        _wait(lambda: eps[0].probe(), 120, "replica 0 relaunch")
        out = router.complete({"prompt_tokens": [3, 5],
                               "max_new_tokens": 3}, timeout_s=120)
        np.testing.assert_array_equal(
            out["tokens"],
            np.asarray(gen(params,
                           np.asarray([3, 5], np.int32)[None, :], 3))[0])
