"""Byte-level BPE tokenizer: trainer, native↔fallback bit-parity,
round-trips, file format, and the EngineServer text-mode integration."""
import numpy as np
import pytest

from autodist_tpu.runtime import native
from autodist_tpu.runtime.tokenizer import BPETokenizer

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the five boxing wizards jump quickly",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
] * 4


@pytest.fixture(scope="module")
def tok():
    return BPETokenizer.train(CORPUS, vocab_size=256 + 64)


def test_train_learns_merges(tok):
    assert tok.vocab_size > 256
    # The corpus repeats 'the ' and ' qu' heavily: some learned token
    # must span multiple bytes.
    enc = tok.encode("the quick")
    assert len(enc) < len("the quick".encode())


def test_roundtrip_exact(tok):
    for s in CORPUS + ["", "a", "  ", "unseen words survive too",
                       "unicode: héllo wörld ≤≥ 東京"]:
        assert tok.decode(tok.encode(s)) == s


def test_bytes_never_unknown(tok):
    # Every byte is a base token: arbitrary binary-ish text encodes.
    s = bytes(range(256)).decode("latin-1")
    ids = tok.encode(s)
    assert all(0 <= i < tok.vocab_size for i in ids)
    # latin-1 chars >= 128 become multi-byte utf-8, hence more ids than
    # chars is fine; decode restores the exact string.
    assert tok.decode(ids) == s


def test_native_matches_fallback(tok):
    """The C++ encode and the pure-Python loop must agree token-for-token
    (same pretokenizer, same heap best-merge semantics)."""
    if not native.native_available():
        pytest.skip("native runtime unavailable")
    assert tok._get_native() is not None, "native tokenizer not built"
    rng = np.random.RandomState(0)
    alphabet = "abcdefghij klmnopqrstuvwxyz  the quick's 'll 123!? \t\n é東"
    for _ in range(80):
        s = "".join(alphabet[i] for i in
                    rng.randint(0, len(alphabet), rng.randint(0, 80)))
        want = tok._encode_py(s.encode("utf-8"))
        got = tok.encode(s)
        assert got == want, f"native != fallback for {s!r}"


def test_heap_encode_matches_naive_rescan(tok):
    """The O(n log n) heap merge is semantically identical to the
    brute-force 'rescan for the global lowest-rank pair, leftmost
    first' reference on random inputs."""
    def naive(ids):
        ids = list(ids)
        ranks = tok._ranks
        while True:
            best, pos = None, -1
            for i in range(len(ids) - 1):
                r = ranks.get((ids[i], ids[i + 1]))
                if r is not None and (best is None or r[0] < best[0]):
                    best, pos = r, i
            if pos < 0:
                return ids
            ids[pos:pos + 2] = [best[1]]

    rng = np.random.RandomState(3)
    corpus_bytes = " ".join(CORPUS).encode()
    for _ in range(40):
        n = rng.randint(1, 60)
        start = rng.randint(0, len(corpus_bytes) - n)
        seg = list(corpus_bytes[start:start + n])
        assert tok._merge_segment(list(seg)) == naive(seg)


def test_pretokenize_boundaries():
    """The scanner realizes the GPT-2 pattern structure: contractions,
    space-prefixed class runs, digit/letter/punct splits, and the
    \\s+(?!\\S) whitespace rule."""
    from autodist_tpu.runtime.tokenizer import _pretokenize

    def segs(s):
        data = s.encode("utf-8")
        return [data[a:b].decode("utf-8", errors="replace")
                for a, b in _pretokenize(data)]

    assert segs("don't stop") == ["don", "'t", " stop"]
    assert segs("we'll they're I've") == \
        ["we", "'ll", " they", "'re", " I", "'ve"]
    assert segs("abc123 x!?") == ["abc", "123", " x", "!?"]
    assert segs("a   b") == ["a", "  ", " b"]       # run keeps last space
    assert segs("hi  ") == ["hi", "  "]             # trailing run intact
    assert segs(" 's") == [" '", "s"]               # space blocks contraction
    # the ' ?' prefix is a LITERAL space: \t and \n stand alone
    assert segs("foo\nbar") == ["foo", "\n", "bar"]
    assert segs("a\n\nb") == ["a", "\n", "\n", "b"]
    assert segs("a\tb") == ["a", "\t", "b"]
    assert segs("héllo 東京") == ["héllo", " 東京"]   # >=0x80 bytes are letters
    # coverage over the whole byte range never crashes or drops bytes
    everything = bytes(range(256))
    spans = _pretokenize(everything)
    assert spans[0][0] == 0 and spans[-1][1] == 256
    assert all(a < b for a, b in spans)
    assert [a for a, _ in spans[1:]] == [b for _, b in spans[:-1]]


def test_merges_never_cross_pretoken_boundaries(tok):
    """Encoding a concatenation equals concatenating the encodes when
    the boundary is a pretoken boundary — the quality property that
    motivates pretokenization."""
    a, b = "the quick", " brown fox"
    assert tok.encode(a + b) == tok.encode(a) + tok.encode(b)


def test_v1_file_loads_without_pretokenization(tok, tmp_path):
    """Old saved files (format v1) keep their original whole-string
    merge behavior."""
    import json as _json

    p = str(tmp_path / "v1.json")
    with open(p, "w") as f:
        _json.dump({"format": "autodist-bpe-v1",
                    "merges": tok.merges}, f)
    old = BPETokenizer.load(p)
    assert old.pretokenize is False
    s = "the quick brown fox"
    assert old.decode(old.encode(s)) == s


def test_special_tokens(tok, tmp_path):
    """Registration, atomic encode under with_special, plain-encode
    immunity, decode rendering, and v2 persistence."""
    t = BPETokenizer(tok.merges)
    ids = t.add_special_tokens(["<eos>", "<pad>"])
    assert t.eos_id == ids["<eos>"] and t.pad_id == ids["<pad>"]
    assert t.vocab_size == ids["<pad>"] + 1
    s = "hello<eos>world"
    with_sp = t.encode(s, with_special=True)
    assert t.eos_id in with_sp
    assert with_sp == t.encode("hello") + [t.eos_id] + t.encode("world")
    # plain encode treats the literal text as bytes, never the id
    assert t.eos_id not in t.encode(s)
    assert t.decode(with_sp) == s
    p = str(tmp_path / "sp.json")
    t.save(p)
    t2 = BPETokenizer.load(p)
    assert t2.special_tokens == t.special_tokens
    assert t2.encode(s, with_special=True) == with_sp
    with pytest.raises(ValueError, match="already registered"):
        t2.add_special_tokens(["<eos>"])
    with pytest.raises(ValueError, match="collides"):
        BPETokenizer(tok.merges, special_tokens={"<x>": 0})


def test_serve_wires_tokenizer_eos(tok):
    """serve() picks up the tokenizer's <eos> as the engine eos_id."""
    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving.server import serve

    t = BPETokenizer(tok.merges)
    t.add_special_tokens(["<eos>"])
    spec = transformer_lm(vocab_size=t.vocab_size, num_layers=1,
                          num_heads=2, head_dim=8, d_ff=32, max_len=32,
                          seq_len=16, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    srv = serve(spec, params, port=0, tokenizer=t, slots=1, window=16)
    try:
        assert srv._engine._eos_id == t.eos_id
    finally:
        srv.close()


@pytest.mark.slow
def test_train_on_repo_corpus():
    """Train on a real multi-hundred-KB corpus (this repo's docs +
    README): round-trips exactly, compresses, and native matches the
    Python path on real text."""
    import glob
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(root, "*.md"))) + \
        sorted(glob.glob(os.path.join(root, "docs", "*.md"))) + \
        sorted(glob.glob(os.path.join(root, "examples", "*.py")))
    texts = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            texts.append(f.read())
    assert sum(len(t) for t in texts) > 100_000, "corpus too small"
    t = BPETokenizer.train(texts, vocab_size=256 + 512,
                           special_tokens=["<eos>", "<pad>"])
    assert len(t.merges) == 512
    sample = texts[0][:20_000]
    ids = t.encode(sample)
    assert t.decode(ids) == sample
    # real compression: well under one token per byte
    assert len(ids) < 0.55 * len(sample.encode("utf-8"))
    if native.native_available():
        assert ids == t._encode_py(sample.encode("utf-8"))


def test_save_load_roundtrip(tok, tmp_path):
    p = str(tmp_path / "tok.json")
    tok.save(p)
    tok2 = BPETokenizer.load(p)
    assert tok2.merges == tok.merges
    s = "the quick brown fox"
    assert tok2.encode(s) == tok.encode(s)


def test_validation():
    with pytest.raises(ValueError, match="dense"):
        BPETokenizer([(97, 98, 300)])   # ids must start at 256
    with pytest.raises(ValueError, match="not yet defined"):
        BPETokenizer([(97, 999, 256)])
    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer.train(["x"], vocab_size=10)
    with pytest.raises(ValueError, match="autodist-bpe"):
        import json
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"format": "other"}, f)
        BPETokenizer.load(f.name)


def test_decode_range_and_server_vocab_guard(tok):
    """Out-of-range ids fail loudly in decode, and a server whose model
    vocab exceeds the tokenizer's refuses to construct."""
    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import DecodeEngine, EngineServer

    with pytest.raises(ValueError, match="out of range"):
        tok.decode([0, tok.vocab_size])
    spec = transformer_lm(vocab_size=tok.vocab_size + 7, num_layers=1,
                          num_heads=2, head_dim=8, d_ff=32, max_len=32,
                          seq_len=16, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(spec, params, slots=1, window=16)
    with pytest.raises(ValueError, match="vocab"):
        EngineServer(eng, port=0, tokenizer=tok)


def test_server_text_mode_with_bpe(tok):
    """End-to-end: EngineServer(tokenizer=BPETokenizer) serves prompt
    text and returns decoded text."""
    import http.client
    import json

    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import DecodeEngine, EngineServer

    spec = transformer_lm(vocab_size=tok.vocab_size, num_layers=2,
                          num_heads=2, head_dim=8, d_ff=32, max_len=48,
                          seq_len=16, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(spec, params, slots=1, window=32, chunk=4)
    with EngineServer(eng, port=0, tokenizer=tok,
                      request_timeout_s=120) as srv:
        c = http.client.HTTPConnection(*srv.address, timeout=120)
        c.request("POST", "/v1/completions",
                  json.dumps({"prompt": "the quick",
                              "max_new_tokens": 4}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        body = json.loads(r.read())
        c.close()
    assert r.status == 200, body
    assert body["text"].startswith("the quick")
    assert len(body["new_tokens"]) == 4
    assert body["tokens"][:len(tok.encode("the quick"))] == \
        tok.encode("the quick")
