"""Byte-level BPE tokenizer: trainer, native↔fallback bit-parity,
round-trips, file format, and the EngineServer text-mode integration."""
import numpy as np
import pytest

from autodist_tpu.runtime import native
from autodist_tpu.runtime.tokenizer import BPETokenizer

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the five boxing wizards jump quickly",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
] * 4


@pytest.fixture(scope="module")
def tok():
    return BPETokenizer.train(CORPUS, vocab_size=256 + 64)


def test_train_learns_merges(tok):
    assert tok.vocab_size > 256
    # The corpus repeats 'the ' and ' qu' heavily: some learned token
    # must span multiple bytes.
    enc = tok.encode("the quick")
    assert len(enc) < len("the quick".encode())


def test_roundtrip_exact(tok):
    for s in CORPUS + ["", "a", "  ", "unseen words survive too",
                       "unicode: héllo wörld ≤≥ 東京"]:
        assert tok.decode(tok.encode(s)) == s


def test_bytes_never_unknown(tok):
    # Every byte is a base token: arbitrary binary-ish text encodes.
    s = bytes(range(256)).decode("latin-1")
    ids = tok.encode(s)
    assert all(0 <= i < tok.vocab_size for i in ids)
    # latin-1 chars >= 128 become multi-byte utf-8, hence more ids than
    # chars is fine; decode restores the exact string.
    assert tok.decode(ids) == s


def test_native_matches_fallback(tok):
    """The C++ encode and the pure-Python loop must agree token-for-token
    (same repeated-best-merge semantics)."""
    if not native.native_available():
        pytest.skip("native runtime unavailable")
    assert tok._get_native() is not None, "native tokenizer not built"
    rng = np.random.RandomState(0)
    alphabet = "abcdefghij klmnopqrstuvwxyz  the quick"
    for _ in range(50):
        s = "".join(alphabet[i] for i in
                    rng.randint(0, len(alphabet), rng.randint(0, 80)))
        want = tok._encode_py(s.encode())
        got = tok.encode(s)
        assert got == want, f"native != fallback for {s!r}"


def test_save_load_roundtrip(tok, tmp_path):
    p = str(tmp_path / "tok.json")
    tok.save(p)
    tok2 = BPETokenizer.load(p)
    assert tok2.merges == tok.merges
    s = "the quick brown fox"
    assert tok2.encode(s) == tok.encode(s)


def test_validation():
    with pytest.raises(ValueError, match="dense"):
        BPETokenizer([(97, 98, 300)])   # ids must start at 256
    with pytest.raises(ValueError, match="not yet defined"):
        BPETokenizer([(97, 999, 256)])
    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer.train(["x"], vocab_size=10)
    with pytest.raises(ValueError, match="autodist-bpe"):
        import json
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"format": "other"}, f)
        BPETokenizer.load(f.name)


def test_decode_range_and_server_vocab_guard(tok):
    """Out-of-range ids fail loudly in decode, and a server whose model
    vocab exceeds the tokenizer's refuses to construct."""
    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import DecodeEngine, EngineServer

    with pytest.raises(ValueError, match="out of range"):
        tok.decode([0, tok.vocab_size])
    spec = transformer_lm(vocab_size=tok.vocab_size + 7, num_layers=1,
                          num_heads=2, head_dim=8, d_ff=32, max_len=32,
                          seq_len=16, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(spec, params, slots=1, window=16)
    with pytest.raises(ValueError, match="vocab"):
        EngineServer(eng, port=0, tokenizer=tok)


def test_server_text_mode_with_bpe(tok):
    """End-to-end: EngineServer(tokenizer=BPETokenizer) serves prompt
    text and returns decoded text."""
    import http.client
    import json

    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import DecodeEngine, EngineServer

    spec = transformer_lm(vocab_size=tok.vocab_size, num_layers=2,
                          num_heads=2, head_dim=8, d_ff=32, max_len=48,
                          seq_len=16, attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(spec, params, slots=1, window=32, chunk=4)
    with EngineServer(eng, port=0, tokenizer=tok,
                      request_timeout_s=120) as srv:
        c = http.client.HTTPConnection(*srv.address, timeout=120)
        c.request("POST", "/v1/completions",
                  json.dumps({"prompt": "the quick",
                              "max_new_tokens": 4}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        body = json.loads(r.read())
        c.close()
    assert r.status == 200, body
    assert body["text"].startswith("the quick")
    assert len(body["new_tokens"]) == 4
    assert body["tokens"][:len(tok.encode("the quick"))] == \
        tok.encode("the quick")
