"""Analyzer CLI smoke tests (tier-1, CPU-only, fast).

The CLI contract the acceptance criteria pin: a deliberately illegal
strategy (non-divisible partition on the 8-device virtual mesh) exits
nonzero with a rule-tagged diagnostic in seconds, while the shipped
example models × builders come out clean — including the
``examples/linear_regression.py`` and pipeline-example shapes.
"""
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.strategy.base import (
    PSSynchronizerConfig,
    Strategy,
    VarConfig,
)

pytestmark = pytest.mark.analysis


def _run_cli(*args, timeout=60):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_cli_rejects_illegal_strategy_fast(tmp_path):
    """Nonzero exit + rule-tagged diagnostic for a non-divisible
    partition on the 8-device virtual mesh, well under the 5 s budget."""
    gi = GraphItem({"w": jax.ShapeDtypeStruct((3, 4), jnp.float32),
                    "b": jax.ShapeDtypeStruct((4,), jnp.float32)})
    strategy = Strategy(node_config=[
        VarConfig("w", synchronizer=PSSynchronizerConfig(),
                  partitioner="3,1"),
        VarConfig("b", synchronizer=PSSynchronizerConfig())])
    spath = tmp_path / "strategy.json"
    spath.write_text(json.dumps(strategy.to_dict()))
    cpath = tmp_path / "catalog.json"
    cpath.write_text(gi.serialize())

    t0 = time.monotonic()
    r = _run_cli(str(cpath), str(spath), "--mesh", "data=8")
    elapsed = time.monotonic() - t0
    assert r.returncode == 1, r.stdout + r.stderr
    assert "legality/indivisible-partition" in r.stdout
    assert elapsed < 5.0, f"CLI verdict took {elapsed:.1f}s (budget 5s)"


def test_cli_linear_regression_example_clean():
    """The shapes of examples/linear_regression.py under its default
    builder (PSLoadBalancing) analyze clean on the virtual 8-chip mesh."""
    r = _run_cli("linear_regression", "PSLoadBalancing", "--mesh", "data=8")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 error(s)" in r.stdout


def test_cli_pipeline_example_clean():
    """The stage-stacked pipeline example shapes analyze clean on a
    pipe=4 × data=2 mesh (the examples/pipeline_1f1b.py layout)."""
    r = _run_cli("pipeline", "AllReduce", "--mesh", "pipe=4,data=2")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 error(s)" in r.stdout


def test_cli_every_builder_on_every_demo_model():
    """Shipped builders × builtin demo catalogs: all clean (one process,
    importing the CLI in-proc to keep the matrix fast)."""
    from autodist_tpu.analysis.__main__ import main

    for model in ("linear_regression", "mlp", "embedding_lm", "moe"):
        for builder in ("AllReduce", "PS", "PSLoadBalancing",
                        "PartitionedPS", "Parallax", "AutoStrategy"):
            rc = main([model, builder, "--mesh", "data=8"])
            assert rc == 0, (model, builder)


def test_cli_json_output_and_budget(tmp_path):
    from autodist_tpu.analysis.__main__ import main
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["mlp", "AllReduce", "--mesh", "data=8", "--json",
                   "--budget-gb", "0.000001"])
    out = json.loads(buf.getvalue())
    assert rc == 1
    assert any(d["rule"] == "memory/watermark-exceeds-hbm"
               for d in out["diagnostics"])


def test_cli_list_rules_runs():
    from autodist_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
