"""Flight recorder (docs/observability.md "Flight recorder").

Cursor-ring overwrite semantics, cursor↔beacon round-trip through the
heartbeat machinery, hang localization against planted cursor sets
(including ties and multi-host frontiers), crash-bundle round-trip +
the ``--hang-report`` CLI, chaos ``hang`` grammar, traced leg stamps
under ``AUTODIST_FLIGHTREC=legs``, and the supervisor's
bundle-on-failure wiring.  The live 2-process wedge drill is the slow
test at the bottom (``tests/integration/hang_drill.py``).
"""
import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.telemetry import events as ev
from autodist_tpu.telemetry import flightrec as fr

pytestmark = pytest.mark.flightrec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("AUTODIST_TELEMETRY", raising=False)
    monkeypatch.delenv("AUTODIST_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("AUTODIST_FLIGHTREC", raising=False)
    fr.reset_for_testing()
    ev.reset_for_testing()
    yield
    fr.reset_for_testing()
    ev.reset_for_testing()


# -- cursor ring -------------------------------------------------------------

def test_ring_overwrite_semantics():
    ring = fr.CursorRing(capacity=4)
    for i in range(10):
        ring.record(fr.Cursor(leg=f"leg{i}"))
    assert ring.seq == 10
    kept = ring.cursors()
    assert [c.leg for c in kept] == ["leg6", "leg7", "leg8", "leg9"]
    assert [c.seq for c in kept] == [6, 7, 8, 9]
    assert ring.latest().leg == "leg9"
    # partial fill keeps insertion order too
    ring2 = fr.CursorRing(capacity=8)
    ring2.record(fr.Cursor(leg="a"))
    ring2.record(fr.Cursor(leg="b"))
    assert [c.leg for c in ring2.cursors()] == ["a", "b"]
    assert ring2.latest().leg == "b"


def test_record_cursor_and_dump_roundtrip(tmp_path):
    fr.set_fingerprint("fp123")
    cur = fr.record_cursor("rs:f32:0@2/reduce", slot=2, step=7,
                           leg_kind="reduce_scatter")
    assert cur is not None and cur.fingerprint == "fp123"
    path = fr.ring().dump(str(tmp_path / "c.jsonl"))
    loaded = fr.load_cursors(path)
    assert len(loaded) == 1
    assert loaded[0].leg == "rs:f32:0@2/reduce"
    assert loaded[0].slot == 2 and loaded[0].step == 7
    assert loaded[0].leg_kind == "reduce_scatter"


def test_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("AUTODIST_FLIGHTREC", "0")
    assert fr.record_cursor("x") is None
    assert fr.ring().seq == 0
    monkeypatch.setenv("AUTODIST_FLIGHTREC", "")
    monkeypatch.setenv("AUTODIST_TELEMETRY", "0")
    assert fr.record_cursor("x") is None


def test_cursor_line_rendering():
    cur = {"leg": "rs:f32:0", "kind": "leg", "leg_kind":
           "ring_reduce_scatter", "slot": 2, "age_s": 40.0}
    line = fr.cursor_line(cur, extra_age_s=1.0)
    assert line == "in ring_reduce_scatter leg rs:f32:0 slot 2 for 41 s"
    assert fr.cursor_line({"leg": "step", "kind": "phase", "age_s": 3.0,
                           "step": 9}) == "in phase step (step 9) for 3 s"
    assert fr.cursor_line(None) == ""


# -- beacon round-trip -------------------------------------------------------

def test_cursor_beacon_roundtrip(tmp_path):
    from autodist_tpu.resilience.heartbeat import (
        HeartbeatMonitor,
        HeartbeatWriter,
    )

    fr.set_fingerprint("fpabc")
    fr.record_cursor("ag:bucket@gather", slot=fr.END_OF_STEP, step=12,
                     leg_kind="all_gather")
    writer = HeartbeatWriter(str(tmp_path), "w0", interval=60.0)
    writer.beat(step=12)
    health = HeartbeatMonitor(str(tmp_path), timeout=30.0).check("w0")
    assert health.cursor is not None
    assert health.cursor["leg"] == "ag:bucket@gather"
    assert health.cursor["fingerprint"] == "fpabc"
    assert health.cursor["age_s"] >= 0.0
    assert "in all_gather leg ag:bucket@gather" in health.doing()

    # WEDGED verdict events carry the cursor
    ev.configure(None)
    stale = HeartbeatMonitor(str(tmp_path), timeout=0.0)
    time.sleep(0.05)
    bad = stale.failures()
    assert bad["w0"].state == "wedged"
    verdicts = [e for e in ev.get_journal().events
                if e["kind"] == "heartbeat/verdict"]
    assert len(verdicts) == 1
    assert verdicts[0]["cursor"]["leg"] == "ag:bucket@gather"


def test_doing_falls_back_to_snapshot():
    from autodist_tpu.resilience.heartbeat import WorkerHealth

    h = WorkerHealth("w", "alive", snapshot={"step": 3, "loss": 0.5})
    assert "last doing: step 3" in h.doing()
    h2 = WorkerHealth("w", "alive",
                      cursor={"leg": "x@0/reduce", "kind": "leg",
                              "slot": 0, "age_s": 1.0},
                      snapshot={"step": 3})
    assert "in leg x@0/reduce" in h2.doing()


# -- hang localization -------------------------------------------------------

def _legs(*specs):
    """Hand-built leg dicts: ("id", deps...)"""
    return [{"id": s[0], "deps": list(s[1:]), "kind": "all_reduce"}
            for s in specs]


CHAIN = _legs(("A",), ("B", "A"), ("C", "B"))


def test_localize_unique_culprit():
    diag = fr.localize_hang(
        {"legs": CHAIN},
        {"h0": {"leg": "A", "kind": "leg"},
         "h1": {"leg": "C", "kind": "leg"},
         "h2": {"leg": "C", "kind": "leg"}})
    assert diag is not None and not diag.tie
    assert diag.frontier_leg == "A"
    assert diag.culprits == ("h0",)
    assert "h0" in diag.detail and "A" in diag.detail


def test_localize_tie_all_same_leg():
    diag = fr.localize_hang(
        {"legs": CHAIN},
        {"h0": {"leg": "C"}, "h1": {"leg": "C"}})
    assert diag.tie
    assert diag.frontier_leg == "C"
    assert diag.culprits == ("h0", "h1")
    assert "no unique culprit" in diag.detail


def test_localize_multi_host_frontier():
    # diamond: A and B are mutually unordered, both feed C
    legs = _legs(("A",), ("B",), ("C", "A", "B"))
    diag = fr.localize_hang(
        {"legs": legs},
        {"h0": {"leg": "A"}, "h1": {"leg": "B"}, "h2": {"leg": "C"}})
    assert not diag.tie
    assert set(diag.frontier_legs) == {"A", "B"}
    assert diag.culprits == ("h0", "h1")


def test_localize_step_mismatch_wins():
    diag = fr.localize_hang(
        {"legs": CHAIN},
        {"h0": {"leg": "C", "step": 4},
         "h1": {"leg": "A", "step": 5}})
    assert diag.culprits == ("h0",)
    assert "step 4" in diag.detail and "step 5" in diag.detail


def test_localize_unknown_legs_and_empty():
    assert fr.localize_hang({"legs": CHAIN}, {}) is None
    assert fr.localize_hang({"legs": CHAIN}, {"h0": None}) is None
    diag = fr.localize_hang({"legs": CHAIN},
                            {"h0": {"leg": "step", "kind": "phase"},
                             "h1": {"leg": "step", "kind": "phase"}})
    assert diag.tie and diag.frontier_leg is None


def test_pure_fallback_matches_dataflow_reachability():
    """The jax-free ancestor-set fallback and analysis.dataflow's
    packed-bitset HappensBefore must agree on every ordered pair."""
    legs = _legs(("A",), ("B", "A"), ("C", "A"), ("D", "B", "C"),
                 ("E",), ("F", "E", "D"))
    views = fr.leg_views(legs)
    order = fr._topo(views)
    pure = fr._PureReach(views, order)
    from autodist_tpu.analysis.dataflow import HappensBefore

    hb = HappensBefore(views, order)
    ids = [v.id for v in views]
    for a in ids:
        for b in ids:
            assert pure.reaches(a, b) == hb.reaches(a, b), (a, b)


def test_localize_against_real_session_ir():
    """Planted per-host cursors over a REAL session's schedule IR: the
    host stuck at the reduce leg is the culprit; hosts at the gather
    depend on it."""
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1

    _reset_default_autodist_for_testing()
    params = {"l": {"w": jnp.zeros((64, 64), jnp.float32)}}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["l"]["w"]) ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=256 << 10))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-3),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    ir = sess.schedule_ir
    reduce_leg = next(l.id for l in ir.legs
                      if l.kind == "reduce_scatter")
    gather_leg = next(l.id for l in ir.legs if l.kind == "all_gather")
    diag = fr.localize_hang(ir, {
        "h0": {"leg": reduce_leg, "kind": "leg"},
        "h1": {"leg": gather_leg, "kind": "leg"},
        "h2": {"leg": gather_leg, "kind": "leg"}})
    assert diag.culprits == ("h0",)
    assert diag.frontier_leg == reduce_leg
    _reset_default_autodist_for_testing()


# -- traced leg stamps -------------------------------------------------------

def test_traced_leg_stamps_hit_ir_leg_ids(monkeypatch):
    monkeypatch.setenv("AUTODIST_FLIGHTREC", "legs")
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(64, 64) * 0.05,
                                         jnp.float32)} for i in range(2)}
    batch = {"x": rng.randn(16, 64).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(2):
            h = jnp.tanh(h @ p[f"l{i}"]["w"])
        return jnp.mean(h ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=256 << 10))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-3),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    sess.run(batch)
    leg_ids = {l.id for l in sess.schedule_ir.legs}
    seen = {c.leg for c in fr.ring().cursors() if c.kind == "leg"}
    assert seen, "legs mode must stamp leg cursors"
    assert seen <= leg_ids
    # reduce, update, and gather groups all stamped
    assert any("reduce" in s for s in seen)
    assert any(s.startswith("update/") for s in seen)
    assert any("@gather" in s for s in seen)
    # the session stamped the fingerprint onto every cursor
    fp = sess.schedule_ir.fingerprint()
    assert all(c.fingerprint == fp for c in fr.ring().cursors()
               if c.kind == "leg")
    _reset_default_autodist_for_testing()


def test_default_mode_compiles_no_callbacks_on_cpu():
    assert fr.trace_stamps_enabled() is False   # auto == host off-TPU


# -- chaos hang --------------------------------------------------------------

def test_chaos_hang_parses_and_blocks():
    from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos

    events = parse_chaos("hang@step=3,proc=1,leg=g0@-1/reduce,seconds=0.3")
    assert len(events) == 1
    e = events[0]
    assert e.action == "hang" and e.step == 3 and e.proc == 1
    assert e.args["leg"] == "g0@-1/reduce"

    ev.configure(None)
    monkey = ChaosMonkey(events, process_index=1, attempt=0)
    t0 = time.monotonic()
    monkey.on_step(3)
    blocked = time.monotonic() - t0
    assert blocked >= 0.25, "hang must block inside the step"
    # journaled BEFORE firing, like every chaos event
    kinds = [e["kind"] for e in ev.get_journal().events]
    assert "chaos/hang" in kinds
    # the planted cursor names the leg (what localization keys on)
    cur = fr.latest_cursor()
    assert cur is not None and cur.leg == "g0@-1/reduce"
    assert cur.kind == "leg" and cur.step == 3
    # fires at most once
    monkey.on_step(4)
    assert fr.ring().seq == 1


def test_chaos_hang_wrong_proc_does_not_fire():
    from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos

    monkey = ChaosMonkey(parse_chaos("hang@step=3,proc=1,seconds=5"),
                         process_index=0, attempt=0)
    t0 = time.monotonic()
    monkey.on_step(3)
    assert time.monotonic() - t0 < 1.0


# -- crash bundles -----------------------------------------------------------

def _mk_run_dir(tmp_path, monkeypatch):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir, exist_ok=True)
    monkeypatch.setenv("AUTODIST_TELEMETRY_DIR", run_dir)
    ev.configure(run_dir)
    return run_dir


def test_bundle_roundtrip(tmp_path, monkeypatch):
    run_dir = _mk_run_dir(tmp_path, monkeypatch)
    ev.emit_event("supervisor/attempt_start", attempt=0)
    fr.set_fingerprint("fp1")
    fr.record_cursor("A", step=6, leg_kind="all_reduce")

    legs = {"legs": CHAIN, "axes": {"data": 2}}
    verdicts = {
        "proc0": {"state": "wedged", "step": 6, "age": 1.0,
                  "cursor": {"leg": "C", "kind": "leg", "age_s": 40.0,
                             "fingerprint": "fp1"}},
        "proc1": {"state": "wedged", "step": 6, "age": 1.2,
                  "cursor": {"leg": "A", "kind": "leg", "age_s": 41.0,
                             "fingerprint": "fp1"}},
    }
    bundle = fr.dump_bundle(run_dir, reason="drill", ir=legs,
                            verdicts=verdicts)
    assert bundle is not None and os.path.isdir(bundle)
    b = fr.read_bundle(bundle)
    assert b["manifest"]["reason"] == "drill"
    assert b["manifest"]["fingerprint"] == "fp1"
    assert b["verdicts"]["proc1"]["cursor"]["leg"] == "A"
    assert b["diagnosis"]["culprits"] == ["proc1"]
    assert b["diagnosis"]["frontier_leg"] == "A"
    assert b["cursors"], "own cursor ring must be in the bundle"
    assert b["stacks"], "faulthandler stacks must be in the bundle"
    # events tail + schedule IR landed
    assert os.path.isfile(os.path.join(bundle, "events_tail.jsonl"))
    assert os.path.isfile(os.path.join(bundle, "schedule_ir.json"))
    # the hang diagnosis was journaled
    hang_events = [e for e in ev.load_run_events(run_dir)
                   if e["kind"] == fr.EVENT_HANG]
    assert len(hang_events) == 1
    assert hang_events[0]["culprits"] == ["proc1"]
    # find_bundles discovers it
    assert fr.find_bundles(run_dir) == [bundle]

    report = fr.render_hang_report(bundle)
    assert "culprit: proc1" in report
    assert "frontier leg: A" in report
    assert "in leg A" in report


def test_bundle_uses_published_ir(tmp_path, monkeypatch):
    run_dir = _mk_run_dir(tmp_path, monkeypatch)

    class _FakeIR:
        def fingerprint(self):
            return "fpX"

        def to_json(self):
            return json.dumps({"legs": CHAIN, "version": 1})

    assert fr.publish_ir(_FakeIR(), run_dir)
    assert fr.load_published_ir(run_dir)["legs"][0]["id"] == "A"
    verdicts = {"p0": {"state": "wedged",
                       "cursor": {"leg": "B", "kind": "leg"}},
                "p1": {"state": "wedged",
                       "cursor": {"leg": "C", "kind": "leg"}}}
    bundle = fr.dump_bundle(run_dir, reason="x", verdicts=verdicts)
    b = fr.read_bundle(bundle)
    assert b["diagnosis"]["culprits"] == ["p0"]
    assert b["diagnosis"]["frontier_leg"] == "B"


def test_hang_report_cli(tmp_path, monkeypatch):
    run_dir = _mk_run_dir(tmp_path, monkeypatch)
    verdicts = {"p0": {"state": "wedged",
                       "cursor": {"leg": "A", "kind": "leg"}},
                "p1": {"state": "wedged",
                       "cursor": {"leg": "C", "kind": "leg"}}}
    bundle = fr.dump_bundle(run_dir, reason="cli drill",
                            ir={"legs": CHAIN}, verdicts=verdicts)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.telemetry",
         "--hang-report", bundle],
        stdout=subprocess.PIPE, env=env, timeout=120)
    assert out.returncode == 0
    text = out.stdout.decode()
    assert "culprit: p0" in text and "cli drill" in text
    # a run dir works too (newest bundle picked), and the default
    # report grows a hang section
    out = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.telemetry",
         "--hang-report", run_dir],
        stdout=subprocess.PIPE, env=env, timeout=120)
    assert out.returncode == 0 and "culprit: p0" in out.stdout.decode()
    out = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.telemetry", run_dir],
        stdout=subprocess.PIPE, env=env, timeout=120)
    assert out.returncode == 0
    assert "crash bundle(s)" in out.stdout.decode()
    assert "--hang-report" in out.stdout.decode()


def test_hang_report_cli_no_bundle(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.telemetry",
         "--hang-report", str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        timeout=120)
    assert out.returncode == 2


# -- supervisor wiring -------------------------------------------------------

def test_supervisor_attaches_bundle_on_failure(tmp_path, monkeypatch):
    run_dir = _mk_run_dir(tmp_path, monkeypatch)
    from autodist_tpu.resilience import Backoff, Supervisor, SupervisorPolicy

    policy = SupervisorPolicy(
        max_restarts=0,
        backoff=Backoff(max_tries=2, base=0.01, cap=0.02, seed=0))
    sup = Supervisor(policy, workdir=str(tmp_path / "sup"))

    def launch(att):
        return subprocess.Popen([sys.executable, "-c", "raise SystemExit(3)"],
                                start_new_session=True)

    report = sup.run(launch)
    assert not report.ok
    assert report.failures
    bundle = report.failures[0].bundle
    assert bundle is not None and os.path.isdir(bundle)
    assert bundle.startswith(run_dir)   # telemetry dir wins over workdir
    assert os.path.isfile(os.path.join(bundle, "MANIFEST.json"))
    fails = [e for e in ev.load_run_events(run_dir)
             if e["kind"] == "supervisor/attempt_failure"]
    assert fails and fails[0].get("bundle") == bundle


def test_install_fatal_handlers(tmp_path):
    """Arming writes the faulthandler log target and an excepthook that
    dumps a bundle — exercised in-process by invoking the hook."""
    run_dir = str(tmp_path / "fatal")
    assert fr.install_fatal_handlers(run_dir)
    assert fr.install_fatal_handlers(run_dir)   # idempotent
    try:
        raise RuntimeError("boom")
    except RuntimeError:
        info = sys.exc_info()
    sys.excepthook(*info)
    bundles = fr.find_bundles(run_dir)
    assert bundles, "excepthook must dump a crash bundle"
    man = fr.read_bundle(bundles[-1])["manifest"]
    assert "RuntimeError" in man["reason"]


# -- live 2-process wedge drill (slow) ---------------------------------------

@pytest.mark.slow
def test_live_hang_drill(tmp_path):
    """The acceptance drill: chaos ``hang@step`` wedges the worker
    inside the step → the monitor's WEDGED verdict localizes to the
    planted leg and culprit process → a crash bundle is written and
    renders via --hang-report → the supervisor relaunch resumes from
    the peer tier bit-exact vs the uninterrupted oracle."""
    script = os.path.join(REPO, "tests", "integration", "hang_drill.py")

    def base_env(tag):
        env = dict(os.environ)
        for k in ("AUTODIST_WORKER", "AUTODIST_STRATEGY_ID",
                  "AUTODIST_CHAOS", "AUTODIST_SUPERVISE",
                  "AUTODIST_FAILURE_POLICY", "AUTODIST_SUPERVISOR_DIR",
                  "AUTODIST_ATTEMPT", "AUTODIST_TELEMETRY_DIR",
                  "AUTODIST_FLIGHTREC"):
            env.pop(k, None)
        env.update({
            "AUTODIST_REPO_ROOT": REPO,
            "AUTODIST_RESULT_FILE": str(tmp_path / f"result_{tag}.json"),
            "AUTODIST_TEST_PEER": str(tmp_path / f"peer_{tag}"),
            "AUTODIST_TPU_WORKDIR": str(tmp_path / f"workdir_{tag}"),
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        return env

    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    # ORACLE: chaos off, single attempt.
    env = base_env("oracle")
    env["AUTODIST_COORDINATOR_ADDRESS"] = f"127.0.0.1:{free_port()}"
    proc = subprocess.run([sys.executable, "-u", script], env=env,
                          timeout=300, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    assert proc.returncode == 0, proc.stdout.decode()[-4000:]
    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        oracle = json.load(f)

    # DRILL: worker (proc 1) hangs inside step 6 of attempt 0.  The
    # drill script resolves the PLANT placeholder to a real leg id of
    # its schedule IR and records it in planted.json.
    env = base_env("drill")
    run_dir = str(tmp_path / "telemetry")
    env.update({
        "AUTODIST_SUPERVISE": "1",
        "AUTODIST_CHAOS": "hang@step=6,proc=1,attempt=0,leg=PLANT",
        "AUTODIST_TELEMETRY_DIR": run_dir,
        "AUTODIST_TEST_PLANTED": str(tmp_path / "planted.json"),
        "AUTODIST_SUPERVISOR_REPORT": str(tmp_path / "report.json"),
    })
    proc = subprocess.run([sys.executable, "-u", script], env=env,
                          timeout=600, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, out[-6000:]
    with open(env["AUTODIST_SUPERVISOR_REPORT"], encoding="utf-8") as f:
        report = json.load(f)
    assert report["ok"]
    assert report["attempts"] == 2
    fail = report["failures"][0]
    # the WEDGED verdict named the culprit process and the planted leg
    assert fail["kind"] == "heartbeat"
    assert "proc1" in (fail["culprit"] or "")
    assert "wedged" in fail["detail"]
    with open(env["AUTODIST_TEST_PLANTED"], encoding="utf-8") as f:
        planted = json.load(f)
    assert planted["leg"] in fail["detail"]
    # the bundle exists, renders, and localizes to the planted leg
    bundle = fail["bundle"]
    assert bundle and os.path.isdir(bundle)
    b = fr.read_bundle(bundle)
    diag = b.get("diagnosis") or {}
    assert diag.get("frontier_leg") == planted["leg"]
    assert diag.get("culprits") == ["proc1"]
    report_text = fr.render_hang_report(bundle)
    assert planted["leg"] in report_text
    assert "culprit: proc1" in report_text
    # recovery is bit-exact vs the uninterrupted oracle
    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        chief = json.load(f)
    assert chief["attempt"] == 1
    assert chief["final_step"] == oracle["final_step"]
    np.testing.assert_array_equal(chief["final_w"], oracle["final_w"])
    assert chief["final_b"] == oracle["final_b"]
