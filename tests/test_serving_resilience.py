"""Serving-plane fault tolerance (docs/serving.md, "Fault tolerance").

Fast half, no subprocesses:

* chaos grammar — the serving actions parse, filter by replica/attempt,
  fire once on progress thresholds, and journal BEFORE executing;
* router — expired-deadline fast-fail (no post with a floored
  timeout), mark-down hold expiry and re-entry, the per-replica
  circuit breaker (open → half-open probe → re-open with doubled
  hold), drain-aware candidate filtering, shed-aware 503 handling,
  token-exact in-flight recovery against scripted streaming fakes
  (mid-stream death, eos-in-partial, exhausted budget), and
  first-wins hedging with loser cancellation;
* engine — deadline-aware admission shedding off measured p90s,
  SLO-class deadline defaults, and the in-flight expiry sweep
  (queued and decoding phases) with the no-leak invariant;
* server — graceful drain over HTTP (429 + draining flag, in-flight
  completion, undrain), 504 timeout/deadline responses with
  Retry-After + journal events, and the drop_response / stale_stats
  chaos injections.

Slow half: live drills with real supervised replica subprocesses —
a chaos-killed replica mid-decode under threaded load (every greedy
request completes token-exact, with resume-not-restart evidence),
and a rolling restart that drops nothing.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from autodist_tpu.resilience.chaos import (ServingChaos, parse_chaos,
                                           replica_index_from_env)
from autodist_tpu.serving.router import Router, RouterDeadlineError
from autodist_tpu.telemetry import get_journal

pytestmark = pytest.mark.serving_resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB = 61
# Same geometry as tests/test_serving_scheduler.py: the paged programs
# live in a module-scope jit cache, so shapes compile once per process.
GEOM = dict(slots=2, window=32, block_size=8, num_blocks=24, chunk=4)


def _events_since(mark, kind):
    return [e for e in get_journal().events[mark:] if e["kind"] == kind]


def _mark():
    return len(get_journal().events)


# ---------------------------------------------------------------------------
# chaos grammar + ServingChaos
# ---------------------------------------------------------------------------

def test_chaos_grammar_parses_serving_actions():
    evs = parse_chaos("kill_replica@replica=0,tokens=5;"
                      "slow_replica@replica=1,seconds=0.25;"
                      "drop_response@replica=0,count=2;"
                      "stale_stats@requests=3")
    assert [e.action for e in evs] == ["kill_replica", "slow_replica",
                                       "drop_response", "stale_stats"]
    assert evs[0].replica == 0 and evs[0].args["tokens"] == "5"
    assert evs[1].replica == 1 and evs[1].args["seconds"] == "0.25"
    assert evs[2].args["count"] == "2"
    assert evs[3].replica is None and evs[3].args["requests"] == "3"


def test_replica_index_from_env(monkeypatch):
    monkeypatch.delenv("AUTODIST_REPLICA", raising=False)
    monkeypatch.delenv("AUTODIST_REPLICA_NAME", raising=False)
    assert replica_index_from_env() is None
    monkeypatch.setenv("AUTODIST_REPLICA_NAME", "replica-3")
    assert replica_index_from_env() == 3
    monkeypatch.setenv("AUTODIST_REPLICA", "7")      # explicit wins
    assert replica_index_from_env() == 7


def test_serving_chaos_replica_filter_and_thresholds():
    evs = parse_chaos("kill_replica@replica=0,tokens=5,code=9")
    other = ServingChaos(evs, replica=1)
    other.on_tick(requests=99, generated=99)          # wrong replica
    assert not evs[0].fired

    evs = parse_chaos("kill_replica@replica=0,tokens=5,requests=2")
    chaos = ServingChaos(evs, replica=0)
    exits = []
    chaos._exit = exits.append
    chaos.on_tick(requests=2, generated=4)            # tokens not met
    chaos.on_tick(requests=1, generated=9)            # requests not met
    assert not exits
    mark = _mark()
    chaos.on_tick(requests=2, generated=5)            # both met: fires
    assert exits == [43]
    # journaled BEFORE executing, with the firing context
    (ev,) = _events_since(mark, "chaos/kill_replica")
    assert ev["replica"] == 0 and ev["generated"] == 5
    chaos.on_tick(requests=9, generated=9)            # fired-once
    assert exits == [43]


def test_serving_chaos_armed_behaviors():
    chaos = ServingChaos(parse_chaos(
        "slow_replica@seconds=0.25;drop_response@count=2;stale_stats@"))
    assert bool(chaos)
    assert chaos.slow_s == 0.0 and not chaos.stats_stale
    chaos.on_tick(requests=0, generated=0)
    assert chaos.slow_s == 0.25
    assert chaos.stats_stale
    assert chaos.take_drop() and chaos.take_drop()
    assert not chaos.take_drop()                      # count=2 consumed
    assert not ServingChaos([])                       # empty = falsy


def test_serving_chaos_ignores_training_actions():
    chaos = ServingChaos(parse_chaos("kill@step=5,proc=0"))
    assert not chaos                                  # training-plane only


# ---------------------------------------------------------------------------
# router: deadline fast-fail, mark-down expiry, breaker, drain, shed
# ---------------------------------------------------------------------------

class FakeReplica:
    """Duck-typed endpoint without post_stream: the plain-post path."""

    def __init__(self, name, queue_depth=0, mode="ok", retry_after=2.0):
        self.name = name
        self.queue_depth = queue_depth
        self.mode = mode
        self.retry_after = retry_after
        self.served = []
        self.posts = 0
        self.probe_delay = 0.0

    def probe(self, timeout=2.0):
        if self.probe_delay:
            time.sleep(self.probe_delay)
        return True

    def fetch_stats(self):
        return {"outstanding": 0,
                "queue_depth_total": self.queue_depth,
                "block_occupancy": 0.0,
                "draining": self.mode == "draining"}

    def post(self, body, timeout):
        self.posts += 1
        if self.mode == "die":
            raise OSError("connection reset by peer")
        if self.mode == "draining":
            return 429, {"error": "replica is draining",
                         "draining": True,
                         "retry_after_s": self.retry_after}
        if self.mode == "shed":
            return 503, {"error": "cannot meet deadline", "shed": True,
                         "retry_after_s": self.retry_after}
        self.served.append(body)
        return 200, {"id": len(self.served), "tokens": [1, 2, 3],
                     "new_tokens": [2, 3]}


def _router(*eps, **kw):
    kw.setdefault("probe_ttl_s", 0.0)
    kw.setdefault("stats_ttl_s", 0.0)
    kw.setdefault("retry_wait_s", 0.01)
    return Router(eps, **kw)


def test_router_expired_deadline_no_floored_post():
    """Satellite fix: a spent timeout budget raises the typed deadline
    error immediately — the old path posted once more with a 1 s
    timeout floor AFTER the deadline passed."""
    a = FakeReplica("a")
    r = _router(a)
    with pytest.raises(RouterDeadlineError):
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2},
                   timeout_s=0.0)
    assert a.posts == 0

    # budget spent DURING candidate selection (a slow probe), not just
    # before it: still no post
    a.probe_delay = 0.06
    with pytest.raises(RouterDeadlineError):
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2},
                   timeout_s=0.05)
    assert a.posts == 0


def test_router_mark_down_hold_expires_and_reenters():
    a = FakeReplica("a")
    b = FakeReplica("b", queue_depth=5)
    r = _router(a, b)
    r.mark_down(a, hold_s=0.08)
    assert [ep.name for ep in r.live_replicas()] == ["b"]
    r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert len(b.served) == 1 and a.posts == 0        # a held down
    time.sleep(0.1)
    assert sorted(ep.name for ep in r.live_replicas()) == ["a", "b"]
    r.complete({"prompt_tokens": [2], "max_new_tokens": 2})
    assert len(a.served) == 1                         # re-entered, best score


def test_circuit_breaker_opens_half_opens_reopens():
    a = FakeReplica("a", mode="die")
    b = FakeReplica("b", queue_depth=9)
    r = _router(a, b, breaker_threshold=2, breaker_hold_s=0.1)
    for _ in range(2):
        r._down_until.clear()                 # isolate breaker from hold
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert a.posts == 2
    assert r.breaker_open(a)
    assert r.registry.counter(
        "autodist_router_breaker_open_total").value == 1
    r._down_until.clear()
    assert [ep.name for ep in r.live_replicas()] == ["b"]   # breaker holds
    time.sleep(0.12)                          # hold expiry = half-open
    assert sorted(ep.name for ep in r.live_replicas()) == ["a", "b"]
    r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert a.posts == 3                       # the half-open probe request
    assert r.breaker_open(a)                  # ONE failure re-opens
    assert r._breaker_hold["a"] == pytest.approx(0.4)       # doubled twice
    # recovery: a success resets the consecutive-failure ledger
    a.mode = "ok"
    time.sleep(0.25)
    r._down_until.clear()
    r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert len(a.served) == 1 and not r.breaker_open(a)
    assert "a" not in r._fails


def test_router_skips_draining_replica_without_mark_down():
    a = FakeReplica("a", mode="draining", retry_after=0.6)
    b = FakeReplica("b", queue_depth=9)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert out["tokens"] == [1, 2, 3] and len(b.served) == 1
    # a was NOT marked down (healthy, just leaving) and the next
    # request skips it without burning a post on the guaranteed 429
    assert "a" not in r._down_until
    posts_before = a.posts
    r.complete({"prompt_tokens": [2], "max_new_tokens": 2})
    assert a.posts == posts_before and len(b.served) == 2
    # drain hold expires: a serves again once it stops refusing
    a.mode = "ok"
    time.sleep(0.7)
    r.complete({"prompt_tokens": [3], "max_new_tokens": 2})
    assert len(a.served) == 1


def test_router_shed_503_routes_elsewhere_without_mark_down():
    a = FakeReplica("a", mode="shed", retry_after=4.0)
    b = FakeReplica("b", queue_depth=9)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert out["tokens"] == [1, 2, 3] and len(b.served) == 1
    assert "a" not in r._down_until           # shed is load, not health


# ---------------------------------------------------------------------------
# router: token-exact in-flight recovery + hedging (scripted streams)
# ---------------------------------------------------------------------------

def _continuation(prompt, n):
    """Deterministic token function of the full prefix — resumable by
    construction: generating from prompt+partial continues the exact
    sequence an uninterrupted decode would have produced."""
    out = [int(t) for t in prompt]
    for _ in range(n):
        out.append((sum(out) * 7 + len(out)) % 101)
    return out


class StreamReplica:
    """Endpoint with the post_stream surface: streams one token per
    delta event; optionally dies mid-stream (once) or after streaming
    everything but before the final event (a dropped response)."""

    def __init__(self, name, die_after=None, drop_final=False,
                 delay_s=0.0, queue_depth=0, rid=1):
        self.name = name
        self.die_after = die_after
        self.drop_final = drop_final
        self.delay_s = delay_s
        self.queue_depth = queue_depth
        self.rid = rid
        self.posts = []
        self.cancelled = []

    def probe(self, timeout=2.0):
        return True

    def fetch_stats(self):
        return {"outstanding": 0, "queue_depth_total": self.queue_depth,
                "block_occupancy": 0.0}

    def cancel(self, request_id):
        self.cancelled.append(request_id)
        return True

    def post_stream(self, body, timeout, trace_id="", on_event=None):
        self.posts.append(dict(body))
        prompt = body["prompt_tokens"]
        n = body["max_new_tokens"]
        toks = _continuation(prompt, n)
        new = toks[len(prompt):]
        on_event({"id": self.rid, "done": False, "new_tokens": []})
        for i, t in enumerate(new):
            if self.die_after is not None and i >= self.die_after:
                self.die_after = None
                raise OSError("connection reset by peer")
            on_event({"id": self.rid, "done": False, "new_tokens": [t]})
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.drop_final:
            self.drop_final = False
            raise OSError("stream severed before the final event")
        return 200, {"id": self.rid, "done": True, "tokens": toks,
                     "new_tokens": new}


def test_recovery_resumes_token_exact_on_survivor():
    a = StreamReplica("a", die_after=3)
    b = StreamReplica("b", queue_depth=5)
    r = _router(a, b)
    prompt, n = [5, 9], 8
    oracle = _continuation(prompt, n)
    mark = _mark()
    out = r.complete({"prompt_tokens": prompt, "max_new_tokens": n})
    assert out["new_tokens"] == oracle[len(prompt):]
    assert out["tokens"] == oracle
    assert out["recovered"] is True and out["resumed_tokens"] == 3
    assert "done" not in out
    # resume, not restart: the survivor was asked to prefill the
    # carried tokens and decode ONLY the remainder
    assert b.posts[0]["prompt_tokens"] == oracle[:len(prompt) + 3]
    assert b.posts[0]["max_new_tokens"] == n - 3
    (ev,) = _events_since(mark, "serving/recovered")
    assert ev["resumed_tokens"] == 3 and ev["replica"] == "b"
    assert r.registry.counter(
        "autodist_router_recovered_total").value == 1
    assert r.registry.counter(
        "autodist_router_recovered_tokens_total").value == 3


def test_recovery_finishes_locally_on_eos_in_partial():
    prompt, n = [4, 2], 6
    oracle_new = _continuation(prompt, n)[len(prompt):]
    eos = oracle_new[1]                     # eos lands in the partial
    a = StreamReplica("a", die_after=3)
    b = StreamReplica("b", queue_depth=5)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": prompt, "max_new_tokens": n,
                      "eos_id": eos})
    assert out["new_tokens"] == oracle_new[:2]        # truncated AT eos
    assert out["tokens"] == prompt + oracle_new[:2]
    assert out["recovered"] is True and out["resumed_tokens"] == 2
    assert b.posts == []                    # no resubmit needed


def test_recovery_finishes_locally_on_exhausted_budget():
    """The dying replica streamed every requested token but the final
    response never arrived (the drop_response shape): nothing is left
    to decode, so the router completes the request locally."""
    prompt, n = [7], 5
    oracle = _continuation(prompt, n)
    a = StreamReplica("a", drop_final=True)
    b = StreamReplica("b", queue_depth=5)
    r = _router(a, b)
    out = r.complete({"prompt_tokens": prompt, "max_new_tokens": n})
    assert out["new_tokens"] == oracle[len(prompt):]
    assert out["tokens"] == oracle
    assert out["recovered"] is True and out["resumed_tokens"] == n
    assert b.posts == []


def test_recovery_disabled_or_sampled_uses_plain_post():
    a = FakeReplica("a")
    r = _router(a, recover=False)
    out = r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert "recovered" not in out and a.posts == 1
    # sampling (temperature > 0) must not stream-recover either: a
    # resumed sampled request would re-roll the dice
    b = StreamReplica("b")
    r2 = _router(b)
    with pytest.raises(Exception):
        # StreamReplica has no plain post: proves the router did NOT
        # take the streaming path for a sampled request
        r2.complete({"prompt_tokens": [1], "max_new_tokens": 2,
                     "temperature": 0.8})


def test_hedged_request_first_wins_and_cancels_loser():
    slow = StreamReplica("slow", delay_s=0.5, rid=7)
    fast = StreamReplica("fast", queue_depth=5, rid=11)
    r = _router(slow, fast, hedge_after_s=0.05)
    prompt, n = [3, 1], 4
    oracle = _continuation(prompt, n)
    mark = _mark()
    t0 = time.monotonic()
    out = r.complete({"prompt_tokens": prompt, "max_new_tokens": n})
    assert time.monotonic() - t0 < 0.5      # did not wait for the loser
    assert out["tokens"] == oracle
    assert r.registry.counter("autodist_router_hedged_total").value == 1
    assert r.registry.counter(
        "autodist_router_hedge_wins_total").value == 1
    assert slow.cancelled == [7]            # loser cancelled by its rid
    (ev,) = _events_since(mark, "serving/hedge")
    assert ev["primary"] == "slow" and ev["secondary"] == "fast"


# ---------------------------------------------------------------------------
# engine: deadline shed + expiry sweep
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm():
    import jax

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm

    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


def test_engine_deadline_shed_on_measured_rates(lm):
    from autodist_tpu.serving import DeadlineError, PagedDecodeEngine

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, **GEOM)
    prompt = np.asarray([3, 5, 7], np.int32)
    # below the sample floor the engine admits optimistically
    assert eng._deadline_estimate(10) is None
    eng._qwait_samples.extend([0.2] * 5)
    eng._per_tok_samples.extend([0.1] * 5)
    assert eng._deadline_estimate(10) == pytest.approx(1.2)
    mark = _mark()
    with pytest.raises(DeadlineError) as exc:
        eng.submit(prompt, 10, deadline_s=0.5)
    assert exc.value.retry_after_s > 0
    assert eng.stats.shed_deadline == 1
    (ev,) = _events_since(mark, "serving/shed")
    assert ev["phase"] == "admission"
    assert eng.scheduler_stats()["shed_deadline"] == 1
    # a feasible deadline admits and completes normally
    rid = eng.submit(prompt, 3, deadline_s=30.0)
    out = eng.run()
    assert rid in out and len(out[rid]) == prompt.size + 3
    eng.assert_no_leaks()


def test_engine_deadline_class_defaults(lm):
    from autodist_tpu.serving import DeadlineError, PagedDecodeEngine

    spec, params = lm
    with pytest.raises(ValueError):
        PagedDecodeEngine(spec, params, **GEOM,
                          deadline_defaults={"bogus": 1.0})
    eng = PagedDecodeEngine(spec, params, **GEOM,
                            deadline_defaults={"latency": 0.5})
    eng._qwait_samples.extend([0.2] * 5)
    eng._per_tok_samples.extend([0.1] * 5)
    prompt = np.asarray([2, 4], np.int32)
    with pytest.raises(DeadlineError):
        eng.submit(prompt, 10, slo="latency")   # class default applies
    rid = eng.submit(prompt, 10, slo="throughput")  # no default: admits
    out = eng.run()
    assert rid in out
    eng.assert_no_leaks()


def test_engine_deadline_expiry_sweep_frees_immediately(lm):
    from autodist_tpu.serving import PagedDecodeEngine

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, **GEOM)
    prompt = np.asarray([1, 2, 3], np.int32)
    # queued expiry: deadline passes before the first step
    r1 = eng.submit(prompt, 8, deadline_s=0.01)
    time.sleep(0.03)
    mark = _mark()
    eng.step()
    exp = eng.pop_expired()
    assert exp[r1]["phase"] == "queued" and exp[r1]["overrun_s"] > 0
    assert eng.pop_expired() == {}                    # returns-and-clears
    assert eng.stats.expired_deadline == 1
    (ev,) = _events_since(mark, "serving/shed")
    assert ev["phase"] == "queued" and ev["request_id"] == r1
    while eng.step():
        pass
    assert r1 not in eng.results()
    eng.assert_no_leaks()

    # decoding expiry: blocks and the slot free at the sweep, not at
    # the natural end of decode
    r2 = eng.submit(prompt, 8, deadline_s=60.0)
    eng.step()                                        # admitted
    for req in eng._slot_req:
        if req is not None and req.request_id == r2:
            req.deadline_t = time.monotonic() - 1.0
    eng.step()
    assert eng.pop_expired()[r2]["phase"] == "decoding"
    while eng.step():
        pass
    assert r2 not in eng.results()
    eng.assert_no_leaks()
    # the engine stays fully usable after both expiries
    r3 = eng.submit(prompt, 4, deadline_s=60.0)
    out = eng.run()
    assert len(out[r3]) == prompt.size + 4
    eng.assert_no_leaks()


# ---------------------------------------------------------------------------
# server: drain, deadline/timeout 504s, chaos injections (real HTTP)
# ---------------------------------------------------------------------------

def _post(addr, path, body, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read()), dict(resp.getheaders())
    conn.close()
    return out


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    try:
        return resp.status, json.loads(raw)
    except ValueError:
        return resp.status, raw.decode()


def _paged_server(lm, **kw):
    from autodist_tpu.serving import EngineServer, PagedDecodeEngine

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, **GEOM)
    return EngineServer(eng, port=0, **kw).start()


def test_server_drain_refuses_finishes_inflight_undrains(lm):
    srv = _paged_server(lm)
    try:
        addr = srv.address
        status, body, _ = _post(addr, "/v1/completions",
                                {"prompt_tokens": [1, 2],
                                 "max_new_tokens": 2})
        assert status == 200, body

        # an in-flight request started BEFORE the drain must finish
        slow = {}

        def issue():
            slow["out"] = _post(addr, "/v1/completions",
                                {"prompt_tokens": [3, 4],
                                 "max_new_tokens": 20})

        t = threading.Thread(target=issue)
        t.start()
        time.sleep(0.05)
        status, body, _ = _post(addr, "/admin/drain", {})
        assert status == 200 and body["draining"] is True
        assert srv.draining

        status, st = _get(addr, "/v1/stats")
        assert status == 200 and st["draining"] is True

        status, body, hdrs = _post(addr, "/v1/completions",
                                   {"prompt_tokens": [5],
                                    "max_new_tokens": 2})
        assert status == 429 and body["draining"] is True
        assert body["retry_after_s"] > 0
        assert any(k.lower() == "retry-after" for k in hdrs)

        t.join(timeout=120)
        status, body, _ = slow["out"]
        assert status == 200 and len(body["new_tokens"]) == 20

        status, metrics = _get(addr, "/metrics")
        assert "autodist_serving_drain_refused_total 1" in metrics
        assert "autodist_serving_draining 1" in metrics

        status, body, _ = _post(addr, "/admin/undrain", {})
        assert status == 200 and body["draining"] is False
        status, body, _ = _post(addr, "/v1/completions",
                                {"prompt_tokens": [6],
                                 "max_new_tokens": 2})
        assert status == 200
        status, st = _get(addr, "/v1/stats")
        assert st["draining"] is False
    finally:
        srv.close()
    srv._engine.assert_no_leaks()


def test_server_timeout_504_retry_after_and_journal(lm):
    from autodist_tpu.serving import EngineServer, PagedDecodeEngine

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, **GEOM)
    orig_step = eng.step
    eng.step = lambda: (time.sleep(0.05), orig_step())[1]   # throttle
    srv = EngineServer(eng, port=0, request_timeout_s=0.15).start()
    try:
        mark = _mark()
        status, body, hdrs = _post(srv.address, "/v1/completions",
                                   {"prompt_tokens": [1, 2, 3],
                                    "max_new_tokens": 24})
        assert status == 504
        assert body["retry_after_s"] > 0                 # satellite: 504
        assert any(k.lower() == "retry-after" for k in hdrs)
        evs = _events_since(mark, "serving/timeout")
        assert evs and evs[0]["timeout_s"] == pytest.approx(0.15)
        status, metrics = _get(srv.address, "/metrics")
        assert "autodist_serving_timeouts_total 1" in metrics
        eng.step = orig_step          # un-throttle before the drain
    finally:
        srv.close()
    time.sleep(0.1)
    srv._engine.assert_no_leaks()                        # cancel freed all


def test_server_deadline_expiry_504(lm):
    srv = _paged_server(lm)
    try:
        mark = _mark()
        status, body, hdrs = _post(srv.address, "/v1/completions",
                                   {"prompt_tokens": [1, 2],
                                    "max_new_tokens": 24,
                                    "deadline_s": 0.01})
        assert status == 504
        assert body["deadline_exceeded"] is True
        assert body["phase"] in ("queued", "prefilling", "decoding")
        assert any(k.lower() == "retry-after" for k in hdrs)
        assert _events_since(mark, "serving/shed")
        status, metrics = _get(srv.address, "/metrics")
        assert "autodist_serving_deadline_expired_total 1" in metrics
        # bad deadline_s values are a 400, not a shed
        status, body, _ = _post(srv.address, "/v1/completions",
                                {"prompt_tokens": [1],
                                 "max_new_tokens": 2, "deadline_s": -1})
        assert status == 400
    finally:
        srv.close()
    time.sleep(0.1)
    srv._engine.assert_no_leaks()


def test_server_drop_response_chaos(lm, monkeypatch):
    monkeypatch.setenv("AUTODIST_CHAOS", "drop_response@count=1")
    srv = _paged_server(lm)
    try:
        with pytest.raises((http.client.HTTPException, OSError)):
            _post(srv.address, "/v1/completions",
                  {"prompt_tokens": [1, 2], "max_new_tokens": 2})
        # one drop armed, one consumed: the next response goes through
        status, body, _ = _post(srv.address, "/v1/completions",
                                {"prompt_tokens": [1, 2],
                                 "max_new_tokens": 2})
        assert status == 200, body
    finally:
        srv.close()
    srv._engine.assert_no_leaks()


def test_server_stale_stats_chaos(lm, monkeypatch):
    monkeypatch.setenv("AUTODIST_CHAOS", "stale_stats@")
    srv = _paged_server(lm)
    try:
        time.sleep(0.2)                       # let the driver tick fire
        status, first = _get(srv.address, "/v1/stats")
        assert status == 200
        status, body, _ = _post(srv.address, "/v1/completions",
                                {"prompt_tokens": [1, 2],
                                 "max_new_tokens": 2})
        assert status == 200
        status, again = _get(srv.address, "/v1/stats")
        # frozen: the served request is invisible to the stats surface
        assert again["requests_served"] == first["requests_served"]
        assert again["completed"] == first["completed"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# live drills
# ---------------------------------------------------------------------------

def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.5)
    raise AssertionError(f"timed out waiting for {what}")


def _pool_and_router(tmp_path, chaos=""):
    from autodist_tpu.resilience.backoff import Backoff
    from autodist_tpu.resilience.supervisor import SupervisorPolicy
    from autodist_tpu.serving.router import SupervisedReplicaPool

    script = os.path.join(REPO, "tests", "integration",
                          "serving_replica.py")
    workdir = str(tmp_path / "pool")

    def launch(index, attempt):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            # the pool-canonical address path: rolling_restart() probes
            # pool.address_file(i) to decide a relaunch came back
            "AUTODIST_REPLICA_ADDR_FILE":
                os.path.join(workdir, f"replica_{index}.addr.json"),
            "AUTODIST_REPLICA_HB_DIR": attempt.heartbeat_dir,
            "AUTODIST_REPLICA_NAME": f"replica-{index}",
            "AUTODIST_REPLICA_SEED": "0",
            "AUTODIST_ATTEMPT": str(attempt.index),
        })
        if chaos:
            env["AUTODIST_CHAOS"] = chaos
        else:
            env.pop("AUTODIST_CHAOS", None)
        return subprocess.Popen([sys.executable, "-u", script], env=env,
                                start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)

    policy = SupervisorPolicy(
        max_restarts=6, heartbeat_timeout=15.0, poll_interval=0.2,
        backoff=Backoff(max_tries=8, base=0.5, cap=2.0), kill_grace=3.0)
    pool = SupervisedReplicaPool(2, launch, workdir, policy=policy)
    eps = pool.endpoints()
    router = Router(eps, probe_ttl_s=0.5, stats_ttl_s=0.2,
                    retry_wait_s=0.5, max_attempts=20)
    return pool, eps, router


def _oracle_fn():
    import jax

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm

    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    gen = make_generator(spec)
    return lambda p, n: np.asarray(gen(params, p[None, :], n))[0]


@pytest.mark.slow
def test_chaos_kill_mid_decode_recovers_token_exact(tmp_path):
    """The tentpole drill: chaos kills replica 0 mid-decode (after 10
    generated tokens, attempt 0 only) under 12-thread greedy load.
    Every request completes token-exact against the uninterrupted
    oracle, and at least one carries resume-not-restart evidence
    (recovered + resumed_tokens, plus the serving/recovered journal
    event) — the survivor continued the decode instead of redoing it."""
    # slow_replica paces replica 0 (50ms per driver tick) so the
    # streamed chunk-boundary deltas are on the wire before the kill
    # lands mid-decode; both events are attempt-0-only so the
    # relaunched attempt serves clean.
    chaos = ("slow_replica@replica=0,seconds=0.05,attempt=0;"
             "kill_replica@replica=0,tokens=10,attempt=0")
    pool, eps, router = _pool_and_router(tmp_path, chaos=chaos)
    oracle = _oracle_fn()
    rng = np.random.RandomState(42)
    reqs = [(rng.randint(0, VOCAB, rng.randint(2, 6)).astype(np.int32),
             int(rng.randint(10, 17))) for _ in range(12)]
    want = {i: oracle(p, n) for i, (p, n) in enumerate(reqs)}
    mark = _mark()

    with pool:
        _wait(lambda: all(ep.probe() for ep in eps), 180,
              "both replicas serving")
        results, errors = {}, []

        def issue(i, prompt, n):
            try:
                out = router.complete(
                    {"prompt_tokens": [int(t) for t in prompt],
                     "max_new_tokens": n}, timeout_s=240)
                results[i] = out
            except Exception as e:  # noqa: BLE001 - collected for assert
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=issue, args=(i, p, n))
                   for i, (p, n) in enumerate(reqs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, f"requests failed: {errors}"
        assert sorted(results) == list(range(len(reqs)))
        for i in sorted(want):
            np.testing.assert_array_equal(
                np.asarray(results[i]["tokens"]), want[i],
                err_msg=f"request {i} diverged after chaos kill")
        # the chaos fault actually fired and was journaled first
        assert _events_since(mark, "serving/recovered") or \
            router.registry.counter(
                "autodist_router_recovered_total").value >= 1
        recovered = [results[i] for i in results
                     if results[i].get("recovered")]
        assert recovered, "no request carried resume evidence"
        assert all(r["resumed_tokens"] >= 1 for r in recovered)
        # replica 0 relaunches back into rotation (attempt 1 has no
        # matching chaos event)
        _wait(lambda: eps[0].probe(), 120, "replica 0 relaunch")


@pytest.mark.slow
def test_rolling_restart_drops_nothing(tmp_path):
    """Drain → SIGTERM(exit 75) → supervised relaunch, one replica at
    a time, under continuous load: zero failed requests, all outputs
    token-exact, both replicas come back with fresh processes."""
    pool, eps, router = _pool_and_router(tmp_path)
    oracle = _oracle_fn()
    prompt = np.asarray([3, 5, 7], np.int32)
    want = oracle(prompt, 4)

    with pool:
        _wait(lambda: all(ep.probe() for ep in eps), 180,
              "both replicas serving")
        old_pids = {i: pool.current_proc(i).pid for i in range(2)}
        stop = threading.Event()
        errors, served = [], []

        def load():
            while not stop.is_set():
                try:
                    out = router.complete(
                        {"prompt_tokens": [int(t) for t in prompt],
                         "max_new_tokens": 4}, timeout_s=120)
                    served.append(out)
                    np.testing.assert_array_equal(
                        np.asarray(out["tokens"]), want)
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=load) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            summary = pool.rolling_restart(drain_timeout_s=60.0,
                                           relaunch_timeout_s=180.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=120)
        assert summary["failed"] == [], summary
        assert [r["replica"] for r in summary["restarted"]] == [0, 1]
        assert not errors, f"requests failed during restart: {errors}"
        assert len(served) > 0
        for i in range(2):
            assert pool.current_proc(i).pid != old_pids[i]
        # post-restart sanity: both fresh replicas serve token-exact
        out = router.complete({"prompt_tokens": [int(t) for t in prompt],
                               "max_new_tokens": 4}, timeout_s=120)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
