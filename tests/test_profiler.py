"""Schedule-aware profiler (docs/observability.md "Profiling & Tracing").

Per-leg micro-run timing + trace-span parsing (LegProfiler / LegSample),
leg-granular calibration (fit_leg_constants round-trips on planted
constants and on the committed bench artifacts), calibration.json
persistence + automatic consumption by estimate_ir_cost and
AutoStrategy(search=True) (the constants provably reach the ranking),
Chrome-trace export validated against the Trace Event Format contract
Perfetto requires, cross-host aggregation exactness + the straggler
verdict, the telemetry/leg-drift and telemetry/straggler lint rules,
serving request-trace propagation (router header -> scheduler spans),
and the CLI --compare / --export-trace surfaces.
"""
import gzip
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.telemetry import aggregate as agg
from autodist_tpu.telemetry import calibration as cal
from autodist_tpu.telemetry import profiler as prof
from autodist_tpu.telemetry import registry as reg
from autodist_tpu.telemetry import timeline as tl
from autodist_tpu.telemetry import trace_export as tx

pytestmark = pytest.mark.profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("AUTODIST_TELEMETRY", raising=False)
    monkeypatch.delenv("AUTODIST_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("AUTODIST_CALIBRATION", raising=False)
    cal.reset_calibration_cache_for_testing()
    prof.reset_spans_for_testing()
    reg.reset_for_testing()
    yield
    cal.reset_calibration_cache_for_testing()
    prof.reset_spans_for_testing()
    reg.reset_for_testing()


def _zero1_ir(n_vars=4, d=8, accum=1, guard=False):
    facts = [sir.PlanFact(name=f"w{i}", shape=(256, 256), dtype="float32",
                          sync_kind="AllReduce",
                          sync_mode="reduce_scatter",
                          bucket_bytes=1 << 18, overlap="auto")
             for i in range(n_vars)]
    return sir.ir_from_facts(facts, axes={"data": d}, accum_steps=accum,
                             guard=guard)


# -- LegSample + persistence -------------------------------------------------

def test_leg_sample_roundtrip(tmp_path):
    s = prof.LegSample(schedule_fingerprint="abc", leg_id="b@-1/reduce",
                       kind="reduce_scatter", measured_s=1.5e-4,
                       alg="ring", nbytes=1 << 20, slot=-1,
                       predicted_s=2e-4, host="h1", time_unix=12.0)
    back = prof.LegSample.from_dict(json.loads(s.to_json()))
    assert back == s
    # unknown keys are dropped, not fatal (forward compatibility)
    d = json.loads(s.to_json())
    d["future_field"] = 1
    assert prof.LegSample.from_dict(d).leg_id == s.leg_id

    path = prof.write_leg_samples([s, s], str(tmp_path))
    assert path and os.path.exists(path)
    loaded = prof.load_leg_samples(str(tmp_path))
    assert len(loaded) == 2 and loaded[0].kind == "reduce_scatter"


def test_profile_ir_microbench_covers_every_leg():
    """Micro-runs produce one sample per leg, with positive measured
    times, stamped fingerprints, and leg-priced predictions."""
    ir = _zero1_ir(guard=True)
    samples = prof.LegProfiler(warmup=1, repeats=2).profile_ir(ir)
    assert len(samples) == len(ir.legs)
    by_id = {s.leg_id for s in samples}
    assert by_id == {l.id for l in ir.legs}
    for s in samples:
        assert s.measured_s > 0
        assert s.schedule_fingerprint == ir.fingerprint()
        assert s.kind in cal.LEG_KINDS
    # collective legs carry a prediction from the leg-priced model
    coll = [s for s in samples if s.kind != "update"]
    assert coll and all(s.predicted_s is not None and s.predicted_s > 0
                        for s in coll)
    # the per-kind exposed-ms gauge landed on the process registry
    names = {(m.name, tuple(sorted(m.labels.items())))
             for m in reg.DEFAULT_REGISTRY.metrics()}
    assert any(n == "autodist_leg_exposed_ms" for n, _ in names)


def test_span_kind_mapping():
    assert prof.span_leg_kind(
        "autodist_sync/ring_reduce_scatter/leg2") == "ppermute_hop"
    assert prof.span_leg_kind(
        "autodist_sync/param_gather/bucketA") == "all_gather"
    assert prof.span_leg_kind("autodist_sync/guard_rollup") == "psum_guard"
    assert prof.span_leg_kind(
        "autodist_sync/zero1_shard_update") == "update"
    assert prof.span_leg_kind(
        "autodist_sync/bucket_reduce/b0") == "all_reduce"
    assert prof.span_leg_kind(
        "jit(step)/autodist_sync/quant_ring_all_gather/leg1") \
        == "ppermute_hop"
    assert prof.span_leg_kind("some_matmul_fusion") is None


def test_parse_profiler_trace(tmp_path):
    """A jax-profiler-shaped trace file (gzipped Chrome JSON) yields
    trace-sourced samples for exactly the autodist_sync spans."""
    events = [
        {"name": "autodist_sync/bucket_reduce/b0", "ph": "X",
         "ts": 10.0, "dur": 250.0, "pid": 1, "tid": 1},
        {"name": "autodist_sync/ring_all_gather/leg1", "ph": "X",
         "ts": 300.0, "dur": 80.0, "pid": 1, "tid": 1},
        {"name": "fusion.42", "ph": "X", "ts": 0.0, "dur": 1000.0,
         "pid": 1, "tid": 1},
        {"name": "autodist_sync/guard_rollup", "ph": "X",
         "ts": 400.0, "dur": 5.5, "pid": 1, "tid": 1},
    ]
    sub = tmp_path / "plugins" / "profile" / "run1"
    sub.mkdir(parents=True)
    with gzip.open(sub / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    samples = prof.LegProfiler().parse_trace(str(tmp_path),
                                             schedule_fingerprint="fp9")
    kinds = sorted(s.kind for s in samples)
    assert kinds == ["all_reduce", "ppermute_hop", "psum_guard"]
    assert all(s.source == "trace" for s in samples)
    assert samples[0].schedule_fingerprint == "fp9"
    by_kind = {s.kind: s.measured_s for s in samples}
    assert by_kind["all_reduce"] == pytest.approx(250e-6)
    assert by_kind["psum_guard"] == pytest.approx(5.5e-6)


# -- leg calibration ---------------------------------------------------------

def test_fit_leg_constants_planted_roundtrip():
    """Samples generated from known per-kind constants recover those
    constants (distinct ring-hop vs one-shot alphas included)."""
    true = {"all_reduce": (2e-5, 1e10), "ppermute_hop": (5e-6, 2e10),
            "all_gather": (1e-5, 4e10), "update": (0.0, 8e11)}
    samples = []
    for kind, (a, bw) in true.items():
        for nb in (1 << 16, 1 << 18, 1 << 20, 1 << 22):
            samples.append(prof.LegSample(
                schedule_fingerprint="fp", leg_id=f"{kind}/{nb}",
                kind=kind, measured_s=a + nb / bw, nbytes=nb))
    fitted = cal.fit_leg_constants(samples)
    assert fitted is not None and fitted.n_samples == len(samples)
    for kind, (a, bw) in true.items():
        assert fitted.alphas[kind] == pytest.approx(a, abs=1e-9)
        assert fitted.bandwidths[kind] == pytest.approx(bw, rel=1e-6)
    # the ring-hop launch cost fit independently of the one-shot one
    assert fitted.alphas["ppermute_hop"] != fitted.alphas["all_reduce"]
    # round trip through the JSON schema
    back = cal.LegCalibration.from_dict(fitted.to_dict())
    assert back.bandwidths == fitted.bandwidths
    assert back.alphas == fitted.alphas


def test_fit_leg_constants_quant_overhead():
    """Quantized samples' residual over the full-precision model fits
    the quantize/dequantize per-byte overhead."""
    samples = []
    a, bw, q = 1e-5, 1e10, 3e-12
    for nb in (1 << 18, 1 << 20, 1 << 22):
        samples.append(prof.LegSample(
            schedule_fingerprint="fp", leg_id=f"f32/{nb}",
            kind="all_reduce", measured_s=a + nb / bw, nbytes=nb))
        samples.append(prof.LegSample(
            schedule_fingerprint="fp", leg_id=f"int8/{nb}",
            kind="all_reduce", measured_s=a + nb / bw + q * nb,
            nbytes=nb, compressor="Int8Compressor"))
    fitted = cal.fit_leg_constants(samples)
    assert fitted.quant_overhead_per_byte == pytest.approx(q, rel=1e-3)
    assert fitted.leg_time_s("all_reduce", 1 << 20, quantized=True) > \
        fitted.leg_time_s("all_reduce", 1 << 20)


def test_fit_leg_constants_record_scale_and_acceptance():
    """With StepRecords, the fit learns a step-level scale and scores
    leg-calibrated MAE against the whole-step fit — the acceptance
    comparison (median-anchored leg fit <= mean-anchored step fit on a
    skewed record set)."""
    samples = [prof.LegSample(
        schedule_fingerprint="fpA", leg_id=f"l{i}", kind="all_reduce",
        measured_s=1e-4, nbytes=1 << 20, slot=-1) for i in range(4)]
    rng = np.random.RandomState(0)
    records = [tl.StepRecord(
        step=i, time_unix=float(i), schedule_fingerprint="fpA",
        step_time_s=8e-4 + abs(float(rng.randn())) * 2e-4,
        exposed_bytes=4 * (1 << 20), num_collectives=4)
        for i in range(64)]
    fitted = cal.fit_leg_constants(samples, records)
    assert fitted.n_records == 64
    assert fitted.scale > 0
    pred = fitted.predict_step_time_s("fpA")
    assert pred == pytest.approx(fitted.scale * 4e-4)
    assert fitted.mean_abs_error_s is not None
    assert fitted.step_fit_mean_abs_error_s is not None
    assert fitted.improved, (
        f"leg-calibrated MAE {fitted.mean_abs_error_s} must be <= "
        f"whole-step fit MAE {fitted.step_fit_mean_abs_error_s}")
    # the whole-step pair rode along for estimate_cost consumers
    assert fitted.ici_bandwidth > 0 and fitted.alpha >= 0


def test_fit_on_committed_bench_artifacts():
    """The committed bench artifacts round-trip through the fit: leg
    samples + step records from BENCH_* produce a calibration whose
    record error meets the acceptance bar (leg-calibrated MAE <= the
    whole-step fit's)."""
    samples_path = os.path.join(REPO, "BENCH_leg_samples.jsonl")
    records_path = os.path.join(REPO, "BENCH_telemetry_steps.jsonl")
    if not (os.path.exists(samples_path) and os.path.exists(records_path)):
        pytest.skip("committed bench artifacts absent")
    samples = []
    with open(samples_path, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                samples.append(prof.LegSample.from_dict(json.loads(line)))
    records = []
    with open(records_path, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                records.append(tl.StepRecord.from_dict(json.loads(line)))
    assert samples, "committed leg samples are empty"
    fitted = cal.fit_leg_constants(samples, records)
    assert fitted is not None
    assert set(fitted.bandwidths)
    step_fit = cal.fit_constants(records)
    assert step_fit is not None
    if fitted.mean_abs_error_s is not None:
        assert fitted.mean_abs_error_s <= step_fit.mean_abs_error_s + 1e-9
    # and the committed calibration.json (when present) parses
    committed = cal.load_calibration(
        os.path.join(REPO, "calibration.json"))
    if committed is not None:
        assert committed.version == cal.CALIBRATION_VERSION
        assert committed.bandwidths


def test_calibration_json_roundtrip_and_discovery(tmp_path, monkeypatch):
    fitted = cal.LegCalibration(
        alphas={"all_reduce": 1e-5}, bandwidths={"all_reduce": 1e10},
        ici_bandwidth=2e10, alpha=3e-6, n_samples=7)
    path = cal.save_calibration(fitted, str(tmp_path / "calibration.json"))
    assert cal.load_calibration(path).bandwidths == fitted.bandwidths
    # no env -> no automatic discovery (estimates stay reproducible)
    assert cal.load_default_calibration() is None
    monkeypatch.setenv("AUTODIST_CALIBRATION", path)
    cal.reset_calibration_cache_for_testing()
    got = cal.load_default_calibration()
    assert got is not None and got.ici_bandwidth == 2e10
    # TELEMETRY_DIR discovery path
    monkeypatch.delenv("AUTODIST_CALIBRATION")
    monkeypatch.setenv("AUTODIST_TELEMETRY_DIR", str(tmp_path))
    cal.reset_calibration_cache_for_testing()
    assert cal.load_default_calibration().ici_bandwidth == 2e10
    # corrupt file degrades to None, never raises
    with open(path, "w") as f:
        f.write("{not json")
    cal.reset_calibration_cache_for_testing()
    assert cal.load_default_calibration() is None


def test_estimate_ir_cost_consumes_leg_constants(monkeypatch, tmp_path):
    """The leg-calibrated path changes the estimate (per-kind pricing +
    the update term), and the environment-discovered calibration.json
    is picked up with NO flags."""
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    ir = _zero1_ir()
    base = estimate_ir_cost(ir)
    slow = cal.LegCalibration(
        alphas={k: 1e-3 for k in cal.LEG_KINDS},
        bandwidths={k: 1e6 for k in cal.LEG_KINDS})
    fast = cal.LegCalibration(
        alphas={k: 0.0 for k in cal.LEG_KINDS},
        bandwidths={k: 1e15 for k in cal.LEG_KINDS})
    t_slow = estimate_ir_cost(ir, constants=slow).time_s
    t_fast = estimate_ir_cost(ir, constants=fast).time_s
    assert t_slow > base.time_s > t_fast
    # byte accounting is calibration-independent
    assert estimate_ir_cost(ir, constants=slow).wire_bytes == \
        base.wire_bytes
    # automatic discovery: same result as passing constants explicitly
    path = cal.save_calibration(slow, str(tmp_path / "calibration.json"))
    monkeypatch.setenv("AUTODIST_CALIBRATION", path)
    cal.reset_calibration_cache_for_testing()
    assert estimate_ir_cost(ir).time_s == pytest.approx(t_slow)


def test_auto_strategy_consumes_calibration(monkeypatch, tmp_path):
    """AutoStrategy(search=True) ranks with calibration.json constants
    without flags: launch-dominated constants flip the big-dense pick
    from Zero1 (the wire/update-dominated default) to AllReduce (one
    collective launch) — proof the constants reach the ranking."""
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AutoStrategy

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8, "chief": True}]})
    gi = GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32)})
    baseline = AutoStrategy(search=True)
    baseline.build(gi, spec)
    assert baseline.last_choice == "Zero1"

    path = cal.save_calibration(
        cal.LegCalibration(ici_bandwidth=1e15, alpha=1.0),
        str(tmp_path / "calibration.json"))
    monkeypatch.setenv("AUTODIST_CALIBRATION", path)
    cal.reset_calibration_cache_for_testing()
    calibrated = AutoStrategy(search=True)
    calibrated.build(gi, spec)
    assert calibrated.last_choice == "AllReduce"

    # sane measured constants CONFIRM the default pick (calibration
    # changes the ranking only when measurement disagrees)
    path2 = cal.save_calibration(
        cal.LegCalibration(ici_bandwidth=4.5e10, alpha=5e-6),
        str(tmp_path / "calibration2.json"))
    monkeypatch.setenv("AUTODIST_CALIBRATION", path2)
    cal.reset_calibration_cache_for_testing()
    confirmed = AutoStrategy(search=True)
    confirmed.build(gi, spec)
    assert confirmed.last_choice == "Zero1"


# -- trace export ------------------------------------------------------------

def _assert_valid_chrome_trace(payload):
    """The Trace Event Format contract Perfetto's importer enforces:
    a traceEvents array of objects, each with a string name, a known
    phase, numeric non-negative ts (except metadata), and a numeric
    dur on complete events; pids/tids integral."""
    assert isinstance(payload, dict)
    events = payload["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert isinstance(ev["name"], str) and ev["name"]
        assert ev["ph"] in ("X", "i", "M", "B", "E", "C")
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "i":
            assert ev.get("s") in ("t", "p", "g")
    return events


def _make_run_dir(tmp_path, hosts=("hostA", "hostB")):
    """A run directory holding all four streams across two hosts."""
    run = tmp_path / "run"
    run.mkdir()
    t0 = 1000.0
    for hi, host in enumerate(hosts):
        with open(run / f"steps-{host}-{100 + hi}.jsonl", "w") as f:
            for i in range(6):
                r = tl.StepRecord(
                    step=i, time_unix=t0 + i * 0.01 + 0.01,
                    step_time_s=0.01 * (1 + hi), host=host,
                    phases={"data_load": 0.001, "dispatch": 0.002},
                    loss=1.0 / (i + 1), schedule_fingerprint="fpX")
                f.write(r.to_json() + "\n")
        with open(run / f"events-{host}-{100 + hi}.jsonl", "w") as f:
            f.write(json.dumps({"time": t0 + 0.02, "kind": "chaos/kill",
                                "host": host, "pid": 100 + hi,
                                "step": 2}) + "\n")
    prof.write_leg_samples(
        [prof.LegSample(schedule_fingerprint="fpX", leg_id="b0@-1/reduce",
                        kind="reduce_scatter", measured_s=2e-4,
                        nbytes=1 << 20, predicted_s=1e-4, host=hosts[0],
                        time_unix=t0 + 0.005)], str(run))
    w = prof._SpanWriter(directory=str(run))
    w.record("queue_wait", start_unix=t0 + 0.03, dur_s=0.002,
             trace_id="t123", request_id=7, slo="latency")
    w.record("request", start_unix=t0 + 0.03, dur_s=0.05,
             trace_id="t123", request_id=7)
    w.close()
    return run


def test_export_trace_golden(tmp_path):
    """One merged trace file from a run directory holding StepRecords,
    journal events, leg samples, and serving spans — valid Chrome
    trace, per-host process tracks, every stream represented, trace id
    preserved."""
    run = _make_run_dir(tmp_path)
    path = tx.export_trace(str(run))
    assert path == str(run / "trace.json")
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    events = _assert_valid_chrome_trace(payload)
    # per-host process tracks
    names = [e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert "hostA" in names and "hostB" in names
    cats = {e.get("cat") for e in events if e["ph"] != "M"}
    assert {"train", "phase", "leg", "event", "serving"} <= cats
    # steps from both hosts landed with their phases nested inside
    steps = [e for e in events if e.get("cat") == "train"]
    assert len(steps) == 12          # 2 hosts x 6 steps, all timed
    # the serving spans carry the propagated trace id
    serving = [e for e in events if e.get("cat") == "serving"]
    assert serving and all(
        e["args"]["trace_id"] == "t123" for e in serving)
    # stream counts in the exporter's own provenance
    streams = payload["otherData"]["streams"]
    assert streams["serving_spans"] == 2
    assert streams["leg_samples"] == 1
    assert streams["journal_events"] == 2
    # empty directory -> nothing to export
    empty = tmp_path / "empty"
    empty.mkdir()
    assert tx.export_trace(str(empty)) is None


# -- cross-host aggregation --------------------------------------------------

def test_registry_snapshot_merge_exact(tmp_path):
    """Two hosts' registry snapshots merge into exactly what one global
    registry would hold (fixed-bound histograms + counters)."""
    bounds = (0.01, 0.1, 1.0)
    rng = np.random.RandomState(3)
    a, b = reg.MetricsRegistry(), reg.MetricsRegistry()
    oracle = reg.Histogram("lat_seconds", buckets=bounds)
    for r_, n in ((a, 50), (b, 77)):
        h = r_.histogram("lat_seconds", buckets=bounds)
        for v in rng.uniform(0, 2, n):
            h.observe(v)
            oracle.observe(v)
        r_.counter("steps_total").inc(n)
    agg.write_registry_snapshot(str(tmp_path), a)
    # distinct filename per writer: fake a second host's snapshot
    with open(tmp_path / "metrics-hostB-42.json", "w") as f:
        json.dump(b.to_dict(), f)
    merged = agg.merge_registry_snapshots(str(tmp_path))
    h = merged.histogram("lat_seconds", buckets=bounds)
    assert h.counts == oracle.counts and h.count == oracle.count
    assert merged.counter("steps_total").value == 127


def test_per_host_stats_and_straggler(tmp_path):
    run = _make_run_dir(tmp_path)          # hostB is 2x hostA
    records = tl.load_step_records(str(run))
    hosts = agg.per_host_step_stats(records)
    assert set(hosts) == {"hostA", "hostB"}
    assert hosts["hostA"]["median_s"] == pytest.approx(0.01)
    assert hosts["hostB"]["median_s"] == pytest.approx(0.02)
    out = agg.aggregate_run(str(run))
    assert out["step_skew_ratio"] == pytest.approx(2.0)
    assert out["straggler"] and "hostB" in out["straggler"]
    assert out["straggler_count"] == 1
    # the fleet gauges landed on the process registry
    vals = {m.name: m.value for m in reg.DEFAULT_REGISTRY.metrics()}
    assert vals["autodist_host_step_skew_ratio"] == pytest.approx(2.0)
    assert vals["autodist_straggler_count"] == 1
    # single-host runs are never stragglers
    assert cal.straggler_reason({"only": 0.5}) is None
    assert cal.straggler_reason(
        {"a": 0.010, "b": 0.014}) is None       # under 1.5x


# -- analysis rules ----------------------------------------------------------

def test_leg_drift_and_straggler_lint():
    """The telemetry pass surfaces the new rules from provenance via
    the shared pure rule strings."""
    from tests._analysis_fixtures import AXES8, full_cover, make_gi

    from autodist_tpu.analysis import analyze

    gi = make_gi()
    strat = full_cover(gi)
    tel = {
        "measured_step_time_s": 0.010, "predicted_step_time_s": 0.009,
        "leg_kinds": {
            "reduce_scatter": {"measured_s": 9e-4, "predicted_s": 1e-4},
            "all_gather": {"measured_s": 1.1e-4, "predicted_s": 1e-4},
        },
        "per_host_step_time_s": {"h0": 0.010, "h1": 0.021},
    }
    report = analyze(strat, gi, mesh=AXES8, telemetry=tel,
                     passes=("telemetry",))
    rules = [d.rule for d in report.diagnostics]
    assert "telemetry/leg-drift" in rules
    assert "telemetry/straggler" in rules
    assert "telemetry/model-drift" not in rules     # step ratio is fine
    drift = next(d for d in report.diagnostics
                 if d.rule == "telemetry/leg-drift")
    assert drift.message == cal.leg_drift_reason(
        "reduce_scatter", 9e-4, 1e-4)
    assert drift.location == "reduce_scatter"       # WHICH kind drifted
    straggler = next(d for d in report.diagnostics
                     if d.rule == "telemetry/straggler")
    assert straggler.message == cal.straggler_reason(
        {"h0": 0.010, "h1": 0.021})
    # aggregate_run output accepted directly (hosts mapping)
    report2 = analyze(strat, gi, mesh=AXES8, passes=("telemetry",),
                      telemetry={"hosts": {
                          "h0": {"median_s": 0.010},
                          "h1": {"median_s": 0.030}}})
    assert any(d.rule == "telemetry/straggler"
               for d in report2.diagnostics)


# -- serving request tracing -------------------------------------------------

@pytest.fixture(scope="module")
def lm():
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm

    spec = transformer_lm(vocab_size=61, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


def test_scheduler_emits_request_spans(lm, tmp_path):
    """A paged request submitted with a trace id lands queue-wait /
    prefill / decode spans tagged with that id in the span stream, and
    pop_timings carries the id for the HTTP layer."""
    from autodist_tpu.serving import PagedDecodeEngine

    prof.configure_spans(str(tmp_path))
    spec, params = lm
    eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                            block_size=8, num_blocks=24, chunk=4)
    rng = np.random.RandomState(0)
    rid = eng.submit(rng.randint(0, 61, 4).astype(np.int32), 5,
                     trace_id="trace-xyz")
    results = eng.run()
    assert rid in results
    timings = eng.pop_timings()
    assert timings[rid]["trace_id"] == "trace-xyz"
    spans = prof.load_spans(str(tmp_path))
    by_name = {s["name"]: s for s in spans}
    assert {"queue_wait", "prefill", "decode"} <= set(by_name)
    for s in spans:
        assert s["trace_id"] == "trace-xyz"
        assert s["dur_s"] >= 0 and s["start_unix"] > 0
    assert by_name["decode"]["args"]["generated"] == 5
    # spans order: queue_wait starts <= prefill starts <= decode starts
    assert by_name["queue_wait"]["start_unix"] <= \
        by_name["prefill"]["start_unix"] <= \
        by_name["decode"]["start_unix"]
    eng.assert_no_leaks()


def test_router_trace_id_propagation_and_fallback():
    """The router passes one trace id per logical request to endpoints
    that accept it, and degrades cleanly for duck-typed endpoints that
    predate trace propagation."""
    from autodist_tpu.serving.router import Router

    seen = {}

    class Traced:
        name = "traced"

        def probe(self, timeout=2.0):
            return True

        def fetch_stats(self):
            return {"outstanding": 0}

        def post(self, body, timeout, trace_id=""):
            seen["trace_id"] = trace_id
            return 200, {"ok": True}

    class Legacy:
        name = "legacy"

        def probe(self, timeout=2.0):
            return True

        def fetch_stats(self):
            return {"outstanding": 0}

        def post(self, body, timeout):
            seen["legacy"] = True
            return 200, {"ok": True}

    r = Router([Traced()])
    assert r.complete({"prompt_tokens": [1]})["ok"]
    assert seen["trace_id"]                     # non-empty id propagated
    r2 = Router([Legacy()])
    assert r2.complete({"prompt_tokens": [1]})["ok"]
    assert seen.get("legacy")                   # old signature still works


# -- CLI ---------------------------------------------------------------------

def test_cli_export_trace_and_compare(tmp_path, capsys):
    from autodist_tpu.telemetry.__main__ import main

    run_a = _make_run_dir(tmp_path)
    # run B: same shape, hostA 30% slower -> a step-time regression
    run_b = tmp_path / "run_b"
    run_b.mkdir()
    with open(run_b / "steps-hostA-100.jsonl", "w") as f:
        for i in range(6):
            r = tl.StepRecord(step=i, time_unix=2000.0 + i * 0.02,
                              step_time_s=0.013, host="hostA",
                              phases={"data_load": 0.004})
            f.write(r.to_json() + "\n")
    prof.write_leg_samples(
        [prof.LegSample(schedule_fingerprint="fpX", leg_id="b0@-1/reduce",
                        kind="reduce_scatter", measured_s=9e-4,
                        nbytes=1 << 20, predicted_s=1e-4,
                        time_unix=2000.0)], str(run_b))

    assert main([str(run_a), "--export-trace"]) == 0
    out = capsys.readouterr().out
    assert "trace.json" in out
    with open(run_a / "trace.json", encoding="utf-8") as f:
        _assert_valid_chrome_trace(json.load(f))

    assert main([str(run_a), "--compare", str(run_b), "--json"]) == 0
    cmp = json.loads(capsys.readouterr().out)
    # hostA went 10ms -> 13ms, but run_a's p50 includes hostB's 20ms
    assert cmp["step_time"]["p50_ms"]["a"] is not None
    assert cmp["leg_kinds"]["reduce_scatter"]["delta_pct"] > 3
    assert "drift" in cmp["leg_kinds"]["reduce_scatter"]
    assert any("reduce_scatter" in r for r in cmp["regressions"])
    # human form renders without blowing up
    assert main([str(run_a), "--compare", str(run_b)]) == 0
    human = capsys.readouterr().out
    assert "REGRESSIONS" in human
    # summary path picks up hosts + leg kinds + straggler
    assert main([str(run_a)]) == 0
    summary = capsys.readouterr().out
    assert "telemetry/straggler" in summary
    assert "leg reduce_scatter" in summary


def test_cli_fit_saves_calibration(tmp_path, capsys):
    from autodist_tpu.telemetry.__main__ import main

    run = _make_run_dir(tmp_path)
    assert main([str(run), "--fit", "--save-calibration", "-",
                 "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["leg_calibration"]["n_samples"] == 1
    saved = cal.load_calibration(str(run / "calibration.json"))
    assert saved is not None and "reduce_scatter" in saved.bandwidths


def test_profile_ir_on_real_session_mesh():
    """End to end on a live session: the session's verified IR
    micro-profiles on its own mesh, samples join records through
    fit_leg_constants, and the calibrated estimate_ir_cost prices the
    same IR (the bench child's loop in miniature)."""
    import optax

    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(64, 64) * 0.05, jnp.float32)}
    batch = {"x": rng.randn(8, 64).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"]) ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=1 << 16))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(1e-3),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    ir = sess.schedule_ir
    assert ir is not None
    samples = prof.LegProfiler(mesh=sess.mesh, warmup=1,
                               repeats=2).profile_ir(ir)
    assert len(samples) == len(ir.legs)
    for _ in range(4):
        sess.run(batch)
    records = sess.telemetry.records if sess.telemetry else []
    fitted = cal.fit_leg_constants(samples, records)
    assert fitted is not None
    report = estimate_ir_cost(ir, constants=fitted)
    assert report.time_s > 0
    _reset_default_autodist_for_testing()
