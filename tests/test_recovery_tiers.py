"""Fast-recovery checkpoint tiers (docs/resilience.md): RAM snapshot
ring + digest rule, peer mirroring, restore routing, deadline-aware
preemption, DRAINING heartbeats, goodput math, and the new chaos
grammar.  The multiprocess kill → survivor-peer-restore drill lives in
``tests/integration/recovery_drill.py`` (driven by the slow-tagged test
at the bottom)."""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    from autodist_tpu.autodist import _reset_default_autodist_for_testing
    from autodist_tpu.checkpoint import saver as saver_mod

    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    monkeypatch.delenv("AUTODIST_PREEMPT_GRACE_S", raising=False)
    monkeypatch.delenv("AUTODIST_SNAPSHOT_EVERY", raising=False)
    _reset_default_autodist_for_testing()
    yield
    saver_mod.clear_save_hooks()


def _linear_session(lr=1e-2):
    import jax.numpy as jnp
    import optax

    from autodist_tpu.autodist import (
        AutoDist, _reset_default_autodist_for_testing)
    from autodist_tpu.strategy import AllReduce

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    params = {"linear": {"w": jnp.zeros((8, 4), jnp.float32),
                         "b": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(p, b):
        pred = b["x"] @ p["linear"]["w"] + p["linear"]["b"]
        return jnp.mean((pred - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(lr),
                   loss_fn=loss_fn)
    return ad.create_distributed_session(), \
        {"x": x, "y": (x @ w).astype(np.float32)}


# ---------------------------------------------------------------------------
# snapshot ring + digest rule
# ---------------------------------------------------------------------------

def test_snapshot_ring_keeps_last_k_and_drops_tampered():
    from autodist_tpu.checkpoint.tiers import RamSnapshot, SnapshotRing
    from autodist_tpu.checkpoint.saver import _tree_digest

    def snap(step, value):
        leaves = {"params": [np.full((4,), value, np.float32)],
                  "opt_state": [np.zeros((2,), np.float32)]}
        return RamSnapshot(step=step, leaves=leaves,
                           digest=_tree_digest([leaves[k]
                                                for k in sorted(leaves)]))

    ring = SnapshotRing(keep=2)
    for s in (2, 4, 6):
        ring.add(snap(s, float(s)))
    assert ring.steps() == [4, 6]          # keep=2 evicted step 2
    assert ring.latest().step == 6
    assert ring.nbytes > 0

    # tamper with the newest: the digest re-check drops it and latest()
    # falls back to the previous snapshot (the Saver.latest_step analog)
    ring.get(6).leaves["params"][0][0] = 999.0
    assert ring.latest().step == 4
    assert ring.steps() == [4]

    with pytest.raises(ValueError):
        SnapshotRing(keep=0)


def test_snapshot_serialization_roundtrip_and_corruption():
    from autodist_tpu.checkpoint.tiers import (
        RamSnapshot, SnapshotError, snapshot_from_bytes, snapshot_to_bytes)
    from autodist_tpu.checkpoint.saver import _tree_digest

    leaves = {"params": [np.arange(12, dtype=np.float32).reshape(3, 4),
                         np.ones((2,), np.int32)],
              "opt_state": [np.zeros((5,), np.float32)]}
    snap = RamSnapshot(step=7, leaves=leaves,
                       digest=_tree_digest([leaves[k]
                                            for k in sorted(leaves)]),
                       meta={"mesh_axes": {"data": 1},
                             "data_state": {"epoch": 1, "offset": 3}})
    blob = snapshot_to_bytes(snap)
    back = snapshot_from_bytes(blob)
    assert back.step == 7 and back.verify()
    assert back.meta["data_state"] == {"epoch": 1, "offset": 3}
    for item in leaves:
        for a, b in zip(leaves[item], back.leaves[item]):
            np.testing.assert_array_equal(a, b)

    with pytest.raises(SnapshotError):
        snapshot_from_bytes(blob[: len(blob) // 2])   # truncated wire blob


def test_peer_mirror_push_fetch_retention_and_digest(tmp_path):
    from autodist_tpu.checkpoint.tiers import (
        PeerMirror, RamSnapshot, buddy_of, snapshot_to_bytes)
    from autodist_tpu.checkpoint.saver import _tree_digest

    assert buddy_of(["a", "b", "c"], "a") == "b"
    assert buddy_of(["a", "b", "c"], "c") == "a"
    assert buddy_of(["a"], "a") is None
    assert buddy_of(["a", "b"], "zz") is None

    mirror = PeerMirror(str(tmp_path / "peer"), keep=2)

    def snap(step):
        leaves = {"params": [np.full((3,), float(step), np.float32)],
                  "opt_state": [np.zeros((2,), np.float32)]}
        return RamSnapshot(step=step, leaves=leaves,
                           digest=_tree_digest([leaves[k]
                                                for k in sorted(leaves)]))

    for s in (2, 4, 6):
        mirror.push(snap(s), owner="proc0")
    assert mirror.steps("proc0") == [4, 6]     # retention on the mirror
    got = mirror.fetch("proc0")
    assert got.step == 6 and got.verify()

    # corrupt the newest mirrored blob: fetch skips to the previous one
    path = os.path.join(str(tmp_path / "peer"), "proc0",
                        "snap_step_6.npz")
    with open(path, "wb") as f:
        f.write(b"garbage")
    assert mirror.fetch("proc0").step == 4
    # fetch_any finds the other owner's newest
    mirror.push(snap(8), owner="proc1")
    assert mirror.fetch_any().step == 8
    mirror.clear()
    assert mirror.owners() == []


# ---------------------------------------------------------------------------
# restore routing
# ---------------------------------------------------------------------------

def test_route_restore_newest_wins_and_falls_through(tmp_path):
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.checkpoint.tiers import (
        CheckpointTiers, route_restore)

    sess, batch = _linear_session()
    ckpt = str(tmp_path / "ck")
    peer = str(tmp_path / "peer")
    tiers = CheckpointTiers(sess, snapshot_every=1, keep=3, peer_dir=peer)
    saver = Saver(sess)

    sess.run(batch)
    saver.save(ckpt, step=1)           # persistent @1
    sess.run(batch)
    tiers.snapshot(step=2)             # ram+peer @2 (newer)
    w2 = np.asarray(sess.params["linear"]["w"]).copy()
    sess.run(batch)                    # step 3 never snapshotted

    # newest usable state is the RAM snapshot @2
    fresh, _ = _linear_session()
    t_fresh = CheckpointTiers(fresh, snapshot_every=1, peer_dir=peer)
    step, tier, meta = route_restore(fresh, ckpt, tiers=t_fresh)
    assert (step, tier) == (2, "peer")   # fresh process: ring empty
    np.testing.assert_array_equal(
        np.asarray(fresh.params["linear"]["w"]), w2)

    # the ORIGINAL process still holds the ring: ram wins the tie
    step, tier, _ = route_restore(sess, ckpt, tiers=tiers)
    assert (step, tier) == (2, "ram")

    # corrupt every peer blob: routing falls through to persistent @1
    import shutil
    shutil.rmtree(peer)
    fresh2, _ = _linear_session()
    t2 = CheckpointTiers(fresh2, snapshot_every=1, peer_dir=peer)
    step, tier, _ = route_restore(fresh2, ckpt, tiers=t2)
    assert (step, tier) == (1, "persistent")

    # nothing anywhere -> None
    fresh3, _ = _linear_session()
    assert route_restore(fresh3, str(tmp_path / "empty")) is None


def test_fit_snapshot_every_and_peer_resume_parity(tmp_path):
    """fit(snapshot_every=K) populates the tiers mid-run; a fresh
    process resumes from the PEER tier alone (no persistent dir) and —
    because it replays the lost tail deterministically — lands on
    exactly the oracle's parameters, having lost at most K steps."""
    from autodist_tpu.checkpoint.tiers import CheckpointTiers
    from autodist_tpu.runtime.data_loader import DataLoader

    peer = str(tmp_path / "peer")

    def loader():
        rng = np.random.RandomState(1)
        return DataLoader({"x": rng.randn(32, 8).astype(np.float32),
                           "y": rng.randn(32, 4).astype(np.float32)},
                          batch_size=8, shuffle=True, seed=7)

    # oracle: 3 epochs uninterrupted
    oracle, _ = _linear_session()
    oracle.fit(loader(), epochs=3)
    w_oracle = np.asarray(oracle.params["linear"]["w"]).copy()

    # attempt A: runs 2 of 3 epochs with the RAM tier, then "dies"
    a, _ = _linear_session()
    hist = a.fit(loader(), epochs=2, snapshot_every=2, snapshot_dir=peer)
    assert hist.steps_run == 8
    assert os.path.isdir(peer)

    # attempt B: fresh process, peer tier only (ring empty, no
    # persistent checkpoints anywhere) — must resume ≤2 steps back and
    # complete to the oracle's trajectory exactly
    b, _ = _linear_session()
    tiers_b = CheckpointTiers(b, snapshot_every=2, peer_dir=peer)
    hist_b = b.fit(loader(), epochs=3, tiers=tiers_b, resume=True)
    assert hist_b.resume_tier == "peer"
    assert b.step_count == 12
    # at most snapshot_every steps were replayed beyond the remaining
    # epoch: 12 total - resumed step (8) = 4 = one epoch, no extra loss
    assert hist_b.steps_run <= 4 + 2
    np.testing.assert_allclose(np.asarray(b.params["linear"]["w"]),
                               w_oracle, rtol=1e-6, atol=1e-7)
    # per-attempt goodput accounting rode along
    assert hist_b.goodput and hist_b.goodput["steps"] == hist_b.steps_run


# ---------------------------------------------------------------------------
# deadline-aware preemption
# ---------------------------------------------------------------------------

def _preempt_fit(sess, batch, tmp_path, grace=None, stall=0.0,
                 snapshot_every=2):
    """Run fit with a chaos preemption at step 3 under the given grace/
    storage conditions; returns (history, ckpt_dir, peer_dir)."""
    from autodist_tpu.checkpoint import saver as saver_mod
    from autodist_tpu.resilience import ChaosCallback, ChaosMonkey
    from autodist_tpu.resilience.chaos import parse_chaos

    ckpt = str(tmp_path / "ck")
    peer = str(tmp_path / "peer")
    spec = "preempt@step=3,signal=SIGUSR1" + \
        (f",grace={grace}" if grace is not None else "")
    if stall:
        saver_mod.set_storage_stall(stall)
    monkey = ChaosMonkey(parse_chaos(spec))
    hist = sess.fit({"x": batch["x"], "y": batch["y"]},
                    epochs=2, steps_per_epoch=4,
                    checkpoint_dir=ckpt, checkpoint_every=1,
                    snapshot_every=snapshot_every, snapshot_dir=peer,
                    callbacks=[ChaosCallback(monkey)],
                    preemption_signals=("SIGUSR1",))
    return hist, ckpt, peer


def test_preempt_without_grace_takes_persistent_tier(tmp_path):
    from autodist_tpu.checkpoint import Saver

    sess, batch = _linear_session()
    hist, ckpt, _ = _preempt_fit(sess, batch, tmp_path, grace=None)
    assert hist.preempted and hist.preempt_tier == "persistent"
    assert Saver.latest_step(ckpt) == 3     # saved AT the preempted step


def test_preempt_grace_routes_to_peer_tier(tmp_path, monkeypatch):
    """A tight grace deadline with slow storage: the persistent save
    cannot finish, so the emergency snapshot goes to the peer tier and
    the persistent dir gains NO step at the preempted step."""
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.checkpoint.tiers import PeerMirror
    from autodist_tpu.telemetry import get_journal

    sess, batch = _linear_session()
    # tiny grace + a measured slow save (the storage stall inflates the
    # first epoch save's measured duration past the deadline)
    hist, ckpt, peer = _preempt_fit(sess, batch, tmp_path,
                                    grace=0.05, stall=0.2)
    assert hist.preempted and hist.preempt_tier == "peer"
    # the peer tier holds the preempted step; persistent stayed behind
    assert PeerMirror(peer).fetch_any().step == 3
    assert (Saver.latest_step(ckpt) or 0) < 3
    kinds = [e.get("kind") for e in get_journal().events]
    assert "checkpoint/preempt_decision" in kinds

    # and the resumed fit routes through the PEER tier to step 3
    sess2, _ = _linear_session()
    hist2 = sess2.fit({"x": batch["x"], "y": batch["y"]},
                      epochs=2, steps_per_epoch=4, checkpoint_dir=ckpt,
                      snapshot_every=2, snapshot_dir=peer)
    assert hist2.resume_tier == "peer"
    # dict data has no loader state: the partial epoch re-runs (steps
    # 4..7), then epoch 1 — Keras initial_epoch semantics
    assert not hist2.preempted and sess2.step_count == 11


# ---------------------------------------------------------------------------
# supervisor: preemption exit code is budget-free
# ---------------------------------------------------------------------------

def _proc(code: int) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", f"raise SystemExit({code})"],
        start_new_session=True)


def test_supervisor_preempt_relaunch_keeps_restart_budget(tmp_path):
    """Two preemption exits (75) then success, under max_restarts=0:
    a real failure would give up immediately, preemptions must not."""
    from autodist_tpu.resilience import (
        Backoff, PREEMPTED_EXIT_CODE, Supervisor, SupervisorPolicy)

    assert PREEMPTED_EXIT_CODE == 75
    codes = [75, 75, 0]

    def launch(att):
        return _proc(codes[att.index])

    policy = SupervisorPolicy(
        max_restarts=0,
        backoff=Backoff(max_tries=8, base=0.01, cap=0.02, jitter=0,
                        seed=0),
        poll_interval=0.02)
    sup = Supervisor(policy, hosts=["a"], workdir=str(tmp_path))
    report = sup.run(launch)
    assert report.ok and report.attempts == 3
    assert report.preemptions == 2
    assert all(f.kind == "preempt" for f in report.failures)

    # the backstop still bounds a pathological preemption loop
    policy2 = SupervisorPolicy(
        max_restarts=0, max_preemptions=2,
        backoff=Backoff(max_tries=8, base=0.01, cap=0.02, jitter=0,
                        seed=0),
        poll_interval=0.02)
    sup2 = Supervisor(policy2, hosts=["a"],
                      workdir=str(tmp_path / "w2"))
    report2 = sup2.run(lambda att: _proc(75))
    assert not report2.ok and "preemption backstop" in report2.gave_up


# ---------------------------------------------------------------------------
# heartbeats: DRAINING + phase-tagged checkpoint stalls
# ---------------------------------------------------------------------------

def test_heartbeat_draining_not_wedged(tmp_path):
    from autodist_tpu.resilience.heartbeat import (
        ALIVE, DRAINING, HeartbeatMonitor, HeartbeatWriter, WEDGED)

    d = str(tmp_path)
    w = HeartbeatWriter(d, "w1", interval=60)
    mon = HeartbeatMonitor(d, timeout=30.0, step_timeout=0.05)
    w.beat(step=5)
    assert mon.check("w1").state == ALIVE
    time.sleep(0.1)
    w.set_phase("draining")                 # grace window opens
    h = mon.check("w1")
    assert h.state == DRAINING and "drain" in h.detail
    assert "w1" not in mon.failures()       # draining is NOT a failure
    w.set_phase(None)
    time.sleep(0.1)
    w.beat(step=5)                          # stall persists, no phase
    assert mon.check("w1").state == WEDGED


def test_heartbeat_checkpoint_phase_suppresses_step_stall(tmp_path):
    from autodist_tpu.resilience.heartbeat import (
        ALIVE, HeartbeatMonitor, HeartbeatWriter, heartbeat_phase,
        set_active_writer)

    d = str(tmp_path)
    w = HeartbeatWriter(d, "w1", interval=60)
    mon = HeartbeatMonitor(d, timeout=30.0, step_timeout=0.05)
    w.beat(step=9)
    mon.check("w1")
    time.sleep(0.1)
    set_active_writer(w)
    try:
        with heartbeat_phase("checkpoint/restore"):
            h = mon.check("w1")
            assert h.state == ALIVE and "phase-tagged" in h.detail
    finally:
        set_active_writer(None)
    # phase cleared, stall still there -> the wedge verdict returns
    w.beat(step=9)
    assert mon.check("w1").state == "wedged"


def test_saver_save_bumps_heartbeat_phase(tmp_path):
    """Saver.save on a registered writer leaves phase-tagged beacons —
    the satellite: long saves can't trip the step_timeout verdict."""
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience.heartbeat import (
        HeartbeatWriter, set_active_writer)

    sess, batch = _linear_session()
    sess.run(batch)
    w = HeartbeatWriter(str(tmp_path / "hb"), "w0", interval=60)
    seen = []
    orig = w.beat

    def spy_beat(*a, **kw):
        seen.append(w._phase)
        return orig(*a, **kw)

    w.beat = spy_beat
    set_active_writer(w)
    try:
        Saver(sess).save(str(tmp_path / "ck"))
    finally:
        set_active_writer(None)
    assert "checkpoint/save" in seen


# ---------------------------------------------------------------------------
# chaos grammar: storage_stall, kill during=save
# ---------------------------------------------------------------------------

def test_chaos_storage_stall_blocks_saves(tmp_path):
    from autodist_tpu.checkpoint import Saver, saver as saver_mod
    from autodist_tpu.resilience import ChaosMonkey
    from autodist_tpu.resilience.chaos import parse_chaos

    sess, batch = _linear_session()
    sess.run(batch)
    monkey = ChaosMonkey(parse_chaos("storage_stall@step=1,seconds=0.15"),
                         process_index=0)
    monkey.on_step(1)
    t0 = time.perf_counter()
    Saver(sess).save(str(tmp_path / "ck"))
    assert time.perf_counter() - t0 >= 0.15
    saver_mod.set_storage_stall(0)


def test_chaos_kill_during_save_arms_pre_save_hook(tmp_path):
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience import ChaosMonkey
    from autodist_tpu.resilience.chaos import parse_chaos

    sess, batch = _linear_session()
    sess.run(batch)
    monkey = ChaosMonkey(parse_chaos("kill@step=1,during=save,code=43"),
                         process_index=0)
    exits = []
    monkey._exit = exits.append          # the documented test seam
    monkey.on_step(1)
    assert exits == []                   # NOT dead at the step boundary
    Saver(sess).save(str(tmp_path / "ck"))
    assert exits == [43]                 # died INSIDE the save


def test_chaos_preempt_grace_stamps_env(monkeypatch):
    from autodist_tpu.resilience import ChaosMonkey
    from autodist_tpu.resilience.chaos import parse_chaos

    monkeypatch.delenv("AUTODIST_PREEMPT_GRACE_S", raising=False)
    fired = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: fired.append(sig))
    monkey = ChaosMonkey(parse_chaos("preempt@step=2,grace=3.5"),
                         process_index=0)
    monkey.on_step(2)
    assert fired == [signal.SIGTERM]
    assert os.environ["AUTODIST_PREEMPT_GRACE_S"] == "3.5"


# ---------------------------------------------------------------------------
# fit durability: the finally-wait satellite
# ---------------------------------------------------------------------------

def test_fit_exception_path_waits_for_async_save(tmp_path):
    """A callback crash racing an ASYNC save: the finally must make the
    in-flight save durable before fit unwinds, so the step dir commits
    instead of stranding half-written."""
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.fit import Callback

    sess, batch = _linear_session()
    ckpt = str(tmp_path / "ck")

    class Bomb(Callback):
        def on_epoch_begin(self, epoch):
            if epoch == 2:
                # the epoch-1 async save is still in flight right here
                raise RuntimeError("boom with a save in flight")

    with pytest.raises(RuntimeError, match="boom"):
        sess.fit({"x": batch["x"], "y": batch["y"]}, epochs=3,
                 steps_per_epoch=2, checkpoint_dir=ckpt,
                 checkpoint_every=1, async_checkpoints=True,
                 callbacks=[Bomb()])
    # both epoch saves are committed and verify cleanly
    assert Saver.latest_step(ckpt) == 4
    assert Saver.verify(os.path.join(ckpt, "step_4"), deep=True)


# ---------------------------------------------------------------------------
# goodput math + recovery-gap rule
# ---------------------------------------------------------------------------

def test_goodput_decomposition_pure_math():
    from autodist_tpu.telemetry import StepRecord
    from autodist_tpu.telemetry.goodput import (
        attempt_goodput, checkpoint_cadence, goodput_from_run)

    t0 = 1000.0
    records = [StepRecord(step=s, time_unix=t0 + s, step_time_s=0.1,
                          host="h0") for s in range(1, 9)]
    # steps 5..6 re-run after the restart (recorded twice)
    records += [StepRecord(step=s, time_unix=t0 + 20 + s, step_time_s=0.1,
                           host="h0") for s in (5, 6)]
    events = [
        {"time": t0, "kind": "supervisor/attempt_start", "attempt": 0},
        {"time": t0 + 4, "kind": "checkpoint/save", "step": 4,
         "duration_s": 0.5},
        {"time": t0 + 9, "kind": "checkpoint/save", "step": 8,
         "duration_s": 0.5},
        {"time": t0 + 10, "kind": "supervisor/attempt_failure"},
        {"time": t0 + 15, "kind": "supervisor/attempt_start",
         "attempt": 1},
        {"time": t0 + 30, "kind": "checkpoint/ram_snapshot", "step": 6,
         "duration_s": 0.05},
    ]
    gp = goodput_from_run(records, events)
    assert gp["steps"] == 8
    assert gp["useful_step_s"] == pytest.approx(0.8)
    assert gp["attempts"] == 2
    assert gp["losses"]["restart_s"] == pytest.approx(5.0)   # t+10 -> t+15
    assert gp["losses"]["checkpoint_stall_s"] == pytest.approx(1.05)
    assert gp["losses"]["rollback_s"] == pytest.approx(0.2)  # 2 re-run
    assert gp["wall_s"] == pytest.approx(30.0)
    assert gp["goodput_ratio"] == pytest.approx(0.8 / 30.0, abs=1e-4)

    cad = checkpoint_cadence(records, events)
    assert cad["checkpoint_interval_steps"] == 4
    assert cad["step_time_s"] == pytest.approx(0.1)

    ag = attempt_goodput(10.0, 8.0, ckpt_stall_s=1.0, steps=80)
    assert ag["goodput_ratio"] == pytest.approx(0.8)
    assert attempt_goodput(10.0, None)["goodput_ratio"] is None


def test_recovery_gap_reason_thresholds():
    from autodist_tpu.telemetry.goodput import recovery_gap_reason

    # 1000 steps x 0.5s = 500s exposure > 120s budget
    why = recovery_gap_reason(1000, 0.5)
    assert why is not None and "recovery exposure" in why
    # a RAM tier at 100 steps caps the exposure at 50s -> quiet
    assert recovery_gap_reason(1000, 0.5, snapshot_every=100) is None
    # a RAM tier that is still too coarse fires, naming the tier
    why = recovery_gap_reason(1000, 0.5, snapshot_every=500)
    assert why is not None and "RAM snapshots" in why
    assert recovery_gap_reason(10, 0.5) is None
    assert recovery_gap_reason(None, 0.5) is None
    assert recovery_gap_reason(1000, None) is None


@pytest.mark.analysis
def test_recovery_gap_lint_fires():
    """analysis pass `resilience`: WARN on an exposed cadence, quiet
    when a tier bounds it, inert without provenance."""
    import jax.numpy as jnp

    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.resource_spec import ResourceSpec

    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    gi = GraphItem(params)
    spec = ResourceSpec(resource_info={"nodes": [
        {"address": "127.0.0.1", "chips": 8, "chief": True}]})
    strat = AllReduce().build(gi, spec)

    report = analyze(strat, gi, mesh={"data": 8},
                     resilience={"checkpoint_interval_steps": 2000,
                                 "step_time_s": 0.25})
    assert any(d.rule == "resilience/recovery-gap"
               for d in report.warnings)

    report = analyze(strat, gi, mesh={"data": 8},
                     resilience={"checkpoint_interval_steps": 2000,
                                 "step_time_s": 0.25,
                                 "snapshot_every": 50})
    assert not any(d.rule.startswith("resilience/")
                   for d in report.diagnostics)

    report = analyze(strat, gi, mesh={"data": 8})
    assert not any(d.rule.startswith("resilience/")
                   for d in report.diagnostics)

    report = analyze(strat, gi, mesh={"data": 8},
                     resilience={"step_time_s": 0.25})
    assert any(d.rule == "resilience/no-measurement"
               for d in report.diagnostics)


def test_fit_emits_goodput_event_and_gauge(tmp_path):
    from autodist_tpu.telemetry import get_journal
    from autodist_tpu.telemetry.registry import DEFAULT_REGISTRY

    sess, batch = _linear_session()
    hist = sess.fit({"x": batch["x"], "y": batch["y"]}, epochs=1,
                    steps_per_epoch=4,
                    checkpoint_dir=str(tmp_path / "ck"))
    assert hist.goodput is not None
    assert hist.goodput["steps"] == 4
    assert hist.goodput["checkpoint_stall_s"] > 0
    ev = [e for e in get_journal().events
          if e.get("kind") == "goodput/attempt"]
    assert ev and ev[-1]["steps"] == 4
    gauges = [m for m in DEFAULT_REGISTRY.metrics()
              if m.name == "autodist_goodput_ratio"]
    if hist.goodput["goodput_ratio"] is not None:
        assert gauges and 0 < gauges[0].value <= 1.0


# ---------------------------------------------------------------------------
# the live multiprocess drill (slow)
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRILL = os.path.join(REPO, "tests", "integration", "recovery_drill.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_peer_tier_drill_survives_worker_kill(tmp_path):
    """SIGKILL-grade chaos kill of the worker mid-run; the relaunch
    resumes from the PEER tier (no persistent checkpoint exists at all)
    and ends bit-exact with the uninterrupted oracle."""
    def base_env(tag):
        env = dict(os.environ)
        for k in ("AUTODIST_WORKER", "AUTODIST_CHAOS", "AUTODIST_SUPERVISE",
                  "AUTODIST_FAILURE_POLICY", "AUTODIST_SUPERVISOR_DIR",
                  "AUTODIST_ATTEMPT", "AUTODIST_SNAPSHOT_EVERY",
                  "AUTODIST_SNAPSHOT_DIR"):
            env.pop(k, None)
        env.update({
            "AUTODIST_REPO_ROOT": REPO,
            "AUTODIST_RESULT_FILE": str(tmp_path / f"result_{tag}.json"),
            "AUTODIST_TEST_PEER": str(tmp_path / f"peer_{tag}"),
            "AUTODIST_TPU_WORKDIR": str(tmp_path / f"workdir_{tag}"),
            "AUTODIST_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        return env

    def run(env, timeout=300):
        proc = subprocess.run([sys.executable, "-u", DRILL], env=env,
                              timeout=timeout, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
        return proc.returncode, proc.stdout.decode()

    env = base_env("oracle")
    rc, out = run(env)
    assert rc == 0, f"oracle failed (rc={rc}):\n{out[-4000:]}"
    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        oracle = json.load(f)
    assert oracle["final_step"] == 16

    env = base_env("drill")
    env.update({
        "AUTODIST_SUPERVISE": "1",
        "AUTODIST_CHAOS": "kill@step=6,proc=1,attempt=0",
        "AUTODIST_SUPERVISOR_REPORT": str(tmp_path / "report.json"),
    })
    rc, out = run(env, timeout=480)
    assert rc == 0, f"drill failed (rc={rc}):\n{out[-6000:]}"
    with open(env["AUTODIST_SUPERVISOR_REPORT"], encoding="utf-8") as f:
        report = json.load(f)
    assert report["ok"] and report["attempts"] == 2

    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        chief = json.load(f)
    # attempt 1 resumed from the PEER tier without any persistent dir,
    # losing at most snapshot_every(=2) steps of the 6 attempt 0 ran
    assert chief["attempt"] == 1
    assert chief["resume_tier"] == "peer"
    assert chief["resumed_step"] >= 4
    assert chief["final_step"] == 16
    np.testing.assert_allclose(chief["final_w"], oracle["final_w"],
                               rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(chief["final_b"], oracle["final_b"],
                               rtol=1e-7, atol=1e-8)
