"""Closed-form case matrix: cases × all 8 builders × 2 mesh shapes.

Parity target: the reference's integration case matrix
(``tests/integration/test_all.py:1-70`` — 10 builders × cases c0–c8,
with c0's closed-form numeric assertion ``cases/c0.py:88-124``).  The
cases here widen round-1's single least-squares model to the reference's
breadth: sparse embeddings (c2), a ``lax.scan`` recurrent model (c6's
dynamic-LSTM analog), and bf16 + rematerialization variants — every case
trained for multiple steps through a full DistributedSession and checked
numerically against a single-device loop.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.mesh import build_mesh
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PartitionedAR,
    PartitionedPS,
    PS,
    PSLoadBalancing,
    RandomAxisPartitionAR,
    UnevenPartitionedPS,
)

BUILDERS = [PS, PSLoadBalancing, PartitionedPS, UnevenPartitionedPS,
            AllReduce, PartitionedAR, RandomAxisPartitionAR, Parallax]
MESHES = [{"data": 8}, {"data": 4, "model": 2}]
STEPS = 3


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


# -- cases -------------------------------------------------------------------
def case_sparse():
    """Embedding model (reference c2): vocab ≫ batch, sparse grads."""
    vocab, dim = 96, 16
    params = {"emb": {"table": jnp.asarray(
        np.linspace(-1, 1, vocab * dim).reshape(vocab, dim), jnp.float32)},
        "head": {"w": jnp.ones((dim, 4)) * 0.1}}

    def loss_fn(p, batch):
        h = jnp.take(p["emb"]["table"], batch["ids"], axis=0)
        pred = jnp.mean(h, axis=1) @ p["head"]["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(0)
    batch = {"ids": rng.randint(0, vocab, (16, 5)).astype(np.int32),
             "y": rng.randn(16, 4).astype(np.float32)}
    return params, loss_fn, batch, dict(sparse_vars=["emb/table"]), 1e-4


def case_scan():
    """lax.scan recurrent model (reference c6: dynamic LSTM/while-loop)."""
    d_in, d_h = 8, 16
    k = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(k, 3)
    params = {"cell": {"w_x": jax.random.normal(k1, (d_in, d_h)) * 0.3,
                       "w_h": jax.random.normal(k2, (d_h, d_h)) * 0.3},
              "proj": {"w": jax.random.normal(k3, (d_h, 4)) * 0.3}}

    def loss_fn(p, batch):
        def step(h, x_t):
            h = jnp.tanh(x_t @ p["cell"]["w_x"] + h @ p["cell"]["w_h"])
            return h, h

        x = jnp.swapaxes(batch["x"], 0, 1)          # [T, B, d_in]
        h0 = jnp.zeros((batch["x"].shape[0], d_h))
        _, hs = jax.lax.scan(step, h0, x)
        pred = hs[-1] @ p["proj"]["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(1)
    batch = {"x": rng.randn(16, 12, d_in).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    return params, loss_fn, batch, {}, 1e-4


def case_bf16_remat():
    """bf16 compute + gradient rematerialization (remat='dots')."""
    params = {"l1": {"w": jnp.asarray(
        np.linspace(-0.5, 0.5, 8 * 16).reshape(8, 16), jnp.bfloat16)},
        "l2": {"w": jnp.asarray(
            np.linspace(-0.5, 0.5, 16 * 4).reshape(16, 4), jnp.bfloat16)}}

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["l1"]["w"])
        pred = h @ p["l2"]["w"]
        return jnp.mean((pred.astype(jnp.float32)
                         - batch["y"].astype(jnp.float32)) ** 2)

    rng = np.random.RandomState(2)
    batch = {"x": rng.randn(16, 8).astype(jnp.bfloat16),
             "y": rng.randn(16, 4).astype(np.float32)}
    return params, loss_fn, batch, dict(remat="dots"), 2e-2

def case_while_loop():
    """Data-dependent ``lax.while_loop`` in the step (reference c4:
    ``tf.while_loop``): an input-normalization loop with a value-dependent
    stopping predicate (global max-reduce in ``cond`` — a collective when
    the batch is data-sharded).  It runs on the non-differentiated data
    path: ``lax.while_loop`` has no reverse-mode rule, so the TPU-native
    translation of a differentiated dynamic loop is scan+mask (see
    :func:`case_dynamic_lstm`); the data-dependent trip count stays legal
    on forward values."""
    d = 8
    params = {"lin": {"w": jnp.asarray(
        np.linspace(-0.4, 0.4, d * d).reshape(d, d), jnp.float32)}}

    def loss_fn(p, batch):
        def cond(carry):
            i, v = carry
            return jnp.logical_and(i < 8, jnp.max(jnp.abs(v)) > 1.05)

        def body(carry):
            i, v = carry
            return i + 1, 0.7 * v

        _, v = jax.lax.while_loop(
            cond, body, (0, jax.lax.stop_gradient(batch["x"])))
        pred = jnp.tanh(v) @ p["lin"]["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(3)
    batch = {"x": rng.randn(16, d).astype(np.float32) * 3.0,
             "y": rng.randn(16, d).astype(np.float32)}
    return params, loss_fn, batch, {}, 1e-4


def case_dynamic_lstm():
    """Dynamic-length LSTM (reference c6: ``dynamic_rnn`` + TensorArray):
    a gated LSTM cell scanned over padded sequences with PER-EXAMPLE
    lengths — state updates masked past each row's length, final state
    gathered at the length boundary (the TensorArray read)."""
    d_in, d_h, t_max = 4, 8, 10
    k = jax.random.PRNGKey(4)
    kx, kh, kp = jax.random.split(k, 3)
    params = {"lstm": {"w_x": jax.random.normal(kx, (d_in, 4 * d_h)) * 0.3,
                       "w_h": jax.random.normal(kh, (d_h, 4 * d_h)) * 0.3,
                       "b": jnp.zeros((4 * d_h,))},
              "proj": {"w": jax.random.normal(kp, (d_h, 3)) * 0.3}}

    def loss_fn(p, batch):
        def step(carry, xs):
            h, c = carry
            x_t, live = xs                           # [B,d_in], [B]
            z = x_t @ p["lstm"]["w_x"] + h @ p["lstm"]["w_h"] + p["lstm"]["b"]
            i, f, g, o = jnp.split(z, 4, axis=-1)
            nc = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            nh = jax.nn.sigmoid(o) * jnp.tanh(nc)
            m = live[:, None]                        # freeze finished rows
            return (m * nh + (1 - m) * h, m * nc + (1 - m) * c), None

        x = jnp.swapaxes(batch["x"], 0, 1)           # [T,B,d_in]
        live = (jnp.arange(t_max)[:, None]
                < batch["len"][None, :]).astype(x.dtype)   # [T,B]
        b = batch["x"].shape[0]
        h0 = jnp.zeros((b, d_h))
        (h, _), _ = jax.lax.scan(step, (h0, h0), (x, live))
        pred = h @ p["proj"]["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    rng = np.random.RandomState(5)
    batch = {"x": rng.randn(16, t_max, d_in).astype(np.float32),
             "len": rng.randint(1, t_max + 1, (16,)).astype(np.int32),
             "y": rng.randn(16, 3).astype(np.float32)}
    return params, loss_fn, batch, {}, 1e-4


CASES = {"sparse": case_sparse, "scan": case_scan,
         "bf16_remat": case_bf16_remat, "while_loop": case_while_loop,
         "dynamic_lstm": case_dynamic_lstm}


def _single_device_losses(params, loss_fn, batch, capture_kw):
    from autodist_tpu.graph_item import GraphItem

    gi = GraphItem(params, optimizer=optax.adam(1e-2), loss_fn=loss_fn,
                   **{k: v for k, v in capture_kw.items()
                      if k in ("remat", "sparse_vars")})
    opt = optax.adam(1e-2)
    p, s = params, opt.init(params)
    losses = []
    vg = jax.value_and_grad(gi.loss_fn)
    for _ in range(STEPS):
        loss, g = vg(p, batch)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("mesh_axes", MESHES,
                         ids=["dp8", "dp4tp2"])
@pytest.mark.parametrize("builder_cls", BUILDERS,
                         ids=[b.__name__ for b in BUILDERS])
@pytest.mark.parametrize("case", list(CASES), ids=list(CASES))
def test_case_matrix(case, builder_cls, mesh_axes):
    params, loss_fn, batch, capture_kw, rtol = CASES[case]()
    ref_losses = _single_device_losses(params, loss_fn, batch, capture_kw)

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder_cls(), mesh_axes=mesh_axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, **capture_kw)
    sess = ad.create_distributed_session(mesh=build_mesh(mesh_axes))
    losses = [float(sess.run(batch)["loss"]) for _ in range(STEPS)]
    np.testing.assert_allclose(losses, ref_losses, rtol=rtol)


def test_sparse_gradient_update_runs_sharded():
    """The vocab-sharded embedding's update computation executes on shards:
    the optimized HLO carries shard-shaped [vocab/8, dim] tensors for the
    table, and the table's gradient layout is the sharded opt_spec — the
    gradient never materializes as one replicated dense table on the
    update path (reference c2's sparse-grad property)."""
    params, loss_fn, batch, capture_kw, _ = case_sparse()
    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, **capture_kw)
    sess = ad.create_distributed_session()
    plan = sess._step.compiled_strategy.plan_for("emb/table")
    from jax.sharding import PartitionSpec as P

    assert plan.param_spec == P("data")
    assert plan.opt_spec == P("data")
    placed = sess.place_batch(batch)
    hlo = sess._step.step_fn.lower(
        sess.sharded_params, sess.opt_state, sess.sync_state,
        placed).compile().as_text()
    assert "f32[12,16]" in hlo  # 96/8 = 12-row shard computations exist


# The reference's exact 10-strategy integration list (variants included):
# tests/integration/test_all.py:35-45.
REFERENCE_VARIANTS = [
    lambda: PS(),
    lambda: PartitionedPS(local_proxy_variable=True),
    lambda: AllReduce(chunk_size=1, all_reduce_spec="NCCL",
                      compressor="NoneCompressor"),
    lambda: AllReduce(chunk_size=1, all_reduce_spec="NCCL",
                      compressor="HorovodCompressor"),
    lambda: AllReduce(chunk_size=1, all_reduce_spec="RING",
                      compressor="HorovodCompressorEF"),
    lambda: PSLoadBalancing(local_proxy_variable=True),
    lambda: Parallax(local_proxy_variable=True),
    lambda: PSLoadBalancing(),
    lambda: UnevenPartitionedPS(local_proxy_variable=True),
    lambda: RandomAxisPartitionAR(chunk_size=4),
]


@pytest.mark.parametrize("variant_idx", range(len(REFERENCE_VARIANTS)))
def test_reference_strategy_variant_matrix(variant_idx):
    """The reference's full 10-config strategy list (proxy variables,
    compressors, chunk sizes) trains the scan case through a
    DistributedSession.  Lossy-compressor and proxy configs get loose
    tolerances; exact configs are pinned tight."""
    params, loss_fn, batch, capture_kw, _ = case_scan()
    ref_losses = _single_device_losses(params, loss_fn, batch, capture_kw)

    _reset_default_autodist_for_testing()
    builder = REFERENCE_VARIANTS[variant_idx]()
    ad = AutoDist(strategy_builder=builder, mesh_axes={"data": 8})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, **capture_kw)
    sess = ad.create_distributed_session(mesh=build_mesh({"data": 8}))
    losses = [float(sess.run(batch)["loss"]) for _ in range(STEPS)]
    lossy = variant_idx in (3, 4)          # bf16-wire compressors
    proxy = getattr(builder, "_local_proxy", False)
    rtol = 5e-2 if (lossy or proxy) else 1e-4
    np.testing.assert_allclose(losses, ref_losses, rtol=rtol)
