"""End-to-end distributed training parity tests.

The TPU analog of the reference's case c0 (tests/integration/cases/c0.py:88-124):
fixed seeds, run N steps distributed, and assert the result matches the
single-device computation in closed form — for every strategy builder.
Bit-parity between an 8-device data-parallel run and a single-device run of
the same global batch is the key invariant: gradient-sum-then-divide must
equal full-batch gradient.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PartitionedAR,
    PartitionedPS,
    PS,
    PSLoadBalancing,
    RandomAxisPartitionAR,
    UnevenPartitionedPS,
)


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def _make_problem(seed=0):
    """Least squares: loss = mean((x @ w + b - y)^2). Closed-form grads."""
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    true_w = rng.randn(8, 4).astype(np.float32)
    y = (x @ true_w).astype(np.float32)
    params = {"linear": {"w": jnp.zeros((8, 4), jnp.float32),
                         "b": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["linear"]["w"] + params["linear"]["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, loss_fn, {"x": x, "y": y}


def _single_device_reference(params, loss_fn, batch, lr, steps):
    opt = optax.sgd(lr)
    opt_state = opt.init(params)
    losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    return params, losses


BUILDERS = [PS, PSLoadBalancing, PartitionedPS, UnevenPartitionedPS,
            AllReduce, PartitionedAR, RandomAxisPartitionAR, Parallax]


@pytest.mark.parametrize("builder_cls", BUILDERS)
def test_strategy_matches_single_device(builder_cls):
    params, loss_fn, batch = _make_problem()
    ref_params, ref_losses = _single_device_reference(
        params, loss_fn, batch, lr=0.1, steps=5)

    ad = AutoDist(strategy_builder=builder_cls())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    dist_losses = [float(sess.run(batch)["loss"]) for _ in range(5)]

    np.testing.assert_allclose(dist_losses, ref_losses, rtol=1e-5,
                               err_msg=builder_cls.__name__)
    got = sess.params
    np.testing.assert_allclose(got["linear"]["w"], ref_params["linear"]["w"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got["linear"]["b"], ref_params["linear"]["b"],
                               rtol=1e-5, atol=1e-6)


def test_adam_state_sharded_ps():
    """WUS with a stateful optimizer (Adam): parity + sharded slots."""
    params, loss_fn, batch = _make_problem()
    opt = optax.adam(1e-2)

    # single-device reference
    ref_params = params
    ref_state = opt.init(ref_params)
    for _ in range(3):
        _, grads = jax.value_and_grad(loss_fn)(ref_params, batch)
        updates, ref_state = opt.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)

    ad = AutoDist(strategy_builder=PS())
    with ad.scope():
        ad.capture(params=params, optimizer=opt, loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(3):
        sess.run(batch)
    np.testing.assert_allclose(sess.params["linear"]["w"],
                               ref_params["linear"]["w"], rtol=1e-5, atol=1e-6)
    # the Adam mu slot for w (shape (8,4), dim0 divisible by 8) is sharded
    mu_w = sess.opt_state[0].mu["linear"]["w"]
    assert "data" in str(mu_w.sharding.spec)


def test_mesh_axes_override():
    params, loss_fn, batch = _make_problem()
    ad = AutoDist(strategy_builder=PartitionedPS(),
                  mesh_axes={"data": 4, "model": 2})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    assert dict(sess.mesh.shape) == {"data": 4, "model": 2}
    ref_params, ref_losses = _single_device_reference(
        params, loss_fn, batch, lr=0.1, steps=3)
    losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    # params partitioned over the model axis
    w = sess.sharded_params["linear"]["w"]
    assert "model" in str(w.sharding.spec)


def test_one_autodist_per_process(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "")
    _reset_default_autodist_for_testing()
    AutoDist()
    with pytest.raises(RuntimeError):
        AutoDist()


def test_capture_after_build_rejected():
    params, loss_fn, batch = _make_problem()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    ad.create_distributed_session()
    with pytest.raises(RuntimeError):
        ad.capture(params=params)


def test_function_decorator():
    params, loss_fn, batch = _make_problem()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)

    @ad.function
    def train_step(metrics):
        return metrics["loss"]

    ref_losses = _single_device_reference(params, loss_fn, batch, 0.1, 2)[1]
    assert float(train_step(batch)) == pytest.approx(ref_losses[0], rel=1e-5)
    assert float(train_step(batch)) == pytest.approx(ref_losses[1], rel=1e-5)


def test_function_rejects_non_callable():
    """``run = ad.function(); run(batch)`` is a misuse (ad.function()
    returns the decorator): the batch dict must not be silently accepted
    as a fetch selector."""
    params, loss_fn, batch = _make_problem()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    with pytest.raises(TypeError, match="callable"):
        ad.function()(batch)
    with pytest.raises(TypeError, match="callable"):
        ad.function(batch)
    # the documented plain-runner form still works
    run = ad.function()(None)
    assert "loss" in run(batch)


def test_function_decorator_async_cadence():
    """ad.function(sync_every=N): auto-placement plus the async hot-loop
    cadence — only every N-th call syncs metrics to host numpy; the
    others return device arrays so steps dispatch back-to-back."""
    import jax

    params, loss_fn, batch = _make_problem()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)

    @ad.function(sync_every=3)
    def train_step(metrics):
        return metrics

    outs = [train_step(batch) for _ in range(6)]
    sess = ad.create_distributed_session()
    assert sess.step_count == 6
    for i, out in enumerate(outs):
        synced = (i + 1) % 3 == 0
        assert isinstance(out["loss"], np.ndarray) == synced, (i, out)
        if not synced:
            assert isinstance(out["loss"], jax.Array)
    # The losses themselves match the synchronous reference trajectory.
    ref_losses = _single_device_reference(params, loss_fn, batch, 0.1, 6)[1]
    np.testing.assert_allclose([float(o["loss"]) for o in outs], ref_losses,
                               rtol=1e-4)


def test_worker_loads_serialized_strategy(monkeypatch):
    params, loss_fn, batch = _make_problem()
    # chief builds
    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    strategy = ad.build_strategy()

    # "worker" process loads by id
    _reset_default_autodist_for_testing()
    monkeypatch.setenv("AUTODIST_STRATEGY_ID", strategy.id)
    ad2 = AutoDist(strategy_builder=AllReduce())  # builder ignored on worker
    with ad2.scope():
        ad2.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    s2 = ad2.build_strategy()
    assert s2.id == strategy.id
    assert [n.to_dict() for n in s2.node_config] == \
           [n.to_dict() for n in strategy.node_config]
