"""Leg-calibrated strategy search + drift-triggered hot-swap
(docs/strategies.md "Search"): beam search over the per-variable plan
space — legality-pruned, IR-verified, priced leg-by-leg from planted
calibration constants — and the ScheduleTuner's drift → re-search →
RAM-snapshot hot-swap loop, drilled live against a bit-exact oracle."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce, Zero1
from autodist_tpu.strategy.search import (
    SearchSpace,
    VarGene,
    beam_search,
    evaluate_candidate,
    genes_from_strategy,
    strategy_from_genes,
)
from autodist_tpu.strategy.tuner import ScheduleTuner
from autodist_tpu.telemetry.calibration import (
    LegCalibration,
    drifted_leg_kinds,
)


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _spec(chips=8):
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": chips, "chief": True}]})


def _dense_gi(accum=4):
    """Comm-bound accum fixture: one big dense matrix + bias."""
    return GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32),
                      "b": jnp.zeros((2048,), jnp.float32)},
                     accum_steps=accum)


def _flat_cal(bandwidth=45e9, alpha=5e-6, quant_overhead=0.0, **over):
    """A planted LegCalibration: every kind at the same constants,
    selected kinds overridden via kwargs (e.g. all_reduce=1e6)."""
    from autodist_tpu.telemetry.calibration import LEG_KINDS

    cal = LegCalibration()
    for kind in LEG_KINDS:
        cal.bandwidths[kind] = float(over.get(kind, bandwidth))
        cal.alphas[kind] = alpha
    cal.quant_overhead_per_byte = quant_overhead
    return cal


# -- the search itself --------------------------------------------------------

def test_search_is_deterministic_run_to_run():
    gi, spec = _dense_gi(), _spec()
    space = SearchSpace(max_rounds=3)
    a = beam_search(gi, spec, space=space)
    b = beam_search(gi, spec, space=space)
    assert a.best.fingerprint == b.best.fingerprint
    assert a.best.name == b.best.name
    assert [e.fingerprint for e in a.top(10)] == \
        [e.fingerprint for e in b.top(10)]


def test_search_winner_not_worse_than_any_seed():
    """The fixed builders seed the beam, so the winner's estimate is
    <= every fixed candidate's by construction."""
    gi, spec = _dense_gi(), _spec()
    res = beam_search(gi, spec)
    seeds = [e for e in res.evaluated if e.name.startswith("seed:")]
    assert seeds, "no seed survived"
    assert all(res.best.cost_s <= e.cost_s + 1e-12 for e in seeds)


def test_search_verifies_every_priced_candidate():
    """Every evaluated candidate's plan rebuilds to an IR that passes
    the static verifier (the search's own gate, re-checked here)."""
    gi, spec = _dense_gi(), _spec()
    res = beam_search(gi, spec, space=SearchSpace(max_rounds=1))
    axes = {"data": 8}
    for ev in res.top(10):
        re_ev, _ = evaluate_candidate("re", ev.genes, gi, spec, axes)
        assert re_ev is not None and re_ev.pruned_by is None
        assert re_ev.fingerprint == ev.fingerprint
    from autodist_tpu.analysis.search import facts_for_candidate
    strategy = strategy_from_genes(res.best.genes, gi, spec)
    facts, _, guard, prune = facts_for_candidate(strategy, gi, axes)
    assert prune is None
    ir = sir.ir_from_facts(facts, axes=axes, accum_steps=4, guard=guard)
    assert not sir.errors(sir.verify(ir))


def test_illegal_candidate_prunes_with_rule_id():
    """A gene map whose PS partition axis cannot lower is pruned by the
    legality rules BEFORE pricing, and the rule id is recorded for the
    explain surface."""
    gi = GraphItem({"w": jnp.zeros((7, 3), jnp.float32)})
    spec = _spec(8)
    genes = (("w", VarGene(sync="ps", partition=1)),)   # dim 3 over 8 chips
    ev, strategy = evaluate_candidate("bad", genes, gi, spec,
                                      {"data": 8})
    assert strategy is None
    assert ev.pruned_by is not None
    assert ev.pruned_by.startswith("legality/")


def test_genes_round_trip_through_strategy():
    gi, spec = _dense_gi(), _spec()
    strategy = Zero1(bucket_bytes=1 << 20, overlap="pipeline").build(
        gi, spec)
    genes = genes_from_strategy(strategy, gi)
    rebuilt = strategy_from_genes(genes, gi, spec)
    assert genes_from_strategy(rebuilt, gi) == genes


def test_sparse_ps_priced_at_touched_rows():
    """The pricing shadow: a sparse table under PS prices its exchange
    at touched-row bytes (the Parallax rule), so the search does not
    mis-rank PS against densifying AllReduce."""
    gi = GraphItem({"emb": {"table": jnp.zeros((200_000, 32))},
                    "head": {"w": jnp.zeros((32, 8))}},
                   sparse_vars=["emb/table"])
    spec = _spec()
    axes = {"data": 8}
    ps = (("emb/table", VarGene(sync="ps")), ("head/w", VarGene()))
    ar = (("emb/table", VarGene()), ("head/w", VarGene()))
    ev_ps, _ = evaluate_candidate("ps", ps, gi, spec, axes)
    ev_ar, _ = evaluate_candidate("ar", ar, gi, spec, axes)
    # AR densifies the whole 25.6 MB table; sparse PS moves ~4096 rows.
    assert ev_ps.cost_s < ev_ar.cost_s / 5


def test_planted_calibration_flips_search_winner():
    """Calibration-driven picks: comm-bound constants (slow wire, free
    quantize) must pick the quantized wire; compute-bound constants
    with a punitive quantize overhead must keep full precision — the
    SAME space, flipped only by calibration.json contents."""
    gi, spec = _dense_gi(accum=4), _spec()
    space = SearchSpace(compressors=("NoneCompressor", "Int8Compressor"),
                        max_rounds=2)
    comm_bound = _flat_cal(bandwidth=1e8, alpha=1e-7, quant_overhead=0.0)
    quant_hostile = _flat_cal(bandwidth=1e12, alpha=1e-7,
                              quant_overhead=1e-6)
    a = beam_search(gi, spec, space=space, constants=comm_bound)
    b = beam_search(gi, spec, space=space, constants=quant_hostile)
    assert a.best.fingerprint != b.best.fingerprint
    genes_a = dict(a.best.genes)
    genes_b = dict(b.best.genes)
    assert any(g.compressor == "Int8Compressor"
               for g in genes_a.values()), a.best.name
    assert all(g.compressor == "NoneCompressor"
               for g in genes_b.values()), b.best.name
    # both winners' IRs pass the verifier (gated inside the search; the
    # fingerprints exist only because verification succeeded)
    assert a.best.fingerprint and b.best.fingerprint


def test_auto_strategy_beam_mode_builds_and_records_choice():
    from autodist_tpu.strategy import AutoStrategy

    gi, spec = _dense_gi(), _spec()
    b = AutoStrategy(search="beam")
    s = b.build(gi, spec)
    assert b.last_choice
    assert b.last_search is not None and b.last_search.best is not None
    assert s.node_config


def test_search_report_cli(capsys):
    """The explain surface: --search-report dumps top-K candidates with
    per-leg-kind breakdown (and pruned branches when any)."""
    from autodist_tpu.analysis.__main__ import main

    rc = main(["mlp", "--search-report", "--mesh", "data=4",
               "--topk", "3"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "top candidates" in out
    assert "per-leg-kind" in out
    rc = main(["mlp", "--search-report", "--mesh", "data=4", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    import json
    report = json.loads(out)
    assert report["best"]["per_kind_ms"]
    assert report["n_evals"] > 0


# -- the expert axis ----------------------------------------------------------

def _moe_gi():
    """Comm-favorable MoE fixture: the expert stacks dominate the
    byte budget, so densifying them is the expensive alternative."""
    return GraphItem(
        {"layers_0": {"moe": {"wi": jnp.zeros((8, 256, 1024)),
                              "wo": jnp.zeros((8, 1024, 256))},
                      "dense": {"w": jnp.zeros((256, 256))}}},
        expert_vars=("*/moe/wi", "*/moe/wo"))


def _moe_spec(hbm_gb=16):
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8, "chief": True}],
        "mesh": {"data": 2, "expert": 4}, "hbm_gb": hbm_gb})


@pytest.mark.moe
def test_beam_picks_expert_parallel_on_moe_fixture():
    """The paper's EP argument through the search: on a mesh with an
    expert axis, expert-parallel (1/E grads + the a2a pair) must beat
    densified replication of the expert stacks, and the winner's IR —
    a2a legs included — passes the static verifier."""
    from autodist_tpu.strategy import AutoStrategy

    gi, spec = _moe_gi(), _moe_spec()
    b = AutoStrategy(search="beam")
    b.build(gi, spec)
    best = b.last_search.best
    genes = dict(best.genes)
    assert genes["layers_0/moe/wi"].expert
    assert genes["layers_0/moe/wo"].expert
    assert not genes["layers_0/dense/w"].expert
    # the dense alternative was actually priced — and lost
    dense = [e for e in b.last_search.evaluated
             if e.genes and all(not g.expert for _, g in e.genes)]
    assert dense, "no densified candidate was priced"
    assert best.cost_s < min(e.cost_s for e in dense)
    assert "all_to_all" in best.per_kind_ms
    # rebuild the winning IR and re-verify it end to end
    re_ev, strategy = evaluate_candidate(
        "re", best.genes, gi, spec, {"data": 2, "expert": 4})
    assert re_ev.fingerprint == best.fingerprint
    assert strategy is not None
    from autodist_tpu.analysis.search import facts_for_candidate
    facts, _, guard, prune = facts_for_candidate(
        strategy, gi, {"data": 2, "expert": 4})
    assert prune is None
    moe = sir.moe_facts_from_vars(gi.info.variables,
                                  axes={"data": 2, "expert": 4})
    ir = sir.ir_from_facts(facts, axes={"data": 2, "expert": 4},
                           guard=guard, moe=moe)
    sir.assert_verified(ir, "beam winner")
    assert any(l.kind == sir.LEG_ALL_TO_ALL for l in ir.legs)


@pytest.mark.moe
def test_over_capacity_expert_candidate_pruned_by_watermark():
    """An expert-parallel candidate whose capacity transient cannot fit
    per-chip HBM is rejected BEFORE pricing, with the watermark rule in
    its prune verdict — it must not win on wire cost and OOM at step 1."""
    from autodist_tpu.analysis import dataflow
    from autodist_tpu.strategy.search import VarGene

    gi, spec = _moe_gi(), _moe_spec(hbm_gb=0.125)
    axes = {"data": 2, "expert": 4}
    genes = tuple((v.name, VarGene(expert=v.expert))
                  for v in gi.trainable_var_infos)
    ev, strategy = evaluate_candidate(
        "over", genes, gi, spec, axes, moe_tokens_per_group=1 << 22)
    assert strategy is None
    assert ev.pruned_by.startswith(dataflow.RULE_WATERMARK_EXCEEDS)
    # the same candidate at a sane token load survives and prices
    ev2, s2 = evaluate_candidate(
        "ok", genes, gi, spec, axes, moe_tokens_per_group=1024)
    assert ev2.pruned_by is None and s2 is not None


@pytest.mark.moe
def test_expert_toggle_changes_fingerprint_and_pricing():
    """expert=on and expert=off lower to distinct fact fingerprints
    (the a2a facts are part of the blob) and distinct prices, so the
    dedupe set cannot collapse the two placements."""
    from autodist_tpu.strategy.search import VarGene

    gi, spec = _moe_gi(), _moe_spec()
    axes = {"data": 2, "expert": 4}
    on = tuple((v.name, VarGene(expert=v.expert))
               for v in gi.trainable_var_infos)
    off = tuple((v.name, VarGene()) for v in gi.trainable_var_infos)
    seen: set = set()
    ev_on, _ = evaluate_candidate("on", on, gi, spec, axes,
                                  seen_facts=seen)
    ev_off, _ = evaluate_candidate("off", off, gi, spec, axes,
                                   seen_facts=seen)
    assert ev_on is not None and ev_off is not None   # no dedupe collapse
    assert ev_on.cost_s != ev_off.cost_s
    assert "all_to_all" in ev_on.per_kind_ms
    assert "all_to_all" not in ev_off.per_kind_ms


# -- the drift trigger --------------------------------------------------------

def _samples(kind, t, n=4, nbytes=1 << 20, compressor="NoneCompressor"):
    return [{"kind": kind, "measured_s": t, "nbytes": nbytes,
             "compressor": compressor} for _ in range(n)]


def test_drifted_leg_kinds_fires_past_threshold_only():
    cal = _flat_cal(bandwidth=1e9, alpha=0.0)
    fine = _samples("all_reduce", (1 << 20) / 1e9)          # exactly modeled
    assert drifted_leg_kinds(fine, cal) == {}
    slow = _samples("all_reduce", 10 * (1 << 20) / 1e9)     # 10x drift
    out = drifted_leg_kinds(slow, cal)
    assert set(out) == {"all_reduce"}
    assert "all_reduce" in out["all_reduce"]
    # BELOW-threshold drift (model overprices) fires too
    fast = _samples("all_reduce", 0.05 * (1 << 20) / 1e9)
    assert set(drifted_leg_kinds(fast, cal)) == {"all_reduce"}


def test_calibration_cache_invalidates_across_discovery_switch(
        tmp_path, monkeypatch):
    """The stale-constants footgun: flipping AUTODIST_CALIBRATION
    between an explicit env path and run-dir discovery mid-process must
    reload, and a same-path atomic rewrite is picked up even when the
    float mtime cannot distinguish the writes (inode changes)."""
    import os

    from autodist_tpu.telemetry.calibration import (
        load_default_calibration,
        reset_calibration_cache_for_testing,
        save_calibration,
    )

    reset_calibration_cache_for_testing()
    a = tmp_path / "a" / "calibration.json"
    b_dir = tmp_path / "b"
    a.parent.mkdir()
    b_dir.mkdir()
    save_calibration(_flat_cal(bandwidth=1e7), str(a))
    save_calibration(_flat_cal(bandwidth=2e7),
                     str(b_dir / "calibration.json"))
    monkeypatch.setenv("AUTODIST_CALIBRATION", str(a))
    monkeypatch.delenv("AUTODIST_TELEMETRY_DIR", raising=False)
    assert load_default_calibration().bandwidths["all_reduce"] == 1e7
    # switch env-path -> run-dir discovery mid-process
    monkeypatch.delenv("AUTODIST_CALIBRATION")
    monkeypatch.setenv("AUTODIST_TELEMETRY_DIR", str(b_dir))
    assert load_default_calibration().bandwidths["all_reduce"] == 2e7
    # same-path rewrite with an identical coarse mtime still reloads:
    # pin mtime to the old file's value; the rename changed the inode.
    st = os.stat(b_dir / "calibration.json")
    save_calibration(_flat_cal(bandwidth=3e7),
                     str(b_dir / "calibration.json"))
    os.utime(b_dir / "calibration.json", ns=(st.st_atime_ns,
                                             st.st_mtime_ns))
    assert load_default_calibration().bandwidths["all_reduce"] == 3e7
    reset_calibration_cache_for_testing()


# -- the live drill: drift -> re-search -> hot-swap, bit-exact ----------------

def _session(builder, params, loss_fn, batch, accum=1):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, accum_steps=accum)
    return ad, ad.create_distributed_session()


class _FixedBuilder:
    def __init__(self, strategy):
        self._s = strategy

    def build(self, graph_item, resource_spec):
        return self._s


def test_live_drill_drift_triggers_fingerprint_changing_hot_swap():
    """The acceptance drill: planted leg-drift mid-run triggers a
    re-search and a fingerprint-changing hot-swap through the RAM
    snapshot tier, and the resumed run is bit-exact against an oracle
    that started on the new schedule from the swap step."""
    rng = np.random.RandomState(0)
    params = {"l0": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                      jnp.float32),
                     "b": jnp.zeros(256, jnp.float32)},
              "l1": {"w": jnp.asarray(rng.randn(256, 256) * 0.05,
                                      jnp.float32),
                     "b": jnp.zeros(256, jnp.float32)}}
    batch = {"x": rng.randn(32, 256).astype(np.float32),
             "y": rng.randn(32, 256).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l0"]["w"] + p["l0"]["b"])
        h = h @ p["l1"]["w"] + p["l1"]["b"]
        return jnp.mean((h - b["y"]) ** 2)

    spec = _spec(8)
    # The run starts on plain AllReduce: under the ACTIVE constants
    # (flat defaults) that is a reasonable schedule.
    active = _flat_cal(bandwidth=45e9, alpha=5e-6)
    ad, sess = _session(AllReduce(), params, loss_fn, batch)
    gi = ad.graph_item
    old_fp = sess.schedule_fingerprint
    assert old_fp

    for _ in range(3):
        sess.run(batch)
    swap_step = sess.step_count
    # Oracle anchor: the logical state AT the swap step.
    from autodist_tpu.checkpoint.tiers import capture_snapshot
    anchor = capture_snapshot(sess)

    # Mid-run the world changes: live samples show the all_reduce leg
    # running 20x slower than the active constants predict (a throttled
    # interconnect), while RS/AG/PS legs stay on-model.
    tuner = ScheduleTuner(gi, spec, constants=active,
                          space=SearchSpace(max_rounds=2),
                          calibration_path=None)
    mb = float(1 << 20)
    drifted = []
    for nb in (1 << 18, 1 << 20, 4 << 20):
        drifted += _samples("all_reduce", 20 * nb / 45e9, n=6, nbytes=nb)
        for kind in ("reduce_scatter", "all_gather", "ps_exchange",
                     "ppermute_hop", "update", "psum_guard"):
            drifted += _samples(kind, nb / 45e9 + 5e-6, n=6, nbytes=nb)
    del mb
    tuner.feed_samples(drifted)
    reasons = tuner.drift_reasons()
    assert "all_reduce" in reasons          # the telemetry/leg-drift rule

    swapped = tuner.maybe_retune(sess)
    assert swapped, "drift did not produce a fingerprint-changing swap"
    new_fp = sess.schedule_fingerprint
    assert new_fp and new_fp != old_fp
    assert tuner.swaps == 1
    assert sess.step_count == swap_step      # swap loses no steps

    # The swapped session continues...
    for _ in range(3):
        out = sess.run(batch)
    swapped_params = sess.params
    swapped_loss = float(np.asarray(out["loss"]))
    new_strategy = sess._step.compiled_strategy.strategy
    del sess, ad

    # ...and must be bit-exact vs an oracle that STARTED on the new
    # schedule from the swap step's state (loaded through the SAME
    # snapshot-adoption semantics the swap used).
    ad2, oracle = _session(_FixedBuilder(new_strategy), params, loss_fn,
                           batch)
    tuner.adopt_snapshot(oracle, anchor, oracle._step)
    assert oracle.step_count == swap_step
    assert oracle.schedule_fingerprint == new_fp
    for _ in range(3):
        oout = oracle.run(batch)
    assert float(np.asarray(oout["loss"])) == swapped_loss
    import jax
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        swapped_params, oracle.params)
    del oracle, ad2
    _reset_default_autodist_for_testing()


def test_fit_tuner_wiring_swaps_mid_run():
    """fit(tuner=...) hands the session to the tuner at its interval;
    a planted drift swaps the schedule mid-fit and the loop finishes
    unaware (same History shape, steps uninterrupted)."""
    rng = np.random.RandomState(0)
    params = {"l0": {"w": jnp.asarray(rng.randn(128, 128) * 0.05,
                                      jnp.float32)}}
    batch = {"x": rng.randn(16, 128).astype(np.float32),
             "y": rng.randn(16, 128).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["l0"]["w"] - b["y"]) ** 2)

    spec = _spec()
    ad, sess = _session(AllReduce(), params, loss_fn, batch)
    old_fp = sess.schedule_fingerprint
    tuner = ScheduleTuner(ad.graph_item, spec, interval=3, profile=False,
                          constants=_flat_cal(),
                          space=SearchSpace(max_rounds=1),
                          calibration_path=None)
    drifted = []
    for nb in (1 << 18, 1 << 20):
        drifted += _samples("all_reduce", 20 * nb / 45e9, n=6, nbytes=nb)
        for kind in ("reduce_scatter", "all_gather", "ps_exchange",
                     "ppermute_hop", "update"):
            drifted += _samples(kind, nb / 45e9 + 5e-6, n=6, nbytes=nb)
    tuner.feed_samples(drifted)
    hist = sess.fit(batch, epochs=1, steps_per_epoch=8, tuner=tuner)
    assert hist.steps_run == 8
    assert tuner.swaps == 1
    assert sess.schedule_fingerprint != old_fp
    assert np.isfinite(hist.history["epoch_loss"][-1])
    del sess, ad
    _reset_default_autodist_for_testing()


def test_retune_keeps_schedule_when_current_still_wins():
    """No drift, or a re-search that confirms the running schedule,
    must not swap (the current strategy is injected as a seed)."""
    spec = _spec()
    params = {"w": jnp.zeros((64, 64), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(8, 64).astype(np.float32),
             "y": rng.randn(8, 64).astype(np.float32)}
    ad, sess = _session(AllReduce(), params, loss_fn, batch)
    tuner = ScheduleTuner(ad.graph_item, spec,
                          constants=_flat_cal(), calibration_path=None)
    # no samples -> no drift -> no retune
    assert tuner.maybe_retune(sess) is False
    assert tuner.swaps == 0
    del sess, ad
    _reset_default_autodist_for_testing()
