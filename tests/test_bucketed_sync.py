"""Bucketed gradient sync: planner edge cases + numerical equivalence.

The explicit path issues ONE collective per size-capped, dtype-grouped
bucket (``kernel/synchronization/bucketing.py``) instead of one per
variable.  These tests pin the planner's edge cases named in the PR
issue — a single param larger than ``bucket_bytes``, mixed bf16/f32
grads never sharing a bucket, the uneven tail bucket — and the
numerical-equivalence contract: bucketed sync must reproduce the
per-variable path to ~1e-6 on the CPU mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.kernel.synchronization import bucketing
from autodist_tpu.kernel.synchronization.bucketing import (
    assign_buckets,
    pack_bucket,
    unpack_bucket,
)
from autodist_tpu.strategy import AllReduce

pytestmark = pytest.mark.sync


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _entry(name, shape, dtype="float32", comp="NoneCompressor", group=0,
           mode="all_reduce"):
    return (name, shape, dtype, comp, group, mode)


# -- planner unit tests ------------------------------------------------------

def test_mixed_dtypes_never_share_a_bucket():
    buckets = assign_buckets([
        _entry("a", (8, 8), "float32"),
        _entry("b", (8, 8), "bfloat16"),
        _entry("c", (8,), "float32"),
        _entry("d", (8,), "bfloat16"),
    ])
    by_dtype = {}
    for b in buckets:
        for v in b.vars:
            by_dtype.setdefault(b.dtype, set()).add(v.name)
        assert len({b.dtype}) == 1
    assert by_dtype == {"float32": {"a", "c"}, "bfloat16": {"b", "d"}}


def test_single_param_larger_than_cap_gets_own_bucket():
    cap = 1024  # bytes; the 1024-element f32 var is 4x the cap
    buckets = assign_buckets([
        _entry("small1", (16,)),
        _entry("huge", (1024,)),
        _entry("small2", (16,)),
    ], bucket_bytes=cap)
    huge = [b for b in buckets if "huge" in b.names]
    assert len(huge) == 1 and huge[0].names == ("huge",)  # never split
    # the small vars regroup around it
    smalls = {n for b in buckets for n in b.names if n != "huge"}
    assert smalls == {"small1", "small2"}


def test_cap_splits_consecutive_vars():
    # 6 vars x 256 B with a 512 B cap -> 3 buckets of 2
    buckets = assign_buckets([_entry(f"v{i}", (64,)) for i in range(6)],
                             bucket_bytes=512)
    assert [len(b.vars) for b in buckets] == [2, 2, 2]
    # offsets are contiguous within each bucket
    for b in buckets:
        off = 0
        for v in b.vars:
            assert v.offset == off
            off += v.size
        assert b.total == off


def test_uneven_tail_pads_to_shard_divisor():
    buckets = assign_buckets([_entry("odd", (13,)), _entry("odd2", (7, 5))],
                             shard_divisor=8)
    (b,) = buckets
    assert b.total == 13 + 35
    assert b.padded_total == 48 and b.padded_total % 8 == 0
    assert b.pad == 0 if b.total % 8 == 0 else b.pad == b.padded_total - b.total


def test_group_ids_bound_buckets():
    buckets = assign_buckets([
        _entry("a", (4,), group=0), _entry("b", (4,), group=0),
        _entry("c", (4,), group=1),
    ])
    groups = {b.group: set(b.names) for b in buckets}
    assert groups == {0: {"a", "b"}, 1: {"c"}}


def test_pack_unpack_round_trip():
    buckets = assign_buckets([_entry("m", (3, 5)), _entry("v", (11,))],
                             shard_divisor=8)
    (b,) = buckets
    rng = np.random.RandomState(0)
    leaves = [jnp.asarray(rng.randn(3, 5), jnp.float32),
              jnp.asarray(rng.randn(11), jnp.float32)]
    vec = pack_bucket(b, leaves)
    assert vec.shape == (b.padded_total,)
    assert b.padded_total % 8 == 0
    out = unpack_bucket(b, vec)
    for a, x in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(x))
    # the pad tail is zero
    np.testing.assert_array_equal(np.asarray(vec[b.total:]), 0.0)


def test_powersgd_not_bucketable():
    assert bucketing.bucket_drop_reason((), False, "PowerSGDCompressor")
    assert bucketing.bucket_drop_reason((), False, "NoneCompressor") is None
    assert bucketing.bucket_drop_reason([(0, "model")], False,
                                        "NoneCompressor")


# -- end-to-end equivalence --------------------------------------------------

def _mixed_dtype_problem():
    """Multi-dtype (bf16 + f32) parameters with odd sizes — exercises
    dtype grouping, the uneven tail, and oversized-vs-cap in one model."""
    rng = np.random.RandomState(7)
    params = {
        "f32": {"w": jnp.asarray(rng.randn(13, 9) * 0.1, jnp.float32),
                "b": jnp.asarray(rng.randn(9) * 0.1, jnp.float32)},
        "bf16": {"w": jnp.asarray(rng.randn(9, 4) * 0.1, jnp.bfloat16)},
    }
    batch = {"x": rng.randn(16, 13).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["f32"]["w"] + p["f32"]["b"])
        out = h @ p["bf16"]["w"].astype(jnp.float32)
        return jnp.mean((out - b["y"]) ** 2)

    return params, loss_fn, batch


def _session(builder, params, loss_fn, opt=None):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn)
    return ad.create_distributed_session()


def _count_collectives(sess, batch):
    b = sess.place_batch(batch)
    txt = sess._step.step_fn.lower(
        sess.sharded_params, sess.opt_state, sess.sync_state, b).as_text()
    return {k: txt.count("stablehlo." + k)
            for k in ("all_reduce", "reduce_scatter", "all_gather")}


def test_bucketed_matches_per_variable_numerics():
    """Bucketed explicit sync == per-variable GSPMD sync to ~1e-6 over
    several optimizer steps (pure f32: the reductions are exact up to
    summation order)."""
    rng = np.random.RandomState(3)
    params = {"a": {"w": jnp.asarray(rng.randn(13, 9) * 0.1, jnp.float32),
                    "b": jnp.asarray(rng.randn(9) * 0.1, jnp.float32)},
              "out": {"w": jnp.asarray(rng.randn(9, 4) * 0.1, jnp.float32)}}
    batch = {"x": rng.randn(16, 13).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["a"]["w"] + p["a"]["b"])
        return jnp.mean((h @ p["out"]["w"] - b["y"]) ** 2)

    pervar = _session(AllReduce(), params, loss_fn)
    bucketed = _session(AllReduce(bucket_bytes=1 << 20), params, loss_fn)
    from autodist_tpu.kernel.synchronization import explicit_sync
    assert explicit_sync.uses_explicit_path(bucketed._step.compiled_strategy)
    assert not explicit_sync.uses_explicit_path(
        pervar._step.compiled_strategy)
    for _ in range(6):
        lp = pervar.run(batch)["loss"]
        lb = bucketed.run(batch)["loss"]
        np.testing.assert_allclose(float(lb), float(lp), rtol=1e-6,
                                   atol=1e-7)
    np.testing.assert_allclose(np.asarray(bucketed.params["a"]["w"]),
                               np.asarray(pervar.params["a"]["w"]),
                               rtol=1e-6, atol=1e-7)


def test_bucketed_mixed_dtype_tracks_per_variable():
    """With a bf16 variable in the model both paths reduce that bucket
    in bf16; they track each other to bf16 summation-order tolerance."""
    params, loss_fn, batch = _mixed_dtype_problem()
    pervar = _session(AllReduce(), params, loss_fn)
    bucketed = _session(AllReduce(bucket_bytes=1 << 20), params, loss_fn)
    for _ in range(6):
        lp = pervar.run(batch)["loss"]
        lb = bucketed.run(batch)["loss"]
        np.testing.assert_allclose(float(lb), float(lp), rtol=5e-4)


def test_bucketing_is_invisible_to_elementwise_compression():
    """bf16-cast compression is elementwise, so per-bucket quantization
    must EXACTLY reproduce per-variable quantization (chunk_size=1 puts
    every var in its own group/bucket)."""
    params, loss_fn, batch = _mixed_dtype_problem()
    one = _session(AllReduce(chunk_size=1, compressor="HorovodCompressor"),
                   params, loss_fn)
    many = _session(AllReduce(chunk_size=128,
                              compressor="HorovodCompressor"),
                    params, loss_fn)
    for _ in range(4):
        np.testing.assert_allclose(float(one.run(batch)["loss"]),
                                   float(many.run(batch)["loss"]),
                                   rtol=1e-6, atol=1e-7)
    # ...and the bucketed program issues strictly fewer collectives
    c_one = _count_collectives(one, batch)
    c_many = _count_collectives(many, batch)
    assert c_many["all_reduce"] < c_one["all_reduce"], (c_one, c_many)


def test_bucket_cap_controls_collective_count():
    rng = np.random.RandomState(1)
    params = {f"l{i}": jnp.asarray(rng.randn(32, 32) * 0.1, jnp.float32)
              for i in range(4)}
    batch = {"x": rng.randn(8, 32).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(4):
            h = jnp.tanh(h @ p[f"l{i}"])
        return jnp.mean(h ** 2)

    # 32*32*4 = 4096 B per var: a 4 KiB cap -> one bucket per var; a
    # 1 MiB cap -> one bucket total.
    small = _session(AllReduce(bucket_bytes=4096), params, loss_fn)
    big = _session(AllReduce(bucket_bytes=1 << 20), params, loss_fn)
    n_small = _count_collectives(small, batch)["all_reduce"]
    n_big = _count_collectives(big, batch)["all_reduce"]
    assert n_small - n_big == 3, (n_small, n_big)


def test_grad_accumulation_composes_with_buckets():
    params, loss_fn, batch = _mixed_dtype_problem()
    plain = _session(AllReduce(bucket_bytes=1 << 20), params, loss_fn)

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(bucket_bytes=1 << 20))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, accum_steps=2)
    accum = ad.create_distributed_session()
    for _ in range(3):
        np.testing.assert_allclose(float(accum.run(batch)["loss"]),
                                   float(plain.run(batch)["loss"]),
                                   rtol=5e-5, atol=1e-6)
