"""MPMD pipeline runtime tests (docs/pipeline.md).

Covers the stage partitioner, the DCN activation transport, the
``send_act``/``recv_act`` schedule-IR legs (tier parity, fingerprint
equality, mutation goldens with DISTINCT rule ids), pipeline pricing
(bubble fraction + exposed DCN activation bytes), stage-filtered chaos,
hang localization naming the wedged stage, the ``stages=`` sweep
dimension, and a 2-stage thread-backed parity run against the
single-program ``one_f_one_b`` oracle.  The live 2 stages x 2 DP procs
drill (tests/integration/mpmd_train.py) rides at the end under the
``slow`` marker.
"""
import dataclasses
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.parallel import mpmd
from autodist_tpu.parallel.mpmd import transport as tmod
from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos
from autodist_tpu.resilience.elastic import ElasticResumeError

pytestmark = pytest.mark.mpmd

L, D = 4, 8
S, M = 2, 4


def _layers(seed=0, l=L, d=D):
    rng = np.random.RandomState(seed)
    return [{"w": (rng.randn(d, d) * 0.3).astype(np.float32),
             "b": (rng.randn(d) * 0.1).astype(np.float32)}
            for _ in range(l)]


def _prog(s=S, m=M, **kw):
    kw.setdefault("act_nbytes", 2 * D * 4)
    return mpmd.build_pipeline_ir(layer_params=_layers(), num_stages=s,
                                  num_microbatches=m, **kw)


def _rules(ir):
    return {v.rule for v in sir.errors(sir.verify(ir))}


# -- satellite 3: ONE stage-name spelling everywhere --------------------------

def test_stage_naming_shared_helper():
    assert sir.stage_name(1) == "stage1"
    assert sir.stage_name(3, "expert") == "expert3"
    assert sir.stage_index("stage7") == 7
    assert sir.stage_of("stage1/l2/w") == "stage1"
    assert sir.stage_of("expert3/up") == "expert3"
    # the partitioner's qualified names parse back through the same
    # helper the verifier and MoEFact use
    part, stages = mpmd.partition_params(_layers(), S)
    for i, sp in enumerate(stages):
        for name in sp:
            assert sir.stage_of(name) == sir.stage_name(i)
    assert part.param_names(0) == tuple(sorted(stages[0]))


def test_chaos_stage_spec_normalizes_through_stage_name():
    # `stage=1` and `stage=stage1` are the same filter
    ev_digit = parse_chaos("kill@step=1,proc=0,stage=1")[0]
    ev_named = parse_chaos("kill@step=1,proc=0,stage=stage1")[0]
    assert ev_digit.stage == ev_named.stage == sir.stage_name(1)


# -- partitioner --------------------------------------------------------------

def test_assign_layers_balanced_front_loaded():
    assert mpmd.assign_layers(4, 2) == ((0, 1), (2, 3))
    # the spare layer goes to the EARLY stage (1F1B memory profile)
    assert mpmd.assign_layers(5, 2) == ((0, 1, 2), (3, 4))
    assert mpmd.assign_layers(7, 3) == ((0, 1, 2), (3, 4), (5, 6))
    with pytest.raises(ValueError, match=sir.RULE_STAGE_MISMATCH):
        mpmd.assign_layers(2, 3)


def test_partition_params_naming():
    part, stages = mpmd.partition_params(_layers(), S)
    assert part.layers == ((0, 1), (2, 3))
    assert sorted(stages[0]) == ["stage0/l0/b", "stage0/l0/w",
                                 "stage0/l1/b", "stage0/l1/w"]
    assert sorted(stages[1]) == ["stage1/l2/b", "stage1/l2/w",
                                 "stage1/l3/b", "stage1/l3/w"]
    assert mpmd.strip_stage("stage1/l2/w") == "l2/w"
    assert mpmd.strip_stage("l2/w") == "l2/w"


def test_restage_roundtrip_lossless():
    layers = _layers()
    _, two = mpmd.partition_params(layers, 2)
    four = mpmd.restage_params(two, 4)
    assert len(four) == 4
    back = mpmd.restage_params(four, 2)
    for a, b in zip(two, back):
        assert sorted(a) == sorted(b)
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_restage_torn_save_raises():
    _, two = mpmd.partition_params(_layers(), 2)
    torn = [dict(two[0]), dict(two[1])]
    # layer 2's weight claimed by BOTH stage snapshots: a torn save
    torn[0]["stage0/l2/w"] = two[1]["stage1/l2/w"]
    with pytest.raises(ElasticResumeError, match="torn save"):
        mpmd.restage_params(torn, 2)


def test_stage_mismatch_reason_rule_prefixed():
    assert sir.stage_mismatch_reason(2, 4) is None
    for bad in (sir.stage_mismatch_reason(0, 4),
                sir.stage_mismatch_reason(8, 8, num_layers=4),
                sir.stage_mismatch_reason(4, 2)):
        assert bad is not None and bad.startswith(sir.RULE_STAGE_MISMATCH)
    with pytest.raises(ValueError, match=sir.RULE_STAGE_MISMATCH):
        _prog(s=2, m=1)


def test_preflight_stage_resize():
    prog = _prog()
    meta = {"partition": prog.partition.to_meta(),
            "num_microbatches": M, "act_nbytes": 2 * D * 4}
    new = mpmd.preflight_stage_resize(meta, num_stages=4,
                                      num_microbatches=4)
    assert new.partition.num_stages == 4
    assert new.fingerprint() != prog.fingerprint()
    assert not sir.errors(sir.verify(new.ir))
    with pytest.raises(ElasticResumeError,
                       match=sir.RULE_STAGE_MISMATCH):
        mpmd.preflight_stage_resize(meta, num_stages=8)
    with pytest.raises(ElasticResumeError,
                       match=sir.RULE_STAGE_MISMATCH):
        mpmd.preflight_stage_resize(meta, num_stages=4,
                                    num_microbatches=2)


# -- the IR: tier parity, fingerprints, mutation goldens ----------------------

def test_transport_legs_tier_and_shape():
    prog = _prog()
    assert not sir.errors(sir.verify(prog.ir))
    transport = [l for l in prog.ir.legs if l.kind in sir.TRANSPORT_KINDS]
    # S=2, M=4: one fwd + one bwd boundary, each M send/recv pairs
    assert len(transport) == 2 * 2 * M
    for leg in transport:
        assert leg.tier == sir.TIER_DCN
        assert leg.stage in ("stage0", "stage1")
        bufs = leg.writes if leg.kind == sir.LEG_SEND_ACT else leg.reads
        assert len(bufs) == 1 and bufs[0].startswith("act:")
    sends = [l for l in transport if l.kind == sir.LEG_SEND_ACT]
    assert len(sends) == 2 * M


def test_fingerprint_static_equals_runtime():
    prog = _prog()
    rebuilt = sir.ir_from_facts(list(prog.facts), axes=dict(prog.axes),
                                accum_steps=M,
                                pipeline=list(prog.pipeline))
    assert rebuilt.fingerprint() == prog.ir.fingerprint()
    # the STATIC dedupe key (a hash of the fact INPUTS, not the legs)
    # is deterministic: same facts -> same key -> same program
    assert prog.fingerprint() == _prog().fingerprint()
    assert _prog(m=8).fingerprint() != prog.fingerprint()


def test_pre_mpmd_fingerprints_unchanged():
    # a pipeline-free build must hash identically whether or not the
    # (empty) pipeline argument is spelled out — old fingerprints,
    # checkpoints, and goldens stay valid
    facts = [sir.PlanFact(name="w", shape=(64, 64), dtype="float32",
                          sync_kind="AllReduce")]
    a = sir.ir_from_facts(facts, axes={"data": 2})
    b = sir.ir_from_facts(facts, axes={"data": 2}, pipeline=[])
    assert a.fingerprint() == b.fingerprint()
    assert sir.facts_fingerprint(facts, axes={"data": 2}) \
        == sir.facts_fingerprint(facts, axes={"data": 2}, pipeline=[])


def _clone(ir):
    return sir.ScheduleIR.from_dict(ir.to_dict())


def test_mutation_orphaned_recv_is_act_transport():
    clone = _clone(_prog().ir)
    # drop the LAST backward recv at stage0: its send is orphaned
    clone.legs = [l for l in clone.legs
                  if l.id != f"pipe/pipe/b0@{M - 1}/recv"]
    assert sir.RULE_ACT_TRANSPORT in _rules(clone)


def test_mutation_unordered_recv_is_race_read_write():
    clone = _clone(_prog().ir)
    legs = list(clone.legs)
    i = next(k for k, l in enumerate(legs)
             if l.id == "pipe/pipe/f0@0/recv")
    # recv no longer depends on its send: the act: buffer read races
    # the write AND the transport contract breaks
    legs[i] = dataclasses.replace(legs[i], deps=())
    clone.legs = legs
    rules = _rules(clone)
    assert sir.RULE_RACE_READ_WRITE in rules
    assert sir.RULE_ACT_TRANSPORT in rules


def test_mutation_dangling_dep_is_unknown_dep():
    clone = _clone(_prog().ir)
    legs = list(clone.legs)
    i = next(k for k, l in enumerate(legs)
             if l.id == "pipe/pipe/f0@1/send")
    legs[i] = dataclasses.replace(
        legs[i], deps=legs[i].deps + ("pipe/pipe/f9@9/send",))
    clone.legs = legs
    assert sir.RULE_UNKNOWN_DEP in _rules(clone)


def test_mutation_cycle_is_dep_cycle():
    clone = _clone(_prog().ir)
    legs = list(clone.legs)
    first = next(k for k, l in enumerate(legs)
                 if l.kind in sir.TRANSPORT_KINDS)
    legs[first] = dataclasses.replace(
        legs[first], deps=legs[first].deps + (legs[-1].id,))
    clone.legs = legs
    assert sir.RULE_DEP_CYCLE in _rules(clone)


def test_mutation_misordered_send_slots_is_act_transport():
    clone = _clone(_prog().ir)
    legs = list(clone.legs)
    a = next(k for k, l in enumerate(legs)
             if l.id == "pipe/pipe/f0@0/send")
    b = next(k for k, l in enumerate(legs)
             if l.id == "pipe/pipe/f0@1/send")
    # swap the slots WITHOUT moving the legs: the chain's send order no
    # longer matches microbatch order (a mis-sequenced runner)
    legs[a] = dataclasses.replace(legs[a], slot=1)
    legs[b] = dataclasses.replace(legs[b], slot=0)
    clone.legs = legs
    assert sir.RULE_ACT_TRANSPORT in _rules(clone)


# -- pricing: bubble + exposed DCN activation bytes ---------------------------

def test_cost_model_prices_bubble_and_act_bytes():
    from autodist_tpu.strategy.cost_model import (act_transport_bytes,
                                                  estimate_ir_cost)
    prog = _prog()
    report = estimate_ir_cost(prog.ir, compute_time_s=1.0)
    want = sir.bubble_fraction_1f1b(S, M)
    assert report.bubble_fraction == pytest.approx(want)
    assert want == pytest.approx(1 / 3)
    total, exposed = act_transport_bytes(prog.ir)
    assert total > 0
    # 8 send legs total; only the slot M-1 pair is outside the hidden
    # accumulation window
    assert total == pytest.approx(4 * exposed)
    # no pipeline -> no bubble, no activation wire
    flat = sir.ir_from_facts(list(prog.facts), axes=dict(prog.axes),
                             accum_steps=M)
    assert estimate_ir_cost(flat, compute_time_s=1.0) \
        .bubble_fraction == 0.0
    assert act_transport_bytes(flat) == (0.0, 0.0)


# -- transport ----------------------------------------------------------------

def test_transport_inmemory_roundtrip_and_timeout():
    tmod.reset_registry()
    tr = mpmd.ActivationTransport("", channel="dp0", timeout_s=0.2)
    v = np.arange(12, dtype=np.float32).reshape(3, 4)
    tr.send("act:pipe/f0@0", v)
    got = tr.recv("act:pipe/f0@0")
    assert np.array_equal(got, v)
    # channels are disjoint scopes
    other = mpmd.ActivationTransport("", channel="dp1", timeout_s=0.05)
    with pytest.raises(mpmd.TransportTimeout, match="act:pipe/f0@0"):
        other.recv("act:pipe/f0@0")


def test_transport_directory_nonconsuming_and_gc(tmp_path):
    tmod.reset_registry()
    a = mpmd.ActivationTransport(str(tmp_path), channel="dp0",
                                 timeout_s=1.0)
    v = np.ones((4,), np.float32)
    a.send("s2/act:pipe/f0@0", v)
    tmod.reset_registry()   # force the directory path
    b = mpmd.ActivationTransport(str(tmp_path), channel="dp0",
                                 timeout_s=1.0)
    assert np.array_equal(b.recv("s2/act:pipe/f0@0"), v)
    # NON-consuming: a chaos-restarted runner re-reads the same step
    assert np.array_equal(b.recv("s2/act:pipe/f0@0"), v)
    assert b.gc("s2/") >= 1
    with pytest.raises(mpmd.TransportTimeout):
        b.recv("s2/act:pipe/f0@0", timeout_s=0.05)


def test_transport_corrupt_blob_skipped_then_retransmit(tmp_path):
    tmod.reset_registry()
    tr = mpmd.ActivationTransport(str(tmp_path), channel="dp0",
                                  timeout_s=5.0, poll_s=0.005)
    path = tr._path("act:pipe/f0@0")
    with open(path, "wb") as f:
        f.write(b"ADTPUACT1 garbage that fails the digest")
    tmod.reset_registry()
    v = np.full((3,), 7.0, np.float32)

    def retransmit():
        good = mpmd.ActivationTransport(str(tmp_path), channel="dp0")
        good.send("act:pipe/f0@0", v)

    t = threading.Timer(0.1, retransmit)
    t.start()
    try:
        tmod.reset_registry()   # make the recv poll the directory blob
        got = tr.recv("act:pipe/f0@0")
    finally:
        t.join()
    assert np.array_equal(got, v)


# -- chaos: stage= filtering --------------------------------------------------

def _armed_monkey(spec, **kw):
    monkey = ChaosMonkey(parse_chaos(spec), **kw)
    fired = []
    monkey._exit = lambda code: fired.append(code)
    return monkey, fired


def test_chaos_stage_filter_fires_only_on_matching_stage():
    spec = "kill@step=1,proc=0,stage=1,code=43"
    monkey, fired = _armed_monkey(spec, process_index=0, attempt=0,
                                  stage="stage0")
    monkey.on_step(1)
    assert fired == []          # wrong stage: no fire
    monkey, fired = _armed_monkey(spec, process_index=0, attempt=0,
                                  stage="stage1")
    monkey.on_step(0)
    assert fired == []          # right stage, wrong step
    monkey.on_step(1)
    assert fired == [43]


def test_chaos_stage_from_environment(monkeypatch):
    # StageRunner stamps AUTODIST_STAGE; an unconfigured monkey picks
    # the stage identity up from there
    spec = "kill@step=2,stage=0,code=41"
    monkeypatch.setenv("AUTODIST_STAGE", "stage1")
    monkey, fired = _armed_monkey(spec, process_index=0)
    monkey.on_step(2)
    assert fired == []
    monkeypatch.setenv("AUTODIST_STAGE", "stage0")
    monkey, fired = _armed_monkey(spec, process_index=0)
    monkey.on_step(2)
    assert fired == [41]


# -- hang localization names the wedged stage ---------------------------------

def test_localize_hang_names_wedged_stage(tmp_path):
    from autodist_tpu.telemetry import flightrec as fr

    ir = _prog().ir
    recv = "pipe/pipe/f0@0/recv"      # stage1's first fwd input
    later = f"pipe/pipe/b0@{M - 1}/send"
    diag = fr.localize_hang(ir, {
        "stage1/dp0": {"leg": recv, "kind": "leg", "step": 3},
        "stage1/dp1": {"leg": later, "kind": "leg", "step": 3},
    })
    assert diag is not None
    assert diag.frontier_leg == recv
    assert diag.culprits == ("stage1/dp0",)
    assert diag.stage == "stage1"
    assert "wedged at pipeline stage 'stage1'" in diag.detail
    bundle = tmp_path / "bundle"
    bundle.mkdir()
    (bundle / "hang.json").write_text(json.dumps(diag.to_dict()))
    report = fr.render_hang_report(str(bundle))
    assert "wedged stage: stage1" in report
    assert recv in report


# -- the stages= sweep dimension ----------------------------------------------

def test_simulate_sweep_stages_dimension():
    from autodist_tpu.analysis.simulate import (format_sweep_report,
                                                parse_sweep_spec,
                                                run_sweep)
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy import AllReduce

    gi = GraphItem({"w": jnp.zeros((256, 256), jnp.float32)})

    def make(spec, hier):
        return (AllReduce(hier=True) if hier else AllReduce()).build(
            gi, spec)

    config = parse_sweep_spec("mesh=data=8;slices=1;dcn=25;"
                              "stages=1,2,8;mb=4;act=1")
    assert config["stages"] == [1, 2, 8]
    report = run_sweep(gi, make, config)
    by_stages = {p["stages"]: p for p in report["points"]}
    assert set(by_stages) == {1, 2, 8}
    # 8 stages cannot run 4 microbatches: pruned BEFORE pricing, with
    # the shared rule id
    assert by_stages[8]["pruned_by"].startswith(sir.RULE_STAGE_MISMATCH)
    piped = by_stages[2]
    assert piped["microbatches"] == 4
    for cell in piped["modes"].values():
        assert cell["bubble_fraction"] == pytest.approx(
            sir.bubble_fraction_1f1b(2, 4))
        assert cell["dcn_act_bytes"]["total"] > 0
        assert cell["dcn_act_bytes"]["exposed"] \
            <= cell["dcn_act_bytes"]["total"]
    # single-stage points carry no pipeline cells
    assert "bubble_fraction" not in \
        next(iter(by_stages[1]["modes"].values()))
    text = format_sweep_report(report)
    assert "stages=2" in text and "bubble" in text


# -- the runner: ZeRO-1 kernel + thread-backed parity drill -------------------

def test_make_zero1_update_degenerate_matches_sgd():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    upd = mpmd.make_zero1_update(mesh, lr=0.1, num_shards=1)
    p = jnp.arange(8, dtype=jnp.float32)
    g = jnp.ones((8,), jnp.float32)
    out = np.asarray(upd(g[None, :], p))
    assert np.allclose(out, np.asarray(p) - 0.1 * np.asarray(g))


def test_two_stage_parity_vs_one_f_one_b_oracle():
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.parallel.pipeline_1f1b import one_f_one_b

    layers = _layers()
    part, stage_params = mpmd.partition_params(layers, S)
    prog = mpmd.build_pipeline_ir(layer_params=layers, num_stages=S,
                                  num_microbatches=M,
                                  act_nbytes=2 * D * 4)

    def stage_fn_for(si):
        def fn(p, x):
            h = x
            for j in part.layers[si]:
                pre = f"{sir.stage_name(si)}/l{j}"
                h = jnp.tanh(h @ p[f"{pre}/w"] + p[f"{pre}/b"])
            return h
        return fn

    def mse(y, t):
        return jnp.mean((y - t) ** 2)

    rng = np.random.RandomState(1)
    B = 8
    x = rng.randn(B, D).astype(np.float32)
    tgt = rng.randn(B, D).astype(np.float32)
    rows = B // M
    x_mbs = [x[i * rows:(i + 1) * rows] for i in range(M)]
    t_mbs = [tgt[i * rows:(i + 1) * rows] for i in range(M)]

    tmod.reset_registry()
    runners = [mpmd.StageRunner(
        prog, si, stage_fn=stage_fn_for(si), params=stage_params[si],
        transport=mpmd.ActivationTransport("", channel="dp0"), lr=0.1,
        loss_fn=mse if si == S - 1 else None) for si in range(S)]

    steps, losses = 3, []
    for _ in range(steps):
        res = [None] * S

        def run(si):
            res[si] = runners[si].run_step(
                x_mbs if si == 0 else None,
                t_mbs if si == S - 1 else None)

        ths = [threading.Thread(target=run, args=(si,))
               for si in range(S)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        losses.append(res[S - 1])

    # oracle: the SAME model as one stacked single-program 1F1B loop
    sp = {"w": np.stack([np.stack([layers[j]["w"] for j in run])
                         for run in part.layers]),
          "b": np.stack([np.stack([layers[j]["b"] for j in run])
                         for run in part.layers])}

    def sfn(p, h):
        for j in range(p["w"].shape[0]):
            h = jnp.tanh(h @ p["w"][j] + p["b"][j])
        return h

    mesh = build_mesh({"pipe": S}, devices=jax.devices()[:S])
    cur = {k: jnp.asarray(v) for k, v in sp.items()}
    oracle = []
    for _ in range(steps):
        loss, grads, _ = one_f_one_b(sfn, mse, cur, jnp.asarray(x),
                                     jnp.asarray(tgt), mesh,
                                     num_microbatches=M)
        cur = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, cur,
                                     grads)
        oracle.append(float(loss))

    assert max(abs(a - b) for a, b in zip(losses, oracle)) <= 1e-5


# -- the live 2 stages x 2 DP procs drill -------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRILL = os.path.join(REPO, "tests", "integration", "mpmd_train.py")


@pytest.mark.slow
def test_mpmd_live_drill(tmp_path):
    """2 stages x 2 DP procs over the gloo coordinator: loss parity
    <= 1e-5 vs the single-program oracle, and a chaos-killed stage
    worker recovers through the supervisor BIT-EXACT."""
    result_file = tmp_path / "result.json"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("AUTODIST_")}
    env.update({
        "AUTODIST_REPO_ROOT": REPO,
        "AUTODIST_MPMD_WORKDIR": str(tmp_path / "work"),
        "AUTODIST_RESULT_FILE": str(result_file),
        "PYTHONPATH": REPO,
    })
    proc = subprocess.run([sys.executable, DRILL], env=env,
                          capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, \
        f"drill failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    result = json.loads(result_file.read_text())
    clean, chaos, oracle = (result["clean"], result["chaos"],
                            result["oracle"])
    # parity vs the single-program 1F1B oracle
    assert len(clean["losses"]) == len(oracle["losses"])
    for a, b in zip(clean["losses"], oracle["losses"]):
        assert abs(a - b) <= 1e-5, (clean["losses"], oracle["losses"])
    # the chaos job killed at least one stage worker and recovered
    assert chaos["restarts"] >= 1
    # ... BIT-exact: same losses, same final parameter checksums
    assert chaos["losses"] == clean["losses"]
    assert chaos["checksums"] == clean["checksums"]
    # one schedule fingerprint across every process of every attempt
    assert len(set(clean["fingerprints"])) == 1
    assert set(chaos["fingerprints"]) == set(clean["fingerprints"])
