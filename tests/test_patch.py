"""Implicit program capture (autodist_tpu/patch.py).

Parity target: reference ``PatchTensorFlow.patch_optimizers`` capturing a
plain training script's optimizer + gradients without AutoDist API calls
(``autodist/patch.py:40-116``, exercised by every reference integration case
that just builds a model under ``ad.scope()``)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.patch import PatchOptax
from autodist_tpu.strategy import AllReduce


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()
    yield
    # A failed test must not leave the global patches installed.
    if PatchOptax.active_record() is not None:
        PatchOptax.unpatch()


def _params():
    return {"w": jnp.arange(4.0), "b": jnp.zeros(())}


def _loss(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batch(n=8):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    return {"x": x, "y": (x @ np.arange(4.0) + 1.0).astype(np.float32)}


def test_plain_script_is_captured_implicitly():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        # A plain optax training-script prefix — no AutoDist calls at all.
        opt = optax.sgd(0.1)
        opt.init(_params())
        jax.value_and_grad(_loss)
    sess = ad.create_distributed_session()
    m1 = sess.run(_batch())
    m2 = sess.run(_batch())
    assert m2["loss"] < m1["loss"]  # actually training


def test_implicit_matches_explicit_numerics():
    batch = _batch()

    ad1 = AutoDist(strategy_builder=AllReduce())
    with ad1.scope():
        opt = optax.adamw(1e-2)
        opt.init(_params())
        jax.value_and_grad(_loss)
    s1 = ad1.create_distributed_session()

    _reset_default_autodist_for_testing()
    ad2 = AutoDist(strategy_builder=AllReduce())
    with ad2.scope():
        ad2.capture(params=_params(), optimizer=optax.adamw(1e-2),
                    loss_fn=_loss)
    s2 = ad2.create_distributed_session()

    for _ in range(3):
        l1 = s1.run(batch)["loss"]
        l2 = s2.run(batch)["loss"]
        np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_chain_records_outermost_transformation():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        opt = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.1))
        opt.init(_params())
        jax.grad(_loss)
    rec = ad._implicit_record
    assert rec.optimizer_factory == "chain"
    sess = ad.create_distributed_session()
    assert np.isfinite(sess.run(_batch())["loss"])


def test_has_aux_flag_is_captured():
    def loss_aux(params, batch):
        loss = _loss(params, batch)
        return loss, {"l2": jnp.sum(params["w"] ** 2)}

    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        opt = optax.sgd(0.1)
        opt.init(_params())
        jax.value_and_grad(loss_aux, has_aux=True)
    sess = ad.create_distributed_session()
    metrics = sess.run(_batch())
    assert "aux" in metrics and "l2" in metrics["aux"]


def test_explicit_capture_wins_over_implicit():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        opt = optax.sgd(0.5)  # implicit record (would diverge)
        opt.init({"w": jnp.ones(2), "b": jnp.zeros(())})
        ad.capture(params=_params(), optimizer=optax.sgd(0.1), loss_fn=_loss)
    sess = ad.create_distributed_session()
    assert sess.params["w"].shape == (4,)


def test_scope_exit_restores_namespaces():
    orig_adam = optax.adam
    orig_vg = jax.value_and_grad
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        assert optax.adam is not orig_adam
        assert jax.value_and_grad is not orig_vg
    assert optax.adam is orig_adam
    assert jax.value_and_grad is orig_vg


def test_incomplete_capture_reports_whats_missing():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        opt = optax.sgd(0.1)
        opt.init(_params())
        # no jax.grad call → loss_fn missing
    with pytest.raises(RuntimeError, match="loss_fn"):
        ad.create_distributed_session()


def test_nothing_captured_keeps_legacy_error():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        pass
    with pytest.raises(RuntimeError, match="capture"):
        ad.create_distributed_session()


def test_tracer_params_are_not_captured():
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        opt = optax.sgd(0.1)

        @jax.jit
        def init_under_jit(p):
            return opt.init(p)  # tracer pytree: must not be recorded

        init_under_jit(_params())
        opt.init(_params())  # concrete: recorded
        jax.grad(_loss)
    rec = ad._implicit_record
    assert rec.params is not None
    assert not any(isinstance(x, jax.core.Tracer)
                   for x in jax.tree_util.tree_leaves(rec.params))


def test_patch_gate_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_PATCH", "False")
    orig_adam = optax.adam
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        assert optax.adam is orig_adam  # patching disabled


def test_positional_has_aux_captured():
    """jax.value_and_grad(fun, argnums, has_aux) passed POSITIONALLY must
    still record has_aux."""
    import jax
    import jax.numpy as jnp

    from autodist_tpu.patch import PatchOptax

    def loss_aux(p, b):
        return jnp.sum(p ** 2), {"n": jnp.sum(b)}

    rec = PatchOptax.patch()
    try:
        jax.value_and_grad(loss_aux, 0, True)
    finally:
        out = PatchOptax.unpatch()
    assert out is rec
    assert rec.loss_fn is loss_aux
    assert rec.has_aux is True


def test_loss_fn_overwrite_warns(monkeypatch):
    """A second jax.grad inside the scope wins but warns loudly.  (The
    framework logger sets propagate=False, so spy on the warning call
    instead of caplog.)"""
    import jax
    import jax.numpy as jnp

    from autodist_tpu import patch as patch_mod
    from autodist_tpu.patch import PatchOptax

    warnings = []
    monkeypatch.setattr(patch_mod.logging, "warning",
                        lambda msg, *a: warnings.append(msg % a))

    def train_loss(p, b):
        return jnp.sum(p ** 2)

    def diag(p, b):
        return jnp.sum(p)

    PatchOptax.patch()
    try:
        jax.value_and_grad(train_loss)
        jax.grad(diag)
    finally:
        rec = PatchOptax.unpatch()
    assert rec.loss_fn is diag
    assert any("replaces previously recorded" in w for w in warnings)
