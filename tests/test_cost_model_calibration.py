"""Cost-model calibration: predicted strategy ranking vs MEASURED step
times (VERDICT r2 #6 — turn the advisory ranking into evidence).

The model's times are explicitly "order-of-magnitude for ranking"
(``strategy/cost_model.py``); these tests check the *ranking* claim
against wall-clock measurements of real compiled steps on the virtual
8-device CPU mesh, for a sparse-heavy and a dense workload:

* sparse-heavy — the Parallax argument: builders that densify the
  embedding gradient (AllReduce family) must rank *and measure* slower
  than sparse-PS builders; Kendall tau between predicted and measured
  orderings must be positive.
* dense — all ring lowerings move the same volume, so the model predicts
  near-ties; the check is consistency (the measured-fastest builder's
  predicted time within a small factor of the predicted-fastest), not a
  strict order over ties.

Calibration status recorded here and surfaced by bench.py's scaling
projection: the RANKING is validated on the CPU mesh; the absolute
times (ICI_BANDWIDTH / COLLECTIVE_ALPHA) remain hardware-uncalibrated —
one real chip cannot measure a cross-chip collective.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PartitionedAR,
    PS,
    PSLoadBalancing,
)
from autodist_tpu.strategy.cost_model import estimate_cost


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _spec8():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "127.0.0.1", "chips": 8, "chief": True}]})


def _measure(builder, params, loss_fn, batch, sparse_vars=(), steps=12):
    """Wall-clock step time through the real session path."""
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=loss_fn, sparse_vars=sparse_vars)
    sess = ad.create_distributed_session()
    placed = sess.place_batch(batch)
    for _ in range(3):
        sess.run(placed)
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            sess.run(placed, sync=False)
        float(np.asarray(sess.run(placed)["loss"]))
        reps.append((time.perf_counter() - t0) / (steps + 1))
    return min(reps)   # min over repeats: robust to host noise


def _kendall_tau(a, b):
    """Plain O(n^2) Kendall tau between two equal-length rankings."""
    n = len(a)
    concordant = discordant = 0
    for i in range(n):
        for j in range(i + 1, n):
            s = (a[i] - a[j]) * (b[i] - b[j])
            if s > 0:
                concordant += 1
            elif s < 0:
                discordant += 1
    pairs = n * (n - 1) / 2
    return (concordant - discordant) / pairs


def test_sparse_workload_rank_agreement():
    """Predicted ordering matches measured for the workload where costs
    genuinely differ (dense-vs-sparse embedding sync)."""
    vocab, dim = 200_000, 32
    rng = np.random.RandomState(0)
    params = {
        "emb": {"table": jnp.asarray(rng.randn(vocab, dim) * 0.01,
                                     jnp.float32)},
        "head": {"w": jnp.asarray(rng.randn(dim, 1) * 0.1, jnp.float32)},
    }
    batch = {
        "ids": rng.randint(0, vocab, (256,)).astype(np.int32),
        "y": rng.randn(256).astype(np.float32),
    }

    def loss_fn(p, b):
        rows = jnp.take(p["emb"]["table"], b["ids"], axis=0)
        pred = (rows @ p["head"]["w"])[:, 0]
        return jnp.mean((pred - b["y"]) ** 2)

    builders = [AllReduce(), PartitionedAR(), Parallax(), PSLoadBalancing()]
    spec = _spec8()
    gi = GraphItem(params, sparse_vars=["emb/table"])
    predicted = [estimate_cost(b.build(gi, spec), gi, spec,
                               sparse_rows_hint=256).time_s
                 for b in builders]
    measured = [_measure(b, params, loss_fn, batch,
                         sparse_vars=("emb/table",)) for b in builders]

    # The headline claim: sparse-aware builders beat gradient-densifying
    # ones in BOTH predicted and measured orderings...
    for sparse_aware in (2, 3):          # Parallax, PSLoadBalancing
        for densifying in (0, 1):        # AllReduce, PartitionedAR
            assert predicted[sparse_aware] < predicted[densifying]
            assert measured[sparse_aware] < measured[densifying], (
                builders[sparse_aware], measured)
    # ...and the full orderings correlate beyond what the pairwise
    # asserts already imply (those guarantee tau >= 1/3).
    tau = _kendall_tau(predicted, measured)
    assert tau >= 0.5, (predicted, measured, tau)


def test_dense_workload_prediction_consistency():
    """Dense models: every ring lowering moves the same bytes, so the
    model predicts near-ties — assert it does NOT strongly misorder:
    the measured-fastest builder's predicted time is within 2x of the
    predicted-fastest (ties are fine, contradictions are not)."""
    rng = np.random.RandomState(1)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(512, 512) * 0.05, jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(512, 512) * 0.05, jnp.float32)},
        "out": {"w": jnp.asarray(rng.randn(512, 1) * 0.1, jnp.float32)},
    }
    batch = {"x": rng.randn(128, 512).astype(np.float32),
             "y": rng.randn(128).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"])
        h = jnp.tanh(h @ p["l2"]["w"])
        return jnp.mean(((h @ p["out"]["w"])[:, 0] - b["y"]) ** 2)

    builders = [AllReduce(), PS(), PSLoadBalancing(), PartitionedAR()]
    spec = _spec8()
    gi = GraphItem(params)
    predicted = [estimate_cost(b.build(gi, spec), gi, spec).time_s
                 for b in builders]
    measured = [_measure(b, params, loss_fn, batch) for b in builders]

    fastest_measured = int(np.argmin(measured))
    assert predicted[fastest_measured] <= 2.0 * min(predicted), (
        predicted, measured)
