"""Rematerialization: remat-wrapped training must be numerically identical
to the un-rematerialized run (it only changes what is recomputed)."""
import jax
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.strategy import AllReduce


@pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch"])
def test_remat_matches_plain(policy, monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    spec = transformer_lm(vocab_size=64, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16, seq_len=16)
    params = spec.init(jax.random.PRNGKey(0))
    batch = spec.sample_batch(8)

    def run(remat):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=AllReduce(), mesh_axes={"data": 8})
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, remat=remat)
        sess = ad.create_distributed_session()
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    np.testing.assert_allclose(run(policy), run(None), rtol=1e-6, atol=1e-6)


def test_unknown_policy_raises():
    with pytest.raises(ValueError, match="unknown remat policy"):
        GraphItem({"w": jax.numpy.zeros(2)}, loss_fn=lambda p, b: 0.0,
                  remat="bogus")
