"""Pipeline parallelism vs sequential stage application.

Oracle: applying the S stages one after another on the full batch.  The
pipelined schedule (microbatches + ppermute ring) must match exactly, for
values and gradients, on the virtual CPU mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.mesh import build_mesh
from autodist_tpu.parallel.pipeline import (
    bubble_fraction,
    default_num_microbatches,
    interleaved_stage_order,
    pipeline_apply,
    schedule_ticks,
    stack_stage_params,
)

S, B, D = 4, 8, 16


def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _make(rng):
    stages = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.3,
                                jnp.float32),
               "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32)}
              for _ in range(S)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    return stages, stacked, x


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_no_pipe_axis_scan_path():
    stages, stacked, x = _make(np.random.default_rng(0))
    mesh = build_mesh({"data": 8})
    out = pipeline_apply(_stage_fn, stacked, x, mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("num_microbatches", [None, 8])
def test_pipelined_matches_sequential(num_microbatches):
    stages, stacked, x = _make(np.random.default_rng(1))
    mesh = build_mesh({"pipe": 4, "data": 2})

    @jax.jit
    def run(stacked, x):
        return pipeline_apply(_stage_fn, stacked, x, mesh,
                              num_microbatches=num_microbatches)

    with jax.set_mesh(mesh):
        out = run(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipelined_gradients_match_sequential():
    stages, stacked, x = _make(np.random.default_rng(2))
    mesh = build_mesh({"pipe": 4, "data": 2})

    def loss_pipe(stacked, x):
        return jnp.sum(pipeline_apply(_stage_fn, stacked, x, mesh) ** 2)

    def loss_seq(stages, x):
        return jnp.sum(_sequential(stages, x) ** 2)

    with jax.set_mesh(mesh):
        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked, x)
    g_seq = jax.grad(loss_seq)(stages, x)
    g_seq_stacked = stack_stage_params(g_seq)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[name]),
                                   np.asarray(g_seq_stacked[name]),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_bad_microbatch_count_raises():
    _, stacked, x = _make(np.random.default_rng(3))
    mesh = build_mesh({"pipe": 4, "data": 2})
    with pytest.raises(ValueError, match="not divisible"):
        with jax.set_mesh(mesh):
            pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=3)


def test_pipelined_lm_end_to_end():
    """Full AutoDist pipeline: pipelined LM on a pipe×data×model mesh must
    track the same model trained on a no-pipe mesh step for step."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm
    from autodist_tpu.strategy import PartitionedPS

    def run(axes):
        _reset_default_autodist_for_testing()
        mesh = build_mesh(axes)
        spec = pipelined_transformer_lm(
            mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
            d_ff=32, max_len=16, seq_len=16)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=PartitionedPS(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        rng = np.random.RandomState(0)
        return [float(sess.run(spec.make_batch(rng, 8))["loss"])
                for _ in range(3)]

    piped = run({"pipe": 2, "data": 2, "model": 2})
    flat = run({"data": 4, "model": 2})
    np.testing.assert_allclose(piped, flat, rtol=1e-4, atol=1e-4)
    assert piped[-1] < piped[0]


def test_schedule_tick_counts_and_bubble():
    """GPipe: M+S-1 ticks, bubble (S-1)/(M+S-1); the default M=4S keeps the
    bubble under 20%.  Interleaved V cuts the bubble ~V× at equal M."""
    s = 4
    # GPipe (V=1).
    for m in (4, 8, 16):
        assert schedule_ticks(s, m) == m + s - 1
        assert bubble_fraction(s, m) == pytest.approx(
            (s - 1) / (m + s - 1))
    # Default microbatch count: 4·S when the batch allows.
    m = default_num_microbatches(s, 64)
    assert m == 4 * s
    assert bubble_fraction(s, m) <= (s - 1) / (m + s - 1) + 1e-12
    assert bubble_fraction(s, m) < 0.2
    # Interleaved: ticks M·V + S - 1 of 1/V-size work → bubble ≈ /V.
    for v in (2, 4):
        assert schedule_ticks(s, m, v) == m * v + s - 1
        assert bubble_fraction(s, m, v) == pytest.approx(
            (s - 1) / (m * v + s - 1))
        assert bubble_fraction(s, m, v) < bubble_fraction(s, m) / v * 1.35


@pytest.mark.parametrize("num_microbatches", [4, 8])
@pytest.mark.parametrize("num_virtual", [2, 4])
def test_interleaved_matches_sequential(num_microbatches, num_virtual):
    """Interleaved schedule (V chunks per device) must match sequential
    application of all S·V stages, values and gradients."""
    rng = np.random.default_rng(5)
    n_chunks = 4 * num_virtual
    stages = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.2,
                                jnp.float32),
               "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32)}
              for _ in range(n_chunks)]
    # pipeline_apply expects the stage axis device-major for V>1.
    order = interleaved_stage_order(4, num_virtual)
    stacked = stack_stage_params([stages[g] for g in order])
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    mesh = build_mesh({"pipe": 4, "data": 2})

    def loss_pipe(stacked, x):
        y = pipeline_apply(_stage_fn, stacked, x, mesh,
                           num_microbatches=num_microbatches,
                           num_virtual_stages=num_virtual)
        return jnp.sum(y ** 2), y

    def loss_seq(stages, x):
        return jnp.sum(_sequential(stages, x) ** 2)

    with jax.set_mesh(mesh):
        g_pipe, out = jax.jit(
            jax.grad(loss_pipe, has_aux=True))(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)),
                               rtol=1e-5, atol=1e-5)
    g_seq_list = jax.grad(loss_seq)(stages, x)
    g_seq = stack_stage_params([g_seq_list[g] for g in order])
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[name]),
                                   np.asarray(g_seq[name]),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_interleaved_lm_end_to_end():
    """Pipelined LM with 2 virtual stages tracks the flat-mesh model."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm
    from autodist_tpu.strategy import PartitionedPS

    def run(axes, virtual):
        _reset_default_autodist_for_testing()
        mesh = build_mesh(axes)
        spec = pipelined_transformer_lm(
            mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
            d_ff=32, max_len=16, seq_len=16, num_virtual_stages=virtual)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=PartitionedPS(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        rng = np.random.RandomState(0)
        return [float(sess.run(spec.make_batch(rng, 8))["loss"])
                for _ in range(3)]

    inter = run({"pipe": 2, "data": 4}, 2)
    flat = run({"data": 8}, 1)
    np.testing.assert_allclose(inter, flat, rtol=1e-4, atol=1e-4)


def test_pipeline_apply_eager():
    """Regression: pipeline_apply must work outside jax.jit (partial-manual
    shard_map needs the internal jit wrap)."""
    stages, stacked, x = _make(np.random.default_rng(4))
    mesh = build_mesh({"pipe": 4, "data": 2})
    with jax.set_mesh(mesh):
        out = pipeline_apply(_stage_fn, stacked, x, mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_ps_partitioner_no_duplicate_data_axis():
    """Regression: pipeline var + PS partitioner on a model-less mesh must
    not produce PartitionSpec('pipe', 'data', 'data')."""
    from jax.sharding import NamedSharding
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy.compiler import StrategyCompiler
    from autodist_tpu.strategy.base import (
        PSSynchronizerConfig, Strategy, VarConfig)

    mesh = build_mesh({"pipe": 2, "data": 4})
    gi = GraphItem({"stack": {"w": jnp.zeros((4, 8, 8))}},
                   pipeline_vars=("stack",))
    strat = Strategy(node_config=[VarConfig(
        var_name="stack/w", synchronizer=PSSynchronizerConfig(),
        partitioner="1,4,1")])
    compiled = StrategyCompiler(mesh).compile(strat, gi)
    plan = compiled.plan_for("stack/w")
    # Must be constructible (no DuplicateSpecError) for both layouts.
    NamedSharding(mesh, plan.param_spec)
    NamedSharding(mesh, plan.opt_spec)
    assert plan.param_spec[0] == "pipe"


def test_remat_matches_values_and_gradients():
    """remat=True recomputes stage internals in backward — values and
    gradients stay bit-identical to the non-remat schedule."""
    mesh = build_mesh({"pipe": S, "data": 1})
    rng = np.random.default_rng(7)
    stages, stacked, x = _make(rng)

    def loss(stacked_p, x, remat):
        y = pipeline_apply(_stage_fn, stacked_p, x, mesh, remat=remat)
        return jnp.sum(y ** 2)

    v0, g0 = jax.value_and_grad(lambda p: loss(p, x, False))(stacked)
    v1, g1 = jax.value_and_grad(lambda p: loss(p, x, True))(stacked)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5), g0, g1)


def test_remat_reduces_stashed_activation_memory():
    """The point of remat: the differentiated schedule stashes fewer
    residual bytes.  Compare XLA's temp-buffer sizes for a taller stage
    (several matmuls) — remat must not be larger, and the grad still
    matches."""
    mesh = build_mesh({"pipe": S, "data": 1})
    rng = np.random.default_rng(8)

    def tall_stage(params, x):
        for i in range(4):
            x = jnp.tanh(x @ params[f"w{i}"])
        return x

    stages = [{f"w{i}": jnp.asarray(rng.standard_normal((D, D)) * 0.3,
                                    jnp.float32) for i in range(4)}
              for _ in range(S)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((32, D)), jnp.float32)

    def make_grad(remat):
        def loss(p, x):
            return jnp.sum(pipeline_apply(tall_stage, p, x, mesh,
                                          num_microbatches=8,
                                          remat=remat) ** 2)
        return jax.jit(jax.grad(loss))

    g_plain = make_grad(False)
    g_remat = make_grad(True)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5),
        g_plain(stacked, x), g_remat(stacked, x))

    def temp_bytes(fn):
        mem = fn.lower(stacked, x).compile().memory_analysis()
        assert mem is not None and hasattr(mem, "temp_size_in_bytes"), \
            "memory_analysis unavailable — the regression guard below " \
            "would be vacuous"
        return mem.temp_size_in_bytes

    plain, remat = temp_bytes(g_plain), temp_bytes(g_remat)
    # Strict: losing the jax.checkpoint wrap in a refactor keeps values
    # and gradients identical, so THIS inequality is the feature's only
    # guard (measured ~29% cut for this program: 58,208 vs 81,632 bytes).
    assert remat < plain, (remat, plain)


def test_pipelined_lm_remat_trains():
    """remat threads through the pipelined LM spec and trains."""
    import optax

    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm

    mesh = build_mesh({"pipe": 4, "data": 2})
    spec = pipelined_transformer_lm(
        mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
        d_ff=32, max_len=16, seq_len=16, remat=True)
    params = spec.init(jax.random.PRNGKey(0))
    batch = spec.sample_batch(8)
    opt = optax.sgd(0.1)
    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        loss, g = jax.value_and_grad(spec.loss_fn)(p, b)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    p, s = params, state
    losses = []
    for _ in range(3):
        p, s, l = step(p, s, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]
