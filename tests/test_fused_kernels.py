"""Fused Pallas kernel suite vs its unfused references (docs/kernels.md).

Every kernel runs in interpret mode on the CPU mesh (the exact bodies
the TPU compiles) against the arithmetic it replaces: the guard's two
reductions, the optax Adam chain, the quantize/dequantize composition of
``quant_ring``, and the paged gather-softmax.  Plus the IR surface —
fused leg kinds, fingerprints, mutation goldens for the new
``schedule/fused-inconsistent`` rule — the calibration kinds, the shared
drop-reason rule, and a full fused-vs-unfused session parity drill under
the ``AUTODIST_FUSED_INTERPRET`` escape hatch.
"""
import importlib

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.ops import fused_kernels as fk

pytestmark = pytest.mark.kernels


# ---------------------------------------------------------------------------
# kernel 1: fused detect stats
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [7, 256, 10_001, fk._BLOCK_ELEMS * 2])
def test_detect_stats_matches_reference(n):
    rng = np.random.default_rng(n)
    v = jnp.asarray(rng.standard_normal(n), jnp.float32)
    nf, sq = fk.fused_detect_stats(v)
    assert float(nf) == 0.0
    np.testing.assert_allclose(float(sq), float(jnp.sum(v * v)),
                               rtol=1e-6)


def test_detect_stats_finite_bit_bit_identical():
    """The skip decision is driven by the finite BIT; count > 0 must
    agree with ``1 - all(isfinite)`` exactly for NaN, Inf, and clean
    inputs — not just approximately."""
    rng = np.random.default_rng(0)
    base = jnp.asarray(rng.standard_normal(5000), jnp.float32)
    for poison in (None, jnp.nan, jnp.inf, -jnp.inf):
        v = base if poison is None else base.at[137].set(poison)
        nf, sq = fk.fused_detect_stats(v)
        ref_bit = bool(jnp.all(jnp.isfinite(v)))
        assert (float(nf) == 0.0) == ref_bit
        if poison is None:
            assert np.isfinite(float(sq))
        else:
            # NaN/Inf propagate into the square sum exactly as in the
            # unfused sum(v*v) — the norm is poisoned either way.
            assert not np.isfinite(float(sq))


def test_pack_detect_is_pack_plus_stats():
    from autodist_tpu.kernel.synchronization.bucketing import (
        assign_buckets, pack_bucket)
    buckets = assign_buckets(
        [("a", (32, 8), "float32", "NoneCompressor", 0, "all_reduce"),
         ("b", (100,), "float32", "NoneCompressor", 0, "all_reduce")],
        shard_divisor=8)
    (b,) = buckets
    rng = np.random.default_rng(3)
    leaves = [jnp.asarray(rng.standard_normal((32, 8)), jnp.float32),
              jnp.asarray(rng.standard_normal(100), jnp.float32)]
    vec, nf, sq = fk.fused_pack_detect(b, leaves)
    np.testing.assert_array_equal(np.asarray(vec),
                                  np.asarray(pack_bucket(b, leaves)))
    assert float(nf) == 0.0
    np.testing.assert_allclose(float(sq), float(jnp.sum(vec * vec)),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# kernel 2: fused unscale/clip/Adam update
# ---------------------------------------------------------------------------

def _optax_chain(p, g, opt, state, mult):
    scaled = jax.tree_util.tree_map(lambda x: x * mult, g)
    updates, state = opt.update(scaled, state, p)
    return optax.apply_updates(p, updates), state


@pytest.mark.parametrize("mult_val", [1.0, 0.25])
def test_fused_adam_matches_optax_chain(mult_val):
    """The PR 5 exactness contract: the fused shard update equals the
    optax chain (unscale*clip multiplier, then adam) at 1e-6 over
    multiple steps, with the shared step counter advancing."""
    spec = fk.AdamSpec(lr=1e-3)
    opt = optax.adam(spec.lr, b1=spec.b1, b2=spec.b2, eps=spec.eps)
    rng = np.random.default_rng(7)
    n = 3000
    p_ref = {"v": jnp.asarray(rng.standard_normal(n), jnp.float32)}
    state = opt.init(p_ref)
    p = p_ref["v"]
    mu = jnp.zeros(n, jnp.float32)
    nu = jnp.zeros(n, jnp.float32)
    mult = jnp.float32(mult_val)
    for step in range(3):
        g = {"v": jnp.asarray(rng.standard_normal(n), jnp.float32)}
        p_ref, state = _optax_chain(p_ref, g, opt, state, mult)
        p, mu, nu = fk.fused_adam_update(
            p, g["v"], mu, nu, jnp.int32(step), spec, mult=mult)
        np.testing.assert_allclose(np.asarray(p), np.asarray(p_ref["v"]),
                                   atol=1e-6, rtol=0,
                                   err_msg=f"step {step}")
    adam_ref = fk.find_adam_state(state)
    np.testing.assert_allclose(np.asarray(mu), np.asarray(adam_ref.mu["v"]),
                               atol=1e-6, rtol=0)
    np.testing.assert_allclose(np.asarray(nu), np.asarray(adam_ref.nu["v"]),
                               atol=1e-6, rtol=0)


def test_fusable_adam_behaves_like_optax_adam():
    fused = fk.fusable_adam(1e-2)
    base = optax.adam(1e-2)
    p = {"w": jnp.ones((4,), jnp.float32)}
    g = {"w": jnp.full((4,), 0.5, jnp.float32)}
    u1, _ = fused.update(g, fused.init(p), p)
    u2, _ = base.update(g, base.init(p), p)
    np.testing.assert_array_equal(np.asarray(u1["w"]), np.asarray(u2["w"]))
    assert fused.fused_spec.lr == pytest.approx(1e-2)


def test_adam_state_probe_and_replace():
    opt = optax.adam(1e-3)
    state = opt.init({"x": jnp.zeros(4)})
    adam = fk.find_adam_state(state)
    assert adam is not None and hasattr(adam, "mu")
    new = fk.replace_adam_state(state, adam._replace(count=adam.count + 5))
    assert int(fk.find_adam_state(new).count) == 5
    # a non-adam chain has no addressable moments
    sgd_state = optax.sgd(0.1).init({"x": jnp.zeros(4)})
    assert fk.find_adam_state(sgd_state) is None


# ---------------------------------------------------------------------------
# kernel 3: quantize-at-the-hop
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fmt", [quant_ring.WIRE_INT8,
                                 quant_ring.WIRE_FP8_E4M3])
def test_fused_quantize_matches_quantize_blocks(fmt):
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal(3000) * 3, jnp.float32)
    q_ref, s_ref, sat_ref = quant_ring.quantize_blocks(x, fmt)
    q, s, err, sat = fk.fused_quantize(x, fmt)
    np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q))
    np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s), rtol=1e-6)
    # err is self-consistent with the kernel's own (q, scales) to
    # round-off, and within 2e-5 of the unfused composition (the scale's
    # last-bit difference between XLA and the interpreter amplifies
    # through q*scale).
    np.testing.assert_allclose(
        np.asarray(err),
        np.asarray(x - quant_ring.dequantize_blocks(q, s)),
        atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(err),
        np.asarray(x - quant_ring.dequantize_blocks(q_ref, s_ref)),
        atol=2e-5)
    # fp8 counts |y| > qmax on the unrounded y; the block's amax element
    # sits exactly AT the rail, so the scale's last bit can flip its
    # count by one between XLA and the interpreter.  Int8 rounds first
    # and is robust; non-finite saturation is pinned exactly below.
    slack = 0 if fmt.name == "int8" else 1
    assert abs(float(sat) - float(sat_ref)) <= slack
    poisoned = x.at[5].set(jnp.inf).at[900].set(jnp.nan)
    _, _, _, sat_p = fk.fused_quantize(poisoned, fmt)
    _, _, sat_p_ref = quant_ring.quantize_blocks(poisoned, fmt)
    assert float(sat_p) >= 2.0
    assert abs(float(sat_p) - float(sat_p_ref)) <= slack


def test_fused_hop_matches_composition():
    """One hop boundary fused == dequantize ∘ add ∘ requantize of the
    unfused path (wire payload bit-equal, scales/err at 1e-6)."""
    fmt = quant_ring.WIRE_INT8
    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.standard_normal(2048), jnp.float32)
    chunk = jnp.asarray(rng.standard_normal(2048), jnp.float32)
    q0, s0, _ = quant_ring.quantize_blocks(x, fmt)
    acc_ref = quant_ring.dequantize_blocks(q0, s0) + chunk
    q_ref, s_ref, _ = quant_ring.quantize_blocks(acc_ref, fmt)
    q, s, err, _ = fk.fused_hop_accumulate(q0, s0, chunk, fmt)
    np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q))
    np.testing.assert_allclose(np.asarray(s_ref), np.asarray(s), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(err),
        np.asarray(acc_ref - quant_ring.dequantize_blocks(q_ref, s_ref)),
        atol=1e-6)
    acc = fk.fused_dequant_add(q0, s0, chunk, fmt)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(acc_ref),
                               atol=1e-6)


def test_fused_ring_reduce_scatter_matches_unfused():
    """The whole fused ring on a real 8-device CPU mesh: shard sums,
    error-feedback vectors, and saturation counts match the unfused
    ring at 1e-6 (the wire payloads are the same grid)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from autodist_tpu.utils import compat

    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    rng = np.random.default_rng(13)
    vec = jnp.asarray(rng.standard_normal((n, 2048)), jnp.float32)

    def run(fused):
        def body(v):
            out, err, sat = quant_ring.quantized_ring_reduce_scatter(
                v.reshape(-1), "data", n, quant_ring.WIRE_INT8,
                fused=fused)
            return out, err, sat[None]
        fn = jax.jit(compat.shard_map(
            body, mesh=mesh, in_specs=P("data"),
            out_specs=(P("data"), P("data"), P("data")),
            check_vma=False))
        return fn(vec)

    out_u, err_u, sat_u = run(False)
    out_f, err_f, sat_f = run(True)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_f),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(err_u), np.asarray(err_f),
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(sat_u), np.asarray(sat_f))


# ---------------------------------------------------------------------------
# kernel 4: paged attention
# ---------------------------------------------------------------------------

def _paged_reference(q, kc, vc, bt, rel):
    b, h, dh = q.shape
    bs = kc.shape[1]
    w = bt.shape[1] * bs
    kb = jnp.take(kc, bt, axis=0).reshape(b, w, h, dh)
    vb = jnp.take(vc, bt, axis=0).reshape(b, w, h, dh)
    logits = jnp.einsum("bhk,bwhk->bhw", q, kb.astype(q.dtype)) \
        / jnp.sqrt(jnp.asarray(dh, q.dtype))
    mask = jnp.arange(w)[None, None, :] <= rel[:, None, None]
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bhw,bwhk->bhk", probs, vb.astype(q.dtype))


@pytest.mark.parametrize("rel_spec", ["varied", "first", "full"])
def test_paged_attention_matches_gather_reference(rel_spec):
    rng = np.random.default_rng(21)
    b, h, dh, nb, bs, maxb = 3, 2, 16, 12, 4, 5
    q = jnp.asarray(rng.standard_normal((b, h, dh)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((nb, bs, h, dh)), jnp.float32)
    bt = jnp.asarray(rng.integers(1, nb, (b, maxb)), jnp.int32)
    rel = {"varied": jnp.asarray([0, 7, 19], jnp.int32),
           "first": jnp.zeros((b,), jnp.int32),
           "full": jnp.full((b,), maxb * bs - 1, jnp.int32)}[rel_spec]
    out = fk.paged_attention(q, kc, vc, bt, rel)
    ref = _paged_reference(q, kc, vc, bt, rel)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_paged_engine_token_exact_with_kernel(monkeypatch):
    """The serving acceptance drill: a PagedDecodeEngine decoding
    through the fused kernel is TOKEN-EXACT vs the per-request
    `generate` oracle — prefix blocks, mid-table indirection, dead
    slots and all.  The paged jit cache is cleared so the fused
    decision re-resolves for this trace."""
    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import PagedDecodeEngine
    from autodist_tpu.serving import paged_kv

    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "paged_attention")
    monkeypatch.setenv("AUTODIST_FUSED_INTERPRET", "1")
    paged_kv._paged_chunk_program.clear_cache()
    paged_kv._paged_prefill_program.clear_cache()
    try:
        vocab = 41
        spec = transformer_lm(vocab_size=vocab, num_layers=2, num_heads=2,
                              head_dim=8, d_ff=32, max_len=48, seq_len=16,
                              attn_fn=dense_attention)
        params = spec.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(5)
        reqs = [(rng.randint(0, vocab, p).astype(np.int32), n)
                for p, n in [(3, 5), (6, 3), (2, 6)]]
        eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                                block_size=8, num_blocks=24, chunk=4)
        ids = [eng.submit(p, n) for p, n in reqs]
        results = eng.run()
        gen = make_generator(spec)
        for rid, (prompt, n) in zip(ids, reqs):
            np.testing.assert_array_equal(
                results[rid], np.asarray(gen(params, prompt[None], n))[0])
        eng.assert_no_leaks()
    finally:
        paged_kv._paged_chunk_program.clear_cache()
        paged_kv._paged_prefill_program.clear_cache()


# ---------------------------------------------------------------------------
# knobs + the shared drop-reason rule
# ---------------------------------------------------------------------------

def test_requested_kernels_parsing(monkeypatch):
    monkeypatch.delenv("AUTODIST_FUSED_KERNELS", raising=False)
    assert fk.requested_kernels() == frozenset()
    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "all")
    assert fk.requested_kernels() == frozenset(fk.ALL_KERNELS)
    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "guard, quant_hop")
    assert fk.requested_kernels() == {"guard", "quant_hop"}


def test_drop_reasons_are_shared_strings():
    # off-TPU without the escape hatch
    why = fk.fused_drop_reason("guard", on_tpu=False, interpret_ok=False)
    assert why is not None and "AUTODIST_FUSED_INTERPRET" in why
    assert fk.fused_drop_reason("guard", on_tpu=False,
                                interpret_ok=True) is None
    # update-specific gates
    assert "fusable_adam" in fk.fused_drop_reason(
        "update", on_tpu=True, optimizer_fusable=False)
    assert "ScaleByAdamState" in fk.fused_drop_reason(
        "update", on_tpu=True, adam_state_shaped=False)
    assert "float32" in fk.fused_drop_reason(
        "update", on_tpu=True, f32_buckets=False)
    assert "unknown fused kernel" in fk.fused_drop_reason(
        "nope", on_tpu=True)


def test_resolve_fused_off_tpu_drops_with_warn_reason(monkeypatch):
    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "all")
    monkeypatch.delenv("AUTODIST_FUSED_INTERPRET", raising=False)
    active, drops = fk.resolve_fused(
        guard=True, has_rs=True, has_quant_ring=True,
        optimizer_fusable=True)
    assert active == ()
    assert {k for k, _ in drops} == {"guard", "update", "quant_hop"}
    for _, why in drops:
        assert "TPU backend" in why


def test_resolve_fused_quiet_when_inapplicable(monkeypatch):
    """A requested kernel whose hot path does not exist in the program
    is silently inapplicable, not a WARN."""
    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "all")
    monkeypatch.setenv("AUTODIST_FUSED_INTERPRET", "1")
    active, drops = fk.resolve_fused(
        guard=False, has_rs=False, has_quant_ring=False)
    assert active == () and drops == []


# ---------------------------------------------------------------------------
# schedule IR: fused variants, goldens, pricing, calibration kinds
# ---------------------------------------------------------------------------

def _fused_ir(fused_kernels=("guard", "update", "quant_hop")):
    from autodist_tpu.kernel.synchronization import bucketing, overlap
    entries = [(f"l{i}/w", (256, 256), "float32", "Int8Compressor", 0,
                "reduce_scatter") for i in range(4)]
    buckets = bucketing.assign_buckets(entries, bucket_bytes=256 << 10,
                                       shard_divisor=8)
    plan = overlap.resolve_overlap(["ring"], accum_steps=1,
                                   buckets=buckets, d=8, has_rs=True)
    return sir.build_schedule_ir(axes={"data": 8}, accum_steps=1,
                                 buckets=buckets, plan=plan, guard=True,
                                 fused_kernels=fused_kernels)


def test_fused_ir_variants_verify_and_fingerprint_distinctly():
    base = _fused_ir(())
    fused = _fused_ir()
    assert not sir.errors(sir.verify(base))
    assert not sir.errors(sir.verify(fused))
    assert base.fingerprint() != fused.fingerprint()
    kinds = {l.kind for l in fused.legs}
    assert {sir.LEG_FUSED_HOP, sir.LEG_FUSED_DETECT,
            sir.LEG_FUSED_UPDATE} <= kinds
    assert all(n.get("hop_fused") for n in fused.buckets)
    # serialization round-trips the fused record + fingerprint
    rt = sir.ScheduleIR.from_json(fused.to_json())
    assert rt.fused_kernels == ("guard", "update", "quant_hop")
    assert rt.fingerprint() == fused.fingerprint()
    # an empty fused record serializes exactly as before (stable
    # fingerprints for every pre-fusion program)
    assert "fused_kernels" not in base.to_dict()


def test_golden_fused_legs_without_record_rejected():
    fused = _fused_ir()
    mutated = sir.ScheduleIR.from_json(fused.to_json())
    mutated.fused_kernels = ()
    rules = {v.rule for v in sir.errors(sir.verify(mutated))}
    assert rules == {sir.RULE_FUSED_INCONSISTENT}


def test_golden_fused_hop_for_linear_compressor_rejected():
    fused = _fused_ir()
    mutated = sir.ScheduleIR.from_json(fused.to_json())
    mutated.legs = [
        l if l.kind != sir.LEG_FUSED_HOP else
        sir.Leg(**{**{f: getattr(l, f)
                      for f in sir.Leg.__dataclass_fields__},
                   "compressor": "NoneCompressor"})
        for l in mutated.legs]
    rules = {v.rule for v in sir.errors(sir.verify(mutated))}
    assert sir.RULE_FUSED_INCONSISTENT in rules


def test_golden_fused_hop_order_still_ring_checked():
    """The ring grammar covers fused hops too: swapping two fused hops
    of one chain deadlocks the ppermute and must be rejected by the
    established ring-hop-order rule."""
    fused = _fused_ir()
    mutated = sir.ScheduleIR.from_json(fused.to_json())
    hops = [l for l in mutated.legs if l.kind == sir.LEG_FUSED_HOP]
    chain = hops[0].chain
    chain_hops = [l for l in hops if l.chain == chain]
    assert len(chain_hops) >= 2
    a, b = chain_hops[0], chain_hops[1]

    def swap(l):
        if l.id == a.id:
            return sir.Leg(**{**{f: getattr(a, f)
                                 for f in sir.Leg.__dataclass_fields__},
                              "hop": b.hop})
        if l.id == b.id:
            return sir.Leg(**{**{f: getattr(b, f)
                                 for f in sir.Leg.__dataclass_fields__},
                              "hop": a.hop})
        return l
    mutated.legs = [swap(l) for l in mutated.legs]
    rules = {v.rule for v in sir.errors(sir.verify(mutated))}
    assert sir.RULE_RING_HOP_ORDER in rules


def test_estimate_ir_cost_prices_fused_kinds():
    from autodist_tpu.strategy.cost_model import estimate_ir_cost
    from autodist_tpu.telemetry.calibration import fit_leg_constants

    fused = _fused_ir()
    # uncalibrated: fused wire still counted (fused_hop is a collective)
    rep = estimate_ir_cost(fused)
    assert rep.wire_bytes > 0 and rep.time_s > 0
    samples = [
        dict(kind="fused_hop", measured_s=2e-4, nbytes=40_000,
             compressor="Int8Compressor"),
        dict(kind="fused_detect", measured_s=6e-5, nbytes=262_144,
             compressor="NoneCompressor"),
        dict(kind="fused_update", measured_s=4e-5, nbytes=32_768,
             compressor="NoneCompressor"),
        dict(kind="ppermute_hop", measured_s=3e-4, nbytes=40_000,
             compressor="NoneCompressor"),
    ]
    cal = fit_leg_constants(samples)
    assert {"fused_hop", "fused_detect", "fused_update"} \
        <= set(cal.bandwidths)
    rep_cal = estimate_ir_cost(fused, constants=cal)
    assert rep_cal.time_s > 0
    # fused-vs-unfused price differently once both kinds are fitted
    unfused = _fused_ir(())
    assert estimate_ir_cost(unfused, constants=cal).time_s \
        != pytest.approx(rep_cal.time_s)


def test_profiler_micro_runs_cover_fused_kinds():
    from autodist_tpu.telemetry.profiler import LegProfiler, span_leg_kind

    prof = LegProfiler(warmup=0, repeats=1)
    samples = prof.profile_ir(_fused_ir())
    kinds = {s.kind for s in samples}
    assert {"fused_hop", "fused_detect", "fused_update"} <= kinds
    # the span vocabulary maps the fused sync scopes
    assert span_leg_kind("autodist_sync/quant_ring_fused/leg2") \
        == "fused_hop"
    assert span_leg_kind("autodist_sync/fused_pack_detect/b0") \
        == "fused_detect"
    assert span_leg_kind("autodist_sync/fused_shard_update") \
        == "fused_update"


# ---------------------------------------------------------------------------
# runtime + analysis fallback surfaces
# ---------------------------------------------------------------------------

def _small_session(monkeypatch, kernels, interpret):
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1

    if kernels:
        monkeypatch.setenv("AUTODIST_FUSED_KERNELS", kernels)
    else:
        monkeypatch.delenv("AUTODIST_FUSED_KERNELS", raising=False)
    if interpret:
        monkeypatch.setenv("AUTODIST_FUSED_INTERPRET", "1")
    else:
        monkeypatch.delenv("AUTODIST_FUSED_INTERPRET", raising=False)
    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(288, 288) * 0.05,
                                         jnp.float32)} for i in range(2)}
    batch = {"x": rng.randn(16, 288).astype(np.float32),
             "y": rng.randn(16, 288).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(2):
            h = jnp.tanh(h @ p[f"l{i}"]["w"])
        return jnp.mean((h - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=1 << 20,
                                         compressor="Int8Compressor",
                                         overlap="ring"))
    with ad.scope():
        ad.capture(params=params, optimizer=fk.fusable_adam(1e-3),
                   loss_fn=loss_fn,
                   numerics={"clip_norm": 1.0, "loss_scale": None})
    sess = ad.create_distributed_session()
    return ad, sess, batch


@pytest.mark.slow
def test_session_fused_matches_unfused(monkeypatch):
    """All three training kernels active (interpret escape hatch) vs
    the unfused session: same losses, params within 1e-5 after 3 steps,
    fused record + leg kinds in the IR."""
    from autodist_tpu.autodist import _reset_default_autodist_for_testing

    def run(kernels):
        ad, sess, batch = _small_session(monkeypatch, kernels, True)
        ir = sess.schedule_ir
        placed = sess.place_batch(batch)
        losses = [float(sess.run(placed)["loss"]) for _ in range(3)]
        p = jax.tree_util.tree_map(np.asarray, sess.params)
        _reset_default_autodist_for_testing()
        return ir, losses, p

    ir_u, loss_u, p_u = run("")
    ir_f, loss_f, p_f = run("guard,update,quant_hop")
    assert ir_u.fused_kernels == ()
    assert ir_f.fused_kernels == ("guard", "update", "quant_hop")
    kinds = {l.kind for l in ir_f.legs}
    assert {sir.LEG_FUSED_HOP, sir.LEG_FUSED_DETECT,
            sir.LEG_FUSED_UPDATE} <= kinds
    np.testing.assert_allclose(loss_u, loss_f, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_u),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_allclose(a, b, atol=1e-5)


class _LogGrabber(__import__("logging").Handler):
    """The autodist logger does not propagate (its own handlers), so
    fallback-WARN assertions attach a handler directly — the
    test_quant_ring/bench counter idiom."""

    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


def test_runtime_falls_back_off_tpu_with_warn(monkeypatch):
    """Requested kernels off-TPU (no escape hatch): the session builds
    UNFUSED, logs the shared drop reason once per kernel, and the IR
    records no fused kernels."""
    import logging

    from autodist_tpu.autodist import _reset_default_autodist_for_testing

    grab = _LogGrabber()
    logger = logging.getLogger("autodist_tpu")
    logger.addHandler(grab)
    try:
        ad, sess, batch = _small_session(monkeypatch, "all", False)
        assert sess.schedule_ir.fused_kernels == ()
        assert not any(l.kind in (sir.LEG_FUSED_HOP, sir.LEG_FUSED_DETECT,
                                  sir.LEG_FUSED_UPDATE)
                       for l in sess.schedule_ir.legs)
        msgs = [m for m in grab.messages
                if "falls back to the unfused lowering" in m]
        assert len(msgs) == 3
        assert all("TPU backend" in m for m in msgs)
    finally:
        logger.removeHandler(grab)
        _reset_default_autodist_for_testing()


def test_analysis_surfaces_fused_fallback_warn(monkeypatch):
    """The analysis schedule pass emits schedule/fused-fallback with
    the runtime's exact drop-reason string."""
    from autodist_tpu.autodist import _reset_default_autodist_for_testing
    from autodist_tpu.analysis import analyze

    ad, sess, batch = _small_session(monkeypatch, "all", False)
    try:
        report = analyze(ad.build_strategy(), ad.graph_item,
                         mesh={"data": 8})
        diags = [d for d in report.diagnostics
                 if d.rule == "schedule/fused-fallback"]
        assert diags, [d.rule for d in report.diagnostics]
        assert any("TPU backend" in d.message for d in diags)
    finally:
        _reset_default_autodist_for_testing()


def test_analysis_quiet_when_kernels_active(monkeypatch):
    """With kernels active (escape hatch) the analysis side resolves
    the SAME fused set as the runtime: no fallback WARN, and the
    runtime IR records the kernels."""
    from autodist_tpu.autodist import _reset_default_autodist_for_testing
    from autodist_tpu.analysis import analyze

    ad, sess, batch = _small_session(monkeypatch, "guard,update,quant_hop",
                                     True)
    try:
        report = analyze(ad.build_strategy(), ad.graph_item,
                         mesh={"data": 8})
        assert not [d for d in report.diagnostics
                    if d.rule == "schedule/fused-fallback"]
        assert sess.schedule_ir.fused_kernels \
            == ("guard", "update", "quant_hop")
    finally:
        _reset_default_autodist_for_testing()


def test_paged_drop_reason_warns_once_off_tpu(monkeypatch):
    import logging

    from autodist_tpu.serving import paged_kv

    monkeypatch.setenv("AUTODIST_FUSED_KERNELS", "paged_attention")
    monkeypatch.delenv("AUTODIST_FUSED_INTERPRET", raising=False)
    monkeypatch.setattr(paged_kv, "_paged_kernel_warned", False)
    grab = _LogGrabber()
    logger = logging.getLogger("autodist_tpu")
    logger.addHandler(grab)
    try:
        assert paged_kv._use_fused_paged_attention() is False
        assert paged_kv._use_fused_paged_attention() is False
    finally:
        logger.removeHandler(grab)
    msgs = [m for m in grab.messages
            if "paged-attention kernel falls back" in m]
    assert len(msgs) == 1 and "TPU backend" in msgs[0]


# ---------------------------------------------------------------------------
# shared helpers (pallas_utils + quant_scale satellites)
# ---------------------------------------------------------------------------

def test_flash_attention_uses_shared_tiling_policy():
    fa = importlib.import_module("autodist_tpu.ops.flash_attention")
    from autodist_tpu.ops import pallas_utils
    assert fa._pad_len is pallas_utils.pad_len
    assert fa._pick_block is pallas_utils.pick_block
    assert fa._use_interpret is pallas_utils.use_interpret


def test_shared_scale_rule_matches_both_quantizers():
    from autodist_tpu.ops import quant_scale
    from autodist_tpu.ops.quant import quantize_weight

    amax = jnp.asarray([0.0, 1.0, 254.0], jnp.float32)
    np.testing.assert_allclose(
        np.asarray(quant_scale.chunk_scale(amax, 127.0)),
        [1e-30, 1.0 / 127.0, 2.0])
    np.testing.assert_allclose(
        np.asarray(quant_scale.channel_scale(amax, 127.0)),
        [1.0, 1.0 / 127.0, 2.0])
    # the weight quantizer preserves its historical zero-column rule
    w = jnp.zeros((4, 2), jnp.float32).at[:, 1].set(
        jnp.asarray([1.0, -2.0, 0.5, 2.0]))
    qw = quantize_weight(w)
    np.testing.assert_allclose(np.asarray(qw.scale)[0], [1.0, 2.0 / 127.0])
    assert np.all(np.asarray(qw.q)[:, 0] == 0)
