"""One serving replica for the router drill: a paged-KV engine behind
an EngineServer, supervised from the parent via heartbeat beacons.

Launched by ``SupervisedReplicaPool`` (tests/test_serving_router.py and
the serving bench): builds the tests' tiny deterministic LM, starts the
HTTP server on an ephemeral port, publishes the address atomically to
``AUTODIST_REPLICA_ADDR_FILE``, and beats
``AUTODIST_REPLICA_HB_DIR``/``AUTODIST_REPLICA_NAME`` with the engine's
tick count so the supervisor can tell WEDGED from slow.  Runs until
killed — replica death is the event under test.
"""
import json
import os
import sys
import time

import jax

from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.resilience.heartbeat import HeartbeatWriter
from autodist_tpu.serving import serve

VOCAB = 61


def main() -> int:
    addr_file = os.environ["AUTODIST_REPLICA_ADDR_FILE"]
    hb_dir = os.environ.get("AUTODIST_REPLICA_HB_DIR")
    name = os.environ.get("AUTODIST_REPLICA_NAME", "replica")
    seed = int(os.environ.get("AUTODIST_REPLICA_SEED", "0"))

    # The tests' deterministic tiny LM: every replica of a pool builds
    # IDENTICAL params from the seed, so greedy decode is replica-
    # independent — the property that makes re-routing output-exact.
    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(seed))
    srv = serve(spec, params, port=0, paged=True, slots=2, window=32,
                block_size=8, num_blocks=32, chunk=4)
    host, port = srv.address

    writer = None
    if hb_dir:
        writer = HeartbeatWriter(hb_dir, name, interval=0.5)
        writer.start()

    tmp = addr_file + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"host": host, "port": port, "pid": os.getpid()}, f)
    os.replace(tmp, addr_file)
    print(f"replica {name} listening on {host}:{port}", flush=True)

    eng = srv._engine
    while True:
        time.sleep(0.3)
        if writer is not None:
            writer.beat(step=int(eng.stats.ticks))


if __name__ == "__main__":
    sys.exit(main())
