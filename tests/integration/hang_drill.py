"""Live wedge drill (supervisor + chief + worker, peer-tier recovery).

Same three-role layout as ``recovery_drill.py`` (supervisor via
``AUTODIST_SUPERVISE=1``, chief/worker via the real Coordinator over
``jax.distributed``, recovery on the RAM/peer checkpoint tiers — no
persistent checkpoint dir), but the injected fault is a chaos ``hang``:
the worker process blocks INSIDE the step while its heartbeat daemon
keeps beating — the WEDGED-in-a-collective signature only the
monitor's ``step_timeout`` can catch.  Before blocking, the chaos event
stamps a flight-recorder cursor for a REAL leg id of the session's
schedule IR (the ``leg=PLANT`` placeholder in ``AUTODIST_CHAOS`` is
resolved against the IR here and recorded in
``$AUTODIST_TEST_PLANTED``), so the supervisor's verdict must localize
the wedge to the planted leg and the culprit process, write a crash
bundle, and — after the relaunch — ``fit(resume=True)`` must come back
from the peer tier bit-exact with the uninterrupted oracle
(``tests/test_flightrec.py::test_live_hang_drill``)."""
import json
import os
import re
import socket
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", "")).strip()
os.environ["XLA_FLAGS"] = \
    (_flags + " --xla_force_host_platform_device_count=2").strip()
os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

sys.path.insert(0, os.environ.get("AUTODIST_REPO_ROOT",
                                  os.path.dirname(os.path.dirname(
                                      os.path.dirname(
                                          os.path.abspath(__file__))))))

EPOCHS = 4
SNAPSHOT_EVERY = 2
LR = 0.1


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def supervise() -> int:
    from autodist_tpu.resilience import Backoff, Supervisor, SupervisorPolicy

    policy = SupervisorPolicy(
        max_restarts=int(os.environ.get("AUTODIST_TEST_MAX_RESTARTS", "2")),
        backoff=Backoff(max_tries=8, base=0.2, cap=0.5, jitter=0.5, seed=0),
        # The wedge is invisible to beacon age (the daemon keeps
        # beating) — step_timeout is the detector under drill.
        heartbeat_timeout=120.0,
        step_timeout=8.0,
        poll_interval=0.25)
    sup = Supervisor(policy, hosts=["127.0.0.1", "localhost"],
                     workdir=os.environ["AUTODIST_TEST_PEER"] + ".sup")

    def launch(att):
        env = dict(os.environ)
        env.pop("AUTODIST_SUPERVISE", None)
        env.update(att.env())
        env["AUTODIST_COORDINATOR_ADDRESS"] = f"127.0.0.1:{_free_port()}"
        proc = subprocess.Popen([sys.executable, "-u",
                                 os.path.abspath(__file__)],
                                env=env, start_new_session=True)
        return {"chief": proc}

    report = sup.run(launch)
    with open(os.environ["AUTODIST_SUPERVISOR_REPORT"], "w",
              encoding="utf-8") as f:
        json.dump({"ok": report.ok, "attempts": report.attempts,
                   "preemptions": report.preemptions,
                   "gave_up": report.gave_up,
                   "failures": [{"attempt": x.attempt, "kind": x.kind,
                                 "culprit": x.culprit, "detail": x.detail,
                                 "bundle": x.bundle}
                                for x in report.failures]}, f)
    return 0 if report.ok else 1


def train() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        pass
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass

    import numpy as np
    import optax

    from autodist_tpu.autodist import AutoDist
    from autodist_tpu.const import ENV
    from autodist_tpu.resilience import (
        ChaosCallback, ChaosMonkey, HeartbeatCallback, HeartbeatWriter)
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.data_loader import DataLoader
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(42)
    x = rng.randn(32, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32) + 0.25).astype(np.float32)
    params = {"w": np.zeros(3, np.float32), "b": np.zeros((), np.float32)}

    def loss_fn(p, batch):
        import jax.numpy as jnp

        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    pool = []
    for a in ("127.0.0.1", "localhost", socket.gethostname()):
        if a not in pool:
            pool.append(a)
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": pool[i], "chips": 2,
                   **({"chief": True} if i == 0 else {})}
                  for i in range(2)]})

    ad = AutoDist(resource_spec=spec, strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(LR), loss_fn=loss_fn)
    sess = ad.create_distributed_session()

    # Resolve the chaos PLANT placeholder against the REAL schedule IR
    # (deterministic: every process builds the identical IR) BEFORE the
    # monkey parses the spec — the wedge drill plants a leg id the hang
    # localizer can find in the published schedule.
    chaos_spec = os.environ.get("AUTODIST_CHAOS", "")
    if "leg=PLANT" in chaos_spec:
        ir = sess.schedule_ir
        leg = next(l.id for l in ir.legs
                   if l.kind in ("all_reduce", "reduce_scatter",
                                 "ppermute_hop"))
        os.environ["AUTODIST_CHAOS"] = chaos_spec.replace(
            "leg=PLANT", "leg=" + leg)
        planted = os.environ.get("AUTODIST_TEST_PLANTED")
        if planted:
            with open(planted, "w", encoding="utf-8") as f:
                json.dump({"leg": leg, "fingerprint": ir.fingerprint()},
                          f)

    loader = DataLoader({"x": x, "y": y}, batch_size=8, shuffle=True,
                        seed=7)
    monkey = ChaosMonkey.from_env()
    callbacks = [ChaosCallback(monkey)]
    sup_dir = ENV.AUTODIST_SUPERVISOR_DIR.val
    if sup_dir:
        writer = HeartbeatWriter(
            os.path.join(sup_dir, "hb"),
            f"proc{ENV.AUTODIST_PROCESS_ID.val}", interval=0.5,
            chaos=monkey)
        callbacks.append(HeartbeatCallback(writer))

    # Peer-tier recovery only (env AUTODIST_SNAPSHOT_EVERY/_DIR): the
    # relaunched attempt resumes from the survivor's mirror.
    hist = sess.fit(loader, epochs=EPOCHS, resume=True,
                    callbacks=callbacks)

    result = {
        "role": "worker" if ENV.AUTODIST_WORKER.val else "chief",
        "attempt": ENV.AUTODIST_ATTEMPT.val,
        "process_index": jax.process_index(),
        "final_step": sess.step_count,
        "steps_run_this_attempt": hist.steps_run,
        "resume_tier": hist.resume_tier,
        "final_w": np.asarray(sess.params["w"]).tolist(),
        "final_b": float(np.asarray(sess.params["b"])),
    }
    out = os.environ["AUTODIST_RESULT_FILE"]
    if ENV.AUTODIST_WORKER.val:
        out += ".worker"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(result, f)
    print(f"[{result['role']}] done: step={sess.step_count} "
          f"(resumed via {hist.resume_tier})", flush=True)

    jax.distributed.shutdown()
    if ad.coordinator is not None:
        ad.coordinator.join()


if __name__ == "__main__":
    os.environ.setdefault("AUTODIST_SNAPSHOT_EVERY", str(SNAPSHOT_EVERY))
    os.environ.setdefault("AUTODIST_SNAPSHOT_DIR",
                          os.environ["AUTODIST_TEST_PEER"])
    if os.environ.get("AUTODIST_SUPERVISE"):
        sys.exit(supervise())
    train()
