"""Live MPMD pipeline drill: 2 stages x 2 DP processes over gloo.

The ISSUE 19 acceptance drill.  The PARENT (default mode) orchestrates
two stage GROUPS — each its own ``jax.distributed`` world (own
coordinator port, 2 processes x 1 CPU device, gloo collectives) — whose
only coupling is the shared ``AUTODIST_MPMD_DIR`` activation plane.
Each child process (``AUTODIST_MPMD_ROLE=stage``) runs one
:class:`~autodist_tpu.parallel.mpmd.runner.StageRunner` over THE
verified :func:`~autodist_tpu.parallel.mpmd.partition.build_pipeline_ir`
program, with bucketed ZeRO-1 sync inside the stage group.

Two jobs, three assertions (the pytest driver in tests/test_mpmd.py):

* **parity** — the no-chaos job's per-step losses match the
  single-program ``one_f_one_b`` oracle (same stacked params, pipe=2
  mesh, one process) to <= 1e-5;
* **bit-exact recovery** — the chaos job
  (``kill@step=1,proc=0,attempt=0,stage=1`` fells one worker of stage
  1; the parent supervisor relaunches that WHOLE group on a fresh port
  with ``AUTODIST_ATTEMPT=1``, and the runners restore their per-step
  snapshots) reproduces the no-chaos job's losses and final parameter
  bytes exactly — the restarted group replays the wedged step from the
  transport plane's still-published blobs (recv's non-consuming
  contract);
* **static == runtime** — every child asserts the fingerprint it
  executes equals an independently rebuilt ``ir_from_facts``
  fingerprint, and reports it for cross-process equality.

Result protocol: each child appends one JSON line per completed step to
``$AUTODIST_MPMD_LOG.s<stage>r<rank>`` (losses survive a mid-run kill);
the parent writes the stitched report to ``$AUTODIST_RESULT_FILE``.
"""
import json
import os
import re
import socket
import subprocess
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", "")).strip()
# stage workers contribute ONE local device each to their 2-process
# gloo world; the parent needs two for the single-program oracle mesh
_ndev = 1 if os.environ.get("AUTODIST_MPMD_ROLE") == "stage" else 2
os.environ["XLA_FLAGS"] = \
    (_flags + f" --xla_force_host_platform_device_count={_ndev}").strip()
os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

sys.path.insert(0, os.environ.get("AUTODIST_REPO_ROOT",
                                  os.path.dirname(os.path.dirname(
                                      os.path.dirname(
                                          os.path.abspath(__file__))))))

S, DP = 2, 2                 # stages x data-parallel ranks per stage
L, D = 4, 8                  # layers, width
M = 4                        # microbatches
B = 16                       # global batch (M x DP x 2 rows)
STEPS = 4
LR = 0.1
KILL_CODE = 43


def _case():
    """The deterministic model + data every process derives locally."""
    import numpy as np

    rng = np.random.RandomState(0)
    layers = [{"w": (rng.randn(D, D) * 0.3).astype(np.float32),
               "b": (rng.randn(D) * 0.1).astype(np.float32)}
              for _ in range(L)]
    x = rng.randn(B, D).astype(np.float32)
    tgt = rng.randn(B, D).astype(np.float32)
    return layers, x, tgt


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- child: one stage worker --------------------------------------------------

def stage_worker() -> None:
    stage = int(os.environ["AUTODIST_MPMD_STAGE"])
    rank = int(os.environ["AUTODIST_MPMD_DP_RANK"])
    coord = os.environ["AUTODIST_MPMD_COORD"]

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 1)
    except AttributeError:
        pass    # older jaxlibs only honor the XLA_FLAGS form above
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    # THIS stage group's own world: stage-local rendezvous, so the
    # pipeline is genuinely MPMD — two programs that never co-issue.
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=DP, process_id=rank)

    import jax.numpy as jnp  # noqa: F401
    import numpy as np

    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.parallel import mpmd

    layers, x, tgt = _case()
    part, stage_params = mpmd.partition_params(layers, S)
    prog = mpmd.build_pipeline_ir(
        layer_params=layers, num_stages=S, num_microbatches=M,
        act_nbytes=(B // (M * DP)) * D * 4, data_axis=DP,
        zero1=True, bucket_bytes=1 << 20)
    # static == runtime: an independent ir_from_facts rebuild must hash
    # to the fingerprint this runner executes.
    rebuilt = sir.ir_from_facts(
        list(prog.facts), axes=dict(prog.axes),
        accum_steps=int(prog.ir.accum_steps), pipeline=list(prog.pipeline))
    assert rebuilt.fingerprint() == prog.ir.fingerprint(), \
        (rebuilt.fingerprint(), prog.ir.fingerprint())

    names = part.param_names(stage)

    def stage_fn(p, h):
        for j in sorted({n.split("/")[1] for n in p},
                        key=lambda s: int(s[1:])):
            pre = f"{sir.stage_name(stage)}/{j}"
            h = jnp.tanh(h @ p[f"{pre}/w"] + p[f"{pre}/b"])
        return h

    def mse(y, t):
        return jnp.mean((y - t) ** 2)

    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("data",))
    transport = mpmd.ActivationTransport(
        os.environ["AUTODIST_MPMD_DIR"], channel=f"dp{rank}")
    runner = mpmd.StageRunner(
        prog, stage, stage_fn=stage_fn, params=stage_params[stage],
        transport=transport, lr=LR,
        loss_fn=mse if stage == S - 1 else None,
        mesh=mesh, zero1=True,
        state_dir=os.environ["AUTODIST_MPMD_STATE"])

    # This DP rank's slice of every microbatch: disjoint halves, so the
    # DP-mean loss/grads equal the oracle's full-microbatch mean.
    rows = B // (M * DP)
    x_mbs = [x[j * DP * rows + rank * rows:
               j * DP * rows + (rank + 1) * rows] for j in range(M)]
    t_mbs = [tgt[j * DP * rows + rank * rows:
                 j * DP * rows + (rank + 1) * rows] for j in range(M)]

    log = f"{os.environ['AUTODIST_MPMD_LOG']}.s{stage}r{rank}"
    while runner.step < STEPS:
        loss = runner.run_step(
            x_mbs if stage == 0 else None,
            t_mbs if stage == S - 1 else None)
        with open(log, "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "step": runner.step - 1, "loss": float(loss),
                "attempt": int(os.environ.get("AUTODIST_ATTEMPT", "0")),
                "fingerprint": runner.fingerprint}) + "\n")
            f.flush()
    checksum = float(sum(np.abs(np.asarray(runner.params[n], np.float64))
                         .sum() for n in names))
    with open(log, "a", encoding="utf-8") as f:
        f.write(json.dumps({"done": True, "checksum": checksum,
                            "fingerprint": runner.fingerprint}) + "\n")
    jax.distributed.shutdown()


# -- parent: orchestrate + supervise ------------------------------------------

def _launch_group(stage: int, *, workdir: str, attempt: int,
                  chaos: str) -> list:
    port = _free_port()
    procs = []
    for rank in range(DP):
        env = dict(os.environ)
        env.update({
            "AUTODIST_MPMD_ROLE": "stage",
            "AUTODIST_MPMD_STAGE": str(stage),
            "AUTODIST_MPMD_DP_RANK": str(rank),
            "AUTODIST_MPMD_COORD": f"127.0.0.1:{port}",
            "AUTODIST_MPMD_DIR": os.path.join(workdir, "acts"),
            "AUTODIST_MPMD_STATE": os.path.join(workdir, "state"),
            "AUTODIST_MPMD_LOG": os.path.join(workdir, "steps"),
            "AUTODIST_MPMD_TIMEOUT_S": "300",
            "AUTODIST_ATTEMPT": str(attempt),
            "AUTODIST_CHAOS": chaos,
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__)], env=env,
            start_new_session=True))
    return procs


def _run_job(workdir: str, *, chaos: str) -> dict:
    """One full pipeline job; supervises a chaos-killed stage group."""
    os.makedirs(os.path.join(workdir, "acts"), exist_ok=True)
    os.makedirs(os.path.join(workdir, "state"), exist_ok=True)
    groups = {st: _launch_group(st, workdir=workdir, attempt=0,
                                chaos=chaos) for st in range(S)}
    restarts = 0
    deadline = time.monotonic() + 540
    while time.monotonic() < deadline:
        running = [p for ps in groups.values() for p in ps
                   if p.poll() is None]
        if not running:
            break
        for st, ps in list(groups.items()):
            if any(p.poll() == KILL_CODE for p in ps):
                # The supervisor bit: a chaos-killed worker takes its
                # WHOLE stage group down (the dead rank's gloo peers
                # cannot make progress), and the group relaunches on a
                # fresh coordinator port as attempt 1.  The other
                # stage's group keeps running — it just blocks in
                # transport recv until the restarted group catches up.
                for p in ps:
                    if p.poll() is None:
                        p.terminate()
                for p in ps:
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        p.kill()
                restarts += 1
                groups[st] = _launch_group(st, workdir=workdir,
                                           attempt=restarts, chaos=chaos)
        time.sleep(0.25)
    codes = {f"s{st}r{i}": p.returncode
             for st, ps in groups.items() for i, p in enumerate(ps)}
    for ps in groups.values():
        for p in ps:
            if p.poll() is None:
                p.kill()
    # Stitch per-step losses from the last stage's rank-0 log (the
    # DP-mean loss is identical on every rank); a step may appear twice
    # (pre-kill + replayed) — the LAST entry is the surviving timeline.
    losses: dict = {}
    checksums = {}
    fingerprints = set()
    for st in range(S):
        for r in range(DP):
            path = os.path.join(workdir, f"steps.s{st}r{r}")
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("fingerprint"):
                        fingerprints.add(rec["fingerprint"])
                    if rec.get("done"):
                        checksums[f"s{st}r{r}"] = rec["checksum"]
                    elif st == S - 1:
                        losses[int(rec["step"])] = float(rec["loss"])
    return {"losses": [losses.get(k) for k in range(STEPS)],
            "checksums": checksums, "restarts": restarts,
            "exit_codes": codes,
            "fingerprints": sorted(fingerprints)}


def _oracle() -> dict:
    """Single-program one_f_one_b reference on a pipe=2 mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.parallel.mpmd import partition_params
    from autodist_tpu.parallel.pipeline_1f1b import one_f_one_b

    layers, x, tgt = _case()
    part, _ = partition_params(layers, S)
    sp = {"w": np.stack([np.stack([layers[j]["w"] for j in run])
                         for run in part.layers]),
          "b": np.stack([np.stack([layers[j]["b"] for j in run])
                         for run in part.layers])}

    def sfn(p, h):
        for j in range(p["w"].shape[0]):
            h = jnp.tanh(h @ p["w"][j] + p["b"][j])
        return h

    def mse(y, t):
        return jnp.mean((y - t) ** 2)

    mesh = build_mesh({"pipe": S}, devices=jax.devices()[:S])
    cur = {k: jnp.asarray(v) for k, v in sp.items()}
    losses = []
    for _ in range(STEPS):
        loss, d, _ = one_f_one_b(sfn, mse, cur, jnp.asarray(x),
                                 jnp.asarray(tgt), mesh,
                                 num_microbatches=M)
        cur = jax.tree_util.tree_map(
            lambda p, g: (p.astype(jnp.float32)
                          - LR * g.astype(jnp.float32)).astype(p.dtype),
            cur, d)
        losses.append(float(loss))
    checksum = float(sum(np.abs(np.asarray(v, np.float64)).sum()
                         for v in cur.values()))
    return {"losses": losses, "checksum": checksum}


def main() -> None:
    base = os.environ["AUTODIST_MPMD_WORKDIR"]
    chaos_spec = f"kill@step=1,proc=0,attempt=0,stage={S - 1}"
    clean = _run_job(os.path.join(base, "clean"), chaos="")
    chaos = _run_job(os.path.join(base, "chaos"), chaos=chaos_spec)
    oracle = _oracle()
    report = {"clean": clean, "chaos": chaos, "oracle": oracle}
    with open(os.environ["AUTODIST_RESULT_FILE"], "w",
              encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"clean_losses": clean["losses"],
                      "chaos_losses": chaos["losses"],
                      "oracle_losses": oracle["losses"],
                      "restarts": chaos["restarts"]}), flush=True)


if __name__ == "__main__":
    if os.environ.get("AUTODIST_MPMD_ROLE") == "stage":
        stage_worker()
    else:
        main()
