"""Live multi-process training script (chief + worker on localhost CPU).

The pytest driver (``tests/test_multiprocess.py``) launches this script once
as the CHIEF; the real :class:`~autodist_tpu.coordinator.Coordinator` then
re-launches it on the "other node" (also localhost) exactly the way the
reference chief re-ran the user script on every worker host
(``autodist/coordinator.py:46-90``, exercised by
``tests/integration/test_dist.py:1-43`` on a real 2-machine cluster).

Covers, live: strategy build → serialize → ship → worker deserialize
(``AUTODIST_STRATEGY_ID``), env plumbing, ``Cluster.start()`` actually
calling ``jax.distributed.initialize`` (PJRT coordination service +
gloo collectives on CPU), and lockstep SPMD training across two OS
processes with 2 local devices each.

Result protocol: each process writes ``$AUTODIST_RESULT_FILE[.worker]``
with its observed losses and topology facts.
"""
import json
import os
import sys

# 2 local CPU devices per process -> 4 global devices over 2 processes.
# Env vars alone are NOT enough: the image's sitecustomize pins
# JAX_PLATFORMS=axon (remote TPU), so steer via jax.config before any
# backend init (same trick as tests/conftest.py / __graft_entry__.py).
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.environ.get("AUTODIST_REPO_ROOT",
                                  os.path.dirname(os.path.dirname(
                                      os.path.dirname(
                                          os.path.abspath(__file__))))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

import numpy as np  # noqa: E402

from autodist_tpu.autodist import AutoDist  # noqa: E402
from autodist_tpu.const import ENV  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy import (  # noqa: E402
    AllReduce, PartitionedPS, PSLoadBalancing)

STEPS = 4
LR = 0.1


def make_batch():
    rng = np.random.RandomState(42)
    x = rng.randn(32, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32) + 0.25).astype(np.float32)
    return {"x": x, "y": y}


def loss_fn(params, batch):
    import jax.numpy as jnp

    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def main():
    import optax

    builder = {"AllReduce": AllReduce,
               "PSLoadBalancing": PSLoadBalancing,
               "PartitionedPS": PartitionedPS}[
                   os.environ.get("AUTODIST_TEST_BUILDER", "AllReduce")]()
    # Optional mesh override (e.g. "model=4"): with model as the ONLY
    # axis it necessarily spans the two processes — cross-process tensor
    # parallelism, beyond the reference's data-parallel-only multi-machine
    # matrix.  (In "data=2,model=2" canonical ordering, data would be the
    # process-spanning axis.)
    mesh_axes = None
    if os.environ.get("AUTODIST_TEST_MESH"):
        mesh_axes = {k: int(v) for k, v in
                     (kv.split("=") for kv in
                      os.environ["AUTODIST_TEST_MESH"].split(","))}
    # Two "nodes", both local: the chief fans the script out with
    # subprocess+env exactly as it would over SSH to a remote host.
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "127.0.0.1", "chips": 2, "chief": True},
                  {"address": "localhost", "chips": 2}]})

    # Params as numpy: no jax computation may run before
    # jax.distributed.initialize (see Cluster.start).
    params = {"w": np.zeros(3, np.float32), "b": np.zeros((), np.float32)}

    ad = AutoDist(resource_spec=spec, strategy_builder=builder,
                  mesh_axes=mesh_axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(LR), loss_fn=loss_fn)

    # Fault-injection hook (tests/test_multiprocess.py): the worker dies
    # AFTER deserializing the chief's strategy but before rendezvous, while
    # the chief blocks in jax.distributed.initialize — the watcher thread
    # must abort the whole job (reference fail-fast, coordinator.py:98-110).
    if (os.environ.get("AUTODIST_TEST_CRASH_WORKER")
            and ENV.AUTODIST_WORKER.val):
        strategy = ad.build_strategy()
        print(f"[worker] injected crash after loading strategy "
              f"{strategy.id}", flush=True)
        sys.exit(17)

    sess = ad.create_distributed_session()

    import jax

    batch = make_batch()
    losses = [float(sess.run(batch)["loss"]) for _ in range(STEPS)]
    final_w = np.asarray(sess.params["w"]).tolist()  # before the extra step

    # Multi-host input path: each process feeds only ITS half of the global
    # batch (disjoint rows) through place_local_batch — the
    # make_array_from_process_local_data translation of the reference's
    # feed-splitting Remapper.  The resulting loss must equal evaluating
    # the same global batch fed identically from every process.
    pidx, pcount = jax.process_index(), jax.process_count()
    if sess.mesh.shape.get("data", 1) > 1:
        rows = batch["x"].shape[0] // pcount
        local = {k: v[pidx * rows:(pidx + 1) * rows]
                 for k, v in batch.items()}
        sharded_loss = float(sess.run(sess.place_local_batch(local),
                                      sync=True)["loss"])
    else:
        # No multi-way data axis (pure-TP mesh): batches replicate, so
        # disjoint local shards have no sharded layout to land in.
        sharded_loss = None

    result = {
        "role": "worker" if ENV.AUTODIST_WORKER.val else "chief",
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "mesh": dict(sess.mesh.shape),
        "strategy_id": ad._strategy.id,
        "losses": losses,
        "sharded_input_loss": sharded_loss,
        "final_w": final_w,
    }
    out = os.environ["AUTODIST_RESULT_FILE"]
    if ENV.AUTODIST_WORKER.val:
        out += ".worker"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(result, f)
    print(f"[{result['role']}] done: losses={losses}", flush=True)

    # Explicit shutdown BEFORE the chief joins the worker: jax's atexit
    # shutdown runs a coordination-service barrier, so a chief blocked in
    # join() while the worker waits in that barrier would deadlock.
    jax.distributed.shutdown()
    if ad.coordinator is not None:
        ad.coordinator.join()


if __name__ == "__main__":
    main()
