"""Live multi-process training script (chief + worker on localhost CPU).

The pytest driver (``tests/test_multiprocess.py``) launches this script once
as the CHIEF; the real :class:`~autodist_tpu.coordinator.Coordinator` then
re-launches it on the "other node" (also localhost) exactly the way the
reference chief re-ran the user script on every worker host
(``autodist/coordinator.py:46-90``, exercised by
``tests/integration/test_dist.py:1-43`` on a real 2-machine cluster).

Covers, live: strategy build → serialize → ship → worker deserialize
(``AUTODIST_STRATEGY_ID``), env plumbing, ``Cluster.start()`` actually
calling ``jax.distributed.initialize`` (PJRT coordination service +
gloo collectives on CPU), and lockstep SPMD training across two OS
processes with 2 local devices each.

Result protocol: each process writes ``$AUTODIST_RESULT_FILE[.worker]``
with its observed losses and topology facts.
"""
import json
import os
import sys

# 2 local CPU devices per process -> 4 global devices over 2 processes.
# Env vars alone are NOT enough: the image's sitecustomize pins
# JAX_PLATFORMS=axon (remote TPU), so steer via jax.config before any
# backend init (same trick as tests/conftest.py / __graft_entry__.py).
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.environ.get("AUTODIST_REPO_ROOT",
                                  os.path.dirname(os.path.dirname(
                                      os.path.dirname(
                                          os.path.abspath(__file__))))))

import jax  # noqa: E402

# Single-process oracle mode (AUTODIST_TEST_SINGLE=1): same script, same
# case, same GLOBAL mesh shape, but one process with all 4 devices local
# — the parity reference proving the process boundary changes nothing.
SINGLE = os.environ.get("AUTODIST_TEST_SINGLE", "").lower() \
    not in ("", "0", "false")
# Topology: AUTODIST_TEST_NODES=N processes sharing 4 global devices
# (default 2 nodes x 2 devices; 4 -> 4 nodes x 1 device, so EVERY mesh
# axis necessarily crosses OS-process boundaries).
NODES = int(os.environ.get("AUTODIST_TEST_NODES", "2"))
assert 4 % NODES == 0, NODES
CHIPS = 4 // NODES

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4 if SINGLE else CHIPS)

import numpy as np  # noqa: E402

from autodist_tpu.autodist import AutoDist  # noqa: E402
from autodist_tpu.const import ENV  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy import (  # noqa: E402
    AllReduce, PartitionedPS, PSLoadBalancing)

STEPS = 4
LR = 0.1


def make_batch():
    rng = np.random.RandomState(42)
    x = rng.randn(32, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32) + 0.25).astype(np.float32)
    return {"x": x, "y": y}


def loss_fn(params, batch):
    import jax.numpy as jnp

    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _linear_case():
    params = {"w": np.zeros(3, np.float32), "b": np.zeros((), np.float32)}
    return params, loss_fn, make_batch(), {}


def _sparse_case():
    """Vocab-sharded embedding: the table shards over the process-spanning
    data axis, so gradient scatter-adds cross the OS-process boundary
    (the reference's sparse-PS distributed case, test_dist.py matrix)."""
    vocab, dim = 64, 8
    rng = np.random.RandomState(7)
    params = {
        "emb": (rng.randn(vocab, dim) * 0.1).astype(np.float32),
        "head": (rng.randn(dim) * 0.1).astype(np.float32),
    }

    def sparse_loss(p, batch):
        import jax.numpy as jnp

        rows = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = rows @ p["head"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, vocab, (32,)).astype(np.int32),
             "y": rng.randn(32).astype(np.float32)}
    return params, sparse_loss, batch, {"sparse_vars": ("emb",)}


def _pipeline_case(schedule):
    """Stage-stacked pipelined model on a pipe-ONLY mesh: the pipe axis
    spans the two processes, so every ppermute ring hop (and, for 1f1b,
    the hand-scheduled backward's reverse ring) crosses the process
    boundary.  Params are plain numpy (no jax before rendezvous); the
    mesh is built lazily inside the traced loss/grad (after
    jax.distributed.initialize)."""
    s, d = 4, 8
    rng = np.random.RandomState(11)
    params = {"stack": {
        "w": (rng.randn(s, d, d) * 0.3).astype(np.float32),
        "b": (rng.randn(s, d) * 0.1).astype(np.float32),
    }}
    batch = {"x": rng.randn(8, d).astype(np.float32),
             "y": rng.randn(8, d).astype(np.float32)}

    def stage_fn(p, h):
        import jax.numpy as jnp

        return jnp.tanh(h @ p["w"] + p["b"])

    def mse(y, t):
        import jax.numpy as jnp

        return jnp.mean((y - t) ** 2)

    def pipe_loss(p, batch):
        import jax.numpy as jnp

        from autodist_tpu.mesh import build_mesh
        from autodist_tpu.parallel.pipeline import pipeline_apply

        mesh = build_mesh({"pipe": s})
        y = pipeline_apply(stage_fn, p["stack"], batch["x"], mesh,
                           num_microbatches=4)
        mb = y.reshape((4, 2, d))
        tb = batch["y"].reshape((4, 2, d))
        return jnp.mean(jax.vmap(mse)(mb, tb))

    kwargs = {"pipeline_vars": ("stack",)}
    if schedule == "1f1b":
        from autodist_tpu.mesh import build_mesh
        from autodist_tpu.parallel.pipeline_1f1b import one_f_one_b

        def grad_fn(p, batch):
            mesh = build_mesh({"pipe": s})
            loss, dstack, _ = one_f_one_b(
                stage_fn, mse, p["stack"], batch["x"], batch["y"], mesh,
                num_microbatches=4)
            return loss, {"stack": dstack}

        kwargs["grad_fn"] = grad_fn
    return params, pipe_loss, batch, kwargs


def make_case(name):
    if name == "linear":
        return _linear_case()
    if name == "sparse":
        return _sparse_case()
    if name in ("pipeline", "pipeline1f1b"):
        return _pipeline_case("1f1b" if name.endswith("1f1b") else "gpipe")
    raise ValueError(f"unknown test case {name!r}")


def main():
    import optax

    builder = {"AllReduce": AllReduce,
               "PSLoadBalancing": PSLoadBalancing,
               "PartitionedPS": PartitionedPS,
               # Compressed explicit-shard_map sync across processes:
               # bf16 wire format with error feedback, concat-and-pmean
               # fused groups (the path test_allreduce_group.py covers
               # single-process).
               "AllReduceEF": lambda: AllReduce(
                   compressor="HorovodCompressorEF", fused_groups=True)}[
                   os.environ.get("AUTODIST_TEST_BUILDER", "AllReduce")]()
    case_name = os.environ.get("AUTODIST_TEST_CASE", "linear")
    # Optional mesh override (e.g. "model=4"): with model as the ONLY
    # axis it necessarily spans the two processes — cross-process tensor
    # parallelism, beyond the reference's data-parallel-only multi-machine
    # matrix.  (In "data=2,model=2" canonical ordering, data would be the
    # process-spanning axis.)
    mesh_axes = None
    if os.environ.get("AUTODIST_TEST_MESH"):
        mesh_axes = {k: int(v) for k, v in
                     (kv.split("=") for kv in
                      os.environ["AUTODIST_TEST_MESH"].split(","))}
    # Optional hybrid (multi-slice-style) mesh: the ici/dcn split built
    # AFTER rendezvous via the lazy-mesh hook — data is the DCN-outer
    # axis, model the ICI-inner one (mesh.build_hybrid_mesh semantics).
    hybrid = bool(os.environ.get("AUTODIST_TEST_HYBRID"))

    if SINGLE:
        # One node holding all 4 devices: the parity oracle topology.
        spec = ResourceSpec(resource_info={
            "nodes": [{"address": "127.0.0.1", "chips": 4, "chief": True}]})
    else:
        # N "nodes", all local: the chief fans the script out with
        # subprocess+env exactly as it would over SSH to a remote host.
        # Distinct local addresses give each process its own node
        # identity (every name here resolves to this machine; dedupe in
        # case the hostname IS one of the literals).
        import socket

        pool = []
        for a in ("127.0.0.1", "localhost", socket.gethostname(), "0.0.0.0"):
            if a not in pool:
                pool.append(a)
        assert len(pool) >= NODES, pool
        spec = ResourceSpec(resource_info={
            "nodes": [{"address": pool[i], "chips": CHIPS,
                       **({"chief": True} if i == 0 else {})}
                      for i in range(NODES)]})

    # Params as numpy: no jax computation may run before
    # jax.distributed.initialize (see Cluster.start).
    params, case_loss_fn, batch, capture_kwargs = make_case(case_name)

    ad = AutoDist(resource_spec=spec, strategy_builder=builder,
                  mesh_axes=mesh_axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(LR),
                   loss_fn=case_loss_fn, **capture_kwargs)

    # Fault-injection hook (tests/test_multiprocess.py): the worker dies
    # AFTER deserializing the chief's strategy but before rendezvous, while
    # the chief blocks in jax.distributed.initialize — the watcher thread
    # must abort the whole job (reference fail-fast, coordinator.py:98-110).
    if (os.environ.get("AUTODIST_TEST_CRASH_WORKER")
            and ENV.AUTODIST_WORKER.val):
        strategy = ad.build_strategy()
        print(f"[worker] injected crash after loading strategy "
              f"{strategy.id}", flush=True)
        sys.exit(17)

    mesh_arg = None
    if hybrid:
        from autodist_tpu.mesh import build_hybrid_mesh

        # Lazy: the global device list exists only after rendezvous.
        mesh_arg = lambda: build_hybrid_mesh(  # noqa: E731
            {"model": 2}, {"data": 2})
    sess = ad.create_distributed_session(mesh=mesh_arg)

    import jax

    # Live multi-process SERVING (VERDICT r4 #4): the continuous-batching
    # engine with its slot pool sharded ACROSS the two OS processes.  The
    # host scheduler runs in SPMD lockstep (identical deterministic
    # submissions → identical dispatches); host pulls cross the process
    # boundary through the engine's replicating identity programs.  Each
    # process records every harvested sequence; the pytest driver asserts
    # chief == worker == the single-device `generate` oracle, token-exact
    # (matching the reference's live-cluster standard,
    # tests/integration/test_dist.py:1-43).
    serving_results = None
    if os.environ.get("AUTODIST_TEST_SERVING"):
        from autodist_tpu.models.transformer import dense_attention
        from autodist_tpu.models.transformer_lm import transformer_lm
        from autodist_tpu.serving import DecodeEngine

        spec_s = transformer_lm(vocab_size=97, num_layers=2, num_heads=2,
                                head_dim=8, d_ff=64, max_len=48,
                                seq_len=16, attn_fn=dense_attention)
        params_s = spec_s.init(jax.random.PRNGKey(3))
        eng = DecodeEngine(spec_s, params_s, slots=4, window=32, chunk=4,
                           mesh=sess.mesh, slot_axis="data")
        rng_s = np.random.RandomState(5)
        reqs_s = [(rng_s.randint(0, 97, rng_s.randint(2, 6))
                   .astype(np.int32), int(rng_s.randint(3, 9)))
                  for _ in range(10)]
        ids_s = [eng.submit(p, n) for p, n in reqs_s]
        out_s = eng.run()
        # Capture BEFORE the prefix run: stats are monotonic over the
        # engine lifetime, and the concurrency assertion documents THIS
        # 10-request run.
        util_main = round(eng.stats.slot_utilization, 4)
        # Prefix cache across the process boundary: the shared K/V
        # (replicated) compose with the process-spanning slot shards.
        prefix_s = rng_s.randint(0, 97, 7).astype(np.int32)
        eng.set_prefix(prefix_s)
        pre_reqs = [(rng_s.randint(0, 97, rng_s.randint(2, 5))
                     .astype(np.int32), int(rng_s.randint(3, 7)))
                    for _ in range(4)]
        pre_ids = [eng.submit(p, n, use_prefix=True)
                   for p, n in pre_reqs]
        out_pre = eng.run()
        serving_results = {
            "prompts": [p.tolist() for p, _ in reqs_s],
            "max_new": [n for _, n in reqs_s],
            "tokens": [np.asarray(out_s[rid]).tolist() for rid in ids_s],
            "prefix": prefix_s.tolist(),
            "prefix_prompts": [p.tolist() for p, _ in pre_reqs],
            "prefix_max_new": [n for _, n in pre_reqs],
            "prefix_tokens": [np.asarray(out_pre[rid]).tolist()
                              for rid in pre_ids],
            "slot_utilization": util_main,
        }

    losses = [float(sess.run(batch)["loss"]) for _ in range(STEPS)]
    final = sess.params           # before the extra step below
    final_w = (np.asarray(final["w"]).tolist()
               if "w" in final else None)
    # Case-independent parity fingerprint over ALL trained parameters.
    param_checksum = float(sum(
        np.abs(np.asarray(leaf, np.float64)).sum()
        for leaf in jax.tree_util.tree_leaves(final)))

    # Multi-host input path: each process feeds only ITS half of the global
    # batch (disjoint rows) through place_local_batch — the
    # make_array_from_process_local_data translation of the reference's
    # feed-splitting Remapper.  The resulting loss must equal evaluating
    # the same global batch fed identically from every process.
    pidx, pcount = jax.process_index(), jax.process_count()
    data_size = sess.mesh.shape.get("data", 1)
    if data_size > 1 and pcount > 1 and data_size % pcount == 0:
        nrows = next(iter(batch.values())).shape[0]
        rows = nrows // pcount
        local = {k: v[pidx * rows:(pidx + 1) * rows]
                 for k, v in batch.items()}
        sharded_loss = float(sess.run(sess.place_local_batch(local),
                                      sync=True)["loss"])
    else:
        # No multi-way data axis (pure-TP/pipe mesh) or single process:
        # batches replicate, so disjoint local shards have no sharded
        # layout to land in (single mode skips for step-count parity).
        sharded_loss = None

    # Live distributed checkpoint roundtrip (reference c10's saver-in-
    # distributed-run, but with an exactness assertion): save mid-run,
    # train 2 steps, restore, train the same 2 steps again — the loss
    # pairs must match bit-for-bit if resume is exact.  Orbax saves are
    # collective: every process participates in save AND restore.
    ckpt_losses = None
    if os.environ.get("AUTODIST_TEST_CHECKPOINT"):
        from autodist_tpu.checkpoint import Saver

        ckpt_dir = os.environ["AUTODIST_RESULT_FILE"] + ".ckpt"
        saver = Saver(sess)
        save_step = sess.step_count
        path = saver.save(ckpt_dir, step=save_step)
        after_save = [float(sess.run(batch)["loss"]) for _ in range(2)]
        restored_step = saver.restore(path)
        after_restore = [float(sess.run(batch)["loss"]) for _ in range(2)]
        ckpt_losses = {"after_save": after_save,
                       "after_restore": after_restore,
                       "save_step": save_step,
                       "restored_step": restored_step}

    # Hybrid-mesh evidence: which PROCESS owns each device along each
    # mesh axis — the driver asserts the DCN-outer (data) axis genuinely
    # spans OS processes, i.e. its collectives cross the boundary.
    axis_process_ids = None
    if hybrid:
        devs = sess.mesh.devices          # ndarray indexed by axis order
        names = list(sess.mesh.axis_names)
        di, mi = names.index("data"), names.index("model")
        take = [0] * devs.ndim

        def procs_along(axis):
            idx = list(take)
            out = []
            for j in range(devs.shape[axis]):
                idx[axis] = j
                out.append(int(devs[tuple(idx)].process_index))
            return out

        axis_process_ids = {"data": procs_along(di),
                            "model": procs_along(mi)}

    result = {
        "role": "worker" if ENV.AUTODIST_WORKER.val else "chief",
        "case": case_name,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "mesh": dict(sess.mesh.shape),
        "strategy_id": ad._strategy.id,
        "losses": losses,
        "sharded_input_loss": sharded_loss,
        "final_w": final_w,
        "param_checksum": param_checksum,
        "checkpoint": ckpt_losses,
        "axis_process_ids": axis_process_ids,
        "serving": serving_results,
    }
    out = os.environ["AUTODIST_RESULT_FILE"]
    if ENV.AUTODIST_WORKER.val:
        # process 1 keeps the historical ".worker" name; higher indices
        # (>2-process topologies) get ".worker<idx>".
        idx = jax.process_index()
        out += ".worker" if idx == 1 else f".worker{idx}"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(result, f)
    print(f"[{result['role']}] done: losses={losses}", flush=True)

    # Explicit shutdown BEFORE the chief joins the worker: jax's atexit
    # shutdown runs a coordination-service barrier, so a chief blocked in
    # join() while the worker waits in that barrier would deadlock.
    # (Single-process oracle mode never initialized jax.distributed.)
    if not SINGLE:
        jax.distributed.shutdown()
    if ad.coordinator is not None:
        ad.coordinator.join()


if __name__ == "__main__":
    main()
