"""Supervised-recovery integration script (chief + worker + supervisor).

Three roles, selected by env:

* ``AUTODIST_SUPERVISE=1`` — SUPERVISOR: builds a
  :class:`~autodist_tpu.resilience.Supervisor` and launches this same
  script (train role) as the job's chief, relaunching it with backoff
  when it fails; writes a JSON report to ``$AUTODIST_SUPERVISOR_REPORT``.
* chief (no role env) — TRAIN: 2-node AutoDist job; the real
  Coordinator re-launches the script as the worker (``AUTODIST_WORKER``
  set), both rendezvous via ``jax.distributed``, and ``fit`` trains a
  linear model from a shuffled DataLoader with per-epoch checkpoints,
  exact mid-epoch data state, heartbeats, and the chaos harness.
* worker — same TRAIN code path, launched by the Coordinator.

The chaos spec (``AUTODIST_CHAOS``, e.g. ``kill@step=6,proc=1,attempt=0``)
kills the worker mid-run on the first attempt only; the chief's watcher
fires the ``supervised`` failure policy (marker + exit 73), the
supervisor terminates stragglers, backs off, and relaunches — attempt 1
resumes from the last durable checkpoint and must land on exactly the
same final parameters as an uninterrupted run (the pytest driver,
``tests/test_multiprocess_resilience.py``, asserts this against an
oracle run with chaos disabled).
"""
import json
import os
import re
import socket
import subprocess
import sys

# 2 local CPU devices per process -> 4 global over 2 processes.  Set via
# XLA_FLAGS BEFORE any jax import: unlike dist_train.py's
# jax_num_cpu_devices config (jax >= 0.5), this works on 0.4.x jaxlibs
# too — replacing whatever count the parent test process forced.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", "")).strip()
os.environ["XLA_FLAGS"] = \
    (_flags + " --xla_force_host_platform_device_count=2").strip()
# Cross-process CPU collectives (0.4.x spells it via this knob; newer
# jaxlibs default to a working CPU collectives impl).
os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

sys.path.insert(0, os.environ.get("AUTODIST_REPO_ROOT",
                                  os.path.dirname(os.path.dirname(
                                      os.path.dirname(
                                          os.path.abspath(__file__))))))

EPOCHS = 4
BATCHES_PER_EPOCH = 4   # 32 rows / batch 8
LR = 0.1


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def supervise() -> int:
    from autodist_tpu.resilience import Backoff, Supervisor, SupervisorPolicy

    policy = SupervisorPolicy(
        max_restarts=int(os.environ.get("AUTODIST_TEST_MAX_RESTARTS", "2")),
        backoff=Backoff(max_tries=8, base=0.2, cap=0.5, jitter=0.5, seed=0),
        # generous: the monitor path runs live, but CPU-test step times
        # must never trip it
        heartbeat_timeout=120.0,
        poll_interval=0.25)
    sup = Supervisor(policy, hosts=["127.0.0.1", "localhost"],
                     checkpoint_dir=os.environ["AUTODIST_TEST_CKPT"],
                     workdir=os.environ["AUTODIST_TEST_CKPT"] + ".sup")

    def launch(att):
        env = dict(os.environ)
        env.pop("AUTODIST_SUPERVISE", None)
        env.update(att.env())
        # fresh rendezvous port per attempt: the previous chief's
        # coordination service socket may still be in TIME_WAIT
        env["AUTODIST_COORDINATOR_ADDRESS"] = f"127.0.0.1:{_free_port()}"
        proc = subprocess.Popen([sys.executable, "-u",
                                 os.path.abspath(__file__)],
                                env=env, start_new_session=True)
        return {"chief": proc}

    report = sup.run(launch)
    with open(os.environ["AUTODIST_SUPERVISOR_REPORT"], "w",
              encoding="utf-8") as f:
        json.dump({
            "ok": report.ok, "attempts": report.attempts,
            "hosts": report.hosts, "gave_up": report.gave_up,
            "failures": [{"attempt": x.attempt, "kind": x.kind,
                          "culprit": x.culprit, "detail": x.detail}
                         for x in report.failures],
        }, f)
    return 0 if report.ok else 1


def train() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        pass   # 0.4.x: the XLA_FLAGS form above already took effect
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass   # newer jax: CPU collectives need no explicit selection

    import numpy as np
    import optax

    from autodist_tpu.autodist import AutoDist
    from autodist_tpu.const import ENV
    from autodist_tpu.resilience import (
        ChaosCallback, ChaosMonkey, HeartbeatCallback, HeartbeatWriter)
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.data_loader import DataLoader
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(42)
    x = rng.randn(32, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32) + 0.25).astype(np.float32)
    params = {"w": np.zeros(3, np.float32), "b": np.zeros((), np.float32)}

    def loss_fn(p, batch):
        import jax.numpy as jnp

        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    pool = []
    for a in ("127.0.0.1", "localhost", socket.gethostname()):
        if a not in pool:
            pool.append(a)
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": pool[i], "chips": 2,
                   **({"chief": True} if i == 0 else {})}
                  for i in range(2)]})

    ad = AutoDist(resource_spec=spec, strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(LR), loss_fn=loss_fn)
    sess = ad.create_distributed_session()

    # Every process feeds the same shuffled global batches: one loader,
    # one seed, SPMD lockstep — and its state() rides the checkpoints.
    loader = DataLoader({"x": x, "y": y}, batch_size=8, shuffle=True,
                        seed=7)

    monkey = ChaosMonkey.from_env()
    callbacks = [ChaosCallback(monkey)]
    sup_dir = ENV.AUTODIST_SUPERVISOR_DIR.val
    if sup_dir:
        writer = HeartbeatWriter(
            os.path.join(sup_dir, "hb"),
            f"proc{ENV.AUTODIST_PROCESS_ID.val}", interval=1.0,
            chaos=monkey)
        callbacks.append(HeartbeatCallback(writer))

    hist = sess.fit(loader, epochs=EPOCHS,
                    checkpoint_dir=os.environ["AUTODIST_TEST_CKPT"],
                    checkpoint_every=1, resume=True, callbacks=callbacks)

    result = {
        "role": "worker" if ENV.AUTODIST_WORKER.val else "chief",
        "attempt": ENV.AUTODIST_ATTEMPT.val,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "final_step": sess.step_count,
        "steps_run_this_attempt": hist.steps_run,
        "epoch_loss": hist.history["epoch_loss"],
        "final_w": np.asarray(sess.params["w"]).tolist(),
        "final_b": float(np.asarray(sess.params["b"])),
    }
    out = os.environ["AUTODIST_RESULT_FILE"]
    if ENV.AUTODIST_WORKER.val:
        out += ".worker"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(result, f)
    print(f"[{result['role']}] done: step={sess.step_count}", flush=True)

    # Explicit shutdown BEFORE the chief joins the worker (see
    # dist_train.py: jax's atexit barrier would deadlock the join).
    jax.distributed.shutdown()
    if ad.coordinator is not None:
        ad.coordinator.join()


if __name__ == "__main__":
    if os.environ.get("AUTODIST_SUPERVISE"):
        sys.exit(supervise())
    train()
