"""DistributedSession.fit — the reference's Model.fit path (case c7).

The reference proved Keras ``model.fit`` trains through the distributed
session (``tests/integration/cases/c7.py``); here ``fit`` is a first-class
loop: epochs × steps, callbacks, sparse host syncing, checkpoint/resume.
"""
import numpy as np
import optax
import pytest

import jax.numpy as jnp

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.fit import Callback, History, TimeHistory
from autodist_tpu.strategy import AllReduce, PartitionedPS


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _make_session(builder=None):
    rng = np.random.RandomState(0)
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
    params = {"w": jnp.zeros((3, 1)), "b": jnp.zeros((1,))}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    def batches(n):
        out = []
        for _ in range(n):
            x = rng.randn(16, 3).astype(np.float32)
            out.append({"x": x, "y": (x @ w_true).astype(np.float32)})
        return out

    ad = AutoDist(strategy_builder=builder or AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    return ad.create_distributed_session(), batches


def test_fit_trains_and_records_history():
    sess, batches = _make_session()
    data = batches(8)
    first = float(sess.run(data[0])["loss"])  # pre-training loss scale

    hist = sess.fit(data, epochs=3)
    assert isinstance(hist, History)
    assert hist.epochs_run == 3
    assert hist.steps_run == 24
    assert len(hist.history["epoch_loss"]) == 3
    # Losses decrease across epochs on this convex problem.
    assert hist.history["epoch_loss"][-1] < first
    assert hist.history["epoch_loss"][2] < hist.history["epoch_loss"][0]


def test_fit_single_batch_dict_and_log_every():
    sess, batches = _make_session()
    batch = batches(1)[0]
    hist = sess.fit(batch, epochs=1, steps_per_epoch=10, log_every=3)
    assert hist.steps_run == 10
    # log_every=3 sampled at steps 3,6,9 plus the epoch-end sample.
    assert len(hist.history["loss"]) == 4
    assert hist.history["loss"][-1] <= hist.history["loss"][0]


def test_fit_generator_factory_fresh_per_epoch():
    sess, batches = _make_session()
    data = batches(4)
    calls = []

    def factory():
        calls.append(1)
        return iter(data)

    hist = sess.fit(factory, epochs=2)
    assert len(calls) == 2          # invoked once per epoch
    assert hist.steps_run == 8


def test_fit_callbacks_and_time_history():
    sess, batches = _make_session()
    events = []

    class Recorder(Callback):
        def on_train_begin(self, session):
            events.append("train_begin")

        def on_epoch_begin(self, epoch):
            events.append(f"epoch_begin:{epoch}")

        def on_step_end(self, step, metrics):
            events.append("step")

        def on_epoch_end(self, epoch, logs):
            events.append(f"epoch_end:{epoch}:{sorted(logs)}")

        def on_train_end(self, history):
            events.append("train_end")

    th = TimeHistory(items_per_step=16)
    sess.fit(batches(3), epochs=2, callbacks=[Recorder(), th])
    assert events[0] == "train_begin"
    assert events[-1] == "train_end"
    assert events.count("step") == 6
    assert "epoch_end:1:['epoch_steps', 'loss', 'step']" in events
    assert len(th.epoch_times) == 2
    assert len(th.items_per_sec) == 2
    assert th.items_per_sec[0] > 0


def test_fit_checkpoint_and_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    sess, batches = _make_session(PartitionedPS())
    data = batches(5)
    sess.fit(data, epochs=2, checkpoint_dir=ckpt)
    assert sess.step_count == 10
    trained_w = sess.params["w"]

    # A fresh session resumes from the checkpoint (exact: params + opt
    # slots + step counter) before training further.
    _reset_default_autodist_for_testing()
    sess2, _ = _make_session(PartitionedPS())
    hist = sess2.fit(data, epochs=1, checkpoint_dir=ckpt, resume=True)
    assert sess2.step_count == 15          # resumed at 10, ran 5 more
    assert hist.steps_run == 5

    # And resume=False starts from scratch.
    _reset_default_autodist_for_testing()
    sess3, _ = _make_session(PartitionedPS())
    sess3.fit(data, epochs=1, checkpoint_dir=str(tmp_path / "other"),
              resume=False)
    assert sess3.step_count == 5
    np.testing.assert_array_less(
        np.abs(trained_w - np.array([[1.0], [-2.0], [0.5]])),
        np.abs(sess3.params["w"] - np.array([[1.0], [-2.0], [0.5]])) + 1e-9)


def test_fit_resume_trains_to_total_epochs(tmp_path):
    """epochs is the TOTAL target (Keras semantics): resuming an
    interrupted fit(epochs=N) with steps_per_epoch derivable completes to
    N total epochs instead of running N more."""
    ckpt = str(tmp_path / "ckpt")
    sess, batches = _make_session(PartitionedPS())
    data = batches(5)
    sess.fit(data, epochs=2, steps_per_epoch=5, checkpoint_dir=ckpt)
    assert sess.step_count == 10

    # Re-running the same fit target with more epochs: completes 2 -> 4.
    _reset_default_autodist_for_testing()
    sess2, _ = _make_session(PartitionedPS())
    hist = sess2.fit(data, epochs=4, steps_per_epoch=5,
                     checkpoint_dir=ckpt, resume=True)
    assert sess2.step_count == 20          # epochs 2,3 only
    assert hist.epochs_run == 2

    # Target already met: restores and trains nothing.
    _reset_default_autodist_for_testing()
    sess3, _ = _make_session(PartitionedPS())
    hist3 = sess3.fit(data, epochs=2, steps_per_epoch=5,
                      checkpoint_dir=ckpt, resume=True)
    assert sess3.step_count == 20 and hist3.steps_run == 0

    # Explicit initial_epoch overrides the derivation.
    _reset_default_autodist_for_testing()
    sess4, _ = _make_session(PartitionedPS())
    hist4 = sess4.fit(data, epochs=5, steps_per_epoch=5,
                      checkpoint_dir=ckpt, resume=True, initial_epoch=4)
    assert hist4.epochs_run == 1 and sess4.step_count == 25


def test_fit_preemption_checkpoint_and_resume(tmp_path):
    """A preemption signal (cloud SIGTERM-before-eviction) checkpoints at
    the next step boundary and stops; resume continues from that step."""
    import os
    import signal

    ckpt = str(tmp_path / "ckpt")
    sess, batches = _make_session()
    data = batches(6)

    class Bomb(Callback):
        fired_at = None

        def on_step_end(self, step, metrics):
            if step == 3 and self.fired_at is None:
                self.fired_at = step
                os.kill(os.getpid(), signal.SIGUSR1)

    prev = signal.getsignal(signal.SIGUSR1)
    bomb = Bomb()
    hist = sess.fit(data, epochs=4, checkpoint_dir=ckpt,
                    callbacks=[bomb], preemption_signals=("SIGUSR1",))
    assert bomb.fired_at == 3
    assert hist.preempted
    assert hist.steps_run == 3          # stopped at the next boundary
    assert hist.epochs_run == 0         # the partial epoch is not counted
    assert signal.getsignal(signal.SIGUSR1) is prev   # handler restored

    from autodist_tpu.checkpoint import Saver

    assert Saver.latest_step(ckpt) == 3   # saved AT the preempted step

    # Resume: restores step 3 and trains on (mid-epoch resume re-runs the
    # partial epoch at epoch granularity, as documented).
    _reset_default_autodist_for_testing()
    sess2, _ = _make_session()
    hist2 = sess2.fit(data, epochs=1, steps_per_epoch=6,
                      checkpoint_dir=ckpt, resume=True)
    assert not hist2.preempted
    assert sess2.step_count == 9        # resumed at 3, ran epoch 0's 6


def test_fit_preemption_rejects_unknown_signal():
    import signal

    sess, batches = _make_session()
    prev = signal.getsignal(signal.SIGUSR2)
    with pytest.raises(ValueError, match="unknown signal"):
        sess.fit(batches(2), epochs=1,
                 preemption_signals=("SIGUSR2", "SIGNOPE"))
    # Nothing was installed before the bad name was rejected.
    assert signal.getsignal(signal.SIGUSR2) is prev


def test_fit_preemption_duplicate_signals_restore_cleanly():
    """Duplicate entries (name + number of the same signal) must not
    leave fit's handler installed after return."""
    import signal

    sess, batches = _make_session()
    prev = signal.getsignal(signal.SIGUSR1)
    hist = sess.fit(batches(2), epochs=1,
                    preemption_signals=("SIGUSR1", signal.SIGUSR1,
                                        int(signal.SIGUSR1)))
    assert not hist.preempted
    assert signal.getsignal(signal.SIGUSR1) is prev


def test_fit_preemption_handler_restored_when_callback_raises():
    """An exception anywhere inside the handler scope (here: a callback)
    must still restore the previous handlers."""
    import signal

    sess, batches = _make_session()

    class Boom(Callback):
        def on_epoch_begin(self, epoch):
            raise RuntimeError("user callback bug")

    prev = signal.getsignal(signal.SIGUSR1)
    with pytest.raises(RuntimeError, match="user callback bug"):
        sess.fit(batches(2), epochs=1, callbacks=[Boom()],
                 preemption_signals=("SIGUSR1",))
    assert signal.getsignal(signal.SIGUSR1) is prev


def test_fit_empty_epoch_warns_not_crashes():
    sess, _ = _make_session()
    ends = []

    class Ends(Callback):
        def on_epoch_end(self, epoch, logs):
            ends.append(logs["loss"])

    hist = sess.fit([], epochs=2, callbacks=[Ends()])
    assert hist.epochs_run == 2
    assert hist.steps_run == 0
    assert hist.history["epoch_loss"] == []
    assert ends == [None, None]  # begin/end pairing holds on empty epochs


def test_fit_shared_iterator_chunks_without_dropping_batches():
    """One iterator spanning epochs via steps_per_epoch: every batch is
    trained exactly once, in order — the prefetcher must not pull-and-drop
    batches past the epoch cap."""
    sess, batches = _make_session()
    data = batches(6)
    seen = []
    orig_run = sess.run

    def spy_run(batch, sync=True):
        seen.append(float(np.asarray(batch["x"][0, 0])))
        return orig_run(batch, sync=sync)

    sess.run = spy_run
    hist = sess.fit(iter(data), epochs=3, steps_per_epoch=2,
                    prefetch_depth=2)
    assert hist.steps_run == 6
    assert hist.epochs_run == 3
    assert seen == [float(b["x"][0, 0]) for b in data]


def test_fit_exhausted_iterator_stops_cleanly():
    """A one-shot iterator trains one epoch, then fit stops instead of
    spinning through empty epochs (and epochs_run reflects reality)."""
    sess, batches = _make_session()
    hist = sess.fit(iter(batches(4)), epochs=3)
    assert hist.steps_run == 4
    assert hist.epochs_run == 1
    assert len(hist.history["epoch_loss"]) == 1


def test_fit_log_every_no_duplicate_epoch_sample():
    """Last step on a log_every boundary: the epoch-end sample reuses it
    (no duplicate history entry, no second host sync)."""
    sess, batches = _make_session()
    batch = batches(1)[0]
    hist = sess.fit(batch, epochs=1, steps_per_epoch=9, log_every=3)
    assert hist.history["loss_step"] == [3, 6, 9]
    assert hist.history["epoch_loss"] == [hist.history["loss"][-1]]


def test_fit_final_checkpoint_beyond_stride(tmp_path):
    """epochs not a multiple of checkpoint_every: the tail epochs are
    still checkpointed at train end."""
    from autodist_tpu.checkpoint import Saver

    ckpt = str(tmp_path / "ckpt")
    sess, batches = _make_session()
    sess.fit(batches(2), epochs=3, checkpoint_dir=ckpt, checkpoint_every=2)
    assert Saver.latest_step(ckpt) == 6  # not 4


def test_fit_requires_steps_for_batch_dict():
    sess, batches = _make_session()
    with pytest.raises(ValueError, match="steps_per_epoch"):
        sess.fit(batches(1)[0], epochs=1)


def test_evaluate_no_state_change_and_matches():
    """sess.evaluate computes the loss on current params without any
    update; a second evaluate returns the identical value."""
    sess, batches = _make_session()
    data = batches(3)
    sess.fit(data, epochs=1)
    w_before = np.asarray(sess.params["w"]).copy()
    e1 = float(sess.evaluate(data[0])["loss"])
    e2 = float(sess.evaluate(data[0])["loss"])
    assert e1 == e2
    np.testing.assert_array_equal(np.asarray(sess.params["w"]), w_before)
    assert sess.step_count == 3          # evaluate didn't count as steps
    # mean over an iterable equals the mean of singles
    singles = [float(sess.evaluate(b)["loss"]) for b in data]
    np.testing.assert_allclose(float(sess.evaluate(data)["loss"]),
                               np.mean(singles), rtol=1e-6)


def test_fit_validation_data():
    sess, batches = _make_session()
    train, val = batches(4), batches(2)
    logs_seen = []

    class Val(Callback):
        def on_epoch_end(self, epoch, logs):
            logs_seen.append(logs.get("val_loss"))

    hist = sess.fit(train, epochs=3, validation_data=val,
                    callbacks=[Val()])
    assert len(hist.history["val_loss"]) == 3
    assert logs_seen == hist.history["val_loss"]
    # training on a convex problem: val loss decreases across epochs
    assert hist.history["val_loss"][-1] < hist.history["val_loss"][0]


def test_fit_validation_dict_requires_steps_up_front():
    sess, batches = _make_session()
    with pytest.raises(ValueError, match="validation_steps"):
        sess.fit(batches(2), epochs=2, validation_data=batches(1)[0])


def test_fit_validation_exhausted_generator_warns_not_crashes():
    sess, batches = _make_session()
    hist = sess.fit(batches(2), epochs=3,
                    validation_data=iter(batches(2)))
    assert len(hist.history.get("val_loss", [])) == 1  # only epoch 0
    assert hist.epochs_run == 3                        # training unaffected
