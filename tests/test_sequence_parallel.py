"""Sequence-parallel attention correctness: ring and Ulysses must match
dense attention exactly (same math, different communication schedule)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.mesh import build_mesh
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.parallel import (
    make_ring_attention,
    make_ulysses_attention,
    sequence_parallel_attention,
)


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def _qkv(b=2, t=32, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, t, h, d).astype(np.float32)  # noqa: E731
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq_size", [2, 4, 8])
def test_ring_matches_dense(causal, seq_size):
    mesh = build_mesh({"seq": seq_size})
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal)
    ring = make_ring_attention(mesh)
    out = jax.jit(lambda q, k, v: ring(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq_size", [2, 4])
def test_ulysses_matches_dense(causal, seq_size):
    mesh = build_mesh({"seq": seq_size})
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal)
    uly = make_ulysses_attention(mesh)
    out = jax.jit(lambda q, k, v: uly(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_data_axis_too():
    """Partial-manual shard_map: seq manual, data stays GSPMD."""
    mesh = build_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, True)
    ring = make_ring_attention(mesh)
    sh = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring(q, k, v, True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = build_mesh({"seq": 8})
    q, k, v = _qkv(h=4)  # 4 heads, seq=8
    uly = make_ulysses_attention(mesh)
    with pytest.raises(ValueError, match="divisible"):
        uly(q, k, v, False)


def test_seq_parallel_lm_end_to_end():
    """Train the flagship LM with ring attention on a data x seq mesh and
    match the dense-attention run."""
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.strategy import AllReduce

    mesh = build_mesh({"data": 2, "seq": 4})
    ring = make_ring_attention(mesh)

    def make(attn_fn):
        return transformer_lm(vocab_size=256, num_layers=2, num_heads=4,
                              head_dim=8, d_ff=64, max_len=32, seq_len=32,
                              attn_fn=attn_fn)

    spec_ring, spec_dense = make(ring), make(dense_attention)
    params = spec_dense.init(jax.random.PRNGKey(0))
    batch = spec_dense.sample_batch(8)

    losses = {}
    for name, spec in (("dense", spec_dense), ("ring", spec_ring)):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=AllReduce(),
                      mesh_axes={"data": 2, "seq": 4})
        with ad.scope():
            ad.capture(params=params, optimizer=optax.sgd(0.1),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        losses[name] = [float(sess.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses["ring"], losses["dense"], rtol=1e-4)


def test_factory():
    mesh = build_mesh({"seq": 2})
    assert sequence_parallel_attention("dense", mesh) is dense_attention
    assert callable(sequence_parallel_attention("ring", mesh))
    assert callable(sequence_parallel_attention("ulysses", mesh))
    with pytest.raises(ValueError):
        sequence_parallel_attention("bogus", mesh)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq_size", [2, 4])
def test_ring_flash_matches_dense(causal, seq_size):
    """Ring with the flash kernel inside (log-space lse merge) is exact."""
    mesh = build_mesh({"seq": seq_size})
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal)
    ring = make_ring_attention(mesh, inner="flash", block_q=8, block_k=8,
                               interpret=True)
    out = jax.jit(lambda q, k, v: ring(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match_dense(causal):
    """Gradients flow through the lse merge and the kernel's custom VJP
    (the Δ − dlse backward adjustment) exactly."""
    mesh = build_mesh({"seq": 4})
    q, k, v = _qkv(t=16)
    ring = make_ring_attention(mesh, inner="flash", block_q=8, block_k=8,
                               interpret=True)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-4, atol=5e-4)


def test_flash_with_lse_values_and_grads():
    """flash_attention_with_lse: lse equals the dense log-sum-exp, and a
    loss using BOTH outputs differentiates correctly (dlse path)."""
    from autodist_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(b=1, t=16, h=2, d=8, seed=3)
    o, lse = flash_attention_with_lse(q, k, v, False, block_q=8, block_k=8,
                                      interpret=True)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    ref_lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B,H,T]
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)

    def loss_flash(q):
        o, lse = flash_attention_with_lse(q, k, v, False, block_q=8,
                                          block_k=8, interpret=True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_ref(q):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        p = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    g1 = jax.grad(loss_flash)(q)
    g2 = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(causal):
    """Ulysses with the flash kernel as the per-head-subset attention."""
    mesh = build_mesh({"seq": 4})
    q, k, v = _qkv(t=16, h=4)
    ref = dense_attention(q, k, v, causal)
    uly = make_ulysses_attention(mesh, inner="flash", block_q=8, block_k=8,
                                 interpret=True)
    out = jax.jit(lambda q, k, v: uly(q, k, v, causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # gradients flow through all-to-all + the kernel's custom VJP
    g1 = jax.jit(jax.grad(lambda q: jnp.sum(uly(q, k, v, causal) ** 2)))(q)
    g2 = jax.jit(jax.grad(
        lambda q: jnp.sum(dense_attention(q, k, v, causal) ** 2)))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=5e-4, atol=5e-4)
