"""EngineServer: the HTTP front over DecodeEngine.

Token-exactness through the network boundary (concurrent requests vs
the per-request oracle), SSE streaming (deltas reassemble to the final
result), cancel, stats, tokenizer text mode, and error paths — all on
the CPU backend with a tiny model, real sockets on localhost.
"""
import http.client
import json
import threading

import jax
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.serving import DecodeEngine, EngineServer

VOCAB = 61


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


@pytest.fixture()
def server(lm):
    spec, params = lm
    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=4)
    srv = EngineServer(eng, port=0, request_timeout_s=120).start()
    yield srv
    srv.close()


def _post(addr, path, body, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out


def test_completions_token_exact_concurrent(server, lm):
    """More concurrent requests than engine slots, served over HTTP:
    each response equals the per-request oracle decode."""
    spec, params = lm
    gen = make_generator(spec)
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, VOCAB, p).tolist(), n)
            for p, n in [(3, 5), (1, 8), (5, 3), (2, 6), (4, 4)]]
    out = {}

    def issue(i, prompt, n):
        out[i] = _post(server.address, "/v1/completions",
                       {"prompt_tokens": prompt, "max_new_tokens": n})

    threads = [threading.Thread(target=issue, args=(i, p, n))
               for i, (p, n) in enumerate(reqs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i, (prompt, n) in enumerate(reqs):
        status, body = out[i]
        assert status == 200, body
        want = np.asarray(gen(
            params, np.asarray(prompt, np.int32)[None, :], n))[0]
        np.testing.assert_array_equal(body["tokens"], want)
        assert body["new_tokens"] == body["tokens"][len(prompt):]
        assert len(body["new_tokens"]) == n

    status, st = _get(server.address, "/v1/stats")
    assert status == 200
    assert st["requests_served"] == len(reqs)
    assert st["completed"] == len(reqs)
    assert st["outstanding"] == 0
    assert not st["engine_failed"]


def test_streaming_deltas_reassemble(lm):
    """SSE stream: non-final events carry monotone new-token deltas that
    concatenate exactly to the final result's new_tokens.  The engine's
    step is throttled so chunk boundaries are strictly slower than the
    handler's poll cadence — deltas MUST surface (a tiny CPU decode can
    otherwise finish between two polls)."""
    import time as _time

    spec, params = lm
    gen = make_generator(spec)
    prompt = [7, 3, 11]
    n = 9

    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=2)
    orig_step = eng.step
    eng.step = lambda: (_time.sleep(0.08), orig_step())[1]
    srv = EngineServer(eng, port=0, request_timeout_s=120).start()
    conn = http.client.HTTPConnection(*srv.address, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt_tokens": prompt, "max_new_tokens": n,
                             "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "text/event-stream"
    events = []
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if line.startswith(b"data: "):
            events.append(json.loads(line[len(b"data: "):]))
            if events[-1].get("done"):
                break
    conn.close()
    srv.close()

    assert events and events[-1]["done"]
    final = events[-1]
    want = np.asarray(gen(
        params, np.asarray(prompt, np.int32)[None, :], n))[0]
    np.testing.assert_array_equal(final["tokens"], want)
    deltas = [t for ev in events[:-1] for t in ev["new_tokens"]]
    # Deltas surface at chunk boundaries; together they are a prefix of
    # (possibly all of) the generated tokens, in order.
    assert deltas == final["new_tokens"][:len(deltas)]
    assert len(deltas) > 0   # something streamed before completion


def test_tokenizer_text_mode(lm):
    """With a tokenizer installed, 'prompt' strings round-trip and the
    response carries decoded text."""
    spec, params = lm

    class Toy:
        def encode(self, s):
            return [ord(c) % VOCAB for c in s]

        def decode(self, toks):
            return "".join(chr(97 + (t % 26)) for t in toks)

    eng = DecodeEngine(spec, params, slots=1, window=24, chunk=4)
    with EngineServer(eng, port=0, tokenizer=Toy(),
                      request_timeout_s=120) as srv:
        status, body = _post(srv.address, "/v1/completions",
                             {"prompt": "hi", "max_new_tokens": 3})
        assert status == 200, body
        assert isinstance(body["text"], str)
        assert len(body["text"]) == len(body["tokens"])
        assert len(body["new_tokens"]) == 3


def test_validation_and_unknown_paths(server):
    addr = server.address
    # over-window request → engine ValueError → 400 with the message
    status, body = _post(addr, "/v1/completions",
                         {"prompt_tokens": [1] * 20,
                          "max_new_tokens": 20})
    assert status == 400 and "window" in body["error"]
    status, body = _post(addr, "/v1/completions",
                         {"max_new_tokens": 4})
    assert status == 400 and "prompt_tokens" in body["error"]
    # text prompt without a tokenizer is rejected loudly
    status, body = _post(addr, "/v1/completions",
                         {"prompt": "hello", "max_new_tokens": 4})
    assert status == 400 and "tokenizer" in body["error"]
    status, body = _post(addr, "/v1/completions",
                         {"prompt_tokens": [1, 2], "max_new_tokens": "x"})
    assert status == 400
    status, _ = _post(addr, "/v1/nope", {})
    assert status == 404
    status, _ = _get(addr, "/v1/nope")
    assert status == 404
    status, body = _get(addr, "/healthz")
    assert status == 200 and body["ok"]


def test_late_submit_joins_running_batch(lm):
    """Continuous batching THROUGH the HTTP boundary: a short request
    submitted while a long one is mid-decode joins the running batch
    and finishes first.  Guards the driver-loop lock release — holding
    the lock across the busy loop would serialize the server into one
    batch per drain (the short request would then finish last)."""
    import time as _time

    spec, params = lm
    # Wide margin for loaded CI hosts: the long request holds ~24
    # throttled chunks (>1 s) after the short one lands, while the short
    # one needs ~2 — ordering survives coarse thread scheduling.
    eng = DecodeEngine(spec, params, slots=2, window=48, chunk=2)
    orig_step = eng.step
    eng.step = lambda: (_time.sleep(0.05), orig_step())[1]
    done_order = []

    def issue(tag, n):
        status, body = _post(srv.address, "/v1/completions",
                             {"prompt_tokens": [3, 5], "max_new_tokens": n})
        assert status == 200, body
        done_order.append(tag)

    with EngineServer(eng, port=0, request_timeout_s=120) as srv:
        t_long = threading.Thread(target=issue, args=("long", 46))
        t_long.start()
        _time.sleep(0.4)    # several throttled chunks into the long decode
        t_short = threading.Thread(target=issue, args=("short", 2))
        t_short.start()
        t_long.join()
        t_short.join()
    assert done_order == ["short", "long"]


def test_timeout_cancels_and_frees_the_slot(lm):
    """A request outliving request_timeout_s answers 504 and is
    cancelled (slot freed): a follow-up request still completes."""
    import time as _time

    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=24, chunk=2)
    orig_step = eng.step
    eng.step = lambda: (_time.sleep(0.05), orig_step())[1]
    with EngineServer(eng, port=0, request_timeout_s=0.3) as srv:
        status, body = _post(srv.address, "/v1/completions",
                             {"prompt_tokens": [1, 2],
                              "max_new_tokens": 20})
        assert status == 504 and "cancelled" in body["error"]
        eng.step = orig_step   # un-throttle; the slot must be free
        status, body = _post(srv.address, "/v1/completions",
                             {"prompt_tokens": [4], "max_new_tokens": 2})
        assert status == 200, body
        assert len(body["new_tokens"]) == 2


def test_cancel_unknown_and_queued(server):
    addr = server.address
    # unknown id
    status, body = _post(addr, "/v1/cancel", {"id": 12345})
    assert status == 200 and body["cancelled"] is False
    status, body = _post(addr, "/v1/cancel", {"id": "x"})
    assert status == 400


def test_backpressure_answers_429_with_retry_after(lm):
    """A full engine queue surfaces the typed AdmissionError as HTTP
    429 with a Retry-After header — the bounded-queue satellite."""
    import time as _time

    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=24, chunk=2,
                       max_queue=1)
    orig_step = eng.step
    eng.step = lambda: (_time.sleep(0.2), orig_step())[1]
    with EngineServer(eng, port=0, request_timeout_s=120) as srv:
        t1 = threading.Thread(
            target=_post, args=(srv.address, "/v1/completions",
                                {"prompt_tokens": [1, 2],
                                 "max_new_tokens": 8}))
        t1.start()
        _time.sleep(0.3)       # in flight: slot busy, queue empty
        t2 = threading.Thread(
            target=_post, args=(srv.address, "/v1/completions",
                                {"prompt_tokens": [3],
                                 "max_new_tokens": 8}))
        t2.start()             # queued: queue now full
        _time.sleep(0.3)
        conn = http.client.HTTPConnection(*srv.address, timeout=30)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt_tokens": [4],
                                 "max_new_tokens": 2}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        retry_hdr = resp.getheader("Retry-After")
        conn.close()
        assert resp.status == 429, body
        assert "retry" in body["error"].lower() or "full" in body["error"]
        assert body["retry_after_s"] > 0
        assert retry_hdr is not None and int(retry_hdr) >= 1
        eng.step = orig_step
        t1.join()
        t2.join()
    st = srv.stats()
    assert st["requests_failed"] >= 1        # the 429 counted as failed


@pytest.fixture()
def paged_server(lm):
    from autodist_tpu.serving import PagedDecodeEngine

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                            block_size=8, num_blocks=24, chunk=4)
    srv = EngineServer(eng, port=0, request_timeout_s=120).start()
    yield srv
    srv.close()


def test_paged_engine_through_http(paged_server, lm):
    """The paged scheduler behind the HTTP front: oracle-exact
    completions, SLO class accepted, scheduler surface in /v1/stats,
    serving gauges + TTFT histogram on /metrics."""
    spec, params = lm
    gen = make_generator(spec)
    addr = paged_server.address
    status, body = _post(addr, "/v1/completions",
                         {"prompt_tokens": [3, 5, 7], "max_new_tokens": 5,
                          "slo": "throughput"})
    assert status == 200, body
    want = np.asarray(gen(
        params, np.asarray([3, 5, 7], np.int32)[None, :], 5))[0]
    np.testing.assert_array_equal(body["tokens"], want)

    status, body = _post(addr, "/v1/completions",
                         {"prompt_tokens": [1], "max_new_tokens": 2,
                          "slo": "gold"})
    assert status == 400 and "slo" in body["error"]

    status, st = _get(addr, "/v1/stats")
    assert status == 200
    assert st["queue_depth"] == {"latency": 0, "throughput": 0}
    assert st["block_occupancy"] >= 0
    assert "prefix_hit_rate" in st and "free_blocks" in st
    assert st["ttft_p50_ms"] > 0

    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    conn.close()
    assert "autodist_serving_ttft_seconds_bucket" in text
    assert "autodist_serving_queue_wait_seconds_bucket" in text
    assert "autodist_serving_block_occupancy" in text
    assert 'autodist_serving_queue_depth_class{slo="latency"}' in text


def test_slot_engine_rejects_slo_field(server):
    status, body = _post(server.address, "/v1/completions",
                         {"prompt_tokens": [1, 2], "max_new_tokens": 2,
                          "slo": "latency"})
    assert status == 400 and "SLO" in body["error"]
