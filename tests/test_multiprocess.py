"""Live multi-process integration: chief + worker over jax.distributed.

Launches ``tests/integration/dist_train.py`` as the chief; the REAL
Coordinator re-runs it as a worker process, both rendezvous through the
PJRT coordination service (``Cluster.start`` →
``jax.distributed.initialize``), and train in SPMD lockstep on a 4-device
global mesh (2 CPU devices per process).  Numeric parity is asserted
against a closed-form single-process solution.

Reference analog: ``tests/integration/test_dist.py:1-43`` — which needed a
real 2-machine GPU cluster; here two local processes cover the same code
paths (strategy shipping, env plumbing, rendezvous, collectives)."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "integration", "dist_train.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _reference_losses(steps=4, lr=0.1):
    """Closed-form single-process SGD on the same fixed batch."""
    rng = np.random.RandomState(42)
    x = rng.randn(32, 3).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5], np.float32) + 0.25).astype(np.float32)
    w = np.zeros(3, np.float32)
    b = np.float32(0.0)
    losses = []
    n = x.shape[0]
    for _ in range(steps):
        pred = x @ w + b
        err = pred - y
        losses.append(float(np.mean(err ** 2)))
        gw = 2.0 / n * (x.T @ err)
        gb = np.float32(2.0 * np.mean(err))
        w = w - lr * gw
        b = b - lr * gb
    return losses, w


def _chief_env(tmp_path, builder: str, **extra):
    """Chief subprocess environment (single source for every chief test)."""
    result_file = str(tmp_path / f"result_{builder}.json")
    env = dict(os.environ)
    env.pop("AUTODIST_WORKER", None)
    env.pop("AUTODIST_STRATEGY_ID", None)
    env.update({
        "AUTODIST_RESULT_FILE": result_file,
        "AUTODIST_REPO_ROOT": REPO,
        "AUTODIST_TEST_BUILDER": builder,
        "AUTODIST_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "AUTODIST_TPU_WORKDIR": str(tmp_path / "workdir"),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.update(extra)
    return env, result_file


def _run_chief(tmp_path, builder: str, **extra):
    env, result_file = _chief_env(tmp_path, builder, **extra)
    proc = subprocess.run(
        [sys.executable, "-u", SCRIPT], env=env, timeout=300,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, f"chief failed (rc={proc.returncode}):\n{out[-4000:]}"
    with open(result_file, encoding="utf-8") as f:
        chief = json.load(f)
    with open(result_file + ".worker", encoding="utf-8") as f:
        worker = json.load(f)
    return chief, worker, out


@pytest.mark.parametrize("builder", [
    "AllReduce",
    "PSLoadBalancing",
    # PartitionedPS shards w (dim 3 -> padded to 4) ACROSS the two
    # processes: exercises pad-to-divisible + the collective host gather
    # behind sess.params for non-addressable shards.
    "PartitionedPS",
])
def test_two_process_training_parity(tmp_path, builder):
    chief, worker, out = _run_chief(tmp_path, builder)

    # Topology: two processes rendezvoused into one 4-device runtime.
    assert chief["process_count"] == 2 and worker["process_count"] == 2
    assert chief["process_index"] == 0 and worker["process_index"] == 1
    assert chief["global_devices"] == 4
    assert chief["local_devices"] == 2
    assert chief["mesh"] == {"data": 4}

    # Strategy shipping: the worker deserialized the CHIEF's strategy.
    assert worker["strategy_id"] == chief["strategy_id"]

    # SPMD lockstep: both processes observed identical global losses.
    np.testing.assert_allclose(chief["losses"], worker["losses"], rtol=1e-6)

    # Numeric parity with the closed-form single-process run.
    ref_losses, ref_w = _reference_losses()
    np.testing.assert_allclose(chief["losses"], ref_losses, rtol=1e-4)
    np.testing.assert_allclose(chief["final_w"], ref_w, rtol=1e-4)

    # Multi-host input path: each process fed only its DISJOINT half of
    # the batch via place_local_batch; the resulting global step must
    # match the closed-form 5th step on the full batch.
    ref5, _ = _reference_losses(steps=5)
    np.testing.assert_allclose(chief["sharded_input_loss"], ref5[4],
                               rtol=1e-4)
    np.testing.assert_allclose(worker["sharded_input_loss"], ref5[4],
                               rtol=1e-4)

    assert "jax.distributed initialized" in out


def test_two_process_tensor_parallel_mesh(tmp_path):
    """A model-ONLY mesh (model=4 over 2 processes × 2 devices): the
    model axis necessarily crosses the OS-process boundary, so weight
    shards and their tensor-parallel collectives live on different
    machines — cross-process TENSOR parallelism, beyond the reference's
    data-parallel-only multi-machine matrix.  (A data=2,model=2 mesh
    would NOT cover this: canonical axis ordering makes `data` the
    process-spanning axis.)  Numeric parity with the closed-form
    single-process run must still hold; batches replicate (no data
    axis)."""
    chief, worker, _ = _run_chief(
        tmp_path, "PartitionedPS",
        AUTODIST_TEST_MESH="model=4")
    assert chief["mesh"] == {"model": 4}
    assert chief["process_count"] == 2
    np.testing.assert_allclose(chief["losses"], worker["losses"],
                               rtol=1e-6)
    ref_losses, ref_w = _reference_losses()
    np.testing.assert_allclose(chief["losses"], ref_losses, rtol=1e-4)
    np.testing.assert_allclose(chief["final_w"], ref_w, rtol=1e-4)
    assert chief["sharded_input_loss"] is None  # pure TP: no data axis


def _run_single_oracle(tmp_path, builder: str, **extra):
    """Same script, same case, same global mesh shape — ONE process with
    all 4 devices local.  The parity reference: crossing the OS-process
    boundary must change nothing numerically."""
    env, result_file = _chief_env(tmp_path, builder, **extra)
    env["AUTODIST_TEST_SINGLE"] = "1"
    env["AUTODIST_RESULT_FILE"] = result_file + ".single"
    proc = subprocess.run(
        [sys.executable, "-u", SCRIPT], env=env, timeout=300,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, \
        f"single oracle failed (rc={proc.returncode}):\n{out[-4000:]}"
    with open(result_file + ".single", encoding="utf-8") as f:
        return json.load(f)


@pytest.mark.parametrize("case,builder,mesh", [
    # Sparse embedding: the vocab-sharded table's gradient scatter-adds
    # cross the process boundary (reference sparse distributed case).
    ("sparse", "PSLoadBalancing", None),
    # Compressed sync: bf16+error-feedback wire format on the explicit
    # fused-group shard_map path, across processes.
    ("linear", "AllReduceEF", None),
    # Pipelined model on a pipe-ONLY mesh: every ring hop (activations
    # forward; and for 1f1b, hand-scheduled cotangents backward) crosses
    # the process boundary.
    ("pipeline", "PSLoadBalancing", "pipe=4"),
    ("pipeline1f1b", "PSLoadBalancing", "pipe=4"),
])
def test_two_process_case_matrix(tmp_path, case, builder, mesh):
    """VERDICT r2 #4: widen the live matrix beyond linear regression —
    parity oracle is the SAME case run single-process on the same global
    mesh shape (4 devices), so the assertion is 'the process boundary is
    numerically invisible'."""
    extra = {"AUTODIST_TEST_CASE": case}
    if mesh:
        extra["AUTODIST_TEST_MESH"] = mesh
    chief, worker, _ = _run_chief(tmp_path, builder, **extra)
    single = _run_single_oracle(tmp_path, builder, **extra)

    assert chief["process_count"] == 2 and single["process_count"] == 1
    assert chief["global_devices"] == single["global_devices"] == 4
    assert chief["mesh"] == single["mesh"]
    # SPMD lockstep across the two processes...
    np.testing.assert_allclose(chief["losses"], worker["losses"], rtol=1e-6)
    # ...and parity with the single-process oracle: losses and the
    # all-parameter checksum.
    np.testing.assert_allclose(chief["losses"], single["losses"], rtol=1e-5)
    np.testing.assert_allclose(chief["param_checksum"],
                               single["param_checksum"], rtol=1e-5)
    # Training moved: multi-step loss decrease in every case.
    assert chief["losses"][-1] < chief["losses"][0]


def test_two_process_checkpoint_roundtrip(tmp_path):
    """Live distributed checkpointing (reference c10's saver-in-
    distributed-run): both processes participate in a collective Orbax
    save mid-run, train two steps, restore, and train the same two steps
    again — exact resume means identical loss pairs, observed
    identically on chief and worker.  PartitionedPS so the saved arrays
    are genuinely sharded ACROSS the two processes (logical-layout
    save/restore with padding stripped)."""
    chief, worker, _ = _run_chief(tmp_path, "PartitionedPS",
                                  AUTODIST_TEST_CHECKPOINT="1")
    for side in (chief, worker):
        ck = side["checkpoint"]
        assert ck is not None
        # restore() must reset the step counter to the saved step
        # (absolute value is 5: 4 training steps + the sharded-input
        # extra step precede the checkpoint block).
        assert ck["restored_step"] == ck["save_step"] == 5
        # Exact equality: resume replays the SAME compiled steps from the
        # SAME restored state, so any deviation at all is a restore bug.
        assert ck["after_restore"] == ck["after_save"], ck
    assert chief["checkpoint"]["after_save"] == \
        worker["checkpoint"]["after_save"]


def test_four_process_hybrid_mesh(tmp_path):
    """VERDICT r3 #6: >2 processes AND a hybrid (multi-slice-style) mesh,
    live.  4 processes x 1 CPU device rendezvous into a
    ``build_hybrid_mesh({"model": 2}, {"data": 2})`` topology — data is
    the DCN-outer axis (spans the two emulated "slices"), model the
    ICI-inner one.  With one device per process EVERY axis crosses OS
    processes; the recorded per-axis process ids prove the outer-axis
    collective genuinely crosses the boundary, and numeric parity against
    the closed-form single-process solution AND the single-process oracle
    on the same hybrid mesh proves it crosses correctly."""
    env, result_file = _chief_env(tmp_path, "PartitionedPS",
                                  AUTODIST_TEST_NODES="4",
                                  AUTODIST_TEST_HYBRID="1")
    proc = subprocess.run(
        [sys.executable, "-u", SCRIPT], env=env, timeout=300,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, \
        f"chief failed (rc={proc.returncode}):\n{out[-4000:]}"
    with open(result_file, encoding="utf-8") as f:
        chief = json.load(f)
    workers = []
    for suffix in (".worker", ".worker2", ".worker3"):
        with open(result_file + suffix, encoding="utf-8") as f:
            workers.append(json.load(f))

    assert chief["process_count"] == 4
    assert chief["local_devices"] == 1 and chief["global_devices"] == 4
    assert chief["mesh"] == {"data": 2, "model": 2}
    assert sorted(w["process_index"] for w in workers) == [1, 2, 3]

    # The DCN-outer data axis spans processes (and slices): walking the
    # data axis at model=0 must visit >1 process — its psum/reduce
    # crosses the OS-process (emulated-DCN) boundary.  The emulated
    # slice layout is contiguous (procs {0,1} = slice 0, {2,3} = slice
    # 1), so the data hop is exactly the cross-slice hop.
    procs = chief["axis_process_ids"]
    assert len(set(procs["data"])) > 1, procs
    assert len(set(procs["model"])) > 1, procs       # 1 dev/process
    assert procs["data"] == [0, 2], procs            # slice 0 -> slice 1

    # SPMD lockstep across all four processes.
    for w in workers:
        np.testing.assert_allclose(chief["losses"], w["losses"], rtol=1e-6)
        assert w["strategy_id"] == chief["strategy_id"]
    # Numeric parity: closed-form single-device solution...
    ref_losses, ref_w = _reference_losses()
    np.testing.assert_allclose(chief["losses"], ref_losses, rtol=1e-4)
    np.testing.assert_allclose(chief["final_w"], ref_w, rtol=1e-4)
    # ...and the single-process oracle on the SAME hybrid mesh.
    single = _run_single_oracle(tmp_path, "PartitionedPS",
                                AUTODIST_TEST_HYBRID="1")
    assert single["mesh"] == chief["mesh"]
    np.testing.assert_allclose(chief["losses"], single["losses"], rtol=1e-5)
    np.testing.assert_allclose(chief["param_checksum"],
                               single["param_checksum"], rtol=1e-5)


def test_worker_crash_aborts_chief(tmp_path):
    """Fail-fast failure propagation (reference coordinator.py:98-110): a
    worker dying mid-bootstrap must abort the chief instead of leaving it
    hung in rendezvous."""
    env, result_file = _chief_env(tmp_path, "AllReduce",
                                  AUTODIST_TEST_CRASH_WORKER="1")
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-u", SCRIPT], env=env, timeout=240,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    elapsed = time.monotonic() - t0
    out = proc.stdout.decode()
    assert proc.returncode != 0, f"chief should abort, got rc=0:\n{out[-2000:]}"
    assert "injected crash" in out
    assert "aborting job" in out          # the watcher fired
    assert not os.path.exists(result_file)  # chief never finished training
    assert elapsed < 200, f"abort took {elapsed:.0f}s — watcher too slow"


def test_two_process_serving_token_exact(tmp_path):
    """VERDICT r4 #4 — live multi-process SERVING: the decode engine's
    slot pool sharded across 2 real OS processes (4 slots over the
    4-device data axis, 2 devices per process).  Both processes run the
    host scheduler in SPMD lockstep and must harvest IDENTICAL
    sequences, each token-exact vs the single-device `generate` oracle —
    the process boundary is invisible to serving, matching the
    reference's live-cluster standard
    (`/root/reference/tests/integration/test_dist.py:1-43`)."""
    chief, worker, _ = _run_chief(tmp_path, "AllReduce",
                                  AUTODIST_TEST_SERVING="1")
    assert chief["process_count"] == 2
    cs, ws = chief["serving"], worker["serving"]
    assert cs is not None and ws is not None
    # chief and worker observed the same harvest
    assert cs["tokens"] == ws["tokens"]
    assert len(cs["tokens"]) == 10
    # token-exact vs the per-request oracle, rebuilt locally (same seeds)
    import jax

    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm

    spec = transformer_lm(vocab_size=97, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=64, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(3))
    gen = make_generator(spec)
    for prompt, n, got in zip(cs["prompts"], cs["max_new"], cs["tokens"]):
        want = np.asarray(
            gen(params, np.asarray(prompt, np.int32)[None], n))[0]
        np.testing.assert_array_equal(np.asarray(got), want)
    # prefix cache across the process boundary: shared K/V + sharded
    # slots, still token-exact vs the concat oracle
    assert cs["prefix_tokens"] == ws["prefix_tokens"]
    prefix = np.asarray(cs["prefix"], np.int32)
    for prompt, n, got in zip(cs["prefix_prompts"], cs["prefix_max_new"],
                              cs["prefix_tokens"]):
        full = np.concatenate([prefix, np.asarray(prompt, np.int32)])
        want = np.asarray(gen(params, full[None], n))[0]
        np.testing.assert_array_equal(np.asarray(got),
                                      want[prefix.size:])
    # the sharded pool actually ran concurrently
    assert cs["slot_utilization"] > 0.3
