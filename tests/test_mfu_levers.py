"""MFU levers: per-layer remat and bf16 optimizer state.

Numerics first: remat must be gradient-invisible (bit-identical loss
and gradients — it only changes WHAT is stored between fwd and bwd),
and bf16 moments must track f32-state training closely while actually
storing half the bytes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.ops.opt_state_dtype import cast_opt_state
from autodist_tpu.strategy import AllReduce


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


@pytest.mark.parametrize("remat", ["dots", "full"])
def test_remat_is_gradient_invisible(remat):
    """checkpointing changes memory, not math: loss and grads match the
    un-remat model to float-exactness on identical params."""
    kw = dict(vocab_size=61, num_layers=2, num_heads=2, head_dim=8,
              d_ff=32, max_len=16, seq_len=16, attn_fn=dense_attention)
    base = transformer_lm(**kw)
    ckpt = transformer_lm(**kw, remat=remat)
    params = base.init(jax.random.PRNGKey(0))
    batch = base.sample_batch(4)
    l0, g0 = jax.value_and_grad(base.loss_fn)(params, batch)
    l1, g1 = jax.value_and_grad(ckpt.loss_fn)(params, batch)
    assert float(l0) == float(l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_remat_composes_with_session():
    """A remat model trains through the ordinary AutoDist path."""
    spec = transformer_lm(vocab_size=61, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16, seq_len=16,
                          attn_fn=dense_attention, remat="dots")
    params = spec.init(jax.random.PRNGKey(0))
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()
    batch = sess.place_batch(spec.sample_batch(8))
    losses = [float(sess.run(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]


def test_bf16_opt_state_dtype_and_convergence():
    """cast_opt_state stores adam moments in bf16 (count stays int32)
    and tracks the f32-state trajectory on least squares."""
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    w_true = rng.randn(8, 4).astype(np.float32)
    batch = {"x": x, "y": x @ w_true}
    params = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    def run(opt, steps=80):
        state = opt.init(params)
        p = params
        losses = []
        step = jax.jit(lambda p, s, b: _step(opt, p, s, b))
        for _ in range(steps):
            loss, p, state = step(p, state, batch)
            losses.append(float(loss))
        return losses, state

    def _step(opt, p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return loss, optax.apply_updates(p, u), s

    f32_losses, _ = run(optax.adam(0.05))
    bf16_losses, bf16_state = run(cast_opt_state(optax.adam(0.05)))

    moment_dtypes = {str(leaf.dtype) for leaf in
                     jax.tree_util.tree_leaves(bf16_state)
                     if hasattr(leaf, "dtype") and leaf.ndim > 0
                     and jnp.issubdtype(leaf.dtype, jnp.floating)}
    assert moment_dtypes == {"bfloat16"}, moment_dtypes
    counts = [leaf for leaf in jax.tree_util.tree_leaves(bf16_state)
              if hasattr(leaf, "dtype")
              and jnp.issubdtype(leaf.dtype, jnp.integer)]
    assert counts, "adam count leaf lost"

    # same optimization trajectory to bf16 tolerance; both converge
    np.testing.assert_allclose(bf16_losses[:20], f32_losses[:20], rtol=0.1)
    assert bf16_losses[-1] < bf16_losses[0] * 1e-3


def test_bf16_opt_state_through_session_and_checkpoint(tmp_path):
    """The narrow state composes with capture/session sharding and
    survives a save/restore roundtrip with dtypes intact."""
    from autodist_tpu.checkpoint import Saver

    spec = transformer_lm(vocab_size=61, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params,
                   optimizer=cast_opt_state(optax.adamw(1e-2)),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()
    batch = sess.place_batch(spec.sample_batch(8))
    l0 = float(sess.run(batch)["loss"])
    moment_dtypes = {str(leaf.dtype) for leaf in
                     jax.tree_util.tree_leaves(sess.opt_state)
                     if hasattr(leaf, "dtype") and leaf.ndim > 0
                     and jnp.issubdtype(leaf.dtype, jnp.floating)}
    assert moment_dtypes == {"bfloat16"}, moment_dtypes

    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ck"), step=sess.step_count)
    after_save = [float(sess.run(batch)["loss"]) for _ in range(2)]
    saver.restore(path)
    restored_dtypes = {str(leaf.dtype) for leaf in
                       jax.tree_util.tree_leaves(sess.opt_state)
                       if hasattr(leaf, "dtype") and leaf.ndim > 0
                       and jnp.issubdtype(leaf.dtype, jnp.floating)}
    assert restored_dtypes == {"bfloat16"}, restored_dtypes
    after_restore = [float(sess.run(batch)["loss"]) for _ in range(2)]
    assert after_restore == after_save
    assert after_save[-1] < l0
