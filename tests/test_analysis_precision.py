"""Analyzer precision pass: compressor × dtype support-matrix goldens."""
import jax.numpy as jnp
import pytest

from autodist_tpu.analysis import analyze
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.strategy.base import Strategy

from _analysis_fixtures import AXES8, ar_node, full_cover, make_gi, ps_node

pytestmark = pytest.mark.analysis


@pytest.fixture
def gi():
    return make_gi()


def test_bf16_wire_without_error_feedback_warns(gi):
    s = Strategy(node_config=[
        ar_node(v.name, compressor="HorovodCompressor")
        for v in gi.trainable_var_infos])
    report = analyze(s, gi, mesh=AXES8)
    assert any(d.rule == "precision/bf16-wire-no-error-feedback"
               for d in report.warnings)
    # EF variant is quiet on that rule
    s2 = Strategy(node_config=[
        ar_node(v.name, compressor="HorovodCompressorEF")
        for v in gi.trainable_var_infos])
    report2 = analyze(s2, gi, mesh=AXES8)
    assert not report2.by_rule("precision/bf16-wire-no-error-feedback")


def test_unknown_compressor_is_error(gi):
    s = full_cover(gi, but=["dense/kernel"],
                   extra=[ar_node("dense/kernel", compressor="NoSuch")])
    report = analyze(s, gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == ["precision/unknown-compressor"]


def test_integer_dtype_compression_is_error():
    gi = GraphItem({"codes": jnp.zeros((8, 8), jnp.int32)})
    s = Strategy(node_config=[
        ar_node("codes", compressor="HorovodCompressor")])
    report = analyze(s, gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == [
        "precision/compressor-integer-dtype"]


def test_powersgd_rank_fallback_is_info(gi):
    s = full_cover(gi, but=["dense/bias"],
                   extra=[ar_node("dense/bias",
                                  compressor="PowerSGDCompressor")])
    report = analyze(s, gi, mesh=AXES8)
    assert report.by_rule("precision/powersgd-rank-fallback")
    assert not report.has_errors()


def test_bf16_storage_wire_noop_is_info():
    gi = GraphItem({"w": jnp.zeros((8, 8), jnp.bfloat16)})
    s = Strategy(node_config=[
        ar_node("w", compressor="HorovodCompressor")])
    report = analyze(s, gi, mesh=AXES8)
    assert report.by_rule("precision/compressor-wire-noop")


def test_sparse_compressed_warns(gi):
    s = full_cover(gi, but=["emb/table"],
                   extra=[ar_node("emb/table",
                                  compressor="HorovodCompressorEF")])
    report = analyze(s, gi, mesh=AXES8)
    assert any(d.rule == "precision/sparse-compressed"
               for d in report.warnings)


def test_compressed_partition_drop_matches_runtime():
    """The lint's fallback verdict is the runtime's own
    partition_drop_reason — a PS-partitioned var on a pure-DP mesh
    (sharded over the reduction axis) with any compressor in the
    program flags the drop."""
    gi = GraphItem({"big": jnp.zeros((64, 8)), "small": jnp.zeros((8,))})
    s = Strategy(node_config=[
        ps_node("big", partitioner="64,1"),
        ar_node("small", compressor="HorovodCompressorEF")])
    report = analyze(s, gi, mesh=AXES8)
    assert any(d.rule == "precision/compressor-partition-dropped"
               and d.var_name == "big" for d in report.warnings)


def test_uncompressed_program_skips_partition_drop_lint():
    """No compressor and no fused groups ⇒ GSPMD path ⇒ no drop lint."""
    gi = GraphItem({"big": jnp.zeros((64, 8)), "small": jnp.zeros((8,))})
    s = Strategy(node_config=[
        ps_node("big", partitioner="64,1"), ar_node("small")])
    report = analyze(s, gi, mesh=AXES8)
    assert not report.by_rule("precision/compressor-partition-dropped")
