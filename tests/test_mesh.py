"""Mesh construction tests."""
import jax
import pytest

from autodist_tpu import mesh as mesh_lib
from autodist_tpu.resource_spec import ResourceSpec


def test_default_data_mesh():
    m = mesh_lib.build_mesh()
    assert m.axis_names == ("data",)
    assert m.shape["data"] == 8


def test_axes_canonical_order():
    m = mesh_lib.build_mesh({"model": 2, "data": 2, "seq": 2})
    # canonical order: data before seq before model
    assert m.axis_names == ("data", "seq", "model")
    assert dict(m.shape) == {"data": 2, "seq": 2, "model": 2}


def test_remainder_absorbed_into_data():
    m = mesh_lib.build_mesh({"model": 2})
    assert dict(m.shape) == {"data": 4, "model": 2}


def test_mesh_hint_from_resource_spec():
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "mesh": {"data": 4, "model": 2},
    })
    m = mesh_lib.build_mesh(resource_spec=spec)
    assert dict(m.shape) == {"data": 4, "model": 2}


def test_bad_axes():
    with pytest.raises(ValueError):
        mesh_lib.build_mesh({"data": 3})  # 3 does not divide 8


def test_mesh_coords():
    m = mesh_lib.build_mesh({"data": 4, "model": 2})
    dev = m.devices[2][1]
    assert mesh_lib.mesh_coords_of(m, dev) == {"data": 2, "model": 1}


def test_single_device_mesh():
    m = mesh_lib.build_mesh(devices=jax.devices()[:1])
    assert m.shape["data"] == 1


def test_size_one_axes_preserved():
    m = mesh_lib.build_mesh({"data": 8, "model": 1})
    assert m.axis_names == ("data", "model")
    assert dict(m.shape) == {"data": 8, "model": 1}


def test_device_spec_sortable():
    from autodist_tpu.resource_spec import DeviceSpec, DeviceType
    devs = [DeviceSpec("b", DeviceType.TPU, 0), DeviceSpec("a", DeviceType.CPU, 1),
            DeviceSpec("a", DeviceType.TPU, 0)]
    assert sorted(devs)[0].host_address == "a"


class TestHybridMesh:
    """build_hybrid_mesh: DCN-outer/ICI-inner construction on a 2-slice
    virtual mesh, and PS destination-coord placement across slices
    (reference inter-node/intra-node split,
    ps_synchronizer.py:248-329)."""

    def test_two_slice_construction(self):
        import jax

        mesh = mesh_lib.build_hybrid_mesh({"model": 4}, {"data": 2})
        assert dict(mesh.shape) == {"data": 2, "model": 4}
        devs = jax.devices()
        # DCN-outer: slice 0 = first 4 devices = data row 0.
        assert list(mesh.devices[0]) == devs[:4]
        assert list(mesh.devices[1]) == devs[4:]

    def test_shared_axis_dcn_times_ici(self):
        mesh = mesh_lib.build_hybrid_mesh({"data": 2, "model": 2}, {"data": 2})
        # data axis = 2 (DCN) x 2 (ICI) = 4, model = 2.
        assert dict(mesh.shape) == {"data": 4, "model": 2}
        import jax

        devs = jax.devices()
        # Within a data row, devices come from one slice's ICI group first:
        # data index (dcn, ici)-major → rows 0,1 from slice 0.
        slice_of = {d: i // 4 for i, d in enumerate(devs)}
        for row in range(4):
            row_slices = {slice_of[d] for d in mesh.devices[row]}
            assert row_slices == {row // 2}  # DCN-outer ordering

    def test_wrong_device_count_raises(self):
        with pytest.raises(ValueError, match="needs"):
            mesh_lib.build_hybrid_mesh({"model": 4}, {"data": 4})

    def test_single_slice_tpu_fleet_fails_loudly(self):
        """Real TPU devices all reporting slice_index=0 with a declared
        multi-slice topology must raise, not silently emulate a DCN
        split that would actually ride one slice's ICI."""

        class FakeTpu:
            platform = "tpu"
            slice_index = 0

        devs = [FakeTpu() for _ in range(8)]
        with pytest.raises(ValueError, match="single-slice"):
            mesh_lib.build_hybrid_mesh({"model": 4}, {"data": 2},
                                       devices=devs)

    def test_destination_coords_map_to_slices(self):
        """PS reduction destinations resolve to the owning slice's data
        coordinate on a hybrid mesh."""
        import jax.numpy as jnp

        from autodist_tpu.graph_item import GraphItem
        from autodist_tpu.strategy import PS, PSLoadBalancing
        from autodist_tpu.strategy.compiler import StrategyCompiler

        spec = ResourceSpec(resource_info={"nodes": [
            {"address": "host-a", "chips": 4, "chief": True},
            {"address": "host-b", "chips": 4}]})
        mesh = mesh_lib.build_hybrid_mesh({"model": 4}, {"data": 2})
        gi = GraphItem({"w": jnp.zeros((8, 4)), "b": jnp.zeros((8,))})
        cs = StrategyCompiler(mesh, resource_spec=spec).compile(
            PS().build(gi, spec), gi)
        # PS builder targets the first CPU (host-a) → slice/data coord 0.
        assert cs.plan_for("w").destination_coords == {"data": 0}

        cs2 = StrategyCompiler(mesh, resource_spec=spec).compile(
            PSLoadBalancing().build(gi, spec), gi)
        coords = {p.destination_coords["data"]
                  for p in cs2.var_plans.values()}
        assert coords == {0, 1}  # balanced across the two slices

    def test_training_runs_on_hybrid_mesh(self, monkeypatch):
        import jax.numpy as jnp
        import numpy as np
        import optax

        from autodist_tpu.autodist import (
            AutoDist, _reset_default_autodist_for_testing)
        from autodist_tpu.strategy import PSLoadBalancing

        # 2-node spec in one test process: log the worker fan-out instead
        # of SSHing to the fictional second host.
        monkeypatch.setenv("AUTODIST_DEBUG_REMOTE", "True")
        _reset_default_autodist_for_testing()
        spec = ResourceSpec(resource_info={"nodes": [
            {"address": "host-a", "chips": 4, "chief": True},
            {"address": "host-b", "chips": 4}]})
        mesh = mesh_lib.build_hybrid_mesh({"data": 2, "model": 2}, {"data": 2})

        def loss(p, b):
            return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

        rng = np.random.RandomState(0)
        batch = {"x": rng.randn(16, 8).astype(np.float32),
                 "y": rng.randn(16, 2).astype(np.float32)}
        ad = AutoDist(resource_spec=spec, strategy_builder=PSLoadBalancing())
        with ad.scope():
            ad.capture(params={"w": jnp.zeros((8, 2))},
                       optimizer=optax.sgd(0.1), loss_fn=loss)
        sess = ad.create_distributed_session(mesh=mesh)
        losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
        assert losses[2] < losses[0]
