"""Mesh construction tests."""
import jax
import pytest

from autodist_tpu import mesh as mesh_lib
from autodist_tpu.resource_spec import ResourceSpec


def test_default_data_mesh():
    m = mesh_lib.build_mesh()
    assert m.axis_names == ("data",)
    assert m.shape["data"] == 8


def test_axes_canonical_order():
    m = mesh_lib.build_mesh({"model": 2, "data": 2, "seq": 2})
    # canonical order: data before seq before model
    assert m.axis_names == ("data", "seq", "model")
    assert dict(m.shape) == {"data": 2, "seq": 2, "model": 2}


def test_remainder_absorbed_into_data():
    m = mesh_lib.build_mesh({"model": 2})
    assert dict(m.shape) == {"data": 4, "model": 2}


def test_mesh_hint_from_resource_spec():
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "mesh": {"data": 4, "model": 2},
    })
    m = mesh_lib.build_mesh(resource_spec=spec)
    assert dict(m.shape) == {"data": 4, "model": 2}


def test_bad_axes():
    with pytest.raises(ValueError):
        mesh_lib.build_mesh({"data": 3})  # 3 does not divide 8


def test_mesh_coords():
    m = mesh_lib.build_mesh({"data": 4, "model": 2})
    dev = m.devices[2][1]
    assert mesh_lib.mesh_coords_of(m, dev) == {"data": 2, "model": 1}


def test_single_device_mesh():
    m = mesh_lib.build_mesh(devices=jax.devices()[:1])
    assert m.shape["data"] == 1


def test_size_one_axes_preserved():
    m = mesh_lib.build_mesh({"data": 8, "model": 1})
    assert m.axis_names == ("data", "model")
    assert dict(m.shape) == {"data": 8, "model": 1}


def test_device_spec_sortable():
    from autodist_tpu.resource_spec import DeviceSpec, DeviceType
    devs = [DeviceSpec("b", DeviceType.TPU, 0), DeviceSpec("a", DeviceType.CPU, 1),
            DeviceSpec("a", DeviceType.TPU, 0)]
    assert sorted(devs)[0].host_address == "a"
