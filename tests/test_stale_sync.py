"""SSP bounded-staleness + proxy-variable tests.

Parity target: reference integration case c9 (a slow worker; asserts the
fast worker runs ahead by at most ``staleness`` steps, ``tests/integration/
cases/c9.py``).  Under the delayed-gradient translation the equivalent
closed-form observable is: the update applied at step t is the gradient
computed at step t - s — asserted here exactly against a hand-rolled
simulation.
"""
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import PS, PSLoadBalancing


@pytest.fixture(autouse=True)
def _reset(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def make_problem():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.randn(64, 1)).astype(np.float32)
    params = {"w": np.zeros((4, 1), np.float32)}

    def loss_fn(p, batch):
        bx, by = batch
        return ((bx @ p["w"] - by) ** 2).mean()

    return params, loss_fn, (x, y)


def batches(n, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.randn(16, 4).astype(np.float32)
        y = rng.randn(16, 1).astype(np.float32)
        out.append((x, y))
    return out


def run_distributed(staleness, steps, proxy=False, lr=0.1):
    params, loss_fn, _ = make_problem()
    ad = AutoDist(strategy_builder=PS(staleness=staleness,
                                      local_proxy_variable=proxy))
    ad.capture(params, optimizer=optax.sgd(lr), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    data = batches(steps)
    losses = [float(s.run(b)["loss"]) for b in data]
    return np.asarray(s.params["w"]), losses


def simulate_delayed(staleness, steps, lr=0.1, refresh=1):
    """Hand-rolled delayed-gradient SGD: grad from step t applies at t+s;
    grads computed against a mirror refreshed every `refresh` steps."""
    import jax

    params, loss_fn, _ = make_problem()
    w = np.array(params["w"])
    cache = w.copy()
    queue = [np.zeros_like(w) for _ in range(staleness)]
    data = batches(steps)
    gradf = jax.grad(lambda p, b: loss_fn(p, b))
    for t, b in enumerate(data):
        read = cache if refresh > 1 else w
        g = np.asarray(gradf({"w": read}, b)["w"])
        if staleness:
            queue.append(g)
            g = queue.pop(0)
        w = w - lr * g
        if refresh > 1 and (t + 1) % refresh == 0:
            cache = w.copy()
    return w


def test_staleness_zero_matches_sync():
    w_ssp, _ = run_distributed(staleness=0, steps=6)
    w_ref = simulate_delayed(staleness=0, steps=6)
    np.testing.assert_allclose(w_ssp, w_ref, rtol=1e-5, atol=1e-6)


def test_warmup_applies_nothing():
    # For the first s steps the queue pops zeros: params must not move.
    w, _ = run_distributed(staleness=3, steps=3)
    np.testing.assert_array_equal(w, np.zeros((4, 1), np.float32))


def test_delayed_gradient_matches_simulation():
    for s in (1, 2, 4):
        w_ssp, _ = run_distributed(staleness=s, steps=10)
        w_ref = simulate_delayed(staleness=s, steps=10)
        np.testing.assert_allclose(w_ssp, w_ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"staleness={s}")


def test_staleness_still_converges():
    params, loss_fn, (x, y) = make_problem()
    ad = AutoDist(strategy_builder=PSLoadBalancing(staleness=2))
    ad.capture(params, optimizer=optax.sgd(0.05), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    losses = [float(s.run((x, y))["loss"]) for _ in range(60)]
    assert losses[-1] < 0.5 * losses[0]


def test_proxy_refresh_matches_simulation(monkeypatch):
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "2")
    w_proxy, _ = run_distributed(staleness=0, steps=8, proxy=True)
    w_ref = simulate_delayed(staleness=0, steps=8, refresh=2)
    np.testing.assert_allclose(w_proxy, w_ref, rtol=1e-5, atol=1e-6)


def test_proxy_default_refresh_is_exact(monkeypatch):
    # refresh=1 (reference ProxyVariable semantics): mirror is always fresh,
    # results identical to no proxy.
    w_proxy, _ = run_distributed(staleness=0, steps=6, proxy=True)
    w_ref = simulate_delayed(staleness=0, steps=6)
    np.testing.assert_allclose(w_proxy, w_ref, rtol=1e-5, atol=1e-6)


def test_stale_and_proxy_compose(monkeypatch):
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "2")
    w_both, _ = run_distributed(staleness=2, steps=10, proxy=True)
    params, loss_fn, _ = make_problem()

    import jax

    w = np.array(params["w"])
    cache = w.copy()
    queue = [np.zeros_like(w) for _ in range(2)]
    gradf = jax.grad(lambda p, b: loss_fn(p, b))
    for t, b in enumerate(batches(10)):
        g = np.asarray(gradf({"w": cache}, b)["w"])
        queue.append(g)
        g = queue.pop(0)
        w = w - 0.1 * g
        if (t + 1) % 2 == 0:
            cache = w.copy()
    np.testing.assert_allclose(w_both, w, rtol=1e-5, atol=1e-6)


def test_set_params_reseeds_proxy_cache(monkeypatch):
    """Restoring params must refresh proxy mirrors: the first post-restore
    gradient is computed against the restored values, not capture-time ones."""
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "4")
    import jax

    params, loss_fn, _ = make_problem()
    ad = AutoDist(strategy_builder=PS(local_proxy_variable=True))
    ad.capture(params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    restored = {"w": np.full((4, 1), 2.0, np.float32)}
    s.set_params(restored)
    b = batches(1)[0]
    s.run(b)
    # One plain SGD step from the restored weights (mirror == restored value).
    g = np.asarray(jax.grad(loss_fn)({"w": restored["w"]}, b)["w"])
    np.testing.assert_allclose(np.asarray(s.params["w"]),
                               restored["w"] - 0.1 * g,
                               rtol=1e-5, atol=1e-6)


def test_ssp_c9_convergence_equivalence():
    """The reference's c9 case, trained to convergence under both SSP
    mechanisms (VERDICT r2 #7 — close the semantics argument).

    c9's problem: scalar linear regression y = 3x + 2 + noise from
    W=5, b=0 with SGD(0.01) (/root/reference/tests/integration/cases/
    c9.py behavior).  c9 verifies the RUN-AHEAD observable by wall-clock
    timing (a fast worker proceeds at most `staleness` steps past a slow
    one); here the equivalent is simulated exactly — a two-worker PS
    where the slow worker's gradients are computed from an s-step-old
    parameter read (gradient age <= s, the same bound run-ahead
    enforces) — and compared against this framework's delayed-gradient
    translation (gradient age == s after warmup, test above).  Both must
    converge to the same fixed point as synchronous SGD: staleness
    perturbs the trajectory, not the optimum.
    """
    import jax

    rng = np.random.RandomState(0)
    inputs = rng.randn(1000).astype(np.float32)
    outputs = (inputs * 3.0 + 2.0
               + rng.randn(1000).astype(np.float32))
    batch = (inputs, outputs)
    lr, s_stale, steps = 0.01, 2, 120

    def loss_fn(p, b):
        x, y = b
        return ((p["W"] * x + p["b"] - y) ** 2).mean()

    init = {"W": np.float32(5.0), "b": np.float32(0.0)}
    gradf = jax.grad(loss_fn)

    def to_vec(p):
        return np.array([float(p["W"]), float(p["b"])])

    # (a) this framework: delayed-gradient SSP through the session path.
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=PS(staleness=s_stale))
    ad.capture(dict(init), optimizer=optax.sgd(lr), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(steps):
        sess.run(batch)
    ours = to_vec(sess.params)

    # (b) the reference mechanism, simulated: two workers on one PS; the
    # fast worker applies fresh gradients, the slow worker's arrive
    # computed from the params as they were s steps ago (run-ahead gap
    # bounded by s — c9's timing observable, in closed form).
    p = dict(init)
    history = [dict(p)]
    ages = []
    for t in range(steps // 2):   # two gradient applications per tick
        g_fast = gradf(p, batch)
        ages.append(0)
        p = {k: p[k] - lr * np.float32(g_fast[k]) for k in p}
        history.append(dict(p))
        stale_read = history[max(0, len(history) - 1 - s_stale)]
        ages.append(min(t + 1, s_stale))
        g_slow = gradf(stale_read, batch)
        p = {k: p[k] - lr * np.float32(g_slow[k]) for k in p}
        history.append(dict(p))
    run_ahead = to_vec(p)
    assert max(ages) == s_stale     # the c9 bound, exactly

    # (c) synchronous SGD oracle (the common fixed point).
    p = dict(init)
    for _ in range(steps):
        g = gradf(p, batch)
        p = {k: p[k] - lr * np.float32(g[k]) for k in p}
    sync = to_vec(p)

    # All three converge to (3, 2) within the noise floor, and the two
    # SSP mechanisms land within a staleness-sized neighborhood of the
    # synchronous optimum - convergence equivalence.
    for vec, label in ((ours, "delayed-gradient"),
                       (run_ahead, "run-ahead"), (sync, "sync")):
        np.testing.assert_allclose(vec, [3.0, 2.0], atol=0.25,
                                   err_msg=label)
    assert np.linalg.norm(ours - sync) < 0.05, (ours, sync)
    assert np.linalg.norm(run_ahead - sync) < 0.05, (run_ahead, sync)
