"""SSP bounded-staleness + proxy-variable tests.

Parity target: reference integration case c9 (a slow worker; asserts the
fast worker runs ahead by at most ``staleness`` steps, ``tests/integration/
cases/c9.py``).  Under the delayed-gradient translation the equivalent
closed-form observable is: the update applied at step t is the gradient
computed at step t - s — asserted here exactly against a hand-rolled
simulation.
"""
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import PS, PSLoadBalancing


@pytest.fixture(autouse=True)
def _reset(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def make_problem():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.randn(64, 1)).astype(np.float32)
    params = {"w": np.zeros((4, 1), np.float32)}

    def loss_fn(p, batch):
        bx, by = batch
        return ((bx @ p["w"] - by) ** 2).mean()

    return params, loss_fn, (x, y)


def batches(n, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.randn(16, 4).astype(np.float32)
        y = rng.randn(16, 1).astype(np.float32)
        out.append((x, y))
    return out


def run_distributed(staleness, steps, proxy=False, lr=0.1):
    params, loss_fn, _ = make_problem()
    ad = AutoDist(strategy_builder=PS(staleness=staleness,
                                      local_proxy_variable=proxy))
    ad.capture(params, optimizer=optax.sgd(lr), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    data = batches(steps)
    losses = [float(s.run(b)["loss"]) for b in data]
    return np.asarray(s.params["w"]), losses


def simulate_delayed(staleness, steps, lr=0.1, refresh=1):
    """Hand-rolled delayed-gradient SGD: grad from step t applies at t+s;
    grads computed against a mirror refreshed every `refresh` steps."""
    import jax

    params, loss_fn, _ = make_problem()
    w = np.array(params["w"])
    cache = w.copy()
    queue = [np.zeros_like(w) for _ in range(staleness)]
    data = batches(steps)
    gradf = jax.grad(lambda p, b: loss_fn(p, b))
    for t, b in enumerate(data):
        read = cache if refresh > 1 else w
        g = np.asarray(gradf({"w": read}, b)["w"])
        if staleness:
            queue.append(g)
            g = queue.pop(0)
        w = w - lr * g
        if refresh > 1 and (t + 1) % refresh == 0:
            cache = w.copy()
    return w


def test_staleness_zero_matches_sync():
    w_ssp, _ = run_distributed(staleness=0, steps=6)
    w_ref = simulate_delayed(staleness=0, steps=6)
    np.testing.assert_allclose(w_ssp, w_ref, rtol=1e-5, atol=1e-6)


def test_warmup_applies_nothing():
    # For the first s steps the queue pops zeros: params must not move.
    w, _ = run_distributed(staleness=3, steps=3)
    np.testing.assert_array_equal(w, np.zeros((4, 1), np.float32))


def test_delayed_gradient_matches_simulation():
    for s in (1, 2, 4):
        w_ssp, _ = run_distributed(staleness=s, steps=10)
        w_ref = simulate_delayed(staleness=s, steps=10)
        np.testing.assert_allclose(w_ssp, w_ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"staleness={s}")


def test_staleness_still_converges():
    params, loss_fn, (x, y) = make_problem()
    ad = AutoDist(strategy_builder=PSLoadBalancing(staleness=2))
    ad.capture(params, optimizer=optax.sgd(0.05), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    losses = [float(s.run((x, y))["loss"]) for _ in range(60)]
    assert losses[-1] < 0.5 * losses[0]


def test_proxy_refresh_matches_simulation(monkeypatch):
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "2")
    w_proxy, _ = run_distributed(staleness=0, steps=8, proxy=True)
    w_ref = simulate_delayed(staleness=0, steps=8, refresh=2)
    np.testing.assert_allclose(w_proxy, w_ref, rtol=1e-5, atol=1e-6)


def test_proxy_default_refresh_is_exact(monkeypatch):
    # refresh=1 (reference ProxyVariable semantics): mirror is always fresh,
    # results identical to no proxy.
    w_proxy, _ = run_distributed(staleness=0, steps=6, proxy=True)
    w_ref = simulate_delayed(staleness=0, steps=6)
    np.testing.assert_allclose(w_proxy, w_ref, rtol=1e-5, atol=1e-6)


def test_stale_and_proxy_compose(monkeypatch):
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "2")
    w_both, _ = run_distributed(staleness=2, steps=10, proxy=True)
    params, loss_fn, _ = make_problem()

    import jax

    w = np.array(params["w"])
    cache = w.copy()
    queue = [np.zeros_like(w) for _ in range(2)]
    gradf = jax.grad(lambda p, b: loss_fn(p, b))
    for t, b in enumerate(batches(10)):
        g = np.asarray(gradf({"w": cache}, b)["w"])
        queue.append(g)
        g = queue.pop(0)
        w = w - 0.1 * g
        if (t + 1) % 2 == 0:
            cache = w.copy()
    np.testing.assert_allclose(w_both, w, rtol=1e-5, atol=1e-6)


def test_set_params_reseeds_proxy_cache(monkeypatch):
    """Restoring params must refresh proxy mirrors: the first post-restore
    gradient is computed against the restored values, not capture-time ones."""
    monkeypatch.setenv("AUTODIST_PROXY_REFRESH", "4")
    import jax

    params, loss_fn, _ = make_problem()
    ad = AutoDist(strategy_builder=PS(local_proxy_variable=True))
    ad.capture(params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    s = ad.create_distributed_session()
    restored = {"w": np.full((4, 1), 2.0, np.float32)}
    s.set_params(restored)
    b = batches(1)[0]
    s.run(b)
    # One plain SGD step from the restored weights (mirror == restored value).
    g = np.asarray(jax.grad(loss_fn)({"w": restored["w"]}, b)["w"])
    np.testing.assert_allclose(np.asarray(s.params["w"]),
                               restored["w"] - 0.1 * g,
                               rtol=1e-5, atol=1e-6)
