"""KV-cache autoregressive decode vs the training forward (oracle)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=97, num_layers=3, num_heads=2,
                          head_dim=8, d_ff=32, max_len=24, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


def test_stepwise_logits_match_full_forward(lm):
    """Teacher-forced decode logits at every position equal the training
    forward's logits — the KV-cache math IS the model."""
    spec, params = lm
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 97, (2, 10)).astype(np.int32)
    gen = make_generator(spec)
    # max_new_tokens=0 edge: pure prefill scoring.
    tokens, step_logits = gen.with_logits(params, prompt, 0)
    np.testing.assert_array_equal(np.asarray(tokens), prompt)
    full = spec.apply_fn(params, prompt)          # [B, P, V]
    # step_logits[t] are position t's next-token logits = full[:, t].
    np.testing.assert_allclose(
        np.asarray(step_logits).transpose(1, 0, 2), full[:, :-1],
        rtol=2e-4, atol=2e-5)


def test_greedy_matches_naive_regrow(lm):
    """Greedy decode with the cache equals the O(T^2) naive loop that
    re-runs the full forward on the growing sequence."""
    spec, params = lm
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 97, (2, 5)).astype(np.int32)
    new = 6
    gen = make_generator(spec)
    out = np.asarray(gen(params, prompt, new))

    seq = prompt
    for _ in range(new):
        logits = spec.apply_fn(params, seq)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1),
                         np.int32)[:, None]
        seq = np.concatenate([seq, nxt], axis=1)
    np.testing.assert_array_equal(out, seq)


def test_temperature_sampling_reproducible_and_valid(lm):
    spec, params = lm
    prompt = np.zeros((3, 2), np.int32)
    gen = make_generator(spec)
    rng = jax.random.PRNGKey(7)
    a = np.asarray(gen(params, prompt, 8, rng=rng, temperature=1.0))
    b = np.asarray(gen(params, prompt, 8, rng=rng, temperature=1.0))
    np.testing.assert_array_equal(a, b)          # same key, same tokens
    assert a.shape == (3, 10)
    assert (a >= 0).all() and (a < 97).all()
    with pytest.raises(ValueError, match="rng"):
        gen(params, prompt, 4, temperature=0.5)


def test_length_validation(lm):
    spec, params = lm
    gen = make_generator(spec)
    with pytest.raises(ValueError, match="max_len"):
        gen(params, np.zeros((1, 20), np.int32), 10)  # 30 > max_len 24


def test_non_lm_spec_rejected():
    from autodist_tpu.models.ncf import ncf
    with pytest.raises(ValueError, match="transformer_lm-family"):
        make_generator(ncf(num_users=10, num_items=10))


def test_with_logits_validates_rng(lm):
    spec, params = lm
    gen = make_generator(spec)
    with pytest.raises(ValueError, match="rng"):
        gen.with_logits(params, np.zeros((1, 2), np.int32), 4,
                        temperature=0.7)


def test_sampling_knob_ranges_validated(lm):
    """top_k > vocab fails loudly at the API (not deep inside lax.top_k),
    and num_beams > vocab would leak the -1e30 duplicate-suppressed
    starter beams through the first top-k."""
    spec, params = lm
    gen = make_generator(spec)
    prompt = np.zeros((1, 2), np.int32)
    rng = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="top_k"):
        gen(params, prompt, 4, rng=rng, temperature=0.7, top_k=98)
    with pytest.raises(ValueError, match="top_k"):
        gen(params, prompt, 4, rng=rng, temperature=0.7, top_k=-1)
    with pytest.raises(ValueError, match="num_beams"):
        gen.beam_search(params, prompt, 4, num_beams=98)
    # top_p is a probability mass: out-of-range values previously made
    # the nucleus filter a silent no-op instead of erroring
    with pytest.raises(ValueError, match="top_p"):
        gen(params, prompt, 4, rng=rng, temperature=0.7, top_p=-0.9)
    with pytest.raises(ValueError, match="top_p"):
        gen(params, prompt, 4, rng=rng, temperature=0.7, top_p=9.0)
    # the boundary values are legal — num_beams == vocab is exactly where
    # a wrong guard would let a -1e30 starter beam survive the first
    # top-k, so assert the winning logprob is finite and sane
    gen(params, prompt, 1, rng=rng, temperature=0.7, top_k=97)
    _, lp = gen.beam_search(params, prompt, 1, num_beams=97)
    assert np.isfinite(float(lp[0])) and float(lp[0]) > -1e6


def test_generate_from_session_sharded_params(lm):
    """Decode runs straight off a session's mesh-sharded parameters
    (vocab-sharded embed under Parallax on a model-axis mesh) and
    produces the same tokens as host-layout params — serving composes
    with the training shardings."""
    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.strategy import Parallax

    spec, params = lm
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=Parallax(),
                  mesh_axes={"model": 2, "data": 4})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.01),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    gen = make_generator(spec)
    prompt = np.random.RandomState(3).randint(0, 97, (2, 4)).astype(np.int32)
    ref = np.asarray(gen(params, prompt, 5))
    out = np.asarray(gen(sess.sharded_params, prompt, 5))
    np.testing.assert_array_equal(out, ref)


def test_beam_search_width_one_equals_greedy(lm):
    """Beam=1 equals greedy decode exactly.  (No width-monotonicity
    assertion: beam search prunes prefixes, so a wider beam is NOT
    guaranteed to end with a higher-scoring sequence than greedy — the
    true invariant is the score's correctness, pinned below.)"""
    spec, params = lm
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 97, (3, 4)).astype(np.int32)
    new = 6
    gen = make_generator(spec)
    greedy = np.asarray(gen(params, prompt, new))
    b1_tokens, b1_lp = gen.beam_search(params, prompt, new, num_beams=1)
    np.testing.assert_array_equal(np.asarray(b1_tokens), greedy)
    b4_tokens, b4_lp = gen.beam_search(params, prompt, new, num_beams=4)
    assert np.asarray(b4_lp).shape == (3,)
    assert np.asarray(b4_tokens).shape == (3, 10)
    with pytest.raises(ValueError, match="num_beams"):
        gen.beam_search(params, prompt, new, num_beams=0)


def test_beam_search_logprob_is_true_sequence_score(lm):
    """The returned beam score equals the sum of per-position
    log-probabilities of the returned sequence under the full forward."""
    spec, params = lm
    prompt = np.array([[11, 23]], np.int32)
    new = 5
    gen = make_generator(spec)
    tokens, lp = gen.beam_search(params, prompt, new, num_beams=3)
    tokens = np.asarray(tokens)
    logits = np.asarray(spec.apply_fn(params, tokens))
    logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    # positions P-1 .. P+new-2 predict the generated tokens
    p = prompt.shape[1]
    total = 0.0
    for i in range(new):
        total += float(logp[0, p - 1 + i, tokens[0, p + i]])
    np.testing.assert_allclose(float(lp[0]), total, rtol=1e-4, atol=1e-4)


def test_top_k_and_top_p_sampling(lm):
    """top_k=1 at any temperature is greedy (the filter keeps only the
    argmax); top_p near 0 likewise; both validate their preconditions."""
    spec, params = lm
    rng_np = np.random.RandomState(9)
    prompt = rng_np.randint(0, 97, (2, 4)).astype(np.int32)
    new = 6
    gen = make_generator(spec)
    greedy = np.asarray(gen(params, prompt, new))
    key = jax.random.PRNGKey(3)
    k1 = np.asarray(gen(params, prompt, new, rng=key, temperature=1.0,
                        top_k=1))
    np.testing.assert_array_equal(k1, greedy)
    p_tiny = np.asarray(gen(params, prompt, new, rng=key, temperature=1.0,
                            top_p=1e-9))
    np.testing.assert_array_equal(p_tiny, greedy)
    # a real nucleus still produces valid tokens and differs run-to-run
    # with different keys (sanity, not a distribution test)
    a = np.asarray(gen(params, prompt, new, rng=jax.random.PRNGKey(1),
                       temperature=1.0, top_p=0.9))
    assert (a >= 0).all() and (a < 97).all()
    with pytest.raises(ValueError, match="temperature"):
        gen(params, prompt, new, top_k=5)


def test_generate_from_exported_weights(lm, tmp_path):
    """The serving story end-to-end: weights exported to disk
    (checkpoint interchange layout), restored without a session, and
    decoded — token-identical to the live params."""
    from autodist_tpu.checkpoint.saver import Saver, save_params

    spec, params = lm
    path = save_params(str(tmp_path / "weights"), params)
    restored = Saver.restore_params(path)
    gen = make_generator(spec)
    prompt = np.random.RandomState(11).randint(0, 97, (2, 4)).astype(
        np.int32)
    np.testing.assert_array_equal(
        np.asarray(gen(restored, prompt, 5)),
        np.asarray(gen(params, prompt, 5)))


def test_score_matches_loss_fn(lm):
    """gen.score's mean NLL over the batch equals the training loss_fn
    (both are mean next-token cross entropy), and its perplexity is
    exp(per-token NLL)."""
    spec, params = lm
    rng = np.random.RandomState(13)
    tokens = rng.randint(0, 97, (4, 12)).astype(np.int32)
    gen = make_generator(spec)
    ll, ppl = gen.score(params, tokens)
    assert np.asarray(ll).shape == (4,) and np.asarray(ppl).shape == (4,)
    t = tokens.shape[1] - 1
    mean_nll = float(-np.asarray(ll).mean() / t)
    train_loss = float(spec.loss_fn(params, {"tokens": tokens}))
    np.testing.assert_allclose(mean_nll, train_loss, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(ppl),
                               np.exp(-np.asarray(ll) / t), rtol=1e-6)


def test_score_rejects_single_token(lm):
    spec, params = lm
    gen = make_generator(spec)
    with pytest.raises(ValueError, match="length >= 2"):
        gen.score(params, np.zeros((2, 1), np.int32))


def test_eos_stop_token(lm):
    """A row that generates eos_id keeps emitting it (static-shape
    masking); tokens before the stop match the unstopped run; eos in the
    PROMPT is data, not a stop; eos_id=None is unchanged behavior."""
    spec, params = lm
    gen = make_generator(spec)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 97, (3, 6)).astype(np.int32)
    free = np.asarray(gen(params, prompt, 8))          # no stopping
    # Pick the token row 0 greedily emits at its SECOND generated slot as
    # eos: the stopped run must match up to and including that slot, then
    # pad with it.
    eos = int(free[0, 7])
    stopped = np.asarray(gen(params, prompt, 8, eos_id=eos))
    np.testing.assert_array_equal(stopped[0, :8], free[0, :8])
    assert (stopped[0, 8:] == eos).all(), (eos, stopped[0])
    # Rows that never emit eos are untouched.
    for b in range(1, 3):
        if eos not in free[b, 6:]:
            np.testing.assert_array_equal(stopped[b], free[b])
    # eos inside the prompt does not stop generation.
    p2 = prompt.copy()
    p2[:, 2] = eos
    out2 = np.asarray(gen(params, p2, 4, eos_id=eos))
    assert (out2[:, :6] == p2).all()
    free2 = np.asarray(gen(params, p2, 4))
    # first generated slot identical (prompt eos ignored)
    np.testing.assert_array_equal(out2[:, 6], free2[:, 6])
    # eos_id=None identical to omitting it.
    np.testing.assert_array_equal(np.asarray(gen(params, prompt, 4)),
                                  np.asarray(gen(params, prompt, 4,
                                                 eos_id=None)))
    with pytest.raises(ValueError, match="eos_id"):
        gen(params, prompt, 4, eos_id=97)
