"""AllReduce group/chunking: fused collectives.

Parity target: the reference merges ``chunk_size`` consecutive variables
into one collective via scoped-allocator groups
(``autodist/strategy/all_reduce_strategy.py:21-90``).  Here
``AllReduce(fused_groups=True)`` routes through the explicit shard_map path
and concatenates each group's gradients into ONE ``pmean`` — verified by
counting all-reduce ops in the compiled HLO."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


N_LAYERS = 6


def _params():
    return {f"layer_{i}": {"w": jnp.full((4, 4), 0.01 * (i + 1)),
                           "b": jnp.zeros(4)}
            for i in range(N_LAYERS)}


def _loss(params, batch):
    h = batch["x"]
    for i in range(N_LAYERS):
        h = jnp.tanh(h @ params[f"layer_{i}"]["w"] + params[f"layer_{i}"]["b"])
    return jnp.mean((h - batch["y"]) ** 2)


def _batch():
    rng = np.random.RandomState(3)
    return {"x": rng.randn(16, 4).astype(np.float32),
            "y": rng.randn(16, 4).astype(np.float32)}


def _session(chunk_size, fused):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(chunk_size=chunk_size,
                                             fused_groups=fused))
    with ad.scope():
        ad.capture(params=_params(), optimizer=optax.sgd(0.1), loss_fn=_loss)
    return ad.create_distributed_session()


def _count_all_reduces(sess):
    """Collective ops in the traced (StableHLO) program: what OUR sync path
    emits, before XLA's own combiner runs (the CPU backend merges
    everything at the optimized level, masking the difference)."""
    batch = sess.place_batch(_batch())
    lowered = sess._step.step_fn.lower(
        sess.sharded_params, sess.opt_state, sess.sync_state, batch)
    return lowered.as_text().count("stablehlo.all_reduce")


def _session_pervar():
    """Per-variable explicit sync: a compressor (f32 cast = identity) forces
    the explicit path with one collective per variable — the unfused
    reference point."""
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(chunk_size=1,
                                             compressor="HorovodCompressor"))
    with ad.scope():
        ad.capture(params=_params(), optimizer=optax.sgd(0.1), loss_fn=_loss)
    return ad.create_distributed_session()


def test_fused_grouping_reduces_collective_count():
    many = _count_all_reduces(_session_pervar())
    few = _count_all_reduces(_session(chunk_size=2 * N_LAYERS, fused=True))
    # 2*N_LAYERS vars merge into ONE fused pmean (the backward pass's
    # jax-inserted psums and the loss pmean are common to both programs).
    assert few < many, (few, many)
    assert many - few == 2 * N_LAYERS - 1


def test_small_groups_fuse_per_group():
    some = _count_all_reduces(_session(chunk_size=N_LAYERS, fused=True))
    few = _count_all_reduces(_session(chunk_size=2 * N_LAYERS, fused=True))
    assert some == few + 1  # two groups -> two fused pmeans vs one


def test_fused_matches_gspmd_numerics():
    batch = _batch()
    fused = _session(chunk_size=2 * N_LAYERS, fused=True)
    plain = _session(chunk_size=2 * N_LAYERS, fused=False)
    for _ in range(4):
        lf = fused.run(batch)["loss"]
        lp = plain.run(batch)["loss"]
        np.testing.assert_allclose(lf, lp, rtol=1e-5)
    for name in ("layer_0", "layer_3"):
        np.testing.assert_allclose(fused.params[name]["w"],
                                   plain.params[name]["w"], rtol=1e-5)


def test_fused_flag_round_trips_through_ir():
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.base import Strategy

    gi = GraphItem(_params())
    spec = ResourceSpec(
        resource_info={"nodes": [{"address": "localhost", "chips": 8}]})
    s = AllReduce(chunk_size=4, fused_groups=True).build(gi, spec)
    s.serialize()
    s2 = Strategy.deserialize(s.id)
    sync = s2.node_config[0].synchronizer
    assert sync.fused is True and sync.group == 0
    assert s2.node_config[5].synchronizer.group == 1


def test_combiner_bytes_computed_for_gspmd_path():
    sess = _session(chunk_size=2 * N_LAYERS, fused=False)
    from autodist_tpu.kernel.graph_transformer import GraphTransformer

    gt = GraphTransformer(sess._step.compiled_strategy, sess._gi)
    # 6x (4x4 + 4) float32 = 6 * 80 bytes.
    assert gt._combiner_bytes() == N_LAYERS * (16 + 4) * 4
