"""Launcher CLI (`python -m autodist_tpu.run`) + SYS_RESOURCE_PATH plumbing.

Parity target: the reference's same-script-on-every-worker execution model
(``autodist/coordinator.py:46-90``) fronted by an ``ad run``-style CLI
(SURVEY §2.9); the spec path rides the reference's own
``SYS_RESOURCE_PATH`` env (``autodist/const.py:55-89``)."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_resource_spec_env_pickup(tmp_path, monkeypatch):
    spec_file = tmp_path / "spec.yml"
    spec_file.write_text(
        "nodes:\n  - address: 10.0.0.7\n    chips: 4\n    chief: true\n")
    monkeypatch.setenv("SYS_RESOURCE_PATH", str(spec_file))
    from autodist_tpu.resource_spec import ResourceSpec

    spec = ResourceSpec()  # bare: env supplies the file
    assert spec.chief == "10.0.0.7"
    assert spec.num_chips == 4
    assert spec.source_file == str(spec_file)


def test_cli_runs_unmodified_script(tmp_path):
    """End-to-end: the CLI binds a spec to a script whose only framework
    code is a bare AutoDist() + implicit capture, and trains it."""
    spec_file = tmp_path / "spec.yml"
    spec_file.write_text(
        "nodes:\n  - address: localhost\n    chips: 8\n    chief: true\n")
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os, sys, json
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
        import jax.numpy as jnp, numpy as np, optax
        from autodist_tpu import AutoDist

        params = {"w": jnp.zeros(3)}
        def loss(p, b):
            return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

        ad = AutoDist()   # bare: spec comes from the launcher env
        with ad.scope():  # implicit capture: plain optax script
            opt = optax.sgd(0.1)
            opt.init(params)
            jax.value_and_grad(loss)
        sess = ad.create_distributed_session()
        rng = np.random.RandomState(0)
        batch = {"x": rng.randn(16, 3).astype(np.float32),
                 "y": rng.randn(16).astype(np.float32)}
        losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
        out = {"losses": losses, "mesh": dict(sess.mesh.shape),
               "chief": ad.resource_spec.chief, "argv": sys.argv[1:]}
        open(os.environ["RESULT_FILE"], "w").write(json.dumps(out))
    """))
    env = dict(os.environ)
    env.pop("SYS_RESOURCE_PATH", None)
    env.update({"RESULT_FILE": str(tmp_path / "out.json"),
                "AUTODIST_IS_TESTING": "True",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.run", "-r", str(spec_file),
         str(script), "--epochs", "3"],
        env=env, timeout=180, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, out[-3000:]
    result = json.loads((tmp_path / "out.json").read_text())
    assert result["mesh"] == {"data": 8}
    assert result["chief"] == "localhost"
    assert result["argv"] == ["--epochs", "3"]  # script args pass through
    assert result["losses"][2] < result["losses"][0]


def test_cli_missing_spec_errors(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.run", "-r",
         str(tmp_path / "nope.yml"), "x.py"],
        capture_output=True, timeout=60)
    assert proc.returncode == 2
    assert b"resource spec not found" in proc.stderr
