"""Overlap-aware sync scheduler (kernel/synchronization/overlap.py).

The contracts of the PR issue: (1) pipelined accumulation is
numerically equivalent (1e-6) to the sequential loop on the CPU mesh
across sync modes × compressors — including uneven tail microbatches
and the single-microbatch degenerate case; (2) ring decomposition
lowers large buckets to explicit ppermute steps (and one-shot below the
threshold) with identical numerics; (3) the ZeRO-1 param all-gather
issues in reverse bucket order; (4) the analysis rules
(sync/ring-degenerate ERROR, sync/overlap-fallback WARN) share their
reason strings with the runtime; (5) sync state is only donated when
every entry is rewritten each step.
"""
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.kernel.synchronization import overlap as ov
from autodist_tpu.kernel.synchronization.bucketing import assign_buckets
from autodist_tpu.strategy import AllReduce, Zero1
from autodist_tpu.utils import compat

pytestmark = [pytest.mark.sync, pytest.mark.overlap]


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


# -- ring / one-shot collective lowerings ------------------------------------

def _data_mesh():
    n = jax.device_count()
    return Mesh(np.array(jax.devices()).reshape(n), ("data",)), n


def test_ring_legs_match_lax_collectives():
    """ring RS == psum_scatter, ring AG == all_gather(tiled), ring AR ==
    pmean, one-shot == pmean — same math, schedulable legs."""
    mesh, n = _data_mesh()
    x = np.random.RandomState(0).randn(n * 40).astype(np.float32)

    def f(xs):
        rs_ref = lax.psum_scatter(xs, "data", scatter_dimension=0,
                                  tiled=True)
        return (ov.ring_reduce_scatter(xs, "data", n), rs_ref,
                ov.ring_all_gather(rs_ref, "data", n),
                lax.all_gather(rs_ref, "data", axis=0, tiled=True),
                ov.ring_all_reduce_mean(xs, "data", n),
                ov.one_shot_all_reduce_mean(xs, "data", n),
                lax.pmean(xs, "data"))

    m = compat.shard_map(f, mesh=mesh, in_specs=P("data"),
                         out_specs=(P("data"),) * 7, check_vma=False)
    rs, rs_ref, ag, ag_ref, ar, os_, ar_ref = jax.jit(m)(x)
    np.testing.assert_allclose(np.asarray(rs), np.asarray(rs_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ag), np.asarray(ag_ref))
    np.testing.assert_allclose(np.asarray(ar), np.asarray(ar_ref),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(os_), np.asarray(ar_ref),
                               rtol=1e-6, atol=1e-7)


def test_ring_degenerate_single_device_is_identity():
    x = jnp.arange(8.0)
    assert ov.ring_reduce_scatter(x, "data", 1) is x
    assert ov.ring_all_gather(x, "data", 1) is x
    assert ov.ring_all_reduce_mean(x, "data", 1) is x


# -- schedule resolution (pure rules) ----------------------------------------

def _bucket(dtype="float32", comp="NoneCompressor", nbytes=1024,
            mode="all_reduce"):
    n = max(nbytes // np.dtype(dtype).itemsize, 1)
    (b,) = assign_buckets([("v", (n,), dtype, comp, 0, mode)])
    return b


def test_resolve_none_wins_over_everything():
    plan = ov.resolve_overlap(["full", "none", "ring"], accum_steps=4,
                              buckets=[_bucket()], d=8, has_rs=True)
    assert plan.mode == "none"
    assert not (plan.pipeline or plan.ring or plan.prefetch
                or plan.one_shot_small)


def test_auto_pipelines_only_f32_uncompressed_buckets():
    f32 = _bucket("float32")
    bf16 = _bucket("bfloat16")
    comp = _bucket(comp="HorovodCompressorEF")
    plan = ov.resolve_overlap(["auto"], accum_steps=4,
                              buckets=[f32, bf16, comp], d=8, has_rs=False)
    assert plan.pipeline
    assert ov.pipeline_eligible(f32, plan.mode, 4)
    assert not ov.pipeline_eligible(bf16, plan.mode, 4)
    assert not ov.pipeline_eligible(comp, plan.mode, 4)
    # the blocked buckets carry shared-rule drop reasons
    dropped = dict(plan.drops)
    assert bf16.key in dropped and "low-precision rounding" in \
        dropped[bf16.key]
    assert comp.key in dropped and "quantizes once per bucket" in \
        dropped[comp.key]
    # explicit pipeline forces the bf16 bucket in
    assert ov.pipeline_eligible(bf16, "pipeline", 4)


def test_pipeline_degenerate_single_microbatch_falls_back():
    plan = ov.resolve_overlap(["pipeline"], accum_steps=1,
                              buckets=[_bucket()], d=8, has_rs=False)
    assert not plan.pipeline
    assert any("no microbatch loop" in why for _, why in plan.drops)


def test_auto_with_no_accum_is_quiet():
    plan = ov.resolve_overlap(["auto"], accum_steps=1,
                              buckets=[_bucket()], d=8, has_rs=False)
    assert not plan.pipeline and not plan.drops


def test_gather_schedule_reverses_bucket_order():
    bs = assign_buckets(
        [(f"v{i}", (64,), "float32", "NoneCompressor", i, "reduce_scatter")
         for i in range(3)])
    assert [b.order for b in bs] == [0, 1, 2]
    assert [b.order for b in ov.gather_schedule(bs, True)] == [2, 1, 0]
    assert [b.order for b in ov.gather_schedule(bs, False)] == [0, 1, 2]


def test_microbatch_slices():
    assert ov.microbatch_slices(8, 4) == [(0, 2), (2, 2), (4, 2), (6, 2)]
    assert ov.microbatch_slices(7, 3) == [(0, 3), (3, 2), (5, 2)]
    assert ov.microbatch_slices(4, 3) == [(0, 2), (2, 1), (3, 1)]
    with pytest.raises(ValueError, match="exceeds"):
        ov.microbatch_slices(2, 3)


# -- pipelined accumulation: numerical equivalence ---------------------------

def _problem(rows=32, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(24, 32) * 0.1, jnp.float32),
               "b": jnp.zeros(32, jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(32, 4) * 0.1, jnp.float32)},
    }
    batch = {"x": rng.randn(rows, 24).astype(np.float32),
             "y": rng.randn(rows, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"] + p["l1"]["b"])
        return jnp.mean((h @ p["l2"]["w"] - b["y"]) ** 2)

    return params, loss_fn, batch


def _session(builder, params, loss_fn, accum=1, opt=None):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn, accum_steps=accum)
    return ad.create_distributed_session()


def _assert_same_trajectory(a, b, batch, steps=6, rtol=1e-6, atol=1e-7):
    for _ in range(steps):
        la, lb = a.run(batch)["loss"], b.run(batch)["loss"]
        np.testing.assert_allclose(float(la), float(lb), rtol=rtol,
                                   atol=atol)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            rtol=rtol, atol=atol),
        a.params, b.params)


@pytest.mark.parametrize("mk", [
    lambda o: AllReduce(bucket_bytes=1 << 20, overlap=o),
    lambda o: Zero1(overlap=o),
], ids=["all_reduce", "reduce_scatter"])
def test_pipelined_matches_sequential_loop(mk):
    """The acceptance contract: with accumulation active, the pipelined
    schedule (per-microbatch bucket collectives overlapping backward)
    reproduces the sequential accumulate-then-reduce loop to 1e-6 on
    both sync modes."""
    params, loss_fn, batch = _problem()
    pipelined = _session(mk("auto"), params, loss_fn, accum=4)
    sequential = _session(mk("none"), params, loss_fn, accum=4)
    _assert_same_trajectory(pipelined, sequential, batch)


@pytest.mark.parametrize("compressor", [
    "HorovodCompressor", "HorovodCompressorEF", "Int8Compressor",
    "PowerSGDCompressor"])
def test_compressed_modes_fall_back_and_stay_exact(compressor):
    """Quantizing compressors keep the one-compressed-collective-per-
    bucket-per-step contract: overlap='auto' falls back to the
    sequential loop, so the trajectory is IDENTICAL to overlap='none'
    (not merely close) for every compressor."""
    params, loss_fn, batch = _problem()
    auto = _session(AllReduce(compressor=compressor, bucket_bytes=1 << 20,
                              overlap="auto"), params, loss_fn, accum=2)
    off = _session(AllReduce(compressor=compressor, bucket_bytes=1 << 20,
                             overlap="none"), params, loss_fn, accum=2)
    _assert_same_trajectory(auto, off, batch, steps=4)


def test_pipelined_uneven_tail_microbatches():
    """32-row global batch over 8 devices = 4 local rows; accum_steps=3
    runs uneven [2, 1, 1] microbatches, row-weighted in both the
    pipelined (unrolled) and sequential schedules."""
    params, loss_fn, batch = _problem(rows=32)
    pipelined = _session(AllReduce(bucket_bytes=1 << 20, overlap="auto"),
                         params, loss_fn, accum=3)
    sequential = _session(AllReduce(bucket_bytes=1 << 20, overlap="none"),
                          params, loss_fn, accum=3)
    _assert_same_trajectory(pipelined, sequential, batch)
    # ...and both match the unaccumulated full-batch step (row-mean loss)
    plain = _session(AllReduce(bucket_bytes=1 << 20), params, loss_fn)
    pipelined2 = _session(AllReduce(bucket_bytes=1 << 20, overlap="auto"),
                          params, loss_fn, accum=3)
    _assert_same_trajectory(pipelined2, plain, batch, rtol=1e-5, atol=1e-6)


def test_pipelined_zero1_uneven_tail():
    params, loss_fn, batch = _problem(rows=32)
    pipelined = _session(Zero1(overlap="auto"), params, loss_fn, accum=3)
    sequential = _session(Zero1(overlap="none"), params, loss_fn, accum=3)
    _assert_same_trajectory(pipelined, sequential, batch)


def test_single_microbatch_degenerate_case():
    """overlap='pipeline' with accum_steps=1 falls back (nothing to
    pipeline) and matches the plain step exactly."""
    params, loss_fn, batch = _problem()
    forced = _session(AllReduce(bucket_bytes=1 << 20, overlap="pipeline"),
                      params, loss_fn, accum=1)
    plain = _session(AllReduce(bucket_bytes=1 << 20, overlap="none"),
                     params, loss_fn, accum=1)
    _assert_same_trajectory(forced, plain, batch)


def test_explicit_pipeline_forces_bf16_bucket():
    """auto skips bf16 buckets (extra per-microbatch rounding); an
    explicit overlap='pipeline' pipelines them too, tracking the
    sequential loop at bf16 summation-order tolerance."""
    rng = np.random.RandomState(7)
    params = {"w16": jnp.asarray(rng.randn(16, 8) * 0.1, jnp.bfloat16),
              "w32": jnp.asarray(rng.randn(8, 4) * 0.1, jnp.float32)}
    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w16"].astype(jnp.float32))
        return jnp.mean((h @ p["w32"] - b["y"]) ** 2)

    forced = _session(AllReduce(bucket_bytes=1 << 20, overlap="pipeline"),
                      params, loss_fn, accum=2)
    seq = _session(AllReduce(bucket_bytes=1 << 20, overlap="none"),
                   params, loss_fn, accum=2)
    for _ in range(4):
        np.testing.assert_allclose(float(forced.run(batch)["loss"]),
                                   float(seq.run(batch)["loss"]),
                                   rtol=5e-3)


def test_pipelined_aux_keeps_stacked_contract():
    """has_aux under the pipelined schedule: aux comes back stacked on a
    leading [accum] axis, same as the sequential loop."""
    params, loss_fn, batch = _problem()

    def loss_aux(p, b):
        loss = loss_fn(p, b)
        return loss, {"l2": loss * 2}

    def make(overlap):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=AllReduce(bucket_bytes=1 << 20,
                                                 overlap=overlap))
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=loss_aux, has_aux=True, accum_steps=4)
        return ad.create_distributed_session()

    piped, seq = make("auto"), make("none")
    op, os_ = piped.run(batch), seq.run(batch)
    assert np.shape(op["aux"]["l2"]) == np.shape(os_["aux"]["l2"])
    np.testing.assert_allclose(np.asarray(op["aux"]["l2"]),
                               np.asarray(os_["aux"]["l2"]), rtol=1e-6)
    np.testing.assert_allclose(float(op["loss"]), float(os_["loss"]),
                               rtol=1e-6)


# -- ring decomposition in the lowered program -------------------------------

def _hlo(sess, batch):
    b = sess.place_batch(batch)
    return sess._step.step_fn.lower(sess.sharded_params, sess.opt_state,
                                    sess.sync_state, b).as_text()


def test_large_bucket_ring_decomposes_to_ppermute():
    """A >=256 KiB bucket under overlap='ring' lowers to explicit
    collective_permute ring steps instead of one monolithic all-reduce;
    numerics match the fused collective."""
    rng = np.random.RandomState(1)
    params = {"big": jnp.asarray(rng.randn(512, 256) * 0.02, jnp.float32)}
    batch = {"x": rng.randn(16, 512).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["big"]) ** 2)

    ring = _session(AllReduce(bucket_bytes=1 << 20, overlap="ring"),
                    params, loss_fn)
    fused = _session(AllReduce(bucket_bytes=1 << 20, overlap="none"),
                     params, loss_fn)
    txt = _hlo(ring, batch)
    assert "stablehlo.collective_permute" in txt
    # ring summation order differs from the fused psum's reduction tree;
    # a few ULPs per step compound through Adam, hence the atol.
    _assert_same_trajectory(ring, fused, batch, steps=4, rtol=1e-3,
                            atol=1e-5)


def test_small_bucket_one_shot_under_explicit_ring():
    """Below the threshold, explicit ring mode picks the one-shot
    gather-and-reduce: the gradient program carries an all_gather where
    'none' carries an all_reduce."""
    params, loss_fn, batch = _problem()
    one_shot = _session(AllReduce(bucket_bytes=1 << 20, overlap="ring"),
                        params, loss_fn)
    fused = _session(AllReduce(bucket_bytes=1 << 20, overlap="none"),
                     params, loss_fn)
    assert "stablehlo.collective_permute" not in _hlo(one_shot, batch)
    assert _hlo(one_shot, batch).count("stablehlo.all_gather") > \
        _hlo(fused, batch).count("stablehlo.all_gather")
    _assert_same_trajectory(one_shot, fused, batch, steps=4)


def test_overlap_knob_routes_explicit_path():
    from autodist_tpu.kernel.synchronization import explicit_sync

    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(overlap="ring"), params, loss_fn)
    assert explicit_sync.uses_explicit_path(sess._step.compiled_strategy)


# -- ZeRO-1 prefetch ---------------------------------------------------------

def test_zero1_full_overlap_matches_reference():
    """overlap='full' (pipeline + ring/one-shot + reverse-order gather)
    still reproduces the plain AllReduce trajectory at 1e-6."""
    params, loss_fn, batch = _problem()
    z = _session(Zero1(overlap="full"), params, loss_fn, accum=2)
    ref = _session(AllReduce(overlap="none"), params, loss_fn, accum=2)
    _assert_same_trajectory(z, ref, batch)


# -- donation audit ----------------------------------------------------------

def test_fallback_sync_state_is_not_donated():
    """A per-variable fallback entry (PowerSGD) can pass through a step
    untouched, so the step must NOT donate sync_state: a reference taken
    before the step (checkpoint saver pattern) stays readable."""
    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(compressor="PowerSGDCompressor"),
                    params, loss_fn, opt=optax.sgd(0.1))
    before = sess.sync_state
    assert before  # PowerSGD carries per-var state
    sess.run(batch)
    sess.run(batch)
    for leaf in jax.tree_util.tree_leaves(before):
        np.asarray(leaf)  # would raise RuntimeError if donated


def test_bucket_only_sync_state_still_donated():
    """Bucket residuals are rewritten unconditionally every step, so the
    all-bucket program keeps the donation (old references are consumed —
    the memory win of donating the residual buffers)."""
    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(compressor="HorovodCompressorEF",
                              bucket_bytes=1 << 20), params, loss_fn)
    before = sess.sync_state
    assert before and all(":" in k for k in before)  # bucket-keyed
    sess.run(batch)
    leaf = jax.tree_util.tree_leaves(before)[0]
    assert leaf.is_deleted()


# -- analysis rules ----------------------------------------------------------

def test_ring_degenerate_axis_is_error():
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem

    gi = GraphItem({"w": jnp.zeros((64, 64), jnp.float32)})
    report = analyze(AllReduce(overlap="ring").build(gi, _spec(1)), gi,
                     mesh={"data": 1})
    errs = report.by_rule("sync/ring-degenerate")
    assert errs and "no ring to permute over" in errs[0].message
    # legal on a real data axis
    ok = analyze(AllReduce(overlap="ring").build(gi, _spec(8)), gi,
                 mesh={"data": 8})
    assert not ok.by_rule("sync/ring-degenerate")


def test_overlap_fallback_warn_shares_runtime_reason():
    """The sync/overlap-fallback WARN carries the exact string
    overlap_drop_reason produces — one rule, lint and runtime."""
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem

    gi = GraphItem({"w": jnp.zeros((64, 64), jnp.float32)})
    report = analyze(
        Zero1(compressor="PowerSGDCompressor").build(gi, _spec(8)),
        gi, mesh={"data": 8})
    warns = report.by_rule("sync/overlap-fallback")
    assert warns
    expected = ov.overlap_drop_reason(
        "auto", accum_steps=1, compressor="PowerSGDCompressor",
        bucketable=False, explicit_path=True)
    assert expected in warns[0].message


def test_overlap_unknown_mode_is_error():
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy.base import (
        AllReduceSynchronizerConfig,
        Strategy,
        VarConfig,
    )

    gi = GraphItem({"w": jnp.zeros((8, 8), jnp.float32)})
    s = Strategy(node_config=[VarConfig(
        "w", synchronizer=AllReduceSynchronizerConfig(overlap="warp"))])
    report = analyze(s, gi, mesh={"data": 8})
    assert report.by_rule("sync/overlap-unknown")


def test_builder_rejects_unknown_overlap():
    with pytest.raises(ValueError, match="overlap"):
        AllReduce(overlap="warp")
    with pytest.raises(ValueError, match="overlap"):
        Zero1(overlap="warp")


def test_overlap_round_trips_through_ir():
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy.base import Strategy

    gi = GraphItem({"w": jnp.zeros((8, 8), jnp.float32)})
    s = Zero1(overlap="full").build(gi, _spec(8))
    s.serialize()
    s2 = Strategy.deserialize(s.id)
    assert s2.node_config[0].synchronizer.overlap == "full"


def test_analysis_cli_flags_illegal_ring_request():
    """Acceptance: the CLI exits nonzero on a ring request over a
    size-1 data axis."""
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "mlp", "Zero1",
         "--mesh", "data=1", "--overlap", "ring"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "sync/ring-degenerate" in proc.stdout
    ok = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "mlp", "Zero1",
         "--mesh", "data=8", "--overlap", "full"],
        capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr


def _spec(chips):
    from autodist_tpu.resource_spec import ResourceSpec

    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": chips, "chief": True}]})
