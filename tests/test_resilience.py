"""Resilience subsystem: supervised recovery, elastic ZeRO-1 resume,
heartbeat/hang detection, chaos harness, checkpoint integrity.

The acceptance contract of the PR issue: chaos-driven unit coverage for
the supervisor policy, heartbeat timeout and checkpoint verify; exact
mid-epoch data resume; bounded remote retries; and a ZeRO-1 checkpoint
written at data-axis 8 resuming at data-axis 4 with params and
optimizer state bit-exact (the multiprocess kill-recover integration
lives in tests/test_multiprocess_resilience.py)."""
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    from autodist_tpu.autodist import _reset_default_autodist_for_testing

    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


# ---------------------------------------------------------------------------
# backoff
# ---------------------------------------------------------------------------

def test_backoff_schedule_bounded_and_deterministic():
    from autodist_tpu.resilience import Backoff

    b = Backoff(max_tries=5, base=1.0, cap=4.0, multiplier=2.0,
                jitter=0.5, seed=11)
    assert [b.nominal(i) for i in (1, 2, 3, 4)] == [1.0, 2.0, 4.0, 4.0]
    # jitter spreads each delay over ±25% but preserves determinism
    assert b.delays() == b.delays()
    for i, d in enumerate(b.delays(), start=1):
        nom = b.nominal(i)
        assert 0.75 * nom <= d <= 1.25 * nom
    # unjittered schedule is exact
    assert Backoff(max_tries=3, base=2.0, jitter=0).delays() == [2.0, 4.0]


def test_backoff_retry_logs_attempts_and_gives_up():
    from autodist_tpu.resilience import Backoff

    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("flake")
        return "ok"

    b = Backoff(max_tries=3, base=0.25, jitter=0, seed=0)
    assert b.retry(flaky, retryable=(OSError,), label="t",
                   sleep=sleeps.append) == "ok"
    assert len(calls) == 3 and sleeps == [0.25, 0.5]

    with pytest.raises(OSError):
        b.retry(lambda: (_ for _ in ()).throw(OSError("always")),
                retryable=(OSError,), sleep=lambda s: None)
    with pytest.raises(ValueError):   # non-retryable propagates at once
        b.retry(lambda: (_ for _ in ()).throw(ValueError("no")),
                retryable=(OSError,), sleep=lambda s: None)


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_chaos_spec_parses_and_filters():
    from autodist_tpu.resilience import parse_chaos
    from autodist_tpu.resilience.chaos import ChaosMonkey

    events = parse_chaos(
        "kill@step=6,proc=1,attempt=0,code=9;"
        "preempt@step=5,signal=SIGTERM;drop_heartbeats@step=3")
    assert [e.action for e in events] == ["kill", "preempt",
                                          "drop_heartbeats"]
    assert events[0].step == 6 and events[0].proc == 1 \
        and events[0].attempt == 0 and events[0].args["code"] == "9"

    # attempt/proc filters: the kill only fires for proc 1 on attempt 0
    m = ChaosMonkey(parse_chaos("kill@step=2,proc=1,attempt=0"),
                    process_index=0, attempt=0)
    fired = []
    m._exit = lambda code: fired.append(code)
    for s in range(5):
        m.on_step(s)
    assert fired == []
    m = ChaosMonkey(parse_chaos("kill@step=2,proc=1,attempt=0"),
                    process_index=1, attempt=1)
    for s in range(5):
        m.on_step(s)
    assert fired == []

    with pytest.raises(ValueError):
        parse_chaos("explode@step=1")


def test_chaos_kill_and_heartbeat_drop_fire_once():
    from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos

    m = ChaosMonkey(parse_chaos("kill@step=3;drop_heartbeats@step=1"),
                    process_index=0, attempt=0)
    fired = []
    m._exit = lambda code: fired.append(code)
    assert m.heartbeats_enabled
    m.on_step(1)
    assert not m.heartbeats_enabled       # dropped at step 1
    m.on_step(2)
    assert fired == []
    m.on_step(3)
    m.on_step(4)
    from autodist_tpu.resilience.chaos import DEFAULT_KILL_CODE
    assert fired == [DEFAULT_KILL_CODE]   # fired exactly once


def test_chaos_callback_drives_monkey():
    from autodist_tpu.resilience import ChaosCallback
    from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos

    m = ChaosMonkey(parse_chaos("preempt@step=2,signal=SIGUSR1"),
                    process_index=0, attempt=0)
    got = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: got.append(s))
    try:
        cb = ChaosCallback(m)
        for s in (1, 2, 3):
            cb.on_step_end(s, {})
    finally:
        signal.signal(signal.SIGUSR1, prev)
    assert got == [signal.SIGUSR1]


# ---------------------------------------------------------------------------
# heartbeat / hang detection
# ---------------------------------------------------------------------------

def test_heartbeat_alive_dead_and_unknown(tmp_path):
    from autodist_tpu.resilience import HeartbeatMonitor, HeartbeatWriter
    from autodist_tpu.resilience.heartbeat import ALIVE, DEAD, UNKNOWN

    d = str(tmp_path)
    w = HeartbeatWriter(d, "w0")
    w.beat(step=4)
    mon = HeartbeatMonitor(d, timeout=30.0)
    h = mon.check("w0")
    assert h.state == ALIVE and h.step == 4 and h.pid == os.getpid()

    # stale beacon + dead pid -> DEAD ("process exited")
    path = w.path
    with open(path, "r+", encoding="utf-8") as f:
        payload = json.load(f)
        payload["pid"] = 2 ** 22 + 12345   # vanishingly unlikely to exist
        f.seek(0), f.truncate(), json.dump(payload, f)
    past = time.time() - 120
    os.utime(path, (past, past))
    assert mon.check("w0").state == DEAD

    # never-seen worker: UNKNOWN within grace, DEAD after
    mon2 = HeartbeatMonitor(d, timeout=30.0, grace=60.0)
    assert mon2.check("ghost").state == UNKNOWN
    mon3 = HeartbeatMonitor(d, timeout=0.0, grace=0.0)
    time.sleep(0.01)
    assert mon3.check("ghost").state == DEAD


def test_heartbeat_distinguishes_wedged_from_dead(tmp_path):
    """The TPU failure mode fail-fast never catches: the process is
    ALIVE (fresh beacons / live pid) but stuck in a collective — step
    progress is the only signal."""
    from autodist_tpu.resilience import HeartbeatMonitor, HeartbeatWriter
    from autodist_tpu.resilience.heartbeat import ALIVE, WEDGED

    d = str(tmp_path)
    w = HeartbeatWriter(d, "w1")

    # case 1: beacon stale but pid (ours) alive -> WEDGED
    w.beat(step=7)
    past = time.time() - 120
    os.utime(w.path, (past, past))
    mon = HeartbeatMonitor(d, timeout=30.0)
    h = mon.check("w1")
    assert h.state == WEDGED and "alive" in h.detail

    # case 2: beacons FRESH but the step never advances -> WEDGED via
    # step_timeout (the beacon thread keeps beating from its own thread
    # while the main thread hangs, so age alone would report ALIVE)
    mon2 = HeartbeatMonitor(d, timeout=30.0, step_timeout=0.05)
    w.beat(step=9)
    assert mon2.check("w1").state == ALIVE
    time.sleep(0.1)
    w.beat(step=9)                       # fresh beacon, same step
    h = mon2.check("w1")
    assert h.state == WEDGED and "stalled" in h.detail
    assert "w1" in mon2.failures()
    w.beat(step=10)                      # progress clears the verdict
    assert mon2.check("w1").state == ALIVE


# ---------------------------------------------------------------------------
# supervisor policy
# ---------------------------------------------------------------------------

def _fast_policy(**kw):
    from autodist_tpu.resilience import Backoff, SupervisorPolicy

    kw.setdefault("backoff", Backoff(max_tries=8, base=0.01, cap=0.02,
                                     jitter=0, seed=0))
    kw.setdefault("poll_interval", 0.02)
    return SupervisorPolicy(**kw)


def _proc(code: int) -> subprocess.Popen:
    return subprocess.Popen([sys.executable, "-c", f"raise SystemExit({code})"],
                            start_new_session=True)


def test_supervisor_retries_until_success(tmp_path):
    from autodist_tpu.resilience import Supervisor

    seen = []

    def launch(att):
        seen.append((att.index, tuple(att.hosts)))
        return _proc(0 if att.index >= 2 else 7)

    sup = Supervisor(_fast_policy(max_restarts=3), hosts=["a", "b"],
                     workdir=str(tmp_path))
    report = sup.run(launch)
    assert report.ok and report.attempts == 3
    assert [i for i, _ in seen] == [0, 1, 2]
    assert len(report.failures) == 2
    assert all(f.kind == "exit" for f in report.failures)


def test_supervisor_exhausts_retry_budget(tmp_path):
    from autodist_tpu.resilience import Supervisor

    sup = Supervisor(_fast_policy(max_restarts=1), hosts=["a"],
                     workdir=str(tmp_path))
    report = sup.run(lambda att: _proc(3))
    assert not report.ok and report.attempts == 2
    assert "exhausted" in report.gave_up


def test_supervisor_elastic_drops_dead_host(tmp_path):
    """Per-host failure budget + elastic fall-through: after host 'b'
    fails twice it is declared permanently gone and the next attempt
    launches on the survivors only."""
    from autodist_tpu.resilience import NotifySupervisor, Supervisor

    hosts_seen = []

    def launch(att):
        hosts_seen.append(tuple(att.hosts))
        if "b" in att.hosts:
            # the in-job watcher would do exactly this on b's death:
            NotifySupervisor(att.marker_dir).on_worker_exit("b", 43)
            return _proc(73)
        return _proc(0)

    sup = Supervisor(
        _fast_policy(max_restarts=4, elastic=True, host_failure_budget=2,
                     min_hosts=1),
        hosts=["a", "b"], workdir=str(tmp_path))
    report = sup.run(launch)
    assert report.ok
    assert hosts_seen == [("a", "b"), ("a", "b"), ("a",)]
    assert report.hosts == ["a"]
    assert all(f.culprit == "b" for f in report.failures)


def test_supervisor_reports_resume_step(tmp_path):
    """Attempts after the first see the latest durable checkpoint step —
    what the relaunched job is expected to resume from."""
    from autodist_tpu.resilience import Supervisor

    ckpt = tmp_path / "ck"
    steps_seen = []

    def launch(att):
        steps_seen.append(att.resume_step)
        if att.index == 0:
            # the "job" leaves a committed checkpoint behind, then dies
            os.makedirs(ckpt / "step_5" / "params")
            (ckpt / "step_5" / "params" / "d").write_text("x")
            return _proc(9)
        return _proc(0)

    sup = Supervisor(_fast_policy(max_restarts=2), hosts=["a"],
                     checkpoint_dir=str(ckpt), workdir=str(tmp_path / "w"))
    report = sup.run(launch)
    assert report.ok and steps_seen == [None, 5]


def test_failure_policy_from_env(monkeypatch, tmp_path):
    from autodist_tpu.resilience import (
        Ignore, NotifySupervisor, RestartWorker, policy_from_env)
    from autodist_tpu.resilience.supervisor import (
        ABORT, IGNORE, RELAUNCH, SUPERVISED_ABORT_CODE,
        read_failure_markers)

    monkeypatch.delenv("AUTODIST_FAILURE_POLICY", raising=False)
    assert policy_from_env() is None      # legacy fail-fast
    monkeypatch.setenv("AUTODIST_FAILURE_POLICY", "ignore")
    assert isinstance(policy_from_env(), Ignore)
    monkeypatch.setenv("AUTODIST_FAILURE_POLICY", "restart")
    assert isinstance(policy_from_env(), RestartWorker)
    monkeypatch.setenv("AUTODIST_FAILURE_POLICY", "supervised")
    with pytest.raises(ValueError):       # needs the marker dir
        policy_from_env()
    monkeypatch.setenv("AUTODIST_SUPERVISOR_DIR", str(tmp_path))
    pol = policy_from_env()
    assert isinstance(pol, NotifySupervisor)
    assert pol.exit_code == SUPERVISED_ABORT_CODE
    assert pol.on_worker_exit("10.0.0.7", 43) == ABORT
    markers = read_failure_markers(str(tmp_path))
    assert markers and markers[-1]["address"] == "10.0.0.7" \
        and markers[-1]["code"] == 43

    assert Ignore().on_worker_exit("h", 1) == IGNORE
    rw = RestartWorker()
    rw._backoff = rw._backoff.__class__(max_tries=3, base=0, jitter=0)
    assert rw.on_worker_exit("h", 1) == RELAUNCH
    assert rw.on_worker_exit("h", 1) == RELAUNCH
    assert rw.on_worker_exit("h", 1) == ABORT   # budget exhausted


# ---------------------------------------------------------------------------
# cluster transient retry
# ---------------------------------------------------------------------------

def test_remote_copy_retries_transient_failures(tmp_path, monkeypatch):
    from autodist_tpu.cluster import SSHCluster
    from autodist_tpu.resilience import Backoff, backoff as backoff_mod
    from autodist_tpu.resource_spec import ResourceSpec

    spec = ResourceSpec(resource_info={"nodes": [
        {"address": "127.0.0.1", "chips": 1, "chief": True},
        {"address": "198.51.100.7", "chips": 1}]})
    cluster = SSHCluster(spec, remote_retry=Backoff(max_tries=3, base=0,
                                                    jitter=0))
    calls, warned = [], []

    def fake_run(cmd, **kw):
        calls.append(list(cmd))
        if len(calls) <= 2:
            raise subprocess.CalledProcessError(255, cmd)  # SSH flake
        return subprocess.CompletedProcess(cmd, 0)

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(backoff_mod.logging, "warning",
                        lambda msg, *a: warned.append(msg % a))
    src = tmp_path / "f.txt"
    src.write_text("payload")
    cluster.remote_copy(str(src), "/tmp/f.txt", "198.51.100.7")
    # attempt 1 failed at mkdir, attempt 2 failed at mkdir, attempt 3 ran
    # mkdir+scp — and each retry was logged with its attempt count.
    assert len(calls) == 4
    retries = [m for m in warned if "attempt" in m]
    assert len(retries) == 2 and "1/3" in retries[0]
    assert "remote_copy" in retries[0]

    calls.clear()
    with pytest.raises(subprocess.CalledProcessError):
        cluster2 = SSHCluster(spec, remote_retry=Backoff(
            max_tries=2, base=0, jitter=0))
        monkeypatch.setattr(subprocess, "run", lambda cmd, **kw: (
            _ for _ in ()).throw(subprocess.CalledProcessError(255, cmd)))
        cluster2.remote_file_write("/tmp/x", "data", "198.51.100.7")


# ---------------------------------------------------------------------------
# checkpoint integrity + retention
# ---------------------------------------------------------------------------

def _linear_session(builder=None, opt=None):
    import jax.numpy as jnp
    import optax

    from autodist_tpu.autodist import (
        AutoDist, _reset_default_autodist_for_testing)
    from autodist_tpu.strategy import AllReduce

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    params = {"linear": {"w": jnp.zeros((8, 4), jnp.float32),
                         "b": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(p, b):
        pred = b["x"] @ p["linear"]["w"] + p["linear"]["b"]
        return jnp.mean((pred - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=builder or AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn)
    return ad.create_distributed_session(), \
        {"x": x, "y": (x @ w).astype(np.float32)}


def test_checkpoint_checksums_verify_and_corruption(tmp_path):
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience import corrupt_checkpoint

    sess, batch = _linear_session()
    sess.run(batch)
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ck"))

    meta = Saver.read_meta(path)
    assert meta["format"] >= 2 and set(meta["items"]) >= {"params",
                                                          "opt_state"}
    assert meta["checksums"]["params"] and meta["checksums"]["opt_state"]
    assert Saver.verify(path)
    assert Saver.verify(path, deep=True)

    # byte-level truncation: invisible to the shallow check, caught deep
    corrupt_checkpoint(path, item="params", mode="truncate")
    assert Saver.verify(path)
    assert not Saver.verify(path, deep=True)


def test_latest_step_skips_damaged_checkpoint(tmp_path):
    """A corrupt/truncated newest step — not just a missing params dir —
    must fall back to the previous good step."""
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience import corrupt_checkpoint

    sess, batch = _linear_session()
    d = str(tmp_path / "ck")
    saver = Saver(sess)
    sess.run(batch)
    saver.save(d, step=1)
    sess.run(batch)
    p2 = saver.save(d, step=2)
    assert Saver.latest_step(d) == 2
    # opt_state item vanishes (partial delete): params committed, so the
    # old params-dir-only rule would still pick step 2 — verify must not.
    corrupt_checkpoint(p2, item="opt_state", mode="delete")
    assert Saver.latest_step(d) == 1
    assert Saver.latest_checkpoint(d).endswith("step_1")


def test_checkpoint_retention_keep(tmp_path):
    from autodist_tpu.checkpoint import Saver

    sess, batch = _linear_session()
    d = str(tmp_path / "ck")
    saver = Saver(sess, keep=2)
    for step in (1, 2, 3, 4):
        sess.run(batch)
        saver.save(d, step=step)
    dirs = sorted(n for n in os.listdir(d) if n.startswith("step_"))
    assert dirs == ["step_3", "step_4"]
    # the survivors are intact
    assert Saver.latest_step(d) == 4
    with pytest.raises(ValueError):
        Saver(sess, keep=0)


def test_saver_extra_meta_roundtrip(tmp_path):
    from autodist_tpu.checkpoint import Saver

    sess, batch = _linear_session()
    sess.run(batch)
    path = Saver(sess).save(str(tmp_path / "ck"),
                            extra_meta={"data_state": {"epoch": 2,
                                                       "offset": 3}})
    meta = Saver.read_meta(path)
    assert meta["data_state"] == {"epoch": 2, "offset": 3}
    assert meta["mesh_axes"]["data"] == jax.device_count()


# ---------------------------------------------------------------------------
# mid-epoch exact data resume
# ---------------------------------------------------------------------------

def _loader(seed=5, n=32, batch=4, **kw):
    from autodist_tpu.runtime.data_loader import DataLoader

    rng = np.random.RandomState(1)
    x = rng.randn(n, 8).astype(np.float32)
    y = rng.randn(n, 4).astype(np.float32)
    return DataLoader({"x": x, "y": y}, batch_size=batch, shuffle=True,
                      seed=seed, **kw)


def test_data_loader_state_mid_epoch_exact():
    ref = _loader()
    record = [[b["x"].copy() for b in ref] for _ in range(3)]  # 3 epochs

    lo = _loader()
    epoch0 = [b["x"].copy() for b in lo]     # epoch 0 fully
    np.testing.assert_array_equal(epoch0[0], record[0][0])
    it2 = iter(lo)
    taken = [next(it2)["x"].copy() for _ in range(3)]   # epoch 1: 3 batches
    np.testing.assert_array_equal(taken[0], record[1][0])
    state = lo.state()
    assert state == {"epoch": 1, "offset": 3, "seed": 5}

    # a FRESH loader resumes at exactly the next batch
    lo2 = _loader()
    assert lo2.load_state(state) == state
    rest = [b["x"].copy() for b in lo2]
    np.testing.assert_array_equal(rest[0], record[1][3])
    for got, want in zip(rest, record[1][3:]):
        np.testing.assert_array_equal(got, want)
    # and its next epoch matches the uninterrupted epoch 2
    nxt = [b["x"].copy() for b in lo2]
    for got, want in zip(nxt, record[2]):
        np.testing.assert_array_equal(got, want)

    # consumed= overrides the yield count (prefetcher semantics)
    lo3 = _loader()
    it3 = iter(lo3)
    for _ in range(5):
        next(it3)
    st = lo3.state(consumed=2)
    assert st["epoch"] == 0 and st["offset"] == 2

    # boundary normalization: offset == num_batches rolls to next epoch
    assert lo3.load_state({"epoch": 1, "offset": 8, "seed": 5}) \
        == {"epoch": 2, "offset": 0, "seed": 5}
    with pytest.raises(ValueError):
        lo3.load_state({"epoch": 0, "offset": 0, "seed": 99})


def test_fit_resumes_mid_epoch_exactly(tmp_path):
    """Preempt mid-epoch -> checkpoint records the data position ->
    fit(resume=True) continues from the EXACT next batch and lands on
    the same final params as the uninterrupted run (SGD, bit-exact
    replay of the same batch sequence)."""
    import optax

    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.fit import Callback

    # uninterrupted oracle: 3 epochs x 8 batches = 24 steps
    sess_a, _ = _linear_session(opt=optax.sgd(0.05))
    hist_a = sess_a.fit(_loader(), epochs=3,
                        checkpoint_dir=str(tmp_path / "a"))
    assert sess_a.step_count == 24

    class PreemptAt(Callback):
        def __init__(self, step):
            self.step = step

        def on_step_end(self, step, metrics):
            if step == self.step:
                os.kill(os.getpid(), signal.SIGUSR1)

    ck = str(tmp_path / "b")
    sess_b, _ = _linear_session(opt=optax.sgd(0.05))
    hist_b = sess_b.fit(_loader(), epochs=3, checkpoint_dir=ck,
                        preemption_signals=("SIGUSR1",),
                        callbacks=[PreemptAt(11)])
    assert hist_b.preempted and sess_b.step_count == 11
    meta = Saver.read_meta(Saver.latest_checkpoint(ck))
    # step 11 = epoch 1, batches 0-2 consumed -> next is batch 3
    assert meta["data_state"] == {"epoch": 1, "offset": 3, "seed": 5}

    sess_c, _ = _linear_session(opt=optax.sgd(0.05))
    hist_c = sess_c.fit(_loader(), epochs=3, checkpoint_dir=ck,
                        resume=True)
    assert sess_c.step_count == 24
    assert hist_c.steps_run == 13          # 24 - 11: nothing re-run
    np.testing.assert_array_equal(
        np.asarray(sess_c.params["linear"]["w"]),
        np.asarray(sess_a.params["linear"]["w"]))
    np.testing.assert_array_equal(
        np.asarray(sess_c.params["linear"]["b"]),
        np.asarray(sess_a.params["linear"]["b"]))


# ---------------------------------------------------------------------------
# elastic ZeRO-1 resume (data-axis resize)
# ---------------------------------------------------------------------------

def _zero1_session(d, opt=None):
    import jax.numpy as jnp
    import optax

    from autodist_tpu.autodist import (
        AutoDist, _reset_default_autodist_for_testing)
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.strategy import Zero1

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(3)
    # deliberately NOT divisible by 8 or 4 (total 259 elements), so the
    # flat bucket's zero pad differs between the axis sizes and the
    # reshard path genuinely runs
    params = {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32),
              "b": jnp.zeros(3, jnp.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w"])
        return jnp.mean((h[:, :3] + p["b"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "y": rng.randn(16, 3).astype(np.float32)}
    ad = AutoDist(strategy_builder=Zero1())
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn)
    mesh = build_mesh({"data": d}, devices=jax.devices()[:d])
    return ad.create_distributed_session(mesh=mesh), batch


def test_zero1_elastic_resume_8_to_4_bit_exact(caplog):
    """The acceptance criterion: a ZeRO-1 checkpoint written at
    data-axis 8 resumes at data-axis 4 with params AND optimizer state
    bit-exact — no approximate-resume warning on the opt/param path."""
    import logging as pylog
    import tempfile

    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience import elastic_restore

    sess8, batch = _zero1_session(8)
    assert sess8.zero1_buckets and sess8.data_axis_size == 8
    for _ in range(3):
        sess8.run(batch)
    with tempfile.TemporaryDirectory() as d:
        path = Saver(sess8).save(d)
        meta = Saver.read_meta(path)
        assert meta["data_axis_size"] == 8
        layout = meta["zero1_buckets"]
        assert layout and layout[0]["total"] == 259
        assert layout[0]["padded_total"] == 264       # 259 -> /8

        # bucket membership is axis-independent; only the pad changes
        sess4, _ = _zero1_session(4)
        (b4,) = sess4.zero1_buckets
        assert b4.total == 259 and b4.padded_total == 260   # 259 -> /4

        with caplog.at_level(pylog.WARNING):
            step = elastic_restore(sess4, path)
        assert step == 3 and sess4.step_count == 3
        assert not any("approximate" in r.getMessage()
                       for r in caplog.records)

    # params: bit-exact
    for k in ("w", "b"):
        np.testing.assert_array_equal(np.asarray(sess4.params[k]),
                                      np.asarray(sess8.params[k]))
    # optimizer state: every flat bucket leaf's CONTENT (first `total`
    # elements) is bit-exact; only the zero pad length changed
    def flat_moments(sess):
        out = []
        for leaf in jax.tree_util.tree_leaves(sess.opt_state["zero1"]):
            a = np.asarray(leaf)
            if a.ndim == 1 and a.size >= 259:
                out.append(a)
        return out

    m8, m4 = flat_moments(sess8), flat_moments(sess4)
    assert len(m8) == len(m4) >= 2        # adam mu + nu at least
    for a8, a4 in zip(m8, m4):
        assert a8.shape == (264,) and a4.shape == (260,)
        np.testing.assert_array_equal(a8[:259], a4[:259])
        np.testing.assert_array_equal(a4[259:], 0)

    # and training continues: the resumed session tracks the donor run
    l8 = [float(sess8.run(batch)["loss"]) for _ in range(3)]
    l4 = [float(sess4.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(l4, l8, rtol=1e-5)


def test_elastic_restore_rejects_bucket_drift(tmp_path):
    """Changed bucket config between save and resume -> a clear error,
    never a silently-wrong reshard."""
    import optax

    from autodist_tpu.autodist import (
        AutoDist, _reset_default_autodist_for_testing)
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.resilience import ElasticResumeError
    from autodist_tpu.strategy import Zero1

    sess8, batch = _zero1_session(8)
    sess8.run(batch)
    path = Saver(sess8).save(str(tmp_path / "ck"))

    # rebuild with a tiny bucket cap: same vars, different bucket split
    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(3)
    import jax.numpy as jnp
    params = {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32),
              "b": jnp.zeros(3, jnp.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w"])
        return jnp.mean((h[:, :3] + p["b"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=256))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn)
    sess_drift = ad.create_distributed_session(
        mesh=build_mesh({"data": 4}, devices=jax.devices()[:4]))
    assert len(sess_drift.zero1_buckets) > 1
    with pytest.raises(ElasticResumeError):
        Saver(sess_drift).restore(path)


def test_elastic_analysis_rules_and_cli(capsys):
    """elastic/axis-resize surfaced through the existing CLI, including
    the ring-degeneracy re-check on the shrunken axis."""
    from autodist_tpu.analysis.__main__ import main

    rc = main(["mlp", "Zero1", "--mesh", "data=4",
               "--elastic-from", "data=8", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    rules = {d["rule"] for d in out["diagnostics"]}
    assert "elastic/axis-resize" in rules
    assert "elastic/hbm-grows" in rules          # 8 -> 4 shrink
    info = [d for d in out["diagnostics"]
            if d["rule"] == "elastic/axis-resize"][0]
    assert "data=8 -> data=4" in info["message"]

    # growing the axis emits the resize INFO but no HBM warning
    rc = main(["mlp", "Zero1", "--mesh", "data=8",
               "--elastic-from", "data=4", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    rules = {d["rule"] for d in out["diagnostics"]}
    assert "elastic/axis-resize" in rules and "elastic/hbm-grows" not in rules

    # sync/ring-degenerate re-checked against the SHRUNKEN mesh: a ring
    # overlap request cannot survive a fall-through to data=1
    rc = main(["mlp", "Zero1", "--mesh", "data=1", "--overlap", "ring",
               "--elastic-from", "data=8", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(d["rule"] == "sync/ring-degenerate"
               for d in out["diagnostics"])
