"""Block allocator + prefix trie invariants (host-side, no device).

The paged serving stack's correctness rests on these: alloc/free/
refcount/COW bookkeeping, pool-exhaustion watermark behavior, and the
trie's hit/miss/LRU-eviction rules (only full blocks cache, a match
never covers the whole prompt, eviction only touches blocks no request
pins).
"""
import numpy as np
import pytest

from autodist_tpu.serving.paged_kv import (SCRATCH_BLOCK, BlockPool,
                                           BlockPoolExhausted, PrefixTrie)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# BlockPool
# ---------------------------------------------------------------------------

def test_pool_alloc_free_roundtrip():
    pool = BlockPool(num_blocks=9, block_size=4)
    assert pool.capacity == 8 and pool.free_count == 8
    blocks = pool.alloc(5)
    assert len(blocks) == len(set(blocks)) == 5
    assert SCRATCH_BLOCK not in blocks
    assert pool.free_count == 3 and pool.used_count == 5
    assert pool.occupancy() == pytest.approx(5 / 8)
    for b in blocks:
        assert pool.refcount(b) == 1
        assert pool.release(b)          # last ref -> freed
    assert pool.free_count == 8
    pool.verify()
    assert pool.stats.allocs == 5 and pool.stats.frees == 5
    assert pool.stats.high_water == 5


def test_pool_refcount_sharing():
    pool = BlockPool(num_blocks=5, block_size=4)
    (b,) = pool.alloc(1)
    pool.retain(b)                      # a second reader (trie or request)
    assert pool.refcount(b) == 2
    assert not pool.release(b)          # first release keeps it alive
    assert pool.refcount(b) == 1
    assert pool.release(b)              # last reader frees
    pool.verify()
    with pytest.raises(ValueError, match="double free"):
        pool.release(b)
    with pytest.raises(ValueError, match="unallocated"):
        pool.retain(b)


def test_pool_all_or_nothing_exhaustion():
    pool = BlockPool(num_blocks=4, block_size=2)     # capacity 3
    held = pool.alloc(2)
    free_before = pool.free_count
    with pytest.raises(BlockPoolExhausted, match="need 2 blocks"):
        pool.alloc(2)
    # failed alloc leaked nothing
    assert pool.free_count == free_before
    assert pool.stats.exhaustions == 1
    for b in held:
        pool.release(b)
    pool.verify()


def test_pool_cow_semantics():
    pool = BlockPool(num_blocks=6, block_size=4)
    (b,) = pool.alloc(1)
    # exclusively held: write in place, no copy
    same, copied = pool.cow(b)
    assert same == b and not copied
    # shared: the writer gets a fresh block, the shared one keeps the
    # other reader's reference
    pool.retain(b)
    fresh, copied = pool.cow(b)
    assert copied and fresh != b
    assert pool.refcount(b) == 1        # the other reader
    assert pool.refcount(fresh) == 1    # the writer
    assert pool.stats.cow_copies == 1
    pool.release(b)
    pool.release(fresh)
    pool.verify()


def test_pool_scratch_block_reserved():
    pool = BlockPool(num_blocks=3, block_size=2)
    blocks = pool.alloc(2)              # the whole capacity
    assert SCRATCH_BLOCK not in blocks
    with pytest.raises(ValueError, match="scratch"):
        pool.release(SCRATCH_BLOCK)
    for b in blocks:
        pool.release(b)


def test_pool_verify_catches_leak():
    pool = BlockPool(num_blocks=4, block_size=2)
    (b,) = pool.alloc(1)
    pool._refs[b] = 0                   # corrupt: held but refcount 0
    with pytest.raises(AssertionError, match="leaked"):
        pool.verify()


# ---------------------------------------------------------------------------
# PrefixTrie
# ---------------------------------------------------------------------------

def _tokens(*chunks):
    return np.concatenate([np.asarray(c, np.int32) for c in chunks])


def test_trie_insert_match_roundtrip():
    pool = BlockPool(num_blocks=20, block_size=4)
    trie = PrefixTrie(pool)
    prompt = np.arange(11, dtype=np.int32)          # 2 full blocks + 3
    table = pool.alloc(pool.blocks_for_tokens(11 + 4))
    assert trie.insert(prompt, table) == 2          # only full blocks
    assert len(trie) == 2
    # the cached blocks now carry the trie's reference too
    assert pool.refcount(table[0]) == 2
    assert pool.refcount(table[2]) == 1             # partial tail: not cached

    n, blocks = trie.match(prompt)
    assert n == 8 and blocks == table[:2]
    assert pool.refcount(table[0]) == 3             # +1 for the matcher
    for b in blocks:
        pool.release(b)
    # a diverging prompt matches only the shared prefix
    other = _tokens(np.arange(4), [9, 9, 9, 9], [1, 2])
    n, blocks = trie.match(other)
    assert n == 4 and blocks == table[:1]
    pool.release(blocks[0])
    assert trie.stats.lookup_hits == 2
    # miss: nothing cached under a different first block
    n, blocks = trie.match(np.full(9, 7, np.int32))
    assert n == 0 and blocks == []
    for b in table:
        pool.release(b)
    pool.verify()


def test_trie_match_never_covers_whole_prompt():
    """A block-aligned fully-cached prompt still leaves >= 1 suffix
    token to prefill (the program needs a position to sample from, and
    it keeps every write off shared blocks)."""
    pool = BlockPool(num_blocks=20, block_size=4)
    trie = PrefixTrie(pool)
    prompt = np.arange(8, dtype=np.int32)           # exactly 2 blocks
    table = pool.alloc(3)
    trie.insert(prompt, table)
    assert len(trie) == 1                           # (8-1)//4 = 1 block
    n, blocks = trie.match(prompt)
    assert n == 4                                   # never 8
    pool.release(blocks[0])
    for b in table:
        pool.release(b)


def test_trie_lru_eviction_skips_pinned():
    pool = BlockPool(num_blocks=8, block_size=2)    # capacity 7
    trie = PrefixTrie(pool)
    # two cached chains of 2 blocks each (prompts of 5 tokens)
    t1 = pool.alloc(3)
    trie.insert(np.arange(5, dtype=np.int32), t1)
    t2 = pool.alloc(3)
    trie.insert(np.arange(10, 15, dtype=np.int32), t2)
    for b in t1 + t2:                               # requests finished
        pool.release(b)
    assert pool.used_count == 4 and len(trie) == 4
    # chain 1 is older; pin its blocks as an in-flight reader would
    n, pinned = trie.match(np.arange(5, dtype=np.int32))
    assert n == 4
    # evicting 4 can only take chain 2 (leaf-first) — chain 1 is pinned
    freed = trie.evict(4)
    assert freed == 2
    assert trie.stats.evictions == 2
    for b in pinned:
        assert pool.refcount(b) >= 1                # still alive
        pool.release(b)
    # unpinned now: leaf-first eviction clears the rest
    assert trie.evict(4) == 2
    assert len(trie) == 0
    pool.verify()
    assert pool.used_count == 0


def test_trie_lru_order():
    pool = BlockPool(num_blocks=10, block_size=2)
    trie = PrefixTrie(pool)
    a = pool.alloc(2)
    trie.insert(np.arange(3, dtype=np.int32), a)          # chain A
    b = pool.alloc(2)
    trie.insert(np.arange(10, 13, dtype=np.int32), b)     # chain B
    for blk in a + b:
        pool.release(blk)
    # touch A so B becomes LRU
    n, pinned = trie.match(np.arange(3, dtype=np.int32))
    for blk in pinned:
        pool.release(blk)
    assert trie.evict(1) == 1
    # B's block went; A still matches
    n, pinned = trie.match(np.arange(3, dtype=np.int32))
    assert n == 2
    for blk in pinned:
        pool.release(blk)
    n, none = trie.match(np.arange(10, 13, dtype=np.int32))
    assert n == 0 and none == []


def test_trie_duplicate_insert_first_writer_wins():
    pool = BlockPool(num_blocks=10, block_size=2)
    trie = PrefixTrie(pool)
    prompt = np.arange(5, dtype=np.int32)
    t1 = pool.alloc(3)
    assert trie.insert(prompt, t1) == 2
    t2 = pool.alloc(3)
    assert trie.insert(prompt, t2) == 0             # already cached
    # t2's blocks stay exclusively owned and free with their request
    for b in t2:
        assert pool.refcount(b) == 1
        pool.release(b)
    for b in t1:
        pool.release(b)
    assert pool.used_count == 2                     # the cached chain
    trie.clear()
    pool.verify()
    assert pool.used_count == 0
