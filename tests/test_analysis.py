"""Static strategy analyzer ("shardlint") golden-diagnostic tests:
legality + sync-coverage rules, the shipped-builder cleanliness
regression, and the pre-flight ``validate=`` hooks.

Each rule gets a golden case: a legal plan analyzes clean, and each
deliberately broken plan yields EXACTLY the expected ERROR with the
right rule id — the analyzer's whole value is that its verdicts are
precise enough to gate builds on.  The memory, collectives, and
precision passes have their own files (test_analysis_memory.py,
test_analysis_collectives.py, test_analysis_precision.py); the CLI is
covered in test_analysis_cli.py.
"""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.analysis import (
    StrategyValidationError,
    analyze,
    preflight,
)
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.mesh import build_mesh
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    AutoStrategy,
    Parallax,
    PartitionedAR,
    PartitionedPS,
    PS,
    PSLoadBalancing,
    RandomAxisPartitionAR,
    StrategyCompiler,
    UnevenPartitionedPS,
)
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    PSSynchronizerConfig,
    Strategy,
    VarConfig,
)

from _analysis_fixtures import (
    AXES8,
    ar_node,
    full_cover,
    make_gi,
    make_spec8,
    ps_node,
)

pytestmark = pytest.mark.analysis


@pytest.fixture
def gi():
    return make_gi()


@pytest.fixture
def spec8():
    return make_spec8()


# -- legality ----------------------------------------------------------------

def test_legal_plan_has_no_errors(gi):
    report = analyze(full_cover(gi), gi, mesh=AXES8)
    assert not report.has_errors()
    assert not report.warnings


def test_indivisible_partition_is_exactly_one_error():
    gi2 = GraphItem({"w": jnp.zeros((3, 4))})
    s = Strategy(node_config=[ps_node("w", partitioner="3,1")])
    report = analyze(s, gi2, mesh=AXES8)
    errors = report.errors
    assert len(errors) == 1
    assert errors[0].rule == "legality/indivisible-partition"
    assert errors[0].var_name == "w"


def test_padded_partition_is_info_not_error():
    # dim 12 over 8 pads to 16 < 2*12: covered by pad_plans.
    gi2 = GraphItem({"w": jnp.zeros((12, 4))})
    s = Strategy(node_config=[ps_node("w", partitioner="12,1")])
    report = analyze(s, gi2, mesh=AXES8)
    assert not report.has_errors()
    assert report.by_rule("legality/padded-partition")


def test_invalid_partitioner_axis(gi):
    s = full_cover(gi, but=["dense/bias"],
                   extra=[ps_node("dense/bias", partitioner="1,1,4")])
    report = analyze(s, gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == ["legality/invalid-partitioner"]


def test_multi_active_axis_partitioner(gi):
    s = full_cover(gi, but=["dense/kernel"],
                   extra=[ps_node("dense/kernel", partitioner="2,2")])
    report = analyze(s, gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == ["legality/invalid-partitioner"]


def test_ar_partitioner_on_dp_mesh_is_info(gi):
    s = full_cover(gi, but=["dense/kernel"],
                   extra=[VarConfig(
                       "dense/kernel",
                       synchronizer=AllReduceSynchronizerConfig(),
                       partitioner="16,1")])
    report = analyze(s, gi, mesh=AXES8)
    assert not report.has_errors()
    assert report.by_rule("legality/ar-partition-colocated")


def test_structural_axis_claim_warns():
    gi = GraphItem({"stages": jnp.zeros((4, 8, 8))},
                   pipeline_vars=["stages"])
    s = Strategy(node_config=[ps_node("stages", partitioner="4,1,1")])
    report = analyze(s, gi, mesh={"pipe": 4, "data": 2})
    assert any(d.rule == "legality/structural-axis-claimed"
               for d in report.warnings)


def test_compiled_unknown_axis_and_duplicate_axis(gi, spec8):
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(AXES8)
    compiled = StrategyCompiler(mesh).compile(
        AllReduce().build(gi, spec8), gi)
    compiled.var_plans["dense/kernel"].param_spec = P("model")   # unknown
    compiled.var_plans["emb/table"].param_spec = P("data", "data")  # dup
    report = analyze(compiled, gi)
    rules = {d.rule for d in report.errors}
    assert "legality/unknown-mesh-axis" in rules
    assert "legality/duplicate-mesh-axis" in rules


def test_batch_indivisible_warns(gi):
    report = analyze(full_cover(gi), gi, mesh=AXES8,
                     batch={"x": np.zeros((10, 4), np.float32)})
    assert report.by_rule("legality/batch-indivisible")
    assert not report.has_errors()


def test_mesh_hint_mismatch_warns(gi):
    s = full_cover(gi)
    s.graph_config.mesh_axes = {"model": 2}
    report = analyze(s, gi, mesh=AXES8)
    assert any(d.rule == "legality/mesh-hint-mismatch"
               for d in report.warnings)


# -- sync coverage -----------------------------------------------------------

def test_unsynced_trainable_is_exactly_one_error(gi):
    report = analyze(full_cover(gi, but=["dense/bias"]), gi, mesh=AXES8)
    errors = report.errors
    assert len(errors) == 1
    assert errors[0].rule == "sync/unsynced-trainable"
    assert errors[0].var_name == "dense/bias"


def test_shadowed_node_is_error(gi):
    report = analyze(full_cover(gi, extra=[ar_node("dense/kernel")]),
                     gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == ["sync/shadowed-node"]


def test_dead_node_warns(gi):
    report = analyze(full_cover(gi, extra=[ar_node("no/such/var")]),
                     gi, mesh=AXES8)
    assert not report.has_errors()
    assert [d.rule for d in report.warnings] == ["sync/dead-node"]


def test_frozen_var_synced_warns():
    gi = GraphItem({"w": jnp.zeros((8,)), "frozen": jnp.zeros((8,))},
                   untrainable_vars=["frozen"])
    s = Strategy(node_config=[ar_node("w"), ar_node("frozen")])
    report = analyze(s, gi, mesh=AXES8)
    assert not report.has_errors()
    assert [d.rule for d in report.warnings] == ["sync/frozen-var-synced"]


def test_missing_synchronizer_is_error(gi):
    report = analyze(
        full_cover(gi, but=["dense/bias"],
                   extra=[VarConfig("dense/bias")]), gi, mesh=AXES8)
    assert [d.rule for d in report.errors] == ["sync/missing-synchronizer"]


# -- builder regression ------------------------------------------------------

ALL_BUILDERS = [AllReduce, AutoStrategy, Parallax, PartitionedAR,
                PartitionedPS, PS, PSLoadBalancing, RandomAxisPartitionAR,
                UnevenPartitionedPS]


@pytest.mark.parametrize("builder_cls", ALL_BUILDERS,
                         ids=[b.__name__ for b in ALL_BUILDERS])
def test_every_builder_is_analyzer_clean(builder_cls, gi, spec8):
    """Every shipped strategy builder produces a plan with no ERROR and
    no WARN diagnostics on the virtual 8-device mesh — raw and
    compiled."""
    strategy = builder_cls().build(gi, spec8)
    report = analyze(strategy, gi, mesh=AXES8, resource_spec=spec8)
    assert not report.has_errors(), report.format_table()
    assert not report.warnings, report.format_table()

    mesh = build_mesh(AXES8)
    compiled = StrategyCompiler(mesh, resource_spec=spec8).compile(
        strategy, gi)
    report2 = analyze(compiled, gi, resource_spec=spec8)
    assert not report2.has_errors(), report2.format_table()
    assert not report2.warnings, report2.format_table()


# -- pre-flight hooks --------------------------------------------------------

class _IllegalBuilder(PS):
    """Deliberately illegal: a (3, 4) var partitioned 3-ways lowers to an
    indivisible (and pad-unworthy) shard over the 8-wide data axis."""

    def build(self, graph_item, resource_spec):
        nodes = [ps_node("w", partitioner="3,1")]
        if any(v.name == "b" for v in graph_item.trainable_var_infos):
            nodes.append(ar_node("b"))
        return Strategy(node_config=nodes)


def test_preflight_raises_with_full_report(gi):
    s = full_cover(gi, but=["dense/bias"])
    with pytest.raises(StrategyValidationError) as exc:
        preflight(s, gi, mesh=AXES8)
    assert "sync/unsynced-trainable" in str(exc.value)
    assert exc.value.report.has_errors()


def test_create_distributed_session_validate_raises(monkeypatch, spec8):
    """`validate=True` rejects an illegal plan BEFORE the step exists."""
    monkeypatch.setenv("AUTODIST_IS_TESTING", "1")
    from autodist_tpu.autodist import AutoDist

    params = {"w": jnp.zeros((3, 4)), "b": jnp.zeros((4,))}
    ad = AutoDist(strategy_builder=_IllegalBuilder(), resource_spec=spec8)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=lambda p, b: jnp.sum(p["w"]) * 0.0)
    with pytest.raises(StrategyValidationError) as exc:
        ad.create_distributed_session(validate=True)
    assert "legality/indivisible-partition" in str(exc.value)


def test_fit_validate_raises_before_training(monkeypatch, spec8):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "1")
    from autodist_tpu.autodist import AutoDist

    params = {"w": jnp.zeros((3, 4)), "b": jnp.zeros((4,))}
    ad = AutoDist(strategy_builder=_IllegalBuilder(), resource_spec=spec8)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=lambda p, b: jnp.mean(p["w"]) * jnp.mean(b["x"]))
    sess = ad.create_distributed_session()  # builds; lazy step untraced
    batch = {"x": np.ones((8,), np.float32)}
    with pytest.raises(StrategyValidationError):
        sess.fit(batch, epochs=1, steps_per_epoch=1, validate=True)
    # without validate the same session trains
    hist = sess.fit(batch, epochs=1, steps_per_epoch=1)
    assert hist.steps_run == 1


def test_valid_session_passes_validate(spec8, monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "1")
    from autodist_tpu.autodist import AutoDist

    params = {"w": jnp.zeros((16, 8)), "b": jnp.zeros((8,))}
    ad = AutoDist(strategy_builder=AllReduce(), resource_spec=spec8)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=lambda p, b: jnp.mean(p["w"]) * jnp.mean(b["x"]))
    sess = ad.create_distributed_session(validate=True)
    assert sess is not None


def test_validate_env_knob(monkeypatch, spec8):
    """AUTODIST_VALIDATE=1 turns the pre-flight on without code change."""
    monkeypatch.setenv("AUTODIST_IS_TESTING", "1")
    monkeypatch.setenv("AUTODIST_VALIDATE", "1")
    from autodist_tpu.autodist import AutoDist

    params = {"w": jnp.zeros((3, 4)), "b": jnp.zeros((4,))}
    ad = AutoDist(strategy_builder=_IllegalBuilder(), resource_spec=spec8)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=lambda p, b: jnp.sum(p["w"]) * 0.0)
    with pytest.raises(StrategyValidationError):
        ad.create_distributed_session()


# -- auto-strategy pruning ---------------------------------------------------

def test_search_prunes_illegal_candidates(spec8):
    params = {"w": jnp.zeros((3, 4)), "b": jnp.zeros((4,))}
    gi = GraphItem(params, optimizer=optax.sgd(0.1))

    auto = AutoStrategy(search=True,
                        candidates=[_IllegalBuilder(), AllReduce()])
    strategy = auto.build(gi, spec8)
    assert auto.last_choice == "AllReduce"
    report = analyze(strategy, gi, resource_spec=spec8)
    assert not report.has_errors()


def test_search_all_illegal_raises(spec8):
    params = {"w": jnp.zeros((3, 4))}
    gi = GraphItem(params, optimizer=optax.sgd(0.1))

    auto = AutoStrategy(search=True, candidates=[_IllegalBuilder()])
    with pytest.raises(StrategyValidationError):
        auto.build(gi, spec8)
