"""ZeRO-1 (reduce-scatter weight-update sharding): numerics, memory,
cost model, and analysis integration.

The acceptance contract of the PR issue: bucketed + ZeRO-1 sync is
numerically equivalent to the per-variable path on the CPU mesh, the
reduce leg moves strictly fewer bytes than all-reduce mode on >= 2
replicas, and the analysis memory report counts optimizer-state
bytes/device at 1/data-parallel-factor.
"""
import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce, AutoStrategy, Zero1
from autodist_tpu.strategy.cost_model import (
    all_gather_bytes,
    allreduce_bytes,
    estimate_cost,
    reduce_scatter_bytes,
)

pytestmark = pytest.mark.sync


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(32, 48) * 0.1, jnp.float32),
               "b": jnp.zeros(48, jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(48, 4) * 0.1, jnp.float32)},
    }
    batch = {"x": rng.randn(16, 32).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"] + p["l1"]["b"])
        return jnp.mean((h @ p["l2"]["w"] - b["y"]) ** 2)

    return params, loss_fn, batch


def _session(builder, params, loss_fn, opt=None, **capture_kw):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn, **capture_kw)
    return ad.create_distributed_session()


def _device_bytes(tree):
    """Per-device resident bytes of a sharded pytree (one shard per leaf)."""
    tot = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = leaf.addressable_shards[0]
        tot += sh.data.size * sh.data.dtype.itemsize
    return tot


def test_zero1_matches_per_variable_numerics():
    params, loss_fn, batch = _problem()
    ref = _session(AllReduce(), params, loss_fn)
    z = _session(Zero1(), params, loss_fn)
    for _ in range(8):
        np.testing.assert_allclose(float(z.run(batch)["loss"]),
                                   float(ref.run(batch)["loss"]),
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(z.params["l1"]["w"]),
                               np.asarray(ref.params["l1"]["w"]),
                               rtol=1e-6, atol=1e-7)


def test_zero1_emits_reduce_scatter_and_all_gather():
    params, loss_fn, batch = _problem()
    z = _session(Zero1(), params, loss_fn)
    b = z.place_batch(batch)
    txt = z._step.step_fn.lower(z.sharded_params, z.opt_state,
                                z.sync_state, b).as_text()
    assert txt.count("stablehlo.reduce_scatter") >= 1
    assert txt.count("stablehlo.all_gather") >= 1


def test_zero1_shards_optimizer_state_by_dp_factor():
    params, loss_fn, batch = _problem()
    d = jax.device_count()
    assert d >= 2
    ref = _session(AllReduce(), params, loss_fn)
    z = _session(Zero1(), params, loss_fn)
    a, b = _device_bytes(ref.opt_state), _device_bytes(z.opt_state)
    # mu+nu shard 1/d; adam's count scalar stays replicated.
    assert b < a / (d / 1.5), (a, b, d)


def test_zero1_composes_with_bf16_moments():
    """cast_opt_state x ZeRO-1 multiply: ~1/(2d) of replicated f32."""
    from autodist_tpu.ops.opt_state_dtype import cast_opt_state

    params, loss_fn, batch = _problem()
    z32 = _session(Zero1(), params, loss_fn, opt=optax.adam(1e-2))
    z16 = _session(Zero1(), params, loss_fn,
                   opt=cast_opt_state(optax.adam(1e-2)))
    b32, b16 = _device_bytes(z32.opt_state), _device_bytes(z16.opt_state)
    assert b16 < 0.7 * b32, (b32, b16)
    losses = [float(z16.run(batch)["loss"]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.2


def test_zero1_frozen_vars_stay_out():
    params, loss_fn, batch = _problem()
    params = dict(params, scale={"s": jnp.ones((3,), jnp.float32)})
    ref = _session(AllReduce(), params, loss_fn,
                   untrainable_vars=("scale",))
    z = _session(Zero1(), params, loss_fn, untrainable_vars=("scale",))
    for _ in range(4):
        np.testing.assert_allclose(float(z.run(batch)["loss"]),
                                   float(ref.run(batch)["loss"]),
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(z.params["scale"]["s"]), 1.0)


def test_zero1_checkpoint_style_export_import_round_trip():
    params, loss_fn, batch = _problem()
    z = _session(Zero1(), params, loss_fn)
    for _ in range(3):
        z.run(batch)
    # host copies: the donated step buffers must not alias the export
    p, o = jax.tree_util.tree_map(np.asarray, z.export_state())
    step_loss = float(z.run(batch)["loss"])
    z2 = _session(Zero1(), params, loss_fn)
    z2.import_state(p, o)
    np.testing.assert_allclose(float(z2.run(batch)["loss"]), step_loss,
                               rtol=1e-6)


# -- cost model --------------------------------------------------------------

def test_collective_byte_helpers():
    assert allreduce_bytes(100.0, 8) == pytest.approx(2 * (7 / 8) * 100)
    assert reduce_scatter_bytes(100.0, 8) == pytest.approx((7 / 8) * 100)
    assert all_gather_bytes(100.0, 8) == pytest.approx((7 / 8) * 100)
    assert allreduce_bytes(100.0, 8) == pytest.approx(
        reduce_scatter_bytes(100.0, 8) + all_gather_bytes(100.0, 8))
    # d = 1: no traffic at all
    for f in (allreduce_bytes, reduce_scatter_bytes, all_gather_bytes):
        assert f(100.0, 1) == 0.0


def _dense_gi():
    return GraphItem({"w": jnp.zeros((1024, 1024), jnp.float32),
                      "b": jnp.zeros((1024,), jnp.float32)})


def _spec8():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})


def test_cost_model_prices_zero1_reduce_leg_at_half():
    gi, spec = _dense_gi(), _spec8()
    ar = estimate_cost(AllReduce().build(gi, spec), gi, spec)
    z = estimate_cost(Zero1().build(gi, spec), gi, spec)
    zc = [v for v in z.per_var if v.name == "w"][0]
    nbytes = 1024 * 1024 * 4
    # RS leg on grads + AG leg on params: same total wire as all-reduce
    # for uncompressed f32 — the wire TIE is the point; the win is state.
    assert zc.sync == "zero1"
    assert zc.wire_bytes == pytest.approx(
        reduce_scatter_bytes(nbytes, 8) + all_gather_bytes(nbytes, 8))
    assert z.wire_bytes == pytest.approx(ar.wire_bytes)
    # optimizer slots and update traffic shard 1/8
    assert z.opt_state_bytes == pytest.approx(ar.opt_state_bytes / 8)
    assert z.update_bytes == pytest.approx(ar.update_bytes / 8)
    # the sharded update makes ZeRO-1 rank faster on a big dense model
    assert z.time_s < ar.time_s


def test_compressed_zero1_halves_only_reduce_leg():
    gi, spec = _dense_gi(), _spec8()
    z = estimate_cost(Zero1(compressor="HorovodCompressor").build(gi, spec),
                      gi, spec)
    zc = [v for v in z.per_var if v.name == "w"][0]
    nbytes = 1024 * 1024 * 4
    assert zc.wire_bytes == pytest.approx(
        reduce_scatter_bytes(nbytes * 0.5, 8) + all_gather_bytes(nbytes, 8))


def test_auto_strategy_search_picks_zero1_on_dense_model():
    gi, spec = _dense_gi(), _spec8()
    searcher = AutoStrategy(search=True,
                            candidates=[AllReduce(), Zero1()])
    strategy = searcher.build(gi, spec)
    assert searcher.last_choice == "Zero1"
    sync = strategy.node_for("w").synchronizer
    assert sync.sync == "reduce_scatter"


def test_zero1_config_round_trips_through_ir():
    from autodist_tpu.strategy.base import Strategy

    gi, spec = _dense_gi(), _spec8()
    s = Zero1(bucket_bytes=1 << 20).build(gi, spec)
    s.serialize()
    s2 = Strategy.deserialize(s.id)
    sync = s2.node_config[0].synchronizer
    assert sync.sync == "reduce_scatter"
    assert sync.bucket_bytes == 1 << 20


# -- analysis ----------------------------------------------------------------

def test_memory_pass_counts_sharded_optimizer_state():
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import memory as _mem

    gi = GraphItem({"w": jnp.zeros((64, 64), jnp.float32)},
                   optimizer=optax.adam(1e-3))
    spec = _spec8()

    def opt_bytes(builder):
        ctx = _an.AnalysisContext(strategy=builder.build(gi, spec),
                                  graph_item=gi, axes={"data": 8})
        _an.PASS_REGISTRY["legality"](ctx)
        return _mem._opt_state_bytes(ctx)

    rep = opt_bytes(AllReduce())
    z = opt_bytes(Zero1())
    # mu+nu divided by 8; the count scalar stays whole.
    assert z < rep / 4, (rep, z)


def test_zero1_unused_warn_fires_near_budget():
    from autodist_tpu.analysis import analyze

    gi = GraphItem({"w": jnp.zeros((1024, 1024), jnp.float32)},
                   optimizer=optax.adam(1e-3))
    probe = analyze(AllReduce().build(gi, _spec8()), gi, mesh={"data": 8})
    msg = probe.by_rule("memory/hbm-breakdown")[0].message
    total = float(msg.split("≈")[1].split("MiB")[0]) * (1 << 20)
    report = analyze(AllReduce().build(gi, _spec8()), gi, mesh={"data": 8},
                     budget_bytes=int(total / 0.95))
    assert report.by_rule("memory/zero1-unused")
    # ...and stays quiet when ZeRO-1 is already in use
    report_z = analyze(Zero1().build(gi, _spec8()), gi, mesh={"data": 8},
                       budget_bytes=int(total / 0.95))
    assert not report_z.by_rule("memory/zero1-unused")


def test_zero1_fallback_warn_on_partitioned_var():
    from autodist_tpu.analysis import analyze
    from autodist_tpu.strategy.base import (
        AllReduceSynchronizerConfig,
        Strategy,
        VarConfig,
    )

    gi = GraphItem({"w": jnp.zeros((64, 64), jnp.float32)})
    s = Strategy(node_config=[VarConfig(
        "w", synchronizer=AllReduceSynchronizerConfig(
            sync="reduce_scatter"),
        partitioner="4,1")])
    report = analyze(s, gi, mesh={"data": 2, "model": 4})
    assert report.by_rule("legality/zero1-fallback")


def test_analysis_cli_smoke_on_zero1_plan():
    """`python -m autodist_tpu.analysis mlp Zero1 --mesh data=8` exits 0
    and renders the diagnostics table (the CLI acceptance check)."""
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "mlp", "Zero1",
         "--mesh", "data=8"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "memory/hbm-breakdown" in proc.stdout


def test_runtime_zero1_fallback_keeps_training(caplog):
    """A PowerSGD-compressed var cannot join a flat bucket: ZeRO-1 falls
    back per-variable (warned) but the session still trains."""
    params, loss_fn, batch = _problem()
    z = _session(Zero1(compressor="PowerSGDCompressor"), params, loss_fn,
                 opt=optax.sgd(0.1))
    losses = [float(z.run(batch)["loss"]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, losses
