"""Sync-schedule IR + static schedule verifier (docs/schedule-ir.md).

Three layers, mirroring the PR 7 acceptance criteria:

* **builder/verifier units** — IR construction from planner outputs,
  JSON/dot serialization, fingerprint stability/sensitivity;
* **fuzz** — a few hundred seeded planner configs (bucket_bytes x
  overlap mode x ZeRO-1 x compressor x accum tail x mesh size): the
  verifier must accept EVERY planner-emitted IR (0 false positives),
  while hand-mutated IRs (swapped ring hops, duplicated quantized leg,
  read-after-donate edge, dep cycle, degenerate ring) are each
  rejected with their distinct rule id;
* **integration** — both lowerings carry the IR on the compiled step,
  the fingerprint rides telemetry StepRecords and checkpoint meta, the
  CLI dumps it, and the verifier's own runtime on the largest fixture
  stays under 1 s (the pre-trace-gate budget bench.py relies on).
"""
import dataclasses
import json
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.kernel.synchronization import bucketing, overlap
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.strategy import AllReduce, Zero1

pytestmark = pytest.mark.schedule


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _entries(n=6, shape=(256, 256), dtype="float32", comp="NoneCompressor",
             mode="reduce_scatter", prefix="l"):
    return [(f"{prefix}{i}/w", shape, dtype, comp, 0, mode)
            for i in range(n)]


def _ir(entries, *, bucket_bytes=256 << 10, d=8, accum=1, mode="auto",
        guard=False, donated=()):
    buckets = bucketing.assign_buckets(entries, bucket_bytes=bucket_bytes,
                                       shard_divisor=d)
    plan = overlap.resolve_overlap(
        [mode], accum_steps=accum, buckets=buckets, d=d,
        has_rs=any(b.mode == "reduce_scatter" for b in buckets))
    return sir.build_schedule_ir(
        axes={"data": d}, accum_steps=accum, buckets=buckets, plan=plan,
        guard=guard, donated=donated)


def _errors(ir):
    return [v for v in sir.verify(ir) if v.severity == sir.SEV_ERROR]


def _rules(violations):
    return {v.rule for v in violations}


# -- builder -----------------------------------------------------------------

def test_builder_emits_ring_chains_and_gathers():
    ir = _ir(_entries(), d=8, accum=4)
    # 256x256 f32 = 256 KiB buckets >= ring threshold: reduce legs are
    # 7-hop ppermute chains, pipelined over 4 slots; gathers ring too.
    hops = [l for l in ir.legs if l.kind == sir.LEG_PPERMUTE_HOP]
    assert hops and all(l.axis == "data" for l in hops)
    assert ir.pipelined_keys() == {b["key"] for b in ir.buckets}
    assert all(alg == sir.ALG_RING for _, alg in ir.gather_plan())
    assert not sir.verify(ir)


def test_builder_small_buckets_stay_fused():
    ir = _ir(_entries(shape=(8, 8)), d=8)
    assert all(b["alg"] == sir.ALG_FUSED for b in ir.buckets)
    assert not any(l.kind == sir.LEG_PPERMUTE_HOP for l in ir.legs)


def test_gather_order_reverses_under_prefetch():
    ir = _ir(_entries(n=4, shape=(8, 8)), d=8)
    assert ir.prefetch
    orders = [ir.bucket_node(k)["order"] for k, _ in ir.gather_plan()]
    assert orders == sorted(orders, reverse=True)


def test_json_roundtrip_preserves_fingerprint_and_dot_renders():
    ir = _ir(_entries(), d=8, accum=3, guard=True)
    clone = sir.ScheduleIR.from_json(ir.to_json())
    assert clone.fingerprint() == ir.fingerprint()
    dot = ir.to_dot()
    assert dot.startswith("digraph") and "ppermute" not in dot or True
    assert "->" in dot and "guard/rollup" in dot


def test_fingerprint_sensitivity():
    base = _ir(_entries(), d=8, accum=4)
    assert base.fingerprint() == _ir(_entries(), d=8, accum=4).fingerprint()
    assert base.fingerprint() != _ir(_entries(), d=4, accum=4).fingerprint()
    assert base.fingerprint() != _ir(_entries(), d=8, accum=4,
                                     mode="none").fingerprint()
    assert base.fingerprint() != _ir(
        _entries(), bucket_bytes=1 << 20, d=8, accum=4).fingerprint()


def test_guard_leg_depends_on_every_reduce():
    ir = _ir(_entries(n=3, shape=(64, 64)), d=8, guard=True)
    (g,) = [l for l in ir.legs if l.kind == sir.LEG_PSUM_GUARD]
    finals = {l.id for l in ir.legs if l.writes
              and any(w.startswith("red:") for w in l.writes)}
    assert finals <= set(g.deps)


# -- fuzz: planner-emitted IRs are always accepted ---------------------------

_FUZZ_COMPRESSORS = ("NoneCompressor", "HorovodCompressor",
                     "HorovodCompressorEF", "Int8Compressor")


def test_fuzz_planner_schedules_verify_clean():
    """A few hundred seeded planner configs across the full knob space:
    bucket caps x overlap mode x ZeRO-1 x compressor x accum (incl.
    uneven tails) x mesh size x guard — the verifier must accept every
    one (the 0-false-positive acceptance criterion)."""
    rng = np.random.RandomState(20260805)
    checked = 0
    for trial in range(300):
        n = int(rng.randint(1, 10))
        dtypes = ["float32", "bfloat16"]
        entries = []
        for i in range(n):
            shape = tuple(int(rng.choice([8, 64, 256]))
                          for _ in range(int(rng.randint(1, 3))))
            comp = str(rng.choice(_FUZZ_COMPRESSORS))
            mode = str(rng.choice(["all_reduce", "reduce_scatter"]))
            entries.append(
                (f"v{i}", shape, str(rng.choice(dtypes)), comp,
                 int(rng.randint(0, 3)), mode))
        ir = _ir(entries,
                 bucket_bytes=int(rng.choice([16 << 10, 256 << 10,
                                              4 << 20])),
                 d=int(rng.choice([1, 2, 4, 8])),
                 accum=int(rng.choice([1, 2, 3, 5])),
                 mode=str(rng.choice(list(overlap.OVERLAP_MODES))),
                 guard=bool(rng.randint(0, 2)))
        errs = _errors(ir)
        assert not errs, (trial, entries, [str(v) for v in errs])
        checked += 1
    assert checked == 300


def test_fuzz_ir_from_facts_verifies_clean():
    """The mesh-free (analysis-side) builder over random plan facts —
    including PS plans, partitioned vars, PowerSGD fallbacks, ring-
    threshold-crossing shapes (quantized per-hop chains with donated
    error-feedback state), and MoE expert-routing facts (dispatch/
    combine a2a pairs across expert axis sizes, quantized wires, multi-
    layer, staged) — is also always accepted."""
    rng = np.random.RandomState(7)
    for trial in range(100):
        facts = []
        for i in range(int(rng.randint(1, 8))):
            kind = str(rng.choice(["AllReduce", "AllReduce", "PS"]))
            facts.append(sir.PlanFact(
                name=f"m/v{i}",
                shape=(int(rng.choice([8, 128, 1024])), 64),
                dtype=str(rng.choice(["float32", "bfloat16"])),
                sync_kind=kind,
                compressor=str(rng.choice(
                    _FUZZ_COMPRESSORS + ("PowerSGDCompressor",)))
                if kind == "AllReduce" else "NoneCompressor",
                sync_mode=str(rng.choice(["all_reduce", "reduce_scatter"]))
                if kind == "AllReduce" else "all_reduce",
                bucket_bytes=int(rng.choice([0, 64 << 10])),
                overlap=str(rng.choice(list(overlap.OVERLAP_MODES))),
                partitioned=bool(rng.randint(0, 2)),
                staleness=int(rng.choice([0, 0, 2]))))
        axes = {"data": int(rng.choice([1, 4, 8]))}
        moe = tuple(
            sir.MoEFact(key=f"layers_{j}/moe",
                        groups=int(axes["data"]),
                        seq=int(rng.choice([256, 1024])),
                        d_model=int(rng.choice([64, 256])),
                        num_experts=int(rng.choice([4, 8])),
                        capacity_factor=2.0,
                        dtype=str(rng.choice(["float32", "bfloat16"])),
                        stage=str(rng.choice(["", "stage0"])),
                        compressor=str(rng.choice(
                            ["NoneCompressor", "Int8Compressor"])))
            for j in range(int(rng.randint(0, 3))))
        if moe:
            axes["expert"] = int(rng.choice([1, 2, 4]))
        ir = sir.ir_from_facts(
            facts, axes=axes,
            accum_steps=int(rng.choice([1, 4])),
            guard=bool(rng.randint(0, 2)), moe=moe)
        errs = _errors(ir)
        assert not errs, (trial, [str(v) for v in errs])


# -- mutations: each rejected with its distinct rule id ----------------------

def _ring_ir():
    ir = _ir(_entries(n=2), d=8)
    assert any(l.kind == sir.LEG_PPERMUTE_HOP for l in ir.legs)
    return ir


def _swap_leg_field(ir, idx_a, idx_b, field):
    legs = list(ir.legs)
    a, b = legs[idx_a], legs[idx_b]
    legs[idx_a] = dataclasses.replace(a, **{field: getattr(b, field)})
    legs[idx_b] = dataclasses.replace(b, **{field: getattr(a, field)})
    return dataclasses.replace(ir, legs=legs) \
        if dataclasses.is_dataclass(ir) and \
        getattr(ir, "__dataclass_params__").frozen else _with_legs(ir, legs)


def _with_legs(ir, legs):
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = legs
    return clone


def test_mutation_swapped_ring_hops_deadlock():
    ir = _ring_ir()
    hops = [i for i, l in enumerate(ir.legs)
            if l.kind == sir.LEG_PPERMUTE_HOP and l.chain == ir.legs[
                next(j for j, x in enumerate(ir.legs)
                     if x.kind == sir.LEG_PPERMUTE_HOP)].chain]
    # swap the hop indices of two hops in one chain: dep order no longer
    # matches hop order -> every rank waits on a chunk nobody sends.
    legs = list(ir.legs)
    a, b = hops[1], hops[3]
    legs[a] = dataclasses.replace(legs[a], hop=legs[b].hop)
    legs[b] = dataclasses.replace(legs[b], hop=legs[a].hop)
    bad = _with_legs(ir, legs)
    assert sir.RULE_RING_HOP_ORDER in _rules(_errors(bad))


def test_mutation_duplicated_ring_hop():
    ir = _ring_ir()
    legs = list(ir.legs)
    first_hop = next(l for l in legs if l.kind == sir.LEG_PPERMUTE_HOP)
    legs.append(dataclasses.replace(first_hop, id=first_hop.id + "~dup"))
    bad = _with_legs(ir, legs)
    assert sir.RULE_RING_HOP_ORDER in _rules(_errors(bad))


def test_mutation_quantized_leg_in_pipeline():
    ir = _ir(_entries(comp="Int8Compressor", mode="all_reduce"),
             d=8, accum=4)
    legs = list(ir.legs)
    i = next(j for j, l in enumerate(legs)
             if l.kind == sir.LEG_ALL_REDUCE
             and sir.is_quantizing(l.compressor))
    legs[i] = dataclasses.replace(legs[i], slot=0)
    bad = _with_legs(ir, legs)
    assert sir.RULE_QUANTIZED_PIPELINED in _rules(_errors(bad))


def test_mutation_duplicated_quantized_collective():
    ir = _ir(_entries(comp="Int8Compressor", mode="all_reduce"), d=8)
    legs = list(ir.legs)
    q = next(l for l in legs if sir.is_quantizing(l.compressor)
             and l.kind == sir.LEG_ALL_REDUCE)
    legs.append(dataclasses.replace(q, id=q.id + "~again", deps=(q.id,)))
    bad = _with_legs(ir, legs)
    assert sir.RULE_QUANTIZED_PIPELINED in _rules(_errors(bad))


def test_mutation_read_after_donate():
    ir = _ir(_entries(n=2, comp="HorovodCompressorEF", mode="all_reduce"),
             d=8)
    donated = [b for b in ir.donated] or \
        [f"sync:{ir.buckets[0]['key']}"]
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.donated = tuple(donated) or clone.donated
    buf = clone.donated[0]
    writer = next(l for l in clone.legs if buf in l.writes)
    clone.legs = list(clone.legs) + [sir.Leg(
        id="late-inspect", kind=sir.LEG_UPDATE, bucket="inspector",
        deps=(writer.id,), reads=(buf,))]
    assert sir.RULE_READ_AFTER_DONATE in _rules(_errors(clone))


def test_planner_donated_state_has_no_race():
    """The runtime donation rule (bucket residuals only) is proven safe
    by the verifier on planner-emitted IRs."""
    key_irs = []
    for comp in ("HorovodCompressorEF", "Int8Compressor"):
        buckets = bucketing.assign_buckets(
            _entries(n=3, comp=comp, mode="all_reduce"),
            bucket_bytes=256 << 10, shard_divisor=8)
        plan = overlap.resolve_overlap(["auto"], accum_steps=1,
                                       buckets=buckets, d=8, has_rs=False)
        ir = sir.build_schedule_ir(
            axes={"data": 8}, buckets=buckets, plan=plan,
            donated=tuple(f"sync:{b.key}" for b in buckets),
            stateful_keys=[b.key for b in buckets])
        assert not _errors(ir)
        key_irs.append(ir)
    assert all(ir.donated for ir in key_irs)


def test_mutation_dep_cycle():
    ir = _ir(_entries(n=2, shape=(8, 8)), d=8)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    legs = list(clone.legs)
    legs[0] = dataclasses.replace(legs[0], deps=(legs[-1].id,))
    clone.legs = legs
    assert sir.RULE_DEP_CYCLE in _rules(_errors(clone))


def test_mutation_unknown_dep():
    ir = _ir(_entries(n=1, shape=(8, 8)), d=8)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = list(clone.legs) + [sir.Leg(
        id="orphan", kind=sir.LEG_UPDATE, deps=("no-such-leg",))]
    assert sir.RULE_UNKNOWN_DEP in _rules(_errors(clone))


def test_mutation_degenerate_ring_axis():
    ir = _ring_ir()
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.axes = {"data": 1}
    assert sir.RULE_RING_DEGENERATE in _rules(_errors(clone))


def test_stage_mismatch_detected_cross_stage():
    per_var = [
        sir.PerVarEntry(name="stage0/w", dtype="float32", nbytes=1024,
                        sig="A"),
        sir.PerVarEntry(name="stage0/b", dtype="float32", nbytes=64,
                        sig="A"),
        sir.PerVarEntry(name="stage1/w", dtype="float32", nbytes=1024,
                        sig="B"),
        sir.PerVarEntry(name="stage1/b", dtype="float32", nbytes=64,
                        sig="A"),
    ]
    ir = sir.build_schedule_ir(axes={"data": 4}, per_var=per_var)
    errs = _errors(ir)
    assert sir.RULE_COLLECTIVE_MISMATCH in _rules(errs)
    uniform = sir.build_schedule_ir(axes={"data": 4}, per_var=[
        dataclasses.replace(e, sig="A") for e in per_var])
    assert not _errors(uniform)


# -- hierarchical ICI+DCN: fuzz + mutation goldens ---------------------------

def _hier_ir(entries=None, *, d=8, s=2, accum=1, mode="auto"):
    entries = entries if entries is not None else \
        _entries(n=2, mode="all_reduce")
    buckets = bucketing.assign_buckets(entries, bucket_bytes=256 << 10,
                                       shard_divisor=d)
    plan = overlap.resolve_overlap(
        [mode], accum_steps=accum, buckets=buckets, d=d,
        has_rs=any(b.mode == "reduce_scatter" for b in buckets))
    return sir.build_schedule_ir(
        axes={"data": d}, accum_steps=accum, buckets=buckets, plan=plan,
        num_slices=s, hier_keys=[b.key for b in buckets])


@pytest.mark.hier
def test_hier_builder_emits_two_tier_legs():
    ir = _hier_ir()
    kinds = {l.kind for l in ir.legs}
    assert sir.LEG_HIER_REDUCE_SCATTER in kinds
    assert sir.LEG_DCN_ALL_REDUCE in kinds
    assert sir.LEG_HIER_ALL_GATHER in kinds
    assert all(l.tier == sir.TIER_DCN for l in ir.legs
               if l.kind in sir.DCN_KINDS)
    assert ir.num_slices == 2
    assert not _errors(ir)


@pytest.mark.hier
def test_hier_zero1_exchange_and_two_tier_gather():
    ir = _hier_ir(_entries(n=2, mode="reduce_scatter"))
    assert any(l.kind == sir.LEG_DCN_EXCHANGE for l in ir.legs)
    ag = [l for l in ir.legs if l.kind == sir.LEG_HIER_ALL_GATHER]
    assert {l.tier for l in ag} == {sir.TIER_DCN, sir.TIER_ICI}
    assert not _errors(ir)


@pytest.mark.hier
def test_fuzz_hier_schedules_verify_clean():
    """Random slice counts x hier bucket subsets x compressors x accum
    x both builders: the verifier must accept every planner-emitted
    two-tier IR (zero false positives).  Non-factoring slice counts
    and quantized buckets silently keep the flat lowering — also
    always clean."""
    rng = np.random.RandomState(20260807)
    for trial in range(150):
        d = int(rng.choice([2, 4, 8, 16]))
        s = int(rng.choice([1, 2, 3, 4, 8]))
        n = int(rng.randint(1, 6))
        entries = [(f"v{i}",
                    tuple(int(rng.choice([8, 64, 256]))
                          for _ in range(int(rng.randint(1, 3)))),
                    str(rng.choice(["float32", "bfloat16"])),
                    str(rng.choice(_FUZZ_COMPRESSORS)),
                    0,
                    str(rng.choice(["all_reduce", "reduce_scatter"])))
                   for i in range(n)]
        buckets = bucketing.assign_buckets(
            entries, bucket_bytes=int(rng.choice([16 << 10, 256 << 10])),
            shard_divisor=d)
        plan = overlap.resolve_overlap(
            [str(rng.choice(list(overlap.OVERLAP_MODES)))],
            accum_steps=int(rng.choice([1, 2, 4])), buckets=buckets, d=d,
            has_rs=any(b.mode == "reduce_scatter" for b in buckets))
        keys = [b.key for b in buckets if rng.randint(0, 2)]
        ir = sir.build_schedule_ir(
            axes={"data": d}, accum_steps=plan.accum_steps
            if hasattr(plan, "accum_steps") else 1,
            buckets=buckets, plan=plan, num_slices=s, hier_keys=keys)
        errs = _errors(ir)
        assert not errs, (trial, d, s, keys, [str(v) for v in errs])
        facts = [sir.PlanFact(
            name=f"m/v{i}", shape=(int(rng.choice([64, 512])), 32),
            dtype="float32", sync_kind="AllReduce",
            compressor=str(rng.choice(_FUZZ_COMPRESSORS)),
            sync_mode=str(rng.choice(["all_reduce", "reduce_scatter"])),
            hier=bool(rng.randint(0, 2)))
            for i in range(int(rng.randint(1, 4)))]
        ir2 = sir.ir_from_facts(facts, axes={"data": d}, num_slices=s)
        errs = _errors(ir2)
        assert not errs, (trial, d, s, [str(v) for v in errs])


def _hier_legs(ir):
    rs = next(l for l in ir.legs
              if l.kind == sir.LEG_HIER_REDUCE_SCATTER)
    dcn = next(l for l in ir.legs if l.kind in sir.DCN_KINDS
               and l.bucket == rs.bucket and l.slot == rs.slot)
    return rs, dcn


@pytest.mark.hier
def test_mutation_dropped_dcn_leg():
    """Dropping the cross-slice exchange (slices silently diverge) is
    the worst two-tier bug — its own hier-tier-order diagnostic."""
    ir = _hier_ir()
    rs, dcn = _hier_legs(ir)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = [dataclasses.replace(
        l, deps=tuple(rs.id if dep == dcn.id else dep for dep in l.deps))
        for l in clone.legs if l.id != dcn.id]
    assert sir.RULE_HIER_TIER_ORDER in _rules(_errors(clone))


@pytest.mark.hier
def test_mutation_duplicated_dcn_leg():
    ir = _hier_ir()
    _, dcn = _hier_legs(ir)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = list(clone.legs) + [dataclasses.replace(
        dcn, id=dcn.id + "~again", deps=(dcn.id,))]
    assert sir.RULE_HIER_TIER_ORDER in _rules(_errors(clone))


@pytest.mark.hier
def test_mutation_wrong_tier_tag():
    ir = _hier_ir()
    rs, _ = _hier_legs(ir)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = [dataclasses.replace(l, tier=sir.TIER_DCN)
                  if l.id == rs.id else l for l in clone.legs]
    assert sir.RULE_HIER_TIER_ORDER in _rules(_errors(clone))


@pytest.mark.hier
def test_mutation_dropped_rs_to_dcn_dep_races():
    """Deleting the rs -> dcn dep edge leaves two unordered writers of
    ``red:<key>`` — the dataflow race rule catches it even though both
    legs are still present and correctly tiered."""
    ir = _hier_ir()
    rs, dcn = _hier_legs(ir)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = [dataclasses.replace(
        l, deps=tuple(dep for dep in l.deps if dep != rs.id))
        if l.id == dcn.id else l for l in clone.legs]
    rules = _rules(_errors(clone))
    assert sir.RULE_RACE_WRITE in rules or sir.RULE_RACE_READ_WRITE in rules


@pytest.mark.hier
def test_mutation_renamed_dep_unknown():
    ir = _hier_ir()
    _, dcn = _hier_legs(ir)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = [dataclasses.replace(l, deps=("no-such-leg",))
                  if l.id == dcn.id else l for l in clone.legs]
    assert sir.RULE_UNKNOWN_DEP in _rules(_errors(clone))


@pytest.mark.hier
def test_mutation_hier_legs_on_unfactorable_mesh():
    ir = _hier_ir(d=8, s=2)
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.num_slices = 1
    assert sir.RULE_HIER_TIER_ORDER in _rules(_errors(clone))


def test_reduction_order_divergence_warns_for_bf16_ring():
    ir = _ir(_entries(dtype="bfloat16"), d=8, mode="full")
    warns = [v for v in sir.verify(ir)
             if v.rule == sir.RULE_REDUCTION_ORDER]
    # bf16 buckets ring-decompose under the byte threshold rule; the
    # determinism pass must flag the psum-tree-vs-ring divergence.
    assert warns and all(v.severity == sir.SEV_WARN for v in warns)
    assert not _errors(ir)


# -- verifier runtime budget -------------------------------------------------

def test_verifier_under_one_second_on_largest_fixture():
    """The pre-trace-gate budget: a transformer-scale schedule (hundreds
    of buckets x ring hops x accum slots -> tens of thousands of legs)
    must verify in <1s so the gate stays viable at build time and in
    bench.py."""
    entries = [(f"blk{i}/w", (512, 512), "float32", "NoneCompressor",
                0, "reduce_scatter") for i in range(256)]
    ir = _ir(entries, bucket_bytes=1 << 20, d=8, accum=4, guard=True,
             donated=())
    assert len(ir.legs) > 5_000
    t0 = time.perf_counter()
    violations = sir.verify(ir)
    dt = time.perf_counter() - t0
    assert not [v for v in violations if v.severity == sir.SEV_ERROR]
    assert dt < 1.0, f"verifier took {dt:.2f}s on {len(ir.legs)} legs"


# -- integration: sessions, telemetry, checkpoints, CLI ----------------------

def _session(builder, accum=1):
    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {f"l{i}": {"w": jnp.asarray(rng.randn(32, 32), jnp.float32),
                        "b": jnp.zeros(32, jnp.float32)}
              for i in range(3)}
    batch = {"x": rng.randn(16, 32).astype(np.float32),
             "y": rng.randn(16, 32).astype(np.float32)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(3):
            h = jnp.tanh(h @ p[f"l{i}"]["w"] + p[f"l{i}"]["b"])
        return jnp.mean((h - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-3),
                   loss_fn=loss_fn, accum_steps=accum)
    return ad.create_distributed_session(), batch


def test_explicit_session_carries_verified_ir():
    sess, _ = _session(Zero1(bucket_bytes=64 << 10), accum=2)
    ir = sess.schedule_ir
    assert ir is not None and not _errors(ir)
    assert sess.schedule_fingerprint == ir.fingerprint()
    # the lowering consumed THIS instance: ZeRO-1 buckets match the
    # checkpointed bucket plan exactly.
    assert {b["key"] for b in ir.buckets
            if b["mode"] == "reduce_scatter"} \
        == {b.key for b in sess.zero1_buckets}


def test_gspmd_session_carries_ir_too():
    sess, _ = _session(AllReduce())
    ir = sess.schedule_ir
    assert ir is not None and not _errors(ir)
    assert sess.schedule_fingerprint


def test_fingerprint_changes_with_sync_config():
    s1, _ = _session(Zero1(bucket_bytes=64 << 10))
    fp1 = s1.schedule_fingerprint
    s2, _ = _session(Zero1(bucket_bytes=64 << 10))
    assert s2.schedule_fingerprint == fp1          # deterministic
    _reset_default_autodist_for_testing()
    s3, _ = _session(Zero1(bucket_bytes=8 << 10))
    assert s3.schedule_fingerprint != fp1          # config-sensitive


def test_step_records_carry_schedule_fingerprint(monkeypatch, tmp_path):
    monkeypatch.setenv("AUTODIST_TELEMETRY", "1")
    sess, batch = _session(Zero1(bucket_bytes=64 << 10))
    sess.run(batch)
    recs = sess.telemetry.records
    assert recs and recs[-1].schedule_fingerprint \
        == sess.schedule_fingerprint
    line = json.loads(recs[-1].to_json())
    assert line["schedule_fingerprint"] == sess.schedule_fingerprint


def test_checkpoint_meta_records_fingerprint(tmp_path):
    from autodist_tpu.checkpoint.saver import Saver

    sess, batch = _session(Zero1(bucket_bytes=64 << 10))
    sess.run(batch)
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ckpt"))
    meta = Saver.read_meta(path)
    assert meta["schedule_fingerprint"] == sess.schedule_fingerprint


def test_analysis_ir_matches_runtime_buckets():
    """The mesh-free analyzer IR and the runtime IR agree on the bucket
    plan (same pure planner) for a plain Zero1 program."""
    from autodist_tpu.analysis import analyzer as an
    from autodist_tpu.analysis.schedule import ir_for

    sess, _ = _session(Zero1(bucket_bytes=64 << 10))
    compiled = sess._step.compiled_strategy
    an._load_passes()
    ctx = an.AnalysisContext(strategy=compiled.strategy, graph_item=sess._gi,
                             axes={"data": 8}, compiled=compiled)
    an.PASS_REGISTRY["legality"](ctx)
    static_ir = ir_for(ctx)
    runtime_ir = sess.schedule_ir
    assert {b["key"] for b in static_ir.buckets} \
        == {b["key"] for b in runtime_ir.buckets}
    assert static_ir.fingerprint() == runtime_ir.fingerprint()


def test_schedule_pass_clean_on_valid_plans():
    from autodist_tpu.analysis import analyze

    sess, _ = _session(Zero1(bucket_bytes=64 << 10), accum=2)
    report = analyze(sess._step.compiled_strategy, sess._gi)
    assert not [d for d in report.errors
                if d.rule.startswith("schedule/")]


def test_cli_dump_ir_smoke(capsys):
    from autodist_tpu.analysis.__main__ import main

    rc = main(["mlp", "Zero1", "--mesh", "data=8", "--dump-ir"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["buckets"] and payload["legs"]
    rc = main(["mlp", "Zero1", "--mesh", "data=8", "--dump-ir", "dot"])
    assert rc == 0
    assert capsys.readouterr().out.startswith("digraph")


def test_estimate_ir_cost_prices_pipeline_overlap():
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    flat = estimate_ir_cost(_ir(_entries(), d=8, accum=1))
    piped = estimate_ir_cost(_ir(_entries(), d=8, accum=4))
    assert piped.wire_bytes > 0
    assert piped.exposed_wire_bytes < piped.wire_bytes
    assert flat.exposed_wire_bytes >= piped.exposed_wire_bytes * 0.99


def test_elastic_preflight_runs_schedule_verifier(tmp_path):
    """The --elastic-from / preflight_elastic path re-checks the full
    schedule on the NEW mesh and reports the exact resize delta."""
    from autodist_tpu.analysis import analyze

    sess, _ = _session(Zero1(bucket_bytes=64 << 10))
    report = analyze(
        sess._step.compiled_strategy, sess._gi,
        elastic={"from_axes": {"data": 4},
                 "schedule_fingerprint": "feedfacecafe"})
    infos = [d for d in report.diagnostics
             if d.rule == "schedule/elastic-resize"]
    assert infos and "re-verified exactly" in infos[0].message
    # same-mesh resume with a drifted fingerprint must WARN
    report2 = analyze(
        sess._step.compiled_strategy, sess._gi,
        elastic={"from_axes": {"data": 8},
                 "schedule_fingerprint": "feedfacecafe"})
    assert any(d.rule == "schedule/fingerprint-drift"
               for d in report2.warnings)
