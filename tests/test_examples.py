"""Example-suite smoke tests: the runnable examples must not rot.

Parity target: the reference's examples ARE its integration workloads
(``tests/integration/cases`` wrap them).  Each example runs as a
subprocess on the virtual CPU mesh; the image's sitecustomize pins the
TPU backend, so a steering preamble reconfigures jax before the example
imports it (the same trick as tests/conftest.py)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_STEER = (
    "import os; os.environ['JAX_PLATFORMS']='cpu'; "
    "import jax; jax.config.update('jax_platforms','cpu'); "
    "jax.config.update('jax_num_cpu_devices', 8); "
    "import runpy, sys; sys.argv=[sys.argv[1]]+sys.argv[2:]; "
    "runpy.run_path(sys.argv[0], run_name='__main__')"
)


def _run_example(path, args=(), timeout=420):
    env = dict(os.environ)
    env.update({"AUTODIST_IS_TESTING": "True",
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, "-c", _STEER, os.path.join(REPO, path), *args],
        env=env, timeout=timeout, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    out = proc.stdout.decode()
    assert proc.returncode == 0, f"{path} failed:\n{out[-3000:]}"
    return out


def test_linear_regression():
    out = _run_example("examples/linear_regression.py")
    assert "w=" in out or "loss" in out.lower()


def test_implicit_capture():
    out = _run_example("examples/implicit_capture.py")
    assert "step  35" in out


@pytest.mark.integration
def test_long_context():
    _run_example("examples/long_context.py",
                 ("--steps", "2", "--warmup", "1"))


@pytest.mark.integration
def test_moe_pipeline():
    _run_example("examples/moe_pipeline.py",
                 ("--steps", "2", "--warmup", "1"))


@pytest.mark.integration
def test_imagenet_benchmark():
    _run_example("examples/benchmark/imagenet.py",
                 ("--model", "resnet50", "--image-size", "32",
                  "--batch-size", "8", "--steps", "2", "--warmup", "1"))


def test_input_pipeline(tmp_path):
    out = _run_example("examples/input_pipeline.py",
                       ("--epochs", "2", "--rows", "512",
                        "--batch-size", "32",
                        "--checkpoint-dir", str(tmp_path / "ck")))
    assert "final loss" in out
    assert (tmp_path / "ck").is_dir()


@pytest.mark.integration
def test_imagenet_benchmark_fit_epochs():
    out = _run_example("examples/benchmark/imagenet.py",
                       ("--model", "resnet50", "--image-size", "32",
                        "--batch-size", "8", "--steps", "2",
                        "--epochs", "2"))
    assert "epoch 1:" in out


def test_image_classifier():
    out = _run_example("examples/image_classifier.py",
                       ("--image-size", "32", "--batch-size", "8",
                        "--steps", "3"))
    assert "step 2: loss" in out


@pytest.mark.integration
def test_pipeline_1f1b_example():
    out = _run_example("examples/pipeline_1f1b.py",
                       ("--num-layers", "4", "--seq-len", "16",
                        "--batch-size", "8", "--steps", "3"))
    assert "max relative drift" in out


@pytest.mark.integration
def test_lm1b_train_example():
    # The Parallax parity workload at toy sizes (793k-vocab default
    # shrunk); exercises the chunked-xent default loss end-to-end.
    out = _run_example("examples/lm1b/lm1b_train.py",
                       ("--vocab-size", "512", "--emb-dim", "16",
                        "--hidden-dim", "32", "--batch-size", "8",
                        "--steps", "5", "--warmup", "1"))
    assert "words" in out


@pytest.mark.integration
def test_sentiment_classifier_example():
    # Reference examples/sentiment_classifier.py parity; the example
    # asserts its own convergence bar (final loss < 0.45 vs ~0.69 chance).
    out = _run_example("examples/sentiment_classifier.py",
                       ("--steps", "300"))
    assert "final loss" in out


@pytest.mark.integration
def test_generate_text_example():
    # The example enforces its own accuracy bar (assert acc > 0.9);
    # a zero returncode from _run_example is the pass criterion here.
    out = _run_example("examples/generate_text.py", ("--steps", "200"))
    assert "continuation accuracy:" in out


def test_serving_engine_example():
    # The example asserts oracle-exactness of spot-checked results
    # itself; the output lines are the smoke signal.
    out = _run_example("examples/serving_engine.py")
    assert "oracle-exact" in out
    assert "slot_utilization=" in out


def test_lora_finetune_example():
    # The example asserts adapter learning and zero base drift itself.
    out = _run_example("examples/lora_finetune.py", ("--steps", "30"))
    assert "lora_finetune demo OK" in out
    assert "base drift: 0.0" in out


def test_serve_http_example():
    # The example is its own HTTP client (concurrent completions + one
    # SSE stream + stats) and asserts 200s internally.
    out = _run_example("examples/serve_http.py")
    assert "serve_http demo OK" in out
    assert "stream:" in out


@pytest.mark.integration
def test_speculative_draft_example():
    # Trains a target (framework session) and a ~30x-smaller draft,
    # then decodes speculatively; the example asserts acceptance > 0.5
    # and token-exactness vs target greedy itself.
    out = _run_example("examples/speculative_draft.py", timeout=900)
    assert "acceptance rate:" in out
    assert "token-exact" in out


@pytest.mark.integration
def test_pipeline_1f1b_example_interleaved():
    out = _run_example("examples/pipeline_1f1b.py",
                       ("--virtual-stages", "2", "--num-layers", "8",
                        "--seq-len", "16", "--batch-size", "8",
                        "--steps", "3"))
    assert "max relative drift" in out
