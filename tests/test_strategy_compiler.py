"""StrategyCompiler lowering tests."""
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.mesh import build_mesh
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PartitionedAR,
    PartitionedPS,
    PS,
    StrategyCompiler,
    parse_partitioner,
)


@pytest.fixture
def gi():
    params = {
        "dense": {"kernel": jnp.zeros((8, 4)), "bias": jnp.zeros((4,))},
        "emb": {"table": jnp.zeros((96, 8))},
    }
    return GraphItem(params, sparse_vars=["emb/table"])


@pytest.fixture
def spec():
    return ResourceSpec(resource_info={"nodes": [{"address": "localhost", "chips": 8}]})


def test_parse_partitioner():
    assert parse_partitioner("") == (None, 1)
    assert parse_partitioner("1,1") == (None, 1)
    assert parse_partitioner("4,1") == (0, 4)
    assert parse_partitioner("1,2,1") == (1, 2)
    with pytest.raises(ValueError):
        parse_partitioner("2,2")


def test_allreduce_lowering(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(AllReduce().build(gi, spec), gi)
    plan = cs.plan_for("dense/kernel")
    assert plan.sync_kind == "AllReduce"
    assert plan.param_spec == P()
    assert plan.opt_spec == P()
    assert plan.grad_reduce_axes == ("data",)
    assert cs.batch_spec() == P(("data",))


def test_ps_lowering_is_wus(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(PS().build(gi, spec), gi)
    plan = cs.plan_for("dense/kernel")
    assert plan.sync_kind == "PS"
    assert plan.param_spec == P()           # replicated for compute
    assert plan.opt_spec == P("data")       # update sharded: dim0=8 divisible
    bias = cs.plan_for("dense/bias")
    assert bias.opt_spec == P()             # (4,) not divisible by 8 → replicated


def test_partitioned_ps_on_dp_mesh(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(PartitionedPS().build(gi, spec), gi)
    plan = cs.plan_for("dense/kernel")
    # no model axis → PS shards live across the data axis (ZeRO-style)
    assert plan.param_spec == P("data")
    assert plan.partition_axis == 0


def test_partitioned_ps_on_model_mesh(gi, spec):
    mesh = build_mesh({"data": 4, "model": 2})
    cs = StrategyCompiler(mesh).compile(PartitionedPS().build(gi, spec), gi)
    plan = cs.plan_for("dense/kernel")
    assert plan.param_spec == P("model")
    assert plan.num_shards == 2


def test_partitioned_ar_on_dp_mesh_stays_replicated(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(PartitionedAR().build(gi, spec), gi)
    plan = cs.plan_for("dense/kernel")
    assert plan.param_spec == P()  # shards colocated with replicas


def test_parallax_embedding_sharded(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(Parallax().build(gi, spec), gi)
    emb = cs.plan_for("emb/table")
    assert emb.sync_kind == "PS"
    assert emb.param_spec == P("data")  # vocab axis sharded
    dense = cs.plan_for("dense/kernel")
    assert dense.sync_kind == "AllReduce"
    assert dense.param_spec == P()


def test_param_sharding_tree(gi, spec):
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(Parallax().build(gi, spec), gi)
    tree = cs.param_sharding_tree(gi.params)
    assert tree["emb"]["table"].spec == P("data")
    assert tree["dense"]["kernel"].spec == P()


def test_unknown_var_pruned(gi, spec):
    strategy = AllReduce().build(gi, spec)
    strategy.node_config[0].var_name = "ghost/var"
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(strategy, gi)
    assert "ghost/var" not in cs.var_plans
    # the real var still gets a safe default plan
    assert all(name in cs.var_plans
               for name in ("dense/kernel", "dense/bias", "emb/table"))


def test_destination_resolution(gi):
    spec2 = ResourceSpec(resource_info={"nodes": [
        {"address": "a", "chips": 4, "chief": True}, {"address": "b", "chips": 4}]})
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh, resource_spec=spec2).compile(
        PS().build(gi, spec2), gi)
    plan = cs.plan_for("dense/kernel")
    # PS builder puts everything on node "a" (first CPU) → data coord 0
    assert plan.destination_coords == {"data": 0}
    from autodist_tpu.strategy import PSLoadBalancing
    cs2 = StrategyCompiler(mesh, resource_spec=spec2).compile(
        PSLoadBalancing().build(gi, spec2), gi)
    coords = {p.destination_coords["data"] for p in cs2.var_plans.values()}
    assert coords == {0, 4}  # balanced across both hosts


def test_prime_axis_does_not_explode():
    import numpy as np
    gi2 = GraphItem({"emb": {"table": np.zeros((104729, 8), np.float32)}})
    spec2 = ResourceSpec(resource_info={"nodes": [{"address": "a", "chips": 8}]})
    s = PartitionedPS().build(gi2, spec2)
    node = s.node_for("emb/table")
    assert node.partitioner == ""  # prime > cap → unpartitioned
    s2 = PartitionedAR().build(gi2, spec2)
    assert s2.node_for("emb/table").partitioner == ""
