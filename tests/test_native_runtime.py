"""Native host runtime tests: build, loader correctness, bf16 cast.

The native path and the numpy fallback must produce byte-identical epochs for
a given seed (same mt19937_64 Fisher-Yates permutation), so every test that
can runs both and compares.
"""
import numpy as np
import pytest

import ml_dtypes

from autodist_tpu.runtime import DataLoader, fp32_to_bf16, native_available
from autodist_tpu.runtime.data_loader import _mt19937_64_permutation


def make_data(n=100, d=7, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, 10, size=(n,)).astype(np.int32)
    return x, y


def collect_epoch(loader):
    out = []
    for batch in loader:
        # copy: buffers are reused by the pool
        out.append(tuple(np.array(b) for b in
                         (batch.values() if isinstance(batch, dict) else batch)))
    return out


def test_native_builds():
    assert native_available(), "native runtime failed to build/load"


def test_loader_covers_all_rows_unshuffled():
    x, y = make_data(64, 5)
    loader = DataLoader({"x": x, "y": y}, batch_size=16, shuffle=False)
    batches = collect_epoch(loader)
    assert len(batches) == 4
    np.testing.assert_array_equal(np.concatenate([b[0] for b in batches]), x)
    np.testing.assert_array_equal(np.concatenate([b[1] for b in batches]), y)


def test_loader_shuffled_is_permutation_and_seeded():
    x, y = make_data(50, 3)
    l1 = DataLoader((x, y), batch_size=10, shuffle=True, seed=7)
    l2 = DataLoader((x, y), batch_size=10, shuffle=True, seed=7)
    e1, e2 = collect_epoch(l1), collect_epoch(l2)
    for (a1, b1), (a2, b2) in zip(e1, e2):
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(b1, b2)
    allx = np.concatenate([b[0] for b in e1])
    # same multiset of rows
    np.testing.assert_array_equal(np.sort(allx, axis=0), np.sort(x, axis=0))
    # actually shuffled
    assert not np.array_equal(allx, x)


def test_shard_disjoint_and_covering():
    """shard=(i, n) is the multi-host input split: disjoint strided
    subsets whose union is every row, each shuffled locally."""
    x, y = make_data(64, 5)
    rows = []
    for i in range(4):
        loader = DataLoader({"x": x, "y": y}, batch_size=4, shuffle=True,
                            seed=3, shard=(i, 4))
        assert len(loader) == 4                     # 16 local rows / 4
        got = np.concatenate(
            [b[0] for b in collect_epoch(loader)])
        np.testing.assert_array_equal(              # host i's subset only
            np.sort(got, axis=0), np.sort(x[i::4], axis=0))
        rows.append(got)
    # union covers the dataset exactly once
    np.testing.assert_array_equal(
        np.sort(np.concatenate(rows), axis=0), np.sort(x, axis=0))


def test_shard_equal_counts_when_indivisible():
    """66 rows over 4 hosts: every host must see the SAME number of rows
    (16 = 66//4) and batches — unequal per-host batch counts would
    deadlock lockstep collectives; the 2 remainder rows are dropped."""
    x, y = make_data(66, 3)
    lens, rows = set(), []
    for i in range(4):
        loader = DataLoader({"x": x, "y": y}, batch_size=8, shuffle=False,
                            drop_last=False, shard=(i, 4))
        batches = collect_epoch(loader)
        lens.add(len(batches))
        rows.append(np.concatenate([b[0] for b in batches]))
    assert lens == {2}                          # identical on every host
    got = np.concatenate(rows)
    assert got.shape[0] == 64                   # 2 remainder rows dropped
    # still disjoint: every kept row appears exactly once in the union
    uniq = np.unique(got, axis=0)
    assert uniq.shape[0] == 64


def test_shard_validation():
    x, y = make_data(8, 2)
    with pytest.raises(ValueError, match="shard"):
        DataLoader({"x": x, "y": y}, batch_size=2, shard=(4, 4))
    with pytest.raises(ValueError, match="shard"):
        DataLoader({"x": x, "y": y}, batch_size=2, shard=(-1, 2))


def test_epochs_reshuffle():
    x, y = make_data(40, 2)
    loader = DataLoader((x, y), batch_size=10, shuffle=True, seed=3)
    e1 = np.concatenate([b[0] for b in collect_epoch(loader)])
    e2 = np.concatenate([b[0] for b in collect_epoch(loader)])
    assert not np.array_equal(e1, e2)


def test_native_matches_fallback(monkeypatch):
    if not native_available():
        pytest.skip("no native lib")
    x, y = make_data(37, 4, seed=5)
    nat = collect_epoch(DataLoader((x, y), batch_size=8, shuffle=True,
                                   drop_last=False, seed=11))
    from autodist_tpu.runtime.data_loader import DataLoader as DL

    fb = DL((x, y), batch_size=8, shuffle=True, drop_last=False, seed=11)
    fb._use_native = False
    fbb = collect_epoch(fb)
    assert len(nat) == len(fbb) == 5
    for (a1, b1), (a2, b2) in zip(nat, fbb):
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(b1, b2)


def test_short_last_batch_and_drop_last():
    x, y = make_data(35, 2)
    keep = DataLoader((x, y), batch_size=8, shuffle=False, drop_last=False)
    sizes = [b[0].shape[0] for b in keep]
    assert sizes == [8, 8, 8, 8, 3]
    drop = DataLoader((x, y), batch_size=8, shuffle=False, drop_last=True)
    assert [b[0].shape[0] for b in drop] == [8, 8, 8, 8]
    assert len(drop) == 4


def test_bf16_cast_in_loader():
    x, _ = make_data(32, 6)
    loader = DataLoader({"x": x, "y": _}, batch_size=16, shuffle=False,
                        to_bf16=["x"])
    for batch in loader:
        assert batch["x"].dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            np.asarray(batch["x"]),
            x[:16].astype(ml_dtypes.bfloat16))
        break


def test_fp32_to_bf16_matches_numpy_rne():
    rng = np.random.RandomState(0)
    vals = np.concatenate([
        rng.randn(1000).astype(np.float32) * 1e3,
        np.array([0.0, -0.0, np.inf, -np.inf, 1e-40, -1e-40], np.float32),
    ])
    got = np.asarray(fp32_to_bf16(vals))
    want = vals.astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(got.view(np.uint16), want.view(np.uint16))


def test_fp32_to_bf16_nan_stays_nan():
    vals = np.array([np.nan, -np.nan], np.float32)
    out = np.asarray(fp32_to_bf16(vals)).astype(np.float32)
    assert np.isnan(out).all()


def test_mt19937_matches_cpp_reference():
    # First outputs of std::mt19937_64 seeded with 5489 (the C++ default
    # seed, values from the N. M. 2008 reference implementation).
    rng = _mt19937_64_permutation.__globals__["_MT19937_64"](5489)
    first = [rng.next() for _ in range(3)]
    assert first == [14514284786278117030, 4620546740167642908,
                     13109570281517897720]


def test_mismatched_rows_raises():
    x, y = make_data(20, 2)
    with pytest.raises(ValueError):
        DataLoader((x, y[:10]), batch_size=4)


def test_bf16_non_float_raises():
    x, y = make_data(20, 2)
    with pytest.raises(ValueError):
        DataLoader({"x": x, "y": y}, batch_size=4, to_bf16=["y"])


def test_loader_feeds_training(monkeypatch):
    """End-to-end: loader batches drive a distributed session step."""
    import optax

    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import AllReduce

    _reset_default_autodist_for_testing()
    x, _ = make_data(64, 4)
    w = np.random.RandomState(1).randn(4, 1).astype(np.float32)
    ytgt = (x @ w).astype(np.float32)

    ad = AutoDist(strategy_builder=AllReduce())
    params = {"w": np.zeros((4, 1), np.float32)}

    def loss_fn(p, batch):
        bx, by = batch
        return ((bx @ p["w"] - by) ** 2).mean()

    ad.capture(params, optimizer=optax.sgd(0.05), loss_fn=loss_fn)
    session = ad.create_distributed_session()
    loader = DataLoader((x, ytgt), batch_size=16, shuffle=True, seed=0)
    losses = []
    for _epoch in range(10):
        for batch in loader:
            losses.append(float(session.run(batch)["loss"]))
    assert losses[-1] < 0.1 * losses[0]


def test_empty_dataset_yields_no_batches():
    x = np.empty((0, 4), np.float32)
    loader = DataLoader((x,), batch_size=8)
    assert list(loader) == []


def test_early_break_then_new_epoch():
    # Early break must release the held buffer-set (no leak, no deadlock on
    # later epochs).
    x, y = make_data(64, 3)
    loader = DataLoader((x, y), batch_size=8, shuffle=False)
    for _ in range(5):
        for batch in loader:
            break
    full = collect_epoch(loader)
    assert len(full) == 8


def test_run_epoch_with_dataloader():
    """DataLoader → session.run_epoch: host loader + device prefetch +
    async dispatch produce the same training as a plain loop."""
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.runtime.data_loader import DataLoader
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x @ rng.randn(8, 4)).astype(np.float32)

    def loss_fn(p, b):
        return float32_mse(p, b)

    def float32_mse(p, b):
        import jax.numpy as jnp
        pred = b["x"] @ p["w"]
        return jnp.mean((pred - b["y"]) ** 2)

    def train(epoch_runner):
        import os

        import jax.numpy as jnp
        _reset_default_autodist_for_testing()
        os.environ["AUTODIST_IS_TESTING"] = "True"
        ad = AutoDist(strategy_builder=AllReduce(), mesh_axes={"data": 8})
        with ad.scope():
            ad.capture(params={"w": jnp.zeros((8, 4))},
                       optimizer=optax.sgd(0.05), loss_fn=loss_fn)
        sess = ad.create_distributed_session()
        loader = DataLoader({"x": x, "y": y}, batch_size=16, shuffle=True,
                            seed=3)
        for _ in range(3):
            metrics = epoch_runner(sess, loader)
        return float(metrics["loss"]), sess.params["w"]

    l_epoch, w_epoch = train(lambda s, ld: s.run_epoch(ld))
    l_plain, w_plain = train(
        lambda s, ld: [s.run(b) for b in ld][-1])
    np.testing.assert_allclose(l_epoch, l_plain, rtol=1e-6)
    np.testing.assert_allclose(w_epoch, w_plain, rtol=1e-6)
