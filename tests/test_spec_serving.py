"""Speculative decoding in the paged serving plane.

The claim under test is the serving engine's own claim — token-exact
greedy decode vs the per-request ``generate`` oracle — carried into
speculative mode: draft K/V paged out of the SAME block pool, the
target verifying gamma+1 positions per round on the chunked-prefill
program, and SLO-adaptive gamma.  Acceptance may vary with the draft's
quality; the OUTPUT may not.  Every scheduler feature that interacts
with the dual block spans gets a case: prefix-cache hits, chunked
prefill, mid-run admission, block-budget deferral under pool pressure,
eos cut-off, per-request gamma, and the no-leak invariant over the
draft tables.
"""
import jax
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.serving import PagedDecodeEngine

pytestmark = [pytest.mark.serving, pytest.mark.spec_serving]

VOCAB = 61
# Same target geometry as test_serving_scheduler so the paged programs
# come out of the module-scope jit cache already compiled.
GEOM = dict(slots=2, window=32, block_size=8, num_blocks=24, chunk=4)


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    return spec, spec.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    # Different depth AND different init: a draft that genuinely
    # disagrees with the target (low acceptance), so every exactness
    # assertion exercises the reject-and-bonus path, not just accepts.
    spec = transformer_lm(vocab_size=VOCAB, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    return spec, spec.init(jax.random.PRNGKey(9))


def _spec_engine(lm, draft, **over):
    spec, params = lm
    dspec, dparams = draft
    kw = dict(GEOM)
    kw.update(over)
    return PagedDecodeEngine(spec, params, draft_spec=dspec,
                             draft_params=dparams, **kw)


def _oracle(spec, params, prompt, n):
    return np.asarray(make_generator(spec)(params, prompt[None, :], n))[0]


@pytest.mark.parametrize(
    "gamma", [pytest.param(1, marks=pytest.mark.slow), 4])
def test_spec_matches_oracle_exactly(lm, draft, gamma):
    """More requests than slots, varied prompt/output lengths, a bad
    draft: every harvested sequence equals the target-only oracle and
    both block spans recycle."""
    spec, params = lm
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (1, 9), (6, 2), (4, 7), (2, 4)]]
    eng = _spec_engine(lm, draft, gamma=gamma, adapt_gamma=False)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n),
            err_msg=f"request {rid} (P={prompt.size}, N={n}, g={gamma})")
    sp = eng.scheduler_stats()["speculative"]
    assert sp["rounds"] > 0 and sp["proposed"] >= sp["accepted"] >= 0
    eng.assert_no_leaks()


def test_spec_mid_run_admission_exact(lm, draft):
    """Requests admitted WHILE speculative rounds run: the draft
    catch-up prefill and the dual-span admission must not disturb
    in-flight slots."""
    spec, params = lm
    rng = np.random.RandomState(4)
    eng = _spec_engine(lm, draft, gamma=3, adapt_gamma=False)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)
    p3 = rng.randint(0, VOCAB, 5).astype(np.int32)
    r1 = eng.submit(p1, 6)
    assert eng.step()
    r2 = eng.submit(p2, 5)            # joins mid-speculation
    eng.step()
    r3 = eng.submit(p3, 4)
    while eng.step():
        pass
    results = eng.results()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 6))
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 5))
    np.testing.assert_array_equal(results[r3], _oracle(spec, params, p3, 4))
    eng.assert_no_leaks()


def test_spec_chunked_prefill_exact(lm, draft):
    """prefill_chunk smaller than the prompt: target and draft prefill
    walk the prompt in separate chunk waves (the draft lags by design)
    and the verify rounds still start from a consistent K/V."""
    spec, params = lm
    rng = np.random.RandomState(5)
    eng = _spec_engine(lm, draft, gamma=3, adapt_gamma=False,
                       prefill_chunk=3)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(11, 5), (7, 6), (13, 4)]]
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n))
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_prefix_cache_hit_exact(lm, draft):
    """Trie-cached prompt blocks serve the TARGET span only — the
    draft has no trie, so its catch-up prefill must rebuild draft K/V
    over the cached tokens too.  Exact output plus a real cache hit."""
    spec, params = lm
    rng = np.random.RandomState(2)
    shared = rng.randint(0, VOCAB, 17).astype(np.int32)   # 2 full blocks
    prompts = [np.concatenate([shared,
                               rng.randint(0, VOCAB, 3).astype(np.int32)])
               for _ in range(3)]
    eng = _spec_engine(lm, draft, gamma=3, adapt_gamma=False,
                       num_blocks=40)
    r0 = eng.submit(prompts[0], 5)                        # warms the trie
    out = eng.run()
    np.testing.assert_array_equal(out[r0],
                                  _oracle(spec, params, prompts[0], 5))
    ids = [eng.submit(p, 6) for p in prompts[1:]]
    out = eng.run()
    for rid, p in zip(ids, prompts[1:]):
        np.testing.assert_array_equal(out[rid],
                                      _oracle(spec, params, p, 6))
    assert eng.stats.cached_prompt_tokens > 0
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_pool_pressure_deferral_exact(lm, draft):
    """A pool barely larger than one dual span: admission must defer
    (not deadlock, not leak) until blocks recycle, and the deferred
    requests still come out exact."""
    spec, params = lm
    rng = np.random.RandomState(6)
    # capacity 11 blocks; a (P=9, N=7) request spans 2 target + 2 draft
    # blocks at admission and grows to 4+4 — two in flight exhaust it.
    eng = _spec_engine(lm, draft, gamma=3, adapt_gamma=False,
                       num_blocks=12, cache_prefixes=False)
    reqs = [(rng.randint(0, VOCAB, 9).astype(np.int32), 7)
            for _ in range(3)]
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n))
    eng.assert_no_leaks()


def test_spec_eos_matches_plain_paged(lm, draft):
    """eos cut-off parity: the speculative engine truncates at the
    first eos exactly where the non-speculative paged engine does —
    committed tokens only, never an un-verified proposal."""
    spec, params = lm
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, VOCAB, 4).astype(np.int32)
    free = _oracle(spec, params, prompt, 8)
    eos = int(free[prompt.size + 1])      # fires mid-generation
    plain = PagedDecodeEngine(spec, params, **GEOM)
    rp = plain.submit(prompt, 8, eos_id=eos)
    expected = plain.run()[rp]
    eng = _spec_engine(lm, draft, gamma=4, adapt_gamma=False)
    rs = eng.submit(prompt, 8, eos_id=eos)
    got = eng.run()[rs]
    np.testing.assert_array_equal(got, expected)
    eng.assert_no_leaks()


def test_spec_per_request_gamma_exact(lm, draft):
    """submit(gamma=1) pins one request to single-proposal rounds while
    its neighbor drafts at the engine depth — per-slot ``ge`` vectors,
    one shared program."""
    spec, params = lm
    rng = np.random.RandomState(8)
    p1 = rng.randint(0, VOCAB, 4).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 6).astype(np.int32)
    eng = _spec_engine(lm, draft, gamma=4, adapt_gamma=False)
    r1 = eng.submit(p1, 7, gamma=1)
    r2 = eng.submit(p2, 7)
    results = eng.run()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 7))
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 7))
    eng.assert_no_leaks()


@pytest.mark.slow
def test_spec_gamma_adapts_mid_flight(lm, draft):
    """SLO adaptation under backlog: a burst beyond the slot count
    shrinks gamma (latency queue pressure), the drained tail regrows
    it, and the acceptance EWMA caps it — all without breaking
    exactness."""
    spec, params = lm
    rng = np.random.RandomState(9)
    reqs = [(rng.randint(0, VOCAB, 4).astype(np.int32), 8)
            for _ in range(8)]
    eng = _spec_engine(lm, draft, gamma=6, adapt_gamma=True)
    ids = [eng.submit(p, n) for p, n in reqs]     # 8 requests, 2 slots
    trace = []
    while eng.step():
        trace.append(eng.scheduler_stats()["speculative"]["gamma"])
    results = eng.results()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n))
    assert min(trace) < 6, f"gamma never shrank under backlog: {trace}"
    # The tail (idle slot, empty queue) wants to regrow gamma, but a
    # bad draft's acceptance EWMA caps it — degradation toward plain
    # decode wins over the utilization signal.  (The regrow leg with a
    # GOOD draft is the bench child's load-spike drill.)
    sp = eng.scheduler_stats()["speculative"]
    assert sp["accept_ewma"] < 6.0
    cap = max(1, int(round(2 * sp["accept_ewma"])))
    assert trace[-1] <= min(6, cap), \
        f"tail gamma {trace[-1]} exceeds the EWMA cap {cap}"
    assert len(sp["gamma_hist"]) > 1      # adaptation actually moved
    eng.assert_no_leaks()


def test_spec_occupancy_split_and_timings(lm, draft):
    """The observability surface: scheduler_stats splits occupancy
    into target vs draft while in flight (draft > 0) and back to zero
    after the drain; pop_timings carries the per-request speculation
    fields the server histograms."""
    spec, params = lm
    rng = np.random.RandomState(10)
    eng = _spec_engine(lm, draft, gamma=3, adapt_gamma=False)
    rid = eng.submit(rng.randint(0, VOCAB, 6).astype(np.int32), 6)
    eng.step()
    eng.step()
    st = eng.scheduler_stats()
    assert st["draft_blocks_used"] > 0
    assert st["block_occupancy_draft"] > 0
    assert st["block_occupancy_target"] > 0
    while eng.step():
        pass
    eng.results()
    t = eng.pop_timings()[rid]
    assert t["spec_rounds"] >= 1
    assert t["spec_proposed"] >= t["spec_accepted"] >= 0
    assert t["spec_bonus"] >= 1           # every round commits >= 1
    assert t["accept_len_mean"] >= 0.0
    assert t["draft_s"] >= 0.0 and t["verify_s"] >= 0.0
    st = eng.scheduler_stats()
    assert st["draft_blocks_used"] == 0
    assert st["block_occupancy_draft"] == 0.0
    eng.assert_no_leaks()


def test_spec_submit_validation(lm, draft):
    """Knobs that would fail mid-run are rejected at submit/construct
    time: gamma < 1, non-greedy temperature, span + gamma overflowing
    the window, and per-request gamma on a non-speculative engine."""
    spec, params = lm
    dspec, dparams = draft
    prompt = np.zeros(4, np.int32)
    with pytest.raises(ValueError, match="gamma"):
        _spec_engine(lm, draft, gamma=0)
    with pytest.raises(ValueError, match="temperature|greedy"):
        _spec_engine(lm, draft, gamma=2, temperature=0.7)
    eng = _spec_engine(lm, draft, gamma=2, adapt_gamma=False)
    with pytest.raises(ValueError, match="gamma"):
        eng.submit(prompt, 5, gamma=0)
    with pytest.raises(ValueError, match="temperature|greedy"):
        eng.submit(prompt, 5, temperature=0.7)
    with pytest.raises(ValueError, match="window"):
        # span 4+26 = 30 fits the window 32, but not plus gamma 4.
        eng.submit(prompt, 26, gamma=4)
    plain = PagedDecodeEngine(spec, params, **GEOM)
    with pytest.raises(ValueError, match="speculative engine"):
        plain.submit(prompt, 5, gamma=2)
    with pytest.raises(ValueError, match="together"):
        PagedDecodeEngine(spec, params, draft_spec=dspec, **GEOM)


def test_router_weighs_draft_occupancy():
    """A mixed fleet: with draft_occupancy_weight set, the router
    steers away from the replica whose pool is loaded with draft
    pages, all else equal; with the default weight 0 the split is
    invisible (backward-compatible scoring)."""
    from autodist_tpu.serving.router import Router

    class FakeReplica:
        def __init__(self, name, draft_occ):
            self.name = name
            self.draft_occ = draft_occ
            self.served = []

        def probe(self, timeout=2.0):
            return True

        def fetch_stats(self):
            return {"outstanding": 0, "queue_depth_total": 0,
                    "block_occupancy": 0.5,
                    "block_occupancy_draft": self.draft_occ}

        def post(self, body, timeout):
            self.served.append(body)
            return 200, {"id": len(self.served), "tokens": [1]}

    a, b = FakeReplica("a", 0.4), FakeReplica("b", 0.0)
    r = Router([a, b], probe_ttl_s=0.0, stats_ttl_s=0.0,
               draft_occupancy_weight=2.0)
    for _ in range(3):
        r.complete({"prompt_tokens": [1], "max_new_tokens": 2})
    assert len(b.served) == 3 and len(a.served) == 0


def test_spec_http_server_surface(lm, draft):
    """serve(speculative=...) end to end: a token-exact completion
    with a per-request gamma, the spec block on /v1/stats, the spec
    metrics on /metrics, and fail-fast 400 on a bad gamma."""
    import json
    import urllib.error
    import urllib.request

    from autodist_tpu.serving import serve

    spec, params = lm
    dspec, dparams = draft
    srv = serve(spec, params, port=0, slots=2, window=32, block_size=8,
                num_blocks=24, chunk=4,
                speculative={"spec": dspec, "params": dparams,
                             "gamma": 3, "adapt_gamma": True})
    try:
        port = srv.address[1]
        base = f"http://127.0.0.1:{port}"
        prompt = np.random.RandomState(3).randint(0, VOCAB, 5)
        body = json.dumps({"prompt_tokens": [int(x) for x in prompt],
                           "max_new_tokens": 6, "gamma": 2}).encode()
        req = urllib.request.Request(
            base + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=120).read())
        np.testing.assert_array_equal(
            np.asarray(out["tokens"]),
            _oracle(spec, params, prompt.astype(np.int32), 6))
        stats = json.loads(urllib.request.urlopen(
            base + "/v1/stats", timeout=30).read())
        assert "speculative" in stats
        assert stats["speculative"]["rounds"] >= 1
        assert "block_occupancy_draft" in stats
        mets = urllib.request.urlopen(
            base + "/metrics", timeout=30).read().decode()
        for name in ("autodist_serving_spec_accept_len",
                     "autodist_serving_spec_gamma",
                     "autodist_serving_spec_gamma_current",
                     "autodist_serving_block_occupancy_target",
                     "autodist_serving_block_occupancy_draft"):
            assert name in mets, f"missing {name} on /metrics"
        bad = json.dumps({"prompt_tokens": [1, 2], "max_new_tokens": 4,
                          "gamma": 0}).encode()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/completions", data=bad,
                headers={"Content-Type": "application/json"}),
                timeout=30)
        assert err.value.code == 400
    finally:
        srv.close()


@pytest.mark.slow
def test_spec_sustained_load_drill(lm, draft):
    """Long mixed drill: 16 requests arriving in waves over 2 slots
    with adaptation on — sustained slot/block recycling across many
    draft spans, exact throughout, nothing leaked at the end."""
    spec, params = lm
    rng = np.random.RandomState(11)
    reqs = [(rng.randint(0, VOCAB, int(rng.randint(1, 10))).astype(
        np.int32), int(rng.randint(2, 10))) for _ in range(16)]
    eng = _spec_engine(lm, draft, gamma=4, adapt_gamma=True)
    pending = list(reqs)
    ids = []
    while pending:
        for p, n in pending[:3]:
            ids.append(eng.submit(p, n))
        pending = pending[3:]
        eng.step()
    while eng.step():
        pass
    results = eng.results()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n),
            err_msg=f"request {rid} (P={prompt.size}, N={n})")
    eng.assert_no_leaks()
