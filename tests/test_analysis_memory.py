"""Analyzer memory pass: static per-device HBM footprint goldens."""
import jax.numpy as jnp
import optax
import pytest

from autodist_tpu.analysis import analyze
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import Strategy

from _analysis_fixtures import (
    AXES8,
    ar_node,
    full_cover,
    make_gi,
    make_spec8,
    ps_node,
)

pytestmark = pytest.mark.analysis


@pytest.fixture
def gi():
    return make_gi()


def test_hbm_breakdown_always_emitted(gi):
    report = analyze(full_cover(gi), gi, mesh=AXES8)
    assert len(report.by_rule("memory/hbm-breakdown")) == 1


def test_watermark_over_budget_is_exactly_one_error(gi):
    """A plan that lowers to a schedule IR is budget-checked through
    the liveness watermark, not the coarse sum (docs/analysis.md)."""
    report = analyze(full_cover(gi), gi, mesh=AXES8, budget_bytes=1024)
    errors = report.errors
    assert len(errors) == 1
    assert errors[0].rule == "memory/watermark-exceeds-hbm"
    assert len(report.by_rule("memory/watermark")) == 1


def test_watermark_near_budget_warns():
    gi = GraphItem({"w": jnp.zeros((1024, 1024), jnp.float32)},
                   optimizer=optax.adam(1e-3))
    s = Strategy(node_config=[ar_node("w")])
    # the exact watermark total, through the same helpers the pass uses
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import dataflow
    from autodist_tpu.analysis import memory as _mem
    from autodist_tpu.analysis.schedule import ir_for
    ctx = _an.AnalysisContext(strategy=s, graph_item=gi, axes=AXES8)
    _an.PASS_REGISTRY["legality"](ctx)
    base = _mem._param_and_grad_bytes(ctx)["params"] \
        + _mem._opt_state_bytes(ctx)
    wm = dataflow.watermark(ir_for(ctx), base_bytes=int(base))
    assert wm is not None and wm.peak_bytes > 0
    budget = int(wm.peak_bytes / 0.95)              # ~95% utilization
    report = analyze(s, gi, mesh=AXES8, budget_bytes=budget)
    assert not report.has_errors()
    rules = [d.rule for d in report.warnings]
    assert "memory/watermark-near-hbm" in rules
    # near budget + replicated AR optimizer state on a data axis: the
    # ZeRO-1 advisory fires alongside (see test_zero1_unused_warn).
    assert set(rules) <= {"memory/watermark-near-hbm",
                          "memory/zero1-unused"}


def test_hbm_budget_from_resource_spec(gi):
    tiny = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "hbm_gb": 1e-6})
    assert tiny.hbm_bytes_per_chip == int(1e-6 * (1 << 30))
    report = analyze(full_cover(gi), gi, mesh=AXES8, resource_spec=tiny)
    assert [d.rule for d in report.errors] \
        == ["memory/watermark-exceeds-hbm"]


def test_coarse_budget_rules_without_schedule_ir():
    """No synced trainables -> no schedule IR -> the coarse-sum budget
    rules still guard the footprint (activation term here)."""
    import numpy as np
    gi = GraphItem({"w": jnp.zeros((4, 4), jnp.float32)},
                   untrainable_vars=["w"])
    s = Strategy(node_config=[])
    report = analyze(s, gi, mesh=AXES8, budget_bytes=1024,
                     batch={"x": np.zeros((64, 1024), np.float32)})
    assert not report.by_rule("memory/watermark")
    assert [d.rule for d in report.errors] == ["memory/hbm-over-budget"]


def test_hbm_bad_budget_rejected():
    with pytest.raises(Exception):
        ResourceSpec(resource_info={
            "nodes": [{"address": "localhost", "chips": 8}],
            "hbm_gb": -1})


def test_opt_state_bytes_are_dtype_aware():
    """bf16 moments (cast_opt_state) halve the counted optimizer bytes —
    the analyzer reads dtypes out of eval_shape, not assumptions."""
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import memory as _mem
    from autodist_tpu.ops.opt_state_dtype import cast_opt_state

    params = {"w": jnp.zeros((64, 64), jnp.float32)}

    def ctx_for(opt):
        gi = GraphItem(params, optimizer=opt)
        s = Strategy(node_config=[ar_node("w")])
        ctx = _an.AnalysisContext(strategy=s, graph_item=gi, axes=AXES8)
        _an.PASS_REGISTRY["legality"](ctx)
        return ctx

    wide = _mem._opt_state_bytes(ctx_for(optax.adam(1e-3)))
    narrow = _mem._opt_state_bytes(ctx_for(cast_opt_state(optax.adam(1e-3))))
    # adam: mu + nu are the param-shaped blocks; bf16 halves exactly those.
    assert narrow < wide
    assert abs(narrow - wide / 2) / wide < 0.05


def test_ps_wus_shards_optimizer_bytes():
    """PS (weight-update sharding) counts optimizer state at 1/8 of the
    AllReduce (replicated) footprint on an 8-wide data axis."""
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import memory as _mem

    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    gi2 = GraphItem(params, optimizer=optax.adam(1e-3))

    def opt_bytes(strategy):
        ctx = _an.AnalysisContext(strategy=strategy, graph_item=gi2,
                                  axes=AXES8)
        _an.PASS_REGISTRY["legality"](ctx)
        return _mem._opt_state_bytes(ctx)

    rep = opt_bytes(Strategy(node_config=[ar_node("w")]))
    wus = opt_bytes(Strategy(node_config=[ps_node("w")]))
    assert wus < rep / 4  # param-shaped blocks divided by 8; scalars whole


def test_compressor_state_counted(gi):
    """Error-feedback residuals (grad-shaped, per device) show up in the
    sync-state term: EF strategy strictly outweighs the plain one."""
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import memory as _mem

    def sync_bytes(compressor):
        s = Strategy(node_config=[
            ar_node(v.name, compressor=compressor)
            for v in gi.trainable_var_infos])
        ctx = _an.AnalysisContext(strategy=s, graph_item=gi, axes=AXES8)
        _an.PASS_REGISTRY["legality"](ctx)
        return _mem._sync_state_bytes(ctx)

    assert sync_bytes("NoneCompressor") == 0.0
    assert sync_bytes("HorovodCompressorEF") > 0.0


def test_activation_estimate_is_remat_aware():
    """Same batch, remat on vs off: the activation term shrinks."""
    from autodist_tpu.analysis import analyzer as _an
    from autodist_tpu.analysis import memory as _mem
    import numpy as np

    params = {"w": jnp.zeros((8, 8))}
    batch = {"x": np.zeros((64, 128), np.float32)}

    def act(remat):
        gi = GraphItem(params, loss_fn=lambda p, b: 0.0, remat=remat)
        s = Strategy(node_config=[ar_node("w")])
        ctx = _an.AnalysisContext(strategy=s, graph_item=gi, axes=AXES8,
                                  batch=batch)
        _an.PASS_REGISTRY["legality"](ctx)
        return _mem._activation_bytes(ctx)

    assert act("full") < act("dots") < act(None)
