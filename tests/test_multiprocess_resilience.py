"""Live supervised recovery: kill a worker mid-run, recover, match the
uninterrupted run.

The multiprocess acceptance test of the resilience PR: the chaos
harness kills the REAL worker process (launched by the real
Coordinator over the real ``jax.distributed`` rendezvous) at step k of
attempt 0; the chief's supervised failure policy records the culprit
and aborts; the job-level Supervisor terminates stragglers, backs off,
relaunches the whole job on a fresh rendezvous port, and ``fit``
resumes from the last durable checkpoint with the exact data-loader
position — so the recovered run's final parameters are IDENTICAL to an
uninterrupted oracle run (same SGD trajectory over the same shuffled
batch sequence, bit-for-bit on the replayed steps)."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "integration", "resilient_train.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _base_env(tmp_path, tag):
    env = dict(os.environ)
    for k in ("AUTODIST_WORKER", "AUTODIST_STRATEGY_ID", "AUTODIST_CHAOS",
              "AUTODIST_SUPERVISE", "AUTODIST_FAILURE_POLICY",
              "AUTODIST_SUPERVISOR_DIR", "AUTODIST_ATTEMPT"):
        env.pop(k, None)
    env.update({
        "AUTODIST_REPO_ROOT": REPO,
        "AUTODIST_RESULT_FILE": str(tmp_path / f"result_{tag}.json"),
        "AUTODIST_TEST_CKPT": str(tmp_path / f"ckpt_{tag}"),
        "AUTODIST_TPU_WORKDIR": str(tmp_path / f"workdir_{tag}"),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    return env


def _run(env, timeout=300):
    proc = subprocess.run([sys.executable, "-u", SCRIPT], env=env,
                          timeout=timeout, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT)
    return proc.returncode, proc.stdout.decode()


def test_supervised_recovery_from_worker_kill(tmp_path):
    # ORACLE: the same job, chaos off, single attempt, no supervisor.
    env = _base_env(tmp_path, "oracle")
    env["AUTODIST_COORDINATOR_ADDRESS"] = f"127.0.0.1:{_free_port()}"
    rc, out = _run(env)
    assert rc == 0, f"oracle failed (rc={rc}):\n{out[-4000:]}"
    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        oracle = json.load(f)
    assert oracle["final_step"] == 16          # 4 epochs x 4 batches

    # SUPERVISED: kill the worker (proc 1) at step 6 of attempt 0; the
    # retry (attempt 1) must run chaos-free and finish the job.
    env = _base_env(tmp_path, "sup")
    env.update({
        "AUTODIST_SUPERVISE": "1",
        "AUTODIST_CHAOS": "kill@step=6,proc=1,attempt=0",
        "AUTODIST_SUPERVISOR_REPORT": str(tmp_path / "report.json"),
        "AUTODIST_TEST_MAX_RESTARTS": "2",
    })
    rc, out = _run(env, timeout=480)
    assert rc == 0, f"supervised job failed (rc={rc}):\n{out[-6000:]}"
    with open(env["AUTODIST_SUPERVISOR_REPORT"], encoding="utf-8") as f:
        report = json.load(f)
    assert report["ok"]
    # exactly one failure (the injected kill), recovered on attempt 2
    assert report["attempts"] == 2
    assert len(report["failures"]) == 1
    assert report["failures"][0]["kind"] == "exit"
    # the supervised abort marked the WORKER host as the culprit
    assert report["failures"][0]["culprit"] in ("localhost", "chief")

    with open(env["AUTODIST_RESULT_FILE"], encoding="utf-8") as f:
        chief = json.load(f)
    with open(env["AUTODIST_RESULT_FILE"] + ".worker",
              encoding="utf-8") as f:
        worker = json.load(f)
    # the successful attempt was #1 and it RESUMED (ran < 16 steps)
    assert chief["attempt"] == 1 and worker["attempt"] == 1
    assert chief["process_count"] == 2
    assert chief["final_step"] == 16
    assert chief["steps_run_this_attempt"] < 16

    # recovery is EXACT: same final parameters as the uninterrupted run
    np.testing.assert_allclose(chief["final_w"], oracle["final_w"],
                               rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(chief["final_b"], oracle["final_b"],
                               rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(worker["final_w"], oracle["final_w"],
                               rtol=1e-7, atol=1e-8)
    # both attempts' evidence in the log: the watcher fired the policy,
    # the supervisor relaunched, and the resumed fit restored exactly
    assert "aborting job" in out
    assert "supervisor: attempt 2/3" in out
    assert "exact data resume" in out
