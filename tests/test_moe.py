"""MoE routing + expert parallelism.

Oracles: (a) routing invariants (combine weights sum to ≤1, capacity is
respected), (b) a per-token dense reference computation of the same top-2
routed FFN, (c) expert-sharded mesh run == unsharded run, (d) end-to-end
MoE LM training through AutoDist with the expert axis active.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.mesh import build_mesh
from autodist_tpu.parallel.moe import _top2_dispatch, init_moe_params, moe_ffn


def test_dispatch_invariants():
    rng = np.random.default_rng(0)
    probs = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((2, 16, 4)), jnp.float32))
    capacity = 8
    dispatch, combine, aux = _top2_dispatch(probs, capacity)
    assert dispatch.shape == (2, 16, 4, 8)
    # Each token occupies at most 2 expert slots with weights summing to ≤1.
    per_token = combine.sum(axis=(2, 3))
    assert float(per_token.max()) <= 1.0 + 1e-5
    slots = dispatch.astype(np.int32).sum(axis=(2, 3))
    assert int(slots.max()) <= 2
    # No expert buffer slot is used twice within a group.
    slot_use = dispatch.astype(np.int32).sum(axis=1)       # [G,E,C]
    assert int(slot_use.max()) <= 1
    assert float(aux) > 0.0


def test_moe_ffn_matches_dense_reference():
    """Reference: loop over tokens, apply each token's kept experts."""
    rng = np.random.default_rng(1)
    g, s, m, f, e = 2, 8, 4, 16, 4
    params = init_moe_params(jax.random.PRNGKey(0), m, f, e)
    x = jnp.asarray(rng.standard_normal((g, s, m)), jnp.float32)
    capacity = s  # no drops
    y, _ = moe_ffn(params, x, capacity_factor=float(capacity * e) / s)

    probs = jax.nn.softmax(
        jnp.einsum("gsm,me->gse", x, params["router"]), axis=-1)
    dispatch, combine, _ = _top2_dispatch(probs, capacity)
    y_ref = np.zeros((g, s, m), np.float32)
    wsum = combine.sum(axis=(2, 3))
    for gi in range(g):
        for si in range(s):
            acc = np.zeros(m, np.float32)
            for ei in range(e):
                w = float(combine[gi, si, ei].sum())
                if w > 0:
                    h = jax.nn.gelu(x[gi, si] @ params["wi"][ei])
                    acc += w * np.asarray(h @ params["wo"][ei])
            y_ref[gi, si] = acc
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-4)
    # With capacity == s nothing drops: weights sum to 1 per token.
    np.testing.assert_allclose(np.asarray(wsum), 1.0, rtol=1e-5)


def test_expert_sharded_matches_unsharded():
    rng = np.random.default_rng(2)
    g, s, m, f, e = 4, 16, 8, 32, 4
    params = init_moe_params(jax.random.PRNGKey(1), m, f, e)
    x = jnp.asarray(rng.standard_normal((g, s, m)), jnp.float32)
    y0, aux0 = moe_ffn(params, x)

    mesh = build_mesh({"data": 2, "expert": 4})
    shard = jax.NamedSharding(mesh, jax.sharding.PartitionSpec("expert"))
    params_sh = dict(params)
    params_sh["wi"] = jax.device_put(params["wi"], shard)
    params_sh["wo"] = jax.device_put(params["wo"], shard)

    @jax.jit
    def run(p, x):
        return moe_ffn(p, x, mesh=mesh)

    with jax.set_mesh(mesh):
        y1, aux1 = run(params_sh, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux0), float(aux1), rtol=1e-6)


def test_moe_lm_end_to_end():
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.moe_lm import moe_transformer_lm
    from autodist_tpu.strategy import Parallax

    axes = {"data": 2, "expert": 2, "model": 2}
    mesh = build_mesh(axes)
    spec = moe_transformer_lm(
        mesh, vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
        d_ff=32, num_experts=4, max_len=16, seq_len=16)
    params = spec.init(jax.random.PRNGKey(0))

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=Parallax(), mesh_axes=axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                   expert_vars=spec.expert_vars)
    sess = ad.create_distributed_session(mesh=mesh)

    # Expert weights must actually be sharded over the expert axis.
    wi = sess.sharded_params["layers_0"]["moe"]["wi"]
    assert "expert" in str(wi.sharding.spec)

    batch = spec.sample_batch(8)
    losses = [float(sess.run(batch)["loss"]) for _ in range(4)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_pipelined_moe_lm_end_to_end():
    """Pipeline × expert × data in one program; must match the same model
    on a no-pipe mesh step for step."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_moe_lm import \
        pipelined_moe_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    def run(axes):
        _reset_default_autodist_for_testing()
        mesh = build_mesh(axes)
        spec = pipelined_moe_transformer_lm(
            mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
            d_ff=32, num_experts=2, max_len=16, seq_len=16)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars,
                       expert_vars=spec.expert_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        if axes.get("pipe", 1) > 1:
            wi = sess.sharded_params["stack"]["moe"]["wi"]
            assert "pipe" in str(wi.sharding.spec)
            assert "expert" in str(wi.sharding.spec)
        batch = spec.sample_batch(8)
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    piped = run({"pipe": 2, "expert": 2, "data": 2})
    flat = run({"data": 8})
    np.testing.assert_allclose(piped, flat, rtol=1e-4, atol=1e-4)
    assert piped[-1] < piped[0]


@pytest.mark.parametrize("num_virtual", [1, 2])
def test_pipelined_moe_lm_1f1b_matches_gpipe(num_virtual):
    """1F1B x expert x data: the hand-scheduled backward (with the MoE
    aux loss riding the activation channel) matches the autodiff GPipe
    spec step for step on the same pipe x expert x data mesh."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_moe_lm import \
        pipelined_moe_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    axes = {"pipe": 2, "expert": 2, "data": 2}
    mesh = build_mesh(axes)
    kw = dict(vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
              d_ff=32, num_experts=2, max_len=16, seq_len=16,
              num_microbatches=2, num_virtual_stages=num_virtual)
    spec_1f1b = pipelined_moe_transformer_lm(mesh, schedule="1f1b", **kw)
    spec_ref = pipelined_moe_transformer_lm(mesh, schedule="gpipe", **kw)
    assert spec_1f1b.grad_fn is not None and spec_ref.grad_fn is None
    params = spec_ref.init(jax.random.PRNGKey(0))
    batch = spec_ref.sample_batch(8)

    def run(spec, use_gf):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn,
                       grad_fn=spec.grad_fn if use_gf else None,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars,
                       expert_vars=spec.expert_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    l_1f1b = run(spec_1f1b, True)
    l_ref = run(spec_ref, False)
    np.testing.assert_allclose(l_1f1b, l_ref, rtol=3e-4)
    assert l_1f1b[-1] < l_1f1b[0]


# -- the quantized expert wire ------------------------------------------------

@pytest.mark.moe
@pytest.mark.quant
def test_int8_wire_exact_dequant_parity():
    """Grid-exact inputs must cross the int8 a2a wire bit-exactly.

    Construction: d_model == the quant block size (256), identity
    expert FFNs, identity activation, and integer token vectors in
    [-127, 127] whose first feature pins every block's amax to 127 —
    so the per-block scale is exactly 1.0 and int8 quantization is the
    identity on the payload.  The quantized run must then equal the
    full-precision run bit for bit."""
    rng = np.random.default_rng(7)
    g, s, m, e = 2, 8, 256, 4
    eye = jnp.broadcast_to(jnp.eye(m, dtype=jnp.float32), (e, m, m))
    params = {
        "router": jnp.asarray(rng.standard_normal((m, e)), jnp.float32),
        "wi": eye, "wo": eye,
    }
    x = jnp.asarray(rng.integers(-126, 127, size=(g, s, m)), jnp.float32)
    x = x.at[:, :, 0].set(127.0)
    mesh = build_mesh({"data": 2, "expert": 4})
    kw = dict(capacity_factor=float(e), mesh=mesh,
              activation=lambda t: t)
    with jax.set_mesh(mesh):
        y_f32, aux_f32 = moe_ffn(params, x, wire=None, **kw)
        y_q, aux_q = moe_ffn(params, x, wire="int8", **kw)
    np.testing.assert_array_equal(np.asarray(y_f32), np.asarray(y_q))
    np.testing.assert_array_equal(np.asarray(aux_f32), np.asarray(aux_q))


@pytest.mark.moe
@pytest.mark.quant
def test_int8_wire_stays_close_on_generic_inputs():
    """Off-grid inputs pay only per-block int8 rounding across the two
    a2a boundaries — the routed output stays within quantization noise
    of the full-precision run."""
    rng = np.random.default_rng(8)
    g, s, m, f, e = 2, 16, 8, 32, 4
    params = init_moe_params(jax.random.PRNGKey(3), m, f, e)
    x = jnp.asarray(rng.standard_normal((g, s, m)), jnp.float32)
    mesh = build_mesh({"data": 2, "expert": 4})
    with jax.set_mesh(mesh):
        y_f32, _ = moe_ffn(params, x, mesh=mesh)
        y_q, _ = moe_ffn(params, x, mesh=mesh, wire="int8")
    np.testing.assert_allclose(np.asarray(y_f32), np.asarray(y_q),
                               rtol=0.05, atol=0.05)


@pytest.mark.moe
def test_moe_wire_env_knob_shared_with_ir(monkeypatch):
    """AUTODIST_MOE_WIRE=int8 flips BOTH sides through the same knob:
    the runtime wire format and the IR facts' compressor (whose leg
    bytes shrink to the quantized payload + scale grid)."""
    from autodist_tpu.kernel.synchronization import quant_ring
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.parallel.moe import moe_wire_format

    monkeypatch.delenv("AUTODIST_MOE_WIRE", raising=False)
    assert moe_wire_format(None) is None
    assert sir.moe_wire_compressor_default() == "NoneCompressor"
    monkeypatch.setenv("AUTODIST_MOE_WIRE", "int8")
    fmt = moe_wire_format(None)
    assert fmt is not None and fmt.name == "int8"
    assert sir.moe_wire_compressor_default() == "Int8Compressor"

    full = sir.MoEFact(key="l0/moe", groups=2, seq=1024, d_model=64,
                       num_experts=8)
    quant = sir.MoEFact(key="l0/moe", groups=2, seq=1024, d_model=64,
                        num_experts=8, compressor="Int8Compressor")
    elems = full.payload_elems(4)
    assert quant.leg_nbytes(4) == quant_ring.wire_nbytes(
        elems, quant_ring.wire_format_of("Int8Compressor"))
    assert quant.leg_nbytes(4) < full.leg_nbytes(4)


@pytest.mark.moe
def test_runtime_capacity_overflow_warns(monkeypatch):
    """The runtime half of moe/capacity-overflow: an under-provisioned
    capacity_factor logs the shared rule's verdict once per config."""
    from autodist_tpu.parallel import moe as moe_mod

    hits = []
    monkeypatch.setattr(
        moe_mod.logging, "warning",
        lambda msg, *a, **k: hits.append(msg % a if a else msg))
    rng = np.random.default_rng(9)
    params = init_moe_params(jax.random.PRNGKey(4), 8, 16, 4)
    x = jnp.asarray(rng.standard_normal((2, 16, 8)), jnp.float32)
    moe_mod._warned_capacity.clear()
    moe_ffn(params, x, capacity_factor=0.5)
    moe_ffn(params, x, capacity_factor=0.5)       # same config: one line
    overflow = [m for m in hits if "moe/capacity-overflow" in m]
    assert len(overflow) == 1
    assert "75%" in overflow[0]
    hits.clear()
    moe_mod._warned_capacity.clear()
    moe_ffn(params, x, capacity_factor=2.0)       # provisioned: silent
    assert not [m for m in hits if "moe/capacity-overflow" in m]
