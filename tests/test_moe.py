"""MoE routing + expert parallelism.

Oracles: (a) routing invariants (combine weights sum to ≤1, capacity is
respected), (b) a per-token dense reference computation of the same top-2
routed FFN, (c) expert-sharded mesh run == unsharded run, (d) end-to-end
MoE LM training through AutoDist with the expert axis active.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.mesh import build_mesh
from autodist_tpu.parallel.moe import _top2_dispatch, init_moe_params, moe_ffn


def test_dispatch_invariants():
    rng = np.random.default_rng(0)
    probs = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((2, 16, 4)), jnp.float32))
    capacity = 8
    dispatch, combine, aux = _top2_dispatch(probs, capacity)
    assert dispatch.shape == (2, 16, 4, 8)
    # Each token occupies at most 2 expert slots with weights summing to ≤1.
    per_token = combine.sum(axis=(2, 3))
    assert float(per_token.max()) <= 1.0 + 1e-5
    slots = dispatch.astype(np.int32).sum(axis=(2, 3))
    assert int(slots.max()) <= 2
    # No expert buffer slot is used twice within a group.
    slot_use = dispatch.astype(np.int32).sum(axis=1)       # [G,E,C]
    assert int(slot_use.max()) <= 1
    assert float(aux) > 0.0


def test_moe_ffn_matches_dense_reference():
    """Reference: loop over tokens, apply each token's kept experts."""
    rng = np.random.default_rng(1)
    g, s, m, f, e = 2, 8, 4, 16, 4
    params = init_moe_params(jax.random.PRNGKey(0), m, f, e)
    x = jnp.asarray(rng.standard_normal((g, s, m)), jnp.float32)
    capacity = s  # no drops
    y, _ = moe_ffn(params, x, capacity_factor=float(capacity * e) / s)

    probs = jax.nn.softmax(
        jnp.einsum("gsm,me->gse", x, params["router"]), axis=-1)
    dispatch, combine, _ = _top2_dispatch(probs, capacity)
    y_ref = np.zeros((g, s, m), np.float32)
    wsum = combine.sum(axis=(2, 3))
    for gi in range(g):
        for si in range(s):
            acc = np.zeros(m, np.float32)
            for ei in range(e):
                w = float(combine[gi, si, ei].sum())
                if w > 0:
                    h = jax.nn.gelu(x[gi, si] @ params["wi"][ei])
                    acc += w * np.asarray(h @ params["wo"][ei])
            y_ref[gi, si] = acc
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-4)
    # With capacity == s nothing drops: weights sum to 1 per token.
    np.testing.assert_allclose(np.asarray(wsum), 1.0, rtol=1e-5)


def test_expert_sharded_matches_unsharded():
    rng = np.random.default_rng(2)
    g, s, m, f, e = 4, 16, 8, 32, 4
    params = init_moe_params(jax.random.PRNGKey(1), m, f, e)
    x = jnp.asarray(rng.standard_normal((g, s, m)), jnp.float32)
    y0, aux0 = moe_ffn(params, x)

    mesh = build_mesh({"data": 2, "expert": 4})
    shard = jax.NamedSharding(mesh, jax.sharding.PartitionSpec("expert"))
    params_sh = dict(params)
    params_sh["wi"] = jax.device_put(params["wi"], shard)
    params_sh["wo"] = jax.device_put(params["wo"], shard)

    @jax.jit
    def run(p, x):
        return moe_ffn(p, x, mesh=mesh)

    with jax.set_mesh(mesh):
        y1, aux1 = run(params_sh, x)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux0), float(aux1), rtol=1e-6)


def test_moe_lm_end_to_end():
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.moe_lm import moe_transformer_lm
    from autodist_tpu.strategy import Parallax

    axes = {"data": 2, "expert": 2, "model": 2}
    mesh = build_mesh(axes)
    spec = moe_transformer_lm(
        mesh, vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
        d_ff=32, num_experts=4, max_len=16, seq_len=16)
    params = spec.init(jax.random.PRNGKey(0))

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=Parallax(), mesh_axes=axes)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                   expert_vars=spec.expert_vars)
    sess = ad.create_distributed_session(mesh=mesh)

    # Expert weights must actually be sharded over the expert axis.
    wi = sess.sharded_params["layers_0"]["moe"]["wi"]
    assert "expert" in str(wi.sharding.spec)

    batch = spec.sample_batch(8)
    losses = [float(sess.run(batch)["loss"]) for _ in range(4)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_pipelined_moe_lm_end_to_end():
    """Pipeline × expert × data in one program; must match the same model
    on a no-pipe mesh step for step."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_moe_lm import \
        pipelined_moe_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    def run(axes):
        _reset_default_autodist_for_testing()
        mesh = build_mesh(axes)
        spec = pipelined_moe_transformer_lm(
            mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
            d_ff=32, num_experts=2, max_len=16, seq_len=16)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars,
                       expert_vars=spec.expert_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        if axes.get("pipe", 1) > 1:
            wi = sess.sharded_params["stack"]["moe"]["wi"]
            assert "pipe" in str(wi.sharding.spec)
            assert "expert" in str(wi.sharding.spec)
        batch = spec.sample_batch(8)
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    piped = run({"pipe": 2, "expert": 2, "data": 2})
    flat = run({"data": 8})
    np.testing.assert_allclose(piped, flat, rtol=1e-4, atol=1e-4)
    assert piped[-1] < piped[0]


@pytest.mark.parametrize("num_virtual", [1, 2])
def test_pipelined_moe_lm_1f1b_matches_gpipe(num_virtual):
    """1F1B x expert x data: the hand-scheduled backward (with the MoE
    aux loss riding the activation channel) matches the autodiff GPipe
    spec step for step on the same pipe x expert x data mesh."""
    import os
    os.environ["AUTODIST_IS_TESTING"] = "True"
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.pipelined_moe_lm import \
        pipelined_moe_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    axes = {"pipe": 2, "expert": 2, "data": 2}
    mesh = build_mesh(axes)
    kw = dict(vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
              d_ff=32, num_experts=2, max_len=16, seq_len=16,
              num_microbatches=2, num_virtual_stages=num_virtual)
    spec_1f1b = pipelined_moe_transformer_lm(mesh, schedule="1f1b", **kw)
    spec_ref = pipelined_moe_transformer_lm(mesh, schedule="gpipe", **kw)
    assert spec_1f1b.grad_fn is not None and spec_ref.grad_fn is None
    params = spec_ref.init(jax.random.PRNGKey(0))
    batch = spec_ref.sample_batch(8)

    def run(spec, use_gf):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn,
                       grad_fn=spec.grad_fn if use_gf else None,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars,
                       expert_vars=spec.expert_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    l_1f1b = run(spec_1f1b, True)
    l_ref = run(spec_ref, False)
    np.testing.assert_allclose(l_1f1b, l_ref, rtol=3e-4)
    assert l_1f1b[-1] < l_1f1b[0]
