"""untrainable_vars freezes variables for real: zero updates, no
optimizer state, excluded from sync plans — on the GSPMD path, the
explicit shard_map path, and through checkpoint-visible opt state."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce, PSLoadBalancing


def _setup(builder, untrainable, accum_steps=1, batch_size=8):
    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {"backbone": {"w": jnp.asarray(rng.randn(4, 4), jnp.float32)},
              "head": {"w": jnp.asarray(rng.randn(4, 2), jnp.float32),
                       "b": jnp.zeros((2,))}}
    batch = {"x": rng.randn(batch_size, 4).astype(np.float32),
             "y": rng.randn(batch_size, 2).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["backbone"]["w"])
        return jnp.mean((h @ p["head"]["w"] + p["head"]["b"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn, untrainable_vars=untrainable,
                   accum_steps=accum_steps)
    sess = ad.create_distributed_session()
    return sess, params, batch, loss_fn


@pytest.mark.parametrize("builder", [AllReduce(), PSLoadBalancing()])
def test_frozen_leaves_do_not_move(builder):
    sess, params, batch, _ = _setup(builder, ("backbone",))
    for _ in range(4):
        sess.run(batch)
    after = sess.params
    np.testing.assert_array_equal(np.asarray(after["backbone"]["w"]),
                                  np.asarray(params["backbone"]["w"]))
    assert not np.allclose(np.asarray(after["head"]["w"]),
                           np.asarray(params["head"]["w"]))


def test_trainable_updates_match_manual_frozen_baseline():
    """With the backbone frozen, head updates must equal a hand-rolled
    loop that optimizes ONLY the head (same grads, same adam state)."""
    sess, params, batch, loss_fn = _setup(AllReduce(), ("backbone",))
    for _ in range(5):
        sess.run(batch)
    got = sess.params

    head = params["head"]
    opt = optax.adam(1e-2)
    state = opt.init(head)

    def head_loss(h, b):
        return loss_fn({"backbone": params["backbone"], "head": h}, b)

    for _ in range(5):
        g = jax.grad(head_loss)(head, batch)
        upd, state = opt.update(g, state, head)
        head = optax.apply_updates(head, upd)
    np.testing.assert_allclose(np.asarray(got["head"]["w"]),
                               np.asarray(head["w"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["head"]["b"]),
                               np.asarray(head["b"]), rtol=1e-5, atol=1e-6)


def test_no_optimizer_state_for_frozen():
    """The frozen subtree carries no Adam moments: every param-shaped
    leaf in the optimizer state belongs to the trainable subtree."""
    sess, params, _, _ = _setup(AllReduce(), ("backbone",))
    frozen_shape = tuple(params["backbone"]["w"].shape)
    shapes = [tuple(x.shape) for x in jax.tree_util.tree_leaves(
        sess.opt_state) if hasattr(x, "shape")]
    assert frozen_shape not in shapes, \
        f"frozen leaf shape {frozen_shape} found in opt state: {shapes}"


def test_frozen_on_explicit_path():
    """Compressor programs ride the explicit shard_map path; freezing
    must hold there too."""
    sess, params, batch, _ = _setup(
        AllReduce(compressor="HorovodCompressorEF"), ("backbone",),
        accum_steps=2, batch_size=32)   # 8 devices x 2 microbatches x 2
    from autodist_tpu.kernel.synchronization import explicit_sync
    assert explicit_sync.uses_explicit_path(sess._step.compiled_strategy)
    for _ in range(3):
        sess.run(batch)
    after = sess.params
    np.testing.assert_array_equal(np.asarray(after["backbone"]["w"]),
                                  np.asarray(params["backbone"]["w"]))
    assert not np.allclose(np.asarray(after["head"]["w"]),
                           np.asarray(params["head"]["w"]))
