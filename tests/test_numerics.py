"""Numerics guard: fused non-finite detection, exact clipping, loss
scaling, verified-good checkpoints, and anomaly rollback
(docs/numerics.md).

The guard matrix drives one chaos ``nan_grad`` injection through every
sync tier — GSPMD, per-variable fallback, bucketed, ZeRO-1, and
pipelined overlap — and requires detection on the EXACT step plus a
bit-identical skip.  The clipping parity tests hold the sharded
(ZeRO-1 + overlap) clip to 1e-6 against an unsharded optax chain.  The
rollback drill replays the resilience harness pattern: a chaos-driven
anomaly, recovery from the last verified-good checkpoint, and exact
parity with an uninterrupted oracle.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce, Zero1

pytestmark = pytest.mark.numerics

RTOL = 1e-6


def _params():
    rng = np.random.RandomState(0)
    return {"l0": {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32),
                   "b": jnp.zeros((16,), jnp.float32)},
            "l1": {"w": jnp.asarray(rng.randn(16, 4) * 0.1, jnp.float32)}}


def _batches(n=5, rows=32):
    rng = np.random.RandomState(7)
    return [{"x": rng.randn(rows, 16).astype(np.float32),
             "y": rng.randn(rows, 4).astype(np.float32)} for _ in range(n)]


def _loss_fn(p, b):
    h = jnp.tanh(b["x"] @ p["l0"]["w"] + p["l0"]["b"])
    return jnp.mean((h @ p["l1"]["w"] - b["y"]) ** 2)


def _session(builder, numerics, accum=1, params=None, optimizer=None):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params or _params(),
                   optimizer=optimizer or optax.adam(1e-2),
                   loss_fn=_loss_fn, accum_steps=accum, numerics=numerics)
    return ad.create_distributed_session()


def _host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- the guard matrix --------------------------------------------------------

PATHS = {
    # per-variable tier: PowerSGD is non-bucketable, so every var keeps
    # its own collective on the explicit path.
    "gspmd": (lambda: AllReduce(), 1),
    "per_variable": (lambda: AllReduce(compressor="PowerSGDCompressor"), 1),
    "bucketed": (lambda: AllReduce(bucket_bytes=1 << 20), 1),
    "zero1": (lambda: Zero1(bucket_bytes=1 << 20), 1),
    "pipelined": (lambda: Zero1(bucket_bytes=1 << 20), 4),
}


@pytest.mark.parametrize("path", sorted(PATHS))
def test_injected_nan_detected_on_exact_step_and_skip_is_bitwise(
        path, monkeypatch):
    monkeypatch.setenv("AUTODIST_CHAOS", "nan_grad@step=1,var=l0/w")
    builder, accum = PATHS[path]
    sess = _session(builder(), True, accum=accum)
    batch = _batches(1)[0]
    for step in range(3):
        pre_p = _host(sess.params)
        pre_o = _host(jax.tree_util.tree_leaves(sess.opt_state))
        out = sess.run(batch)
        h = out["grad_health"]
        if step == 1:
            assert not bool(h.all_finite), \
                f"{path}: NaN not detected on the injected step"
            # skip: params AND optimizer state bit-identical
            _assert_trees_equal(pre_p, _host(sess.params))
            _assert_trees_equal(
                pre_o, _host(jax.tree_util.tree_leaves(sess.opt_state)))
            assert int(h.skipped_steps) == 1
        else:
            assert bool(h.all_finite), \
                f"{path}: step {step} falsely flagged non-finite"
            assert np.isfinite(float(h.global_norm))
    assert int(out["grad_health"].skipped_steps) == 1


def test_per_bucket_health_keys_cover_the_plan():
    sess = _session(Zero1(bucket_bytes=1 << 20), True)
    out = sess.run(_batches(1)[0])
    pb = out["grad_health"].per_bucket
    assert any(k.startswith("reduce_scatter:") for k in pb)
    for entry in pb.values():
        assert bool(entry["finite"])
        assert float(entry["sq_norm"]) >= 0.0


# -- exact global-norm clipping ---------------------------------------------

@pytest.mark.parametrize("path", ["gspmd", "bucketed", "zero1", "pipelined"])
def test_clip_matches_unsharded_optax_chain(path):
    clip = 0.05
    batches = _batches(5)
    opt = optax.chain(optax.clip_by_global_norm(clip), optax.adam(1e-2))
    ref_p, ref_s = _params(), None
    ref_s = opt.init(ref_p)

    @jax.jit
    def ref_step(p, s, b):
        _, g = jax.value_and_grad(_loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    for b in batches:
        ref_p, ref_s = ref_step(ref_p, ref_s, b)
    ref = _host(ref_p)

    builder, accum = PATHS[path]
    sess = _session(builder(), {"clip_norm": clip, "loss_scale": None},
                    accum=accum)
    for b in batches:
        out = sess.run(b)
    assert bool(out["grad_health"].all_finite)
    for a, g in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(sess.params)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(a),
                                   rtol=RTOL, atol=RTOL)


# -- dynamic loss scaling ----------------------------------------------------

def test_loss_scale_backoff_and_growth(monkeypatch):
    from autodist_tpu.numerics import LossScale

    monkeypatch.setenv("AUTODIST_CHAOS", "nan_grad@step=1")
    sess = _session(
        Zero1(bucket_bytes=1 << 20),
        {"loss_scale": LossScale(init=4.0, growth_factor=2.0,
                                 backoff_factor=0.5, growth_interval=2,
                                 min_scale=0.25)})
    batch = _batches(1)[0]
    scales, skipped = [], []
    for _ in range(5):
        h = sess.run(batch)["grad_health"]
        scales.append(float(h.loss_scale))
        skipped.append(int(h.skipped_steps))
    # step0 clean (good=1) -> step1 NaN: backoff 4->2 -> steps 2,3 clean
    # (good hits the interval after step3 -> grow back to 4 for step 4).
    assert scales == [4.0, 4.0, 2.0, 2.0, 4.0]
    assert skipped == [0, 1, 1, 1, 1]


def test_loss_scale_auto_enables_for_bf16_only():
    p32 = _params()
    sess = _session(AllReduce(bucket_bytes=1 << 20), True, params=p32)
    assert float(sess.run(_batches(1)[0])["grad_health"].loss_scale) == 1.0

    p16 = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), p32)
    sess = _session(AllReduce(bucket_bytes=1 << 20), True, params=p16)
    h = sess.run(_batches(1)[0])["grad_health"]
    assert float(h.loss_scale) == 2.0 ** 15
    assert bool(h.all_finite)


def test_reported_loss_is_unscaled():
    batch = _batches(1)[0]
    plain = _session(AllReduce(bucket_bytes=1 << 20), None)
    ref = float(plain.run(batch)["loss"])
    scaled = _session(AllReduce(bucket_bytes=1 << 20),
                      {"loss_scale": 1024.0})
    out = scaled.run(batch)
    np.testing.assert_allclose(float(out["loss"]), ref, rtol=1e-5)


def test_loss_scale_state_rides_checkpoints(tmp_path, monkeypatch):
    from autodist_tpu.checkpoint import Saver

    monkeypatch.setenv("AUTODIST_CHAOS", "nan_grad@step=0")
    sess = _session(Zero1(bucket_bytes=1 << 20),
                    {"loss_scale": 256.0, "on_nonfinite": "skip"})
    batch = _batches(1)[0]
    sess.run(batch)   # static scale: stays 256 even after the skip
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ck"))
    assert Saver.read_meta(path)["has_sync_state"]

    monkeypatch.delenv("AUTODIST_CHAOS")
    sess2 = _session(Zero1(bucket_bytes=1 << 20),
                     {"loss_scale": 256.0, "on_nonfinite": "skip"})
    saver2 = Saver(sess2)
    step = saver2.restore(path)
    assert step == sess.step_count
    h = sess2.run(batch)["grad_health"]
    # the cumulative skip counter survived the checkpoint round-trip
    assert int(h.skipped_steps) == 1
    assert float(h.loss_scale) == 256.0


# -- build-time safety -------------------------------------------------------

def test_saturating_scale_with_quantizing_compressor_raises():
    with pytest.raises(ValueError, match="saturate"):
        _session(AllReduce(compressor="HorovodCompressorEF",
                           bucket_bytes=1 << 20),
                 {"loss_scale": 1e36})


def test_wire_saturation_flag_pure():
    from autodist_tpu.numerics.guard import wire_saturation

    vec = jnp.asarray([1e5, 1.0], jnp.float32)     # 1e5 overflows fp16
    assert wire_saturation(vec, None) is None
    assert bool(wire_saturation(vec, "float16"))
    assert not bool(wire_saturation(vec, "bfloat16"))


# -- chaos harness events ----------------------------------------------------

def test_chaos_parses_numerics_events_and_on_step_ignores_them():
    from autodist_tpu.resilience.chaos import ChaosMonkey, parse_chaos

    events = parse_chaos("nan_grad@step=3,bucket=b0;inf_grad@step=4,"
                         "var=l0/w;loss_spike@step=9,factor=1e6")
    assert [e.action for e in events] == ["nan_grad", "inf_grad",
                                          "loss_spike"]
    assert events[0].args["bucket"] == "b0"
    assert events[2].args["factor"] == "1e6"
    monkey = ChaosMonkey(events, process_index=0)
    monkey.on_step(9)   # must NOT fire (grad/monitor events ride elsewhere)
    assert not any(e.fired for e in monkey.events)


def test_grad_injections_filter_by_proc_and_attempt(monkeypatch):
    from autodist_tpu.resilience import chaos

    monkeypatch.setenv(
        "AUTODIST_CHAOS",
        "nan_grad@step=1,proc=3;inf_grad@step=2;kill@step=9")
    evs = chaos.grad_injections(process_index=0)
    assert [e.action for e in evs] == ["inf_grad"]
    evs = chaos.grad_injections(process_index=3)
    assert [e.action for e in evs] == ["nan_grad", "inf_grad"]
    monkeypatch.setenv("AUTODIST_CHAOS", "loss_spike@step=5,attempt=1")
    monkeypatch.setenv("AUTODIST_ATTEMPT", "0")
    assert chaos.loss_spike_events(process_index=0) == []


# -- verified-good checkpoints ----------------------------------------------

def test_mark_good_prefers_and_protects(tmp_path):
    from autodist_tpu.checkpoint import Saver

    sess = _session(AllReduce(bucket_bytes=1 << 20), True)
    batch = _batches(1)[0]
    ckdir = str(tmp_path / "ck")
    saver = Saver(sess)
    paths = {}
    for want in (1, 2, 3):
        while sess.step_count < want:
            sess.run(batch)
        paths[want] = saver.save(ckdir)
    assert Saver.latest_step(ckdir) == 3

    assert Saver.mark_good(paths[2])
    # verified-good step 2 outranks the newer merely-uncorrupted step 3
    assert Saver.good_steps(ckdir) == [2]
    assert Saver.latest_step(ckdir) == 2
    assert Saver.last_good_checkpoint(ckdir) == paths[2]

    # restore_last_good restores THE good step, not the newest
    sess.run(batch)
    restored = saver.restore_last_good(ckdir)
    assert restored == 2 and sess.step_count == 2

    # retention never GCs the last good step
    saver_keep = Saver(sess, keep=1)
    while sess.step_count < 5:
        sess.run(batch)
    saver_keep.save(ckdir)
    kept = Saver._committed_steps(ckdir)
    assert 2 in kept, "keep=1 deleted the verified-good rollback anchor"
    assert 5 in kept
    assert 1 not in kept and 3 not in kept


def test_mark_good_refuses_corrupt_step(tmp_path):
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.resilience.chaos import corrupt_checkpoint

    sess = _session(AllReduce(bucket_bytes=1 << 20), True)
    sess.run(_batches(1)[0])
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ck"))
    corrupt_checkpoint(path, item="params", mode="truncate")
    assert not Saver.mark_good(path)
    assert Saver.good_steps(str(tmp_path / "ck")) == []


# -- fit policies ------------------------------------------------------------

def _fit_session(numerics):
    return _session(AllReduce(bucket_bytes=1 << 20), numerics)


def test_fit_skip_counts_in_history(monkeypatch):
    monkeypatch.setenv("AUTODIST_CHAOS", "nan_grad@step=2")
    sess = _fit_session(True)
    hist = sess.fit(_batches(4), epochs=2, steps_per_epoch=4)
    assert hist.history["skipped_steps"][-1] == 1
    assert hist.steps_run == 8


def test_fit_on_nonfinite_raise(monkeypatch):
    from autodist_tpu.numerics import NonFiniteError

    monkeypatch.setenv("AUTODIST_CHAOS", "nan_grad@step=2")
    sess = _fit_session(True)
    with pytest.raises(NonFiniteError, match="step 3"):
        # step counter is 1-based after the run; injection hits the
        # step whose on-device counter is 2 (the third step).
        sess.fit(_batches(4), epochs=2, steps_per_epoch=4,
                 on_nonfinite="raise")


def test_fit_on_nonfinite_requires_guard():
    sess = _session(AllReduce(bucket_bytes=1 << 20), None)
    with pytest.raises(ValueError, match="numerics"):
        sess.fit(_batches(2), epochs=1, on_nonfinite="raise")


# -- the rollback drill ------------------------------------------------------

def test_chaos_loss_spike_rollback_matches_uninterrupted_oracle(
        tmp_path, monkeypatch):
    """The acceptance drill (resilience-harness pattern): a chaos
    loss_spike trips the z-score detector mid-epoch; fit restores the
    last verified-good checkpoint, replays, and the recovered run's
    final parameters match an uninterrupted oracle exactly (the spike
    only touched the MONITORED loss, and list data replays verbatim)."""
    from autodist_tpu.checkpoint import Saver

    batches = _batches(4, rows=32)
    numerics = {"on_nonfinite": "rollback", "spike_zscore": 3.0,
                "spike_window": 8, "rollback_after": 2}

    # ORACLE: same program, chaos off.
    sess = _fit_session(numerics)
    oracle_hist = sess.fit(batches, epochs=4, steps_per_epoch=4,
                           checkpoint_dir=str(tmp_path / "oracle"))
    oracle = _host(sess.params)
    assert "rollbacks" not in oracle_hist.history
    # clean-guard saves are marked verified-good
    assert Saver.good_steps(str(tmp_path / "oracle"))

    # DRILL: spike the monitored loss at step 11 (epoch 2, mid-epoch).
    monkeypatch.setenv("AUTODIST_CHAOS", "loss_spike@step=11,factor=1e6")
    marker_dir = str(tmp_path / "markers")
    monkeypatch.setenv("AUTODIST_SUPERVISOR_DIR", marker_dir)
    sess = _fit_session(numerics)
    hist = sess.fit(batches, epochs=4, steps_per_epoch=4,
                    checkpoint_dir=str(tmp_path / "drill"))

    rb = hist.history["rollbacks"]
    assert len(rb) == 1
    assert rb[0]["at_step"] == 11 and rb[0]["reason"] == "loss spike"
    assert rb[0]["restored_step"] == 8   # last epoch-boundary good save
    assert sess.step_count == 16

    # the failure marker the Supervisor understands, with the reason
    from autodist_tpu.resilience.supervisor import read_failure_markers
    markers = read_failure_markers(marker_dir)
    assert len(markers) == 1
    assert "loss spike" in markers[0]["reason"]
    assert markers[0]["code"] == 74

    # exact-resume parity vs the uninterrupted oracle
    for a, b in zip(jax.tree_util.tree_leaves(oracle),
                    jax.tree_util.tree_leaves(_host(sess.params))):
        np.testing.assert_array_equal(a, b)


def test_rollback_budget_exhaustion_raises(tmp_path, monkeypatch):
    from autodist_tpu.numerics import NonFiniteError

    # an unrecoverable spike source: three queued events — one fires per
    # observation reaching step 11, so every post-rollback replay spikes
    # again until the budget (max_rollbacks=2) is exhausted.
    monkeypatch.setenv(
        "AUTODIST_CHAOS",
        "loss_spike@step=11,factor=1e6;loss_spike@step=11,factor=1e6;"
        "loss_spike@step=11,factor=1e6")
    numerics = {"on_nonfinite": "rollback", "spike_zscore": 3.0,
                "spike_window": 8, "max_rollbacks": 2}
    sess = _fit_session(numerics)
    with pytest.raises(NonFiniteError, match="budget"):
        sess.fit(_batches(4), epochs=4, steps_per_epoch=4,
                 checkpoint_dir=str(tmp_path / "ck"))


# -- analysis rules ----------------------------------------------------------

@pytest.mark.analysis
def test_numerics_rules():
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}]})

    # ERROR: quantizing compressor x saturating loss scale
    gi = GraphItem({"w": jax.ShapeDtypeStruct((64, 64), "float32")},
                   numerics={"loss_scale": 1e36})
    strat = AllReduce(compressor="HorovodCompressorEF").build(gi, spec)
    rep = analyze(strat, gi, mesh={"data": 8})
    assert rep.by_rule("numerics/loss-scale-saturates-wire")
    assert rep.has_errors()

    # WARN: bf16 gradients without the guard
    gi = GraphItem({"w": jax.ShapeDtypeStruct((64, 64), "bfloat16")})
    rep = analyze(AllReduce().build(gi, spec), gi, mesh={"data": 8})
    warn = rep.by_rule("numerics/no-loss-scale")
    assert warn and warn[0].severity.name == "WARN"

    # guard on (auto scale) clears both
    gi = GraphItem({"w": jax.ShapeDtypeStruct((64, 64), "bfloat16")},
                   numerics=True)
    rep = analyze(AllReduce().build(gi, spec), gi, mesh={"data": 8})
    assert not rep.by_rule("numerics/no-loss-scale")
    assert not rep.has_errors()


@pytest.mark.analysis
def test_cli_numerics_flag():
    from autodist_tpu.analysis.__main__ import main

    assert main(["mlp_bf16", "AllReduce", "--mesh", "data=8",
                 "--warn-as-error"]) == 1      # no-loss-scale WARN
    assert main(["mlp_bf16", "AllReduce", "--mesh", "data=8",
                 "--numerics", "on", "--warn-as-error"]) == 0
