"""Tracing & per-stage dumps (SURVEY §5.1 parity).

With AUTODIST_DUMP_GRAPHS set, a session run must leave the staged program
snapshots (plan table, StableHLO, optimized HLO) under the graphs dir; with
AUTODIST_TRACE_STEPS=N the profiler must write a trace capturing the first
N steps.
"""
import glob
import os

import jax
import numpy as np
import optax
import pytest

from autodist_tpu import const


@pytest.fixture
def tracing_env(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    monkeypatch.setenv("AUTODIST_DUMP_GRAPHS", "1")
    monkeypatch.setenv("AUTODIST_TRACE_STEPS", "2")
    monkeypatch.setattr(const, "DEFAULT_GRAPH_DIR",
                        str(tmp_path / "graphs"))
    monkeypatch.setattr(const, "DEFAULT_TRACE_DIR",
                        str(tmp_path / "traces"))
    # tracing.py imported the constants by value; patch them there too.
    from autodist_tpu.utils import tracing
    monkeypatch.setattr(tracing, "DEFAULT_GRAPH_DIR",
                        str(tmp_path / "graphs"))
    monkeypatch.setattr(tracing, "DEFAULT_TRACE_DIR",
                        str(tmp_path / "traces"))
    return tmp_path


def test_dumps_and_trace(tracing_env):
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.transformer_lm import transformer_lm

    _reset_default_autodist_for_testing()
    spec = transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16, seq_len=16)
    params = spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(mesh_axes={"data": 8})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(3):
        sess.run(spec.sample_batch(8))

    run_dirs = glob.glob(str(tracing_env / "graphs" / "*"))
    assert len(run_dirs) == 1
    names = sorted(os.path.basename(p) for p in
                   glob.glob(run_dirs[0] + "/*.txt"))
    assert names == ["1-strategy-plans.txt", "2-step-stablehlo.txt",
                     "3-step-optimized-hlo.txt", "4-placement.txt"]
    plans = open(run_dirs[0] + "/1-strategy-plans.txt").read()
    assert "decoder/layers_0/attn/query/kernel" in plans
    assert "stablehlo" in open(run_dirs[0] + "/2-step-stablehlo.txt").read()
    placement = open(run_dirs[0] + "/4-placement.txt").read()
    assert "decoder/layers_0/attn/query/kernel" in placement
    assert "spec=" in placement and "8xcpu" in placement

    # Profiler trace captured the first 2 steps and closed cleanly.
    trace_files = glob.glob(str(tracing_env / "traces" / "**" / "*"),
                            recursive=True)
    assert any(os.path.isfile(f) for f in trace_files)


def test_ascii_device_grid_shows_shard_ranges():
    """Direct visualization-util check: a data-sharded array renders one
    row per shard with its index range and device."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from autodist_tpu.utils.visualization import (ascii_device_grid,
                                                  sharding_table)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    x = jax.device_put(np.arange(32.0).reshape(16, 2),
                       NamedSharding(mesh, P("data")))
    grid = ascii_device_grid(x)
    assert grid.count("->") == 8
    assert "[0:2, 0:end]" in grid or "[0:2, :]" in grid.replace("0:end", ":")
    table = sharding_table({"v": x})
    assert "PartitionSpec('data'" in table and "(2, 2)" in table


def test_tracing_off_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    monkeypatch.delenv("AUTODIST_DUMP_GRAPHS", raising=False)
    monkeypatch.delenv("AUTODIST_TRACE_STEPS", raising=False)
    from autodist_tpu.utils import tracing
    monkeypatch.setattr(tracing, "DEFAULT_GRAPH_DIR",
                        str(tmp_path / "graphs"))
    monkeypatch.setattr(tracing, "DEFAULT_TRACE_DIR",
                        str(tmp_path / "traces"))

    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.transformer_lm import transformer_lm

    _reset_default_autodist_for_testing()
    spec = transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16, seq_len=16)
    params = spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(mesh_axes={"data": 8})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=spec.loss_fn)
    sess = ad.create_distributed_session()
    m = sess.run(spec.sample_batch(8))
    assert np.isfinite(m["loss"])
    assert not (tmp_path / "graphs").exists()
    assert not (tmp_path / "traces").exists()


@pytest.mark.slow
def test_partial_window_flushes_before_next_session(tracing_env):
    """Regression: a session running fewer steps than AUTODIST_TRACE_STEPS
    must still write its (partial) trace, and a second session must be able
    to start its own window."""
    import optax
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.utils import tracing as tr

    def one_step_session():
        _reset_default_autodist_for_testing()
        spec = transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                              head_dim=8, d_ff=32, max_len=16, seq_len=16)
        params = spec.init(jax.random.PRNGKey(0))
        ad = AutoDist(mesh_axes={"data": 8})
        with ad.scope():
            ad.capture(params=params, optimizer=optax.sgd(0.1),
                       loss_fn=spec.loss_fn)
        sess = ad.create_distributed_session()
        sess.run(spec.sample_batch(8))  # 1 step < AUTODIST_TRACE_STEPS=2

    one_step_session()
    one_step_session()  # must not raise "profiler already active"
    tr.flush_active_trace()
    run_dirs = glob.glob(str(tracing_env / "traces" / "*"))
    assert len(run_dirs) == 2
    for d in run_dirs:
        files = glob.glob(d + "/**/*", recursive=True)
        assert any(os.path.isfile(f) for f in files), d
