"""ResourceSpec / DeviceSpec tests (parity: reference tests/test_resource_spec.py,
tests/test_device_spec.py)."""
import os
import textwrap

import pytest

from autodist_tpu.resource_spec import (
    DeviceSpec,
    DeviceType,
    ResourceSpec,
    ResourceSpecError,
)


def _write(tmp_path, text):
    p = tmp_path / "spec.yml"
    p.write_text(textwrap.dedent(text))
    return str(p)


def test_single_node(tmp_path):
    spec = ResourceSpec(_write(tmp_path, """
        nodes:
          - address: 10.0.0.1
            chips: 4
    """))
    assert spec.num_nodes == 1
    assert spec.num_chips == 4
    # Single node auto-promoted to chief (reference resource_spec.py:120-150).
    assert spec.chief == "10.0.0.1"
    assert [d.name_string() for d in spec.tpu_devices] == [
        "10.0.0.1:TPU:0", "10.0.0.1:TPU:1", "10.0.0.1:TPU:2", "10.0.0.1:TPU:3"]


def test_multi_node_with_ssh(tmp_path):
    spec = ResourceSpec(_write(tmp_path, """
        nodes:
          - address: a
            chips: 4
            chief: true
          - address: b
            chips: 4
            ssh_config: conf
        ssh:
          conf:
            username: u
            key_file: /k
            port: 2222
        network_bandwidth: 100
        mesh:
          data: 2
          model: 4
    """))
    assert spec.num_nodes == 2
    assert spec.chief == "a"
    assert spec.num_chips == 8
    assert spec.ssh_config_for("b").username == "u"
    assert spec.ssh_config_for("b").port == 2222
    assert spec.ssh_config_for("a") is None
    assert spec.network_bandwidth_gbps == 100
    assert spec.mesh_hint == {"data": 2, "model": 4}


def test_gpus_key_compat(tmp_path):
    # The reference's yaml format lists gpu indices; we accept it.
    spec = ResourceSpec(_write(tmp_path, """
        nodes:
          - address: localhost
            gpus: [0, 1]
    """))
    assert spec.num_chips == 2


def test_cpu_only_node(tmp_path):
    spec = ResourceSpec(_write(tmp_path, """
        nodes:
          - address: localhost
            cpus: [0]
    """))
    assert spec.num_chips == 0
    assert [d.device_type for d in spec.devices] == [DeviceType.CPU]


def test_errors(tmp_path):
    with pytest.raises(ResourceSpecError):  # no chief among 2 nodes
        ResourceSpec(_write(tmp_path, """
            nodes:
              - {address: a, chips: 1}
              - {address: b, chips: 1}
        """))
    with pytest.raises(ResourceSpecError):  # two chiefs
        ResourceSpec(_write(tmp_path, """
            nodes:
              - {address: a, chips: 1, chief: true}
              - {address: b, chips: 1, chief: true}
        """))
    with pytest.raises(ResourceSpecError):  # duplicate address
        ResourceSpec(_write(tmp_path, """
            nodes:
              - {address: a, chips: 1, chief: true}
              - {address: a, chips: 1}
        """))
    with pytest.raises(ResourceSpecError):  # unknown ssh config
        ResourceSpec(_write(tmp_path, """
            nodes:
              - {address: a, chips: 1, chief: true, ssh_config: nope}
        """))
    with pytest.raises(ResourceSpecError):
        ResourceSpec(os.path.join(str(tmp_path), "missing.yml"))


def test_auto_from_local_devices():
    spec = ResourceSpec()
    assert spec.num_nodes == 1
    assert spec.chief == "localhost"
    assert spec.num_chips == 8  # virtual CPU device count from conftest


def test_device_spec_roundtrip():
    d = DeviceSpec("1.2.3.4", DeviceType.TPU, 3)
    assert d.name_string() == "1.2.3.4:TPU:3"
    assert DeviceSpec.from_string("1.2.3.4:TPU:3") == d
    assert DeviceSpec.from_string("host") == DeviceSpec("host", DeviceType.CPU, 0)
    assert DeviceSpec.from_string("host:2") == DeviceSpec("host", DeviceType.TPU, 2)
    assert DeviceSpec.from_string("h:gpu:1").device_type == DeviceType.GPU
    with pytest.raises(ValueError):
        DeviceSpec.from_string("a:b:c:d")
