"""Speculative decoding: exact greedy-equivalence with the target model.

The whole point of greedy-acceptance speculation is that the DRAFT can
be arbitrarily bad without changing the output — only the speed.  So
the oracle for every configuration is ``generate.make_generator`` greedy
decode of the TARGET model, asserted token-exact.
"""
import jax
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.speculative import make_speculative_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm

VOCAB = 61


def _lm(layers, heads=2, hd=8, seed=0, max_len=40):
    spec = transformer_lm(vocab_size=VOCAB, num_layers=layers,
                          num_heads=heads, head_dim=hd, d_ff=32,
                          max_len=max_len, seq_len=16,
                          attn_fn=dense_attention)
    return spec, spec.init(jax.random.PRNGKey(seed))


@pytest.fixture(scope="module")
def target():
    return _lm(3, seed=0)


@pytest.fixture(scope="module")
def draft():
    # Different depth AND different init: a genuinely disagreeing draft.
    return _lm(1, seed=9)


@pytest.mark.slow
@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_exact_greedy_equivalence_bad_draft(target, draft, gamma):
    """An unrelated draft model: low acceptance, identical output."""
    t_spec, t_params = target
    d_spec, d_params = draft
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, VOCAB, (3, 7)).astype(np.int32)
    new = 9
    oracle = np.asarray(make_generator(t_spec)(t_params, prompt, new))
    sg = make_speculative_generator(t_spec, d_spec)
    tokens, stats = sg(t_params, d_params, prompt, new, gamma)
    np.testing.assert_array_equal(np.asarray(tokens), oracle)
    assert int(stats["iterations"]) <= new    # >= 1 token per iteration
    proposed = np.asarray(stats["proposed"])
    accepted = np.asarray(stats["accepted"])
    assert proposed.shape == accepted.shape == (prompt.shape[0],)
    assert np.all(proposed >= accepted) and np.all(accepted >= 0)


@pytest.mark.slow
def test_perfect_draft_accepts_everything(target):
    """draft == target: every proposal matches the target's argmax, so
    each verify pass lands gamma+1 tokens and the loop runs
    ~ceil(new/(gamma+1)) iterations — the mechanical upper bound."""
    t_spec, t_params = target
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, VOCAB, (2, 5)).astype(np.int32)
    new, gamma = 12, 3
    oracle = np.asarray(make_generator(t_spec)(t_params, prompt, new))
    sg = make_speculative_generator(t_spec, t_spec)
    tokens, stats = sg(t_params, t_params, prompt, new, gamma)
    np.testing.assert_array_equal(np.asarray(tokens), oracle)
    iters = int(stats["iterations"])
    assert iters <= -(-new // (gamma + 1)) + 1, stats   # ceil + ragged tail
    np.testing.assert_array_equal(np.asarray(stats["accepted"]),
                                  np.asarray(stats["proposed"]))


@pytest.mark.slow
def test_ragged_acceptance_rows_advance_independently(target, draft):
    """Rows accept different counts per iteration (per-row position
    vector): a batch mixing an easy row (prompt repeated tokens) and
    hard rows must still match the oracle row-for-row."""
    t_spec, t_params = target
    d_spec, d_params = draft
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, VOCAB, (4, 6)).astype(np.int32)
    prompt[0, :] = 7                       # degenerate easy row
    new = 8
    oracle = np.asarray(make_generator(t_spec)(t_params, prompt, new))
    sg = make_speculative_generator(t_spec, d_spec)
    tokens, _ = sg(t_params, d_params, prompt, new, gamma=4)
    np.testing.assert_array_equal(np.asarray(tokens), oracle)


@pytest.mark.slow
def test_per_request_counters(target, draft):
    """proposed/accepted/bonus are per-request ``[B]`` vectors (the
    serving engine histograms acceptance length per request): rows with
    different agreement levels report different counts, and each row's
    counters obey the budget arithmetic ``accepted + bonus >= new`` is
    impossible — committed tokens are ``accepted + bonus`` capped at
    ``new``."""
    t_spec, t_params = target
    d_spec, d_params = draft
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, VOCAB, (3, 5)).astype(np.int32)
    new, gamma = 7, 3
    sg = make_speculative_generator(t_spec, d_spec)
    _, stats = sg(t_params, d_params, prompt, new, gamma)
    proposed = np.asarray(stats["proposed"])
    accepted = np.asarray(stats["accepted"])
    bonus = np.asarray(stats["bonus"])
    assert proposed.shape == accepted.shape == bonus.shape == (3,)
    assert np.all(accepted + bonus <= new)
    assert np.all(accepted + bonus >= 1)       # every row finished
    assert np.all(bonus >= 1)                  # a stop needs a mismatch
    #                                            or budget cap, but the
    #                                            FIRST round always
    #                                            commits >= 1 token
    # A perfect draft accepts everything on every row.
    sg_perfect = make_speculative_generator(t_spec, t_spec)
    _, st2 = sg_perfect(t_params, t_params, prompt, new, gamma)
    np.testing.assert_array_equal(np.asarray(st2["accepted"]),
                                  np.asarray(st2["proposed"]))


def test_validation_errors(target, draft):
    t_spec, t_params = target
    d_spec, d_params = draft
    other = transformer_lm(vocab_size=VOCAB + 1, num_layers=1, num_heads=2,
                           head_dim=8, d_ff=32, max_len=40, seq_len=16,
                           attn_fn=dense_attention)
    with pytest.raises(ValueError, match="vocab"):
        make_speculative_generator(t_spec, other)
    from autodist_tpu.models.ncf import ncf
    with pytest.raises(ValueError, match="transformer_lm-family"):
        make_speculative_generator(t_spec, ncf(num_users=4, num_items=4))
    sg = make_speculative_generator(t_spec, d_spec)
    prompt = np.zeros((1, 4), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        sg(t_params, d_params, prompt, 40, 4)   # 4+40+4 > max_len 40
    with pytest.raises(ValueError, match="gamma"):
        sg(t_params, d_params, prompt, 4, 0)
