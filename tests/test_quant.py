"""Weight-only int8 quantization: kernel exactness, decode parity.

The structural guarantee under test: the quantized decode path runs the
SAME TransformerLayer block math (rerouted through the Pallas int8
kernel by the flax interceptor), so its output must match the normal
generator running on the DEQUANTIZED weights — the quantization error is
a model change; the kernel itself adds none.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.quantize import (dequantize_lm_params,
                                          is_quantized, quantize_lm_params)
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.ops.quant import Quantized, int8_matmul, quantize_weight


def test_quantize_weight_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(64, 40).astype(np.float32) * 3)
    qw = quantize_weight(w)
    assert qw.q.dtype == jnp.int8 and qw.scale.shape == (1, 40)
    deq = qw.q.astype(jnp.float32) * qw.scale
    # symmetric round-to-nearest: |err| <= scale/2 per element
    err = jnp.abs(deq - w)
    assert float(jnp.max(err - qw.scale / 2)) <= 1e-6


def test_quantize_weight_zero_column_safe():
    w = jnp.zeros((8, 3))
    qw = quantize_weight(w)
    assert float(jnp.abs(qw.q.astype(jnp.float32) * qw.scale).max()) == 0.0
    np.testing.assert_array_equal(np.asarray(qw.scale), 1.0)


def test_quantize_weight_rejects_non_2d():
    with pytest.raises(ValueError, match="2-D"):
        quantize_weight(jnp.zeros((2, 3, 4)))


@pytest.mark.parametrize("m,k,n", [(5, 64, 40), (8, 128, 512),
                                   (1, 96, 1000), (16, 256, 513)])
def test_int8_matmul_matches_dequant_oracle(m, k, n):
    """The kernel (incl. its padding paths) computes exactly
    x @ (q * scale) up to f32 accumulation order."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    qw = quantize_weight(jnp.asarray(rng.randn(k, n).astype(np.float32)))
    ref = x @ (qw.q.astype(jnp.float32) * qw.scale)
    out = int8_matmul(x, qw)
    assert out.shape == (m, n) and out.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_int8_matmul_leading_dims_and_mismatch():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 3, 32).astype(np.float32))
    qw = quantize_weight(jnp.asarray(rng.randn(32, 16).astype(np.float32)))
    out = int8_matmul(x, qw)
    assert out.shape == (2, 3, 16)
    with pytest.raises(ValueError, match="contraction mismatch"):
        int8_matmul(jnp.zeros((2, 31)), qw)


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=96, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=32, seq_len=32)
    params = spec.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, 96, (3, 6)), jnp.int32)
    return spec, params, prompt


def test_quantized_decode_matches_dequantized_oracle(lm):
    """Token-for-token: quantized decode == normal decode on q*scale."""
    spec, params, prompt = lm
    qp = quantize_lm_params(params)
    assert is_quantized(qp) and not is_quantized(params)
    gen = make_generator(spec)
    tok_q, logits_q = gen.with_logits(qp, prompt, 10)
    dq = dequantize_lm_params(qp, spec)
    tok_d, logits_d = gen.with_logits(dq, prompt, 10)
    np.testing.assert_array_equal(np.asarray(tok_q), np.asarray(tok_d))
    np.testing.assert_allclose(np.asarray(logits_q), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-4)


def test_quantized_decode_tracks_full_precision(lm):
    """Int8 perturbs but does not destroy the model: the quantized
    logits correlate strongly with the full-precision ones.  (On a tiny
    random-init model the absolute logits are near zero, so a relative
    bound is meaningless — the kernel's own exactness is pinned by the
    dequant-oracle test above; real-model quantization quality is a
    property of int8 itself, not of this code.)  Uses a wider model
    than the fixture (d=64, corr 0.94 measured vs 0.90 at d=16; on
    random-init weights the logits are themselves noise, so the bar is
    a deterministic-seed floor, not a quality claim)."""
    spec = transformer_lm(vocab_size=96, num_layers=2, num_heads=4,
                          head_dim=16, d_ff=128, max_len=32, seq_len=32)
    params = spec.init(jax.random.PRNGKey(3))
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, 96, (3, 6)), jnp.int32)
    gen = make_generator(spec)
    _, logits_f = gen.with_logits(params, prompt, 10)
    _, logits_q = gen.with_logits(quantize_lm_params(params), prompt, 10)
    a = np.asarray(logits_f, np.float64).ravel()
    b = np.asarray(logits_q, np.float64).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.9, corr


def test_quantized_beam_and_sampling_run(lm):
    spec, params, prompt = lm
    qp = quantize_lm_params(params)
    gen = make_generator(spec)
    toks, lp = gen.beam_search(qp, prompt, 6, num_beams=3)
    assert toks.shape == (3, 12) and np.isfinite(np.asarray(lp)).all()
    sampled = gen(qp, prompt, 6, rng=jax.random.PRNGKey(1),
                  temperature=0.8, top_k=20)
    assert sampled.shape == (3, 12)


def test_quantized_score_raises(lm):
    spec, params, prompt = lm
    gen = make_generator(spec)
    with pytest.raises(ValueError, match="full-precision"):
        gen.score(quantize_lm_params(params), jnp.zeros((2, 4), jnp.int32))


def test_quantized_tree_is_half_the_bytes(lm):
    spec, params, _ = lm
    qp = quantize_lm_params(params)

    def nbytes(t):
        return sum(x.nbytes if isinstance(x, (Quantized,))
                   else np.asarray(x).nbytes
                   for x in jax.tree_util.tree_leaves(
                       t, is_leaf=lambda y: isinstance(y, Quantized)))

    # f32 weights -> int8 + f32 scales.  On this tiny model (d=16) the
    # kept-full-precision pieces (pos_embed, LN scales) and the
    # per-channel scales are a large fraction, so assert the honest
    # bound: under half.  (At 12Lx768 the ratio is ~0.26.)
    assert nbytes(qp) < 0.5 * nbytes(params)
