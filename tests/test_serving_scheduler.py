"""PagedDecodeEngine vs per-request `generate` (oracle), plus the
scheduler surface: SLO admission order, bounded-queue backpressure,
block-budget deferral, prefix reuse, chunked prefill, slot/block
recycling, and the no-leak invariant.

The engine's claim is the slot engine's — token-exact greedy decode —
carried over to the paged layout: block-table indirection plus masked
attention over gathered pool windows must reproduce the single-request
KV-cache decode bit-for-bit, including requests admitted mid-run and
requests whose prompt prefix comes from the trie instead of prefill.
"""
import jax
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.serving import (AdmissionError, PagedDecodeEngine,
                                  SLO_LATENCY, SLO_THROUGHPUT)

pytestmark = pytest.mark.serving

VOCAB = 61
# One shared engine geometry across the file: the compiled paged
# programs live in a module-scope jit cache, so identical shapes
# compile once per test process.
GEOM = dict(slots=2, window=32, block_size=8, num_blocks=24, chunk=4)


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


def _oracle(spec, params, prompt, n, eos_id=None):
    gen = make_generator(spec)
    out = gen(params, prompt[None, :], n, eos_id=eos_id)
    return np.asarray(out)[0]


def test_paged_matches_generate_exactly(lm):
    """Varied prompt/output lengths across fewer slots than requests:
    every harvested sequence equals the per-request oracle, blocks all
    recycle, and the pool shows no leak after the drain."""
    spec, params = lm
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (1, 9), (6, 2), (4, 7), (2, 4), (5, 6)]]
    eng = PagedDecodeEngine(spec, params, **GEOM)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    assert sorted(results) == sorted(ids)
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, prompt, n),
            err_msg=f"request {rid} (P={prompt.size}, N={n})")
    assert eng.stats.completed == len(reqs) > eng._slots
    assert eng.stats.generated_tokens == sum(n for _, n in reqs)
    assert 0 < eng.stats.slot_utilization <= 1.0
    eng.assert_no_leaks()


def test_paged_mid_run_admission_exact(lm):
    """The acceptance-criterion case: requests admitted WHILE the batch
    decodes are still oracle-exact (continuous batching proper)."""
    spec, params = lm
    rng = np.random.RandomState(4)
    eng = PagedDecodeEngine(spec, params, **GEOM)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)
    p3 = rng.randint(0, VOCAB, 5).astype(np.int32)
    r1 = eng.submit(p1, 6)
    assert eng.step()                 # r1 decoding
    r2 = eng.submit(p2, 5)            # joins mid-run
    eng.step()
    r3 = eng.submit(p3, 4)            # and another
    while eng.step():
        pass
    results = eng.results()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 6))
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 5))
    np.testing.assert_array_equal(results[r3], _oracle(spec, params, p3, 4))
    eng.assert_no_leaks()


def test_paged_prefix_reuse_skips_prefill(lm):
    """Requests sharing a cached prompt prefix reference the trie's
    blocks instead of recomputing them — exact output, non-zero cached
    token count, and the cached blocks are genuinely shared (refcount
    via the no-leak check after the drain)."""
    spec, params = lm
    rng = np.random.RandomState(2)
    shared = rng.randint(0, VOCAB, 17).astype(np.int32)   # 2 full blocks
    tails = [rng.randint(0, VOCAB, 3).astype(np.int32) for _ in range(3)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                            block_size=8, num_blocks=40, chunk=4)
    r0 = eng.submit(prompts[0], 5)                        # warms the trie
    out = eng.run()
    np.testing.assert_array_equal(out[r0],
                                  _oracle(spec, params, prompts[0], 5))
    assert eng.stats.cached_prompt_tokens == 0
    assert len(eng.trie) == 2
    ids = [eng.submit(p, 6) for p in prompts[1:]]
    out = eng.run()
    for rid, p in zip(ids, prompts[1:]):
        np.testing.assert_array_equal(
            out[rid], _oracle(spec, params, p, 6),
            err_msg="prefix-hit request diverged from oracle")
    # both followers skipped the 16 shared tokens
    assert eng.stats.cached_prompt_tokens == 32
    assert eng.stats.prefix_requests == 2
    assert eng.stats.prefix_hit_rate > 0
    assert eng.trie.stats.lookup_hits == 2
    eng.assert_no_leaks()


def test_paged_chunked_prefill_interleaves_and_stays_exact(lm):
    """A long prompt charges in prefill_chunk pieces BETWEEN decode
    chunks: the short request keeps generating while the long prompt
    prefills, and both stay oracle-exact."""
    spec, params = lm
    rng = np.random.RandomState(3)
    eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                            block_size=8, num_blocks=24, chunk=4,
                            prefill_chunk=5)
    short = rng.randint(0, VOCAB, 3).astype(np.int32)
    long_p = rng.randint(0, VOCAB, 23).astype(np.int32)
    ra = eng.submit(short, 12)
    eng.step()                        # short decoding
    ticks_before = eng.stats.ticks
    rb = eng.submit(long_p, 6)        # 23 tokens -> 5 chunks of <=5
    while eng.step():
        pass
    results = eng.results()
    np.testing.assert_array_equal(results[ra],
                                  _oracle(spec, params, short, 12))
    np.testing.assert_array_equal(results[rb],
                                  _oracle(spec, params, long_p, 6))
    assert eng.stats.prefill_chunks >= 5 + 1
    # decode ticks ran during the long prefill (interleaving, not a
    # stall-the-world prefill)
    assert eng.stats.ticks > ticks_before
    eng.assert_no_leaks()


def test_paged_slo_priority_admission(lm):
    """With one slot, a latency-class request submitted AFTER a
    throughput-class request is admitted (and completes) first."""
    spec, params = lm
    rng = np.random.RandomState(5)
    eng = PagedDecodeEngine(spec, params, slots=1, window=32,
                            block_size=8, num_blocks=24, chunk=4)
    opener = eng.submit(rng.randint(0, VOCAB, 2).astype(np.int32), 4)
    eng.step()                                       # slot busy
    r_tp = eng.submit(rng.randint(0, VOCAB, 2).astype(np.int32), 3,
                      slo=SLO_THROUGHPUT)
    r_lat = eng.submit(rng.randint(0, VOCAB, 2).astype(np.int32), 3,
                       slo=SLO_LATENCY)
    order = []
    while eng.step():
        for rid in eng.results():
            order.append(rid)
    for rid in eng.results():
        order.append(rid)
    assert order.index(r_lat) < order.index(r_tp)
    assert order[0] == opener
    eng.assert_no_leaks()


def test_paged_bounded_queue_backpressure(lm):
    """A full SLO queue rejects with the typed AdmissionError and a
    usable Retry-After hint; the other class's queue is unaffected."""
    spec, params = lm
    rng = np.random.RandomState(6)
    eng = PagedDecodeEngine(spec, params, slots=1, window=32,
                            block_size=8, num_blocks=24, chunk=4,
                            max_queue=2)
    prompts = [rng.randint(0, VOCAB, 2).astype(np.int32)
               for _ in range(4)]
    ids = [eng.submit(p, 3) for p in prompts[:2]]    # queue now full
    with pytest.raises(AdmissionError) as exc:
        eng.submit(prompts[2], 3)
    assert exc.value.retry_after_s > 0
    assert eng.stats.rejected_full == 1
    # throughput class still admits
    ids.append(eng.submit(prompts[3], 3, slo=SLO_THROUGHPUT))
    results = eng.run()
    for rid, p in zip(ids, [prompts[0], prompts[1], prompts[3]]):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, 3))
    eng.assert_no_leaks()


def test_paged_block_budget_defers_admission(lm):
    """Pool too small for two concurrent requests: the second DEFERS
    (stays queued, counted) until the first frees its blocks — decode
    never sees a mid-step OOM — then completes exactly."""
    spec, params = lm
    rng = np.random.RandomState(7)
    # capacity 5 blocks of 8; span 18+6=24 -> 3 blocks per request, and
    # a reserve of 0: two concurrent requests would need 6 > 5.
    eng = PagedDecodeEngine(spec, params, slots=2, window=32,
                            block_size=8, num_blocks=6, chunk=4,
                            cache_prefixes=False)
    p1 = rng.randint(0, VOCAB, 18).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 17).astype(np.int32)
    r1 = eng.submit(p1, 6)
    r2 = eng.submit(p2, 5)
    results = eng.run()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 6))
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 5))
    assert eng.stats.deferred_blocks > 0
    eng.assert_no_leaks()

    # a pool that could never hold one full-window request is rejected
    # at construction (the invariant that makes deferral always
    # resolvable, never a livelock)
    with pytest.raises(ValueError, match="cannot hold"):
        PagedDecodeEngine(spec, params, slots=2, window=32,
                          block_size=8, num_blocks=4)


def test_paged_trie_eviction_under_pressure(lm):
    """Cached-but-unpinned prefix blocks are LRU-evicted when a new
    admission needs the room (the pool never deadlocks on its own
    cache)."""
    spec, params = lm
    rng = np.random.RandomState(8)
    # capacity 7: one 24-span request holds 3; its 2 cached prompt
    # blocks stay in the trie after completion (5 used at peak).
    eng = PagedDecodeEngine(spec, params, slots=1, window=32,
                            block_size=8, num_blocks=8, chunk=4)
    p1 = rng.randint(0, VOCAB, 18).astype(np.int32)
    r1 = eng.submit(p1, 6)
    results = eng.run()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 6))
    assert len(eng.trie) == 2
    # a second, unrelated max-size request needs 4 blocks: 5 free + 2
    # cached -> eviction must free at least one cached block
    p2 = rng.randint(0, VOCAB, 20).astype(np.int32)
    r2 = eng.submit(p2, 6)
    results = eng.run()
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 6))
    eng.assert_no_leaks()


def test_paged_cancel_frees_blocks(lm):
    spec, params = lm
    rng = np.random.RandomState(9)
    eng = PagedDecodeEngine(spec, params, **GEOM, cache_prefixes=False)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)
    r1 = eng.submit(p1, 10)
    r2 = eng.submit(p2, 4)
    assert eng.step()
    used_mid = eng.pool.used_count
    assert used_mid > 0
    assert eng.cancel(r1)                 # in-flight: slot + blocks free
    assert not eng.cancel(r1)
    results = eng.run()
    assert sorted(results) == [r2]
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 4))
    eng.assert_no_leaks()
    assert eng.pool.used_count == 0

    r3 = eng.submit(p1, 4)
    assert eng.cancel(r3)                 # still queued: no blocks held
    assert eng.pool.used_count == 0


def test_paged_eos_and_per_request_knobs(lm):
    """Per-request eos stops only its own request (eos kept, truncated
    after); a sampled request decodes alongside an exact greedy one."""
    spec, params = lm
    rng = np.random.RandomState(10)
    prompt = rng.randint(0, VOCAB, 4).astype(np.int32)
    free = _oracle(spec, params, prompt, 6)
    eos = int(free[prompt.size + 1])
    if eos == free[prompt.size]:  # pragma: no cover - degenerate repeat
        pytest.skip("greedy repeats a token; eos choice ambiguous")
    eng = PagedDecodeEngine(spec, params, **GEOM,
                            rng=jax.random.PRNGKey(7))
    r_stop = eng.submit(prompt, 6, eos_id=eos)
    r_sampled = eng.submit(prompt, 6, temperature=1.0)
    results = eng.run()
    np.testing.assert_array_equal(results[r_stop],
                                  free[:prompt.size + 2])
    assert results[r_stop][-1] == eos
    sampled = results[r_sampled]
    assert sampled.size == prompt.size + 6
    assert np.all((sampled >= 0) & (sampled < VOCAB))
    eng.assert_no_leaks()


def test_paged_set_prefix_compat(lm):
    """The set_prefix shim: use_prefix requests prepend the registered
    system prompt, dedup its K/V through the trie, and return only
    prompt+generated — exact vs the concat oracle."""
    spec, params = lm
    rng = np.random.RandomState(11)
    prefix = rng.randint(0, VOCAB, 9).astype(np.int32)   # 1 full block
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 4).astype(np.int32)
    eng = PagedDecodeEngine(spec, params, **GEOM)
    assert eng.set_prefix(prefix) == 9
    r1 = eng.submit(p1, 5, use_prefix=True)
    out = eng.run()
    want1 = _oracle(spec, params, np.concatenate([prefix, p1]), 5)
    np.testing.assert_array_equal(out[r1], want1[prefix.size:])
    # second prefix request hits the cached block
    r2 = eng.submit(p2, 4, use_prefix=True)
    out = eng.run()
    want2 = _oracle(spec, params, np.concatenate([prefix, p2]), 4)
    np.testing.assert_array_equal(out[r2], want2[prefix.size:])
    assert eng.stats.cached_prompt_tokens == 8
    # clear_prefix: future plain submits unaffected, nothing freed that
    # the trie still caches
    eng.clear_prefix()
    with pytest.raises(ValueError, match="no prefix"):
        eng.submit(p1, 3, use_prefix=True)
    r3 = eng.submit(p1, 3)
    np.testing.assert_array_equal(eng.run()[r3],
                                  _oracle(spec, params, p1, 3))
    eng.assert_no_leaks()


def test_paged_pop_timings(lm):
    spec, params = lm
    rng = np.random.RandomState(12)
    eng = PagedDecodeEngine(spec, params, **GEOM)
    rid = eng.submit(rng.randint(0, VOCAB, 3).astype(np.int32), 5)
    eng.run()
    timings = eng.pop_timings()
    assert set(timings) == {rid}
    t = timings[rid]
    assert t["queue_wait_s"] >= 0
    assert t["ttft_s"] >= t["queue_wait_s"]
    assert t["generated"] == 5
    assert t["slo"] == SLO_LATENCY
    assert eng.pop_timings() == {}        # drained


def test_paged_validation(lm):
    spec, params = lm
    eng = PagedDecodeEngine(spec, params, slots=1, window=16,
                            block_size=8, num_blocks=8)
    with pytest.raises(ValueError, match="exceeds the engine"):
        eng.submit(np.arange(10, dtype=np.int32), 10)
    with pytest.raises(ValueError, match="at least one token"):
        eng.submit(np.zeros(0, np.int32), 2)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.arange(2, dtype=np.int32), 0)
    with pytest.raises(ValueError, match="out of vocab"):
        eng.submit(np.array([VOCAB + 3], np.int32), 2)
    with pytest.raises(ValueError, match="slo"):
        eng.submit(np.arange(2, dtype=np.int32), 2, slo="gold")
    with pytest.raises(ValueError, match="floor"):
        eng.submit(np.arange(2, dtype=np.int32), 2, temperature=1e-8)
    with pytest.raises(ValueError, match="rng"):
        eng.submit(np.arange(2, dtype=np.int32), 2, temperature=0.5)
    with pytest.raises(ValueError, match="multiple"):
        PagedDecodeEngine(spec, params, window=30, block_size=8)
    with pytest.raises(ValueError, match="max_len"):
        PagedDecodeEngine(spec, params, window=64, block_size=8)


@pytest.mark.slow
def test_paged_poisoned_after_failed_dispatch(lm, monkeypatch):
    import autodist_tpu.serving.scheduler as sched_mod

    spec, params = lm
    eng = PagedDecodeEngine(spec, params, **GEOM)
    eng.submit(np.arange(2, dtype=np.int32), 4)

    def boom(*a, **k):
        raise RuntimeError("tunnel dropped")

    monkeypatch.setattr(sched_mod, "_paged_prefill_program", boom)
    with pytest.raises(RuntimeError, match="tunnel dropped"):
        eng.run()
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.step()
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.submit(np.arange(2, dtype=np.int32), 2)
    eng.reset()
    prompt = np.arange(3, dtype=np.int32)
    rid = eng.submit(prompt, 4)
    np.testing.assert_array_equal(eng.run()[rid],
                                  _oracle(spec, params, prompt, 4))
    eng.assert_no_leaks()


@pytest.mark.slow
def test_paged_sustained_load_with_rebase(lm):
    """Steady stream over a small pool: tick rebases fire, blocks churn
    through many alloc/free cycles, every result stays exact, nothing
    leaks."""
    spec, params = lm
    rng = np.random.RandomState(13)
    eng = PagedDecodeEngine(spec, params, **GEOM)
    eng._REBASE_AT = 32
    ids, reqs, results = [], [], {}
    for _ in range(14):
        p = rng.randint(0, VOCAB, 3).astype(np.int32)
        reqs.append((p, 6))
        ids.append(eng.submit(p, 6))
        eng.step()
        results.update(eng.results())
    while eng.step():
        pass
    results.update(eng.results())
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, n))
    assert eng._tick < 32 + GEOM["window"] + GEOM["chunk"]
    eng.assert_no_leaks()


@pytest.mark.slow
def test_paged_mesh_sharded_pool(lm):
    """The mesh-sharded block pool: K/V pools sharded over the model
    (TP) axis — per-head attention has no cross-head math, so GSPMD
    runs each head group on its devices — oracle-exact, and donation
    keeps the sharding dispatch to dispatch."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    spec, params = lm
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    rng = np.random.RandomState(16)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (2, 6), (4, 4), (1, 7)]]
    eng = PagedDecodeEngine(spec, params, **GEOM, mesh=mesh)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))
    want = NamedSharding(mesh, PartitionSpec(None, None, None, "model"))
    assert eng._kc.sharding.is_equivalent_to(want, eng._kc.ndim)
    assert eng._vc.sharding.is_equivalent_to(want, eng._vc.ndim)
    eng.assert_no_leaks()

    with pytest.raises(ValueError, match="not in mesh axes"):
        PagedDecodeEngine(spec, params, **GEOM, mesh=mesh,
                          model_axis="data")


@pytest.mark.slow
def test_paged_quantized_params(lm):
    """Weight-only int8 trees route through the same paged programs."""
    from autodist_tpu.models.quantize import quantize_lm_params

    spec, params = lm
    qp = quantize_lm_params(params)
    rng = np.random.RandomState(14)
    gen = make_generator(spec)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 4), (2, 6), (5, 3)]]
    eng = PagedDecodeEngine(spec, qp, **GEOM)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        want = np.asarray(gen(qp, prompt[None, :], n))[0]
        np.testing.assert_array_equal(results[rid], want)
    eng.assert_no_leaks()


# ---------------------------------------------------------------------------
# slot-engine satellites: bounded queue + mid-flight prefix pinning
# ---------------------------------------------------------------------------

def test_slot_engine_bounded_queue(lm):
    from autodist_tpu.serving import DecodeEngine

    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=2,
                       max_queue=2)
    eng.submit(np.arange(2, dtype=np.int32), 3)
    eng.submit(np.arange(2, dtype=np.int32), 3)
    with pytest.raises(AdmissionError) as exc:
        eng.submit(np.arange(2, dtype=np.int32), 3)
    assert exc.value.retry_after_s > 0
    eng.run()
    # queue drained: submits admit again
    eng.submit(np.arange(2, dtype=np.int32), 3)
    eng.run()


def test_slot_engine_mid_flight_prefix_swap_pins_readers(lm):
    """set_prefix mid-flight: admitted requests keep decoding against
    the generation they pinned (exact vs the OLD-prefix oracle), later
    submits use the new one — the stale-prefix-KV bug is closed by
    per-request pinning, not by requiring an idle engine."""
    from autodist_tpu.serving import DecodeEngine

    spec, params = lm
    rng = np.random.RandomState(15)
    old = rng.randint(0, VOCAB, 5).astype(np.int32)
    new = rng.randint(0, VOCAB, 7).astype(np.int32)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)

    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=2)
    eng.set_prefix(old)
    r_old = eng.submit(p1, 8, use_prefix=True)
    assert eng.step()                       # r_old decoding against OLD
    eng.set_prefix(new)                     # swap mid-flight: allowed now
    r_new = eng.submit(p2, 5, use_prefix=True)
    while eng.step():
        pass
    results = eng.results()
    want_old = _oracle(spec, params, np.concatenate([old, p1]), 8)
    np.testing.assert_array_equal(results[r_old], want_old[old.size:],
                                  err_msg="in-flight reader lost its "
                                          "pinned prefix")
    want_new = _oracle(spec, params, np.concatenate([new, p2]), 5)
    np.testing.assert_array_equal(results[r_new], want_new[new.size:])

    # clear_prefix mid-flight: the reader keeps its pin to the end
    eng.set_prefix(old)
    r3 = eng.submit(p1, 6, use_prefix=True)
    assert eng.step()
    eng.clear_prefix()
    with pytest.raises(ValueError, match="no prefix"):
        eng.submit(p2, 3, use_prefix=True)
    while eng.step():
        pass
    out3 = eng.results()[r3]
    np.testing.assert_array_equal(
        out3, _oracle(spec, params, np.concatenate([old, p1]), 6)[old.size:])
