"""Shared helpers for the analyzer test files (one file per pass).

Not a test module — imported by tests/test_analysis*.py.
"""
import jax.numpy as jnp
import optax

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    VarConfig,
)

AXES8 = {"data": 8}


def make_gi():
    """Shapes chosen so every shipped builder lowers cleanly on 8 chips."""
    params = {
        "dense": {"kernel": jnp.zeros((16, 8)), "bias": jnp.zeros((16,))},
        "emb": {"table": jnp.zeros((96, 16))},
    }
    return GraphItem(params, optimizer=optax.adam(1e-3),
                     sparse_vars=["emb/table"])


def make_spec8():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}]})


def ar_node(name, **kw):
    return VarConfig(name, synchronizer=AllReduceSynchronizerConfig(**kw))


def ps_node(name, partitioner="", **kw):
    return VarConfig(name, synchronizer=PSSynchronizerConfig(**kw),
                     partitioner=partitioner)


def full_cover(gi, but=(), extra=()):
    """A strategy covering every trainable var with plain AllReduce,
    minus ``but``, plus ``extra`` nodes."""
    nodes = [ar_node(v.name) for v in gi.trainable_var_infos
             if v.name not in but]
    return Strategy(node_config=nodes + list(extra),
                    graph_config=GraphConfig())
