"""Optimizer-family matrix (reference test_graph_item.py parity).

The reference asserted its optimizer capture worked across 14 optimizer
configs (Adadelta … centered-RMSprop) on a dense+sparse model
(``tests/test_graph_item.py:54-123``).  TPU-natively, "update-op
detection" is gone — any ``optax.GradientTransformation`` is captured —
so the matrix asserts the stronger property: multi-step numeric parity
of the DISTRIBUTED step against a single-device loop for every optimizer
family, including Adafactor, whose factored second-moment slots are NOT
parameter-shaped (the opt-state sharding must replicate them while
sharding the param-shaped blocks).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce, PartitionedPS, PSLoadBalancing

STEPS = 3

OPTIMIZERS = {
    "sgd": lambda: optax.sgd(0.05),
    "momentum_nesterov": lambda: optax.sgd(0.05, momentum=0.9,
                                           nesterov=True),
    "adam": lambda: optax.adam(1e-2),
    "adamw": lambda: optax.adamw(1e-2, weight_decay=1e-3),
    "adagrad": lambda: optax.adagrad(0.05),
    "adadelta": lambda: optax.adadelta(0.5),
    "adamax": lambda: optax.adamax(1e-2),
    "nadam": lambda: optax.nadam(1e-2),
    "rmsprop": lambda: optax.rmsprop(1e-2),
    "rmsprop_centered_momentum": lambda: optax.rmsprop(
        1e-2, centered=True, momentum=0.9),
    "lamb": lambda: optax.lamb(1e-2),
    "lion": lambda: optax.lion(1e-3),
    # min_dim_size_to_factor=8 so factoring actually engages at this
    # test's parameter shapes (the default 128 would silently fall back
    # to full second moments).
    "adafactor": lambda: optax.adafactor(1e-2, min_dim_size_to_factor=8),
}

BUILDERS = [PSLoadBalancing, AllReduce, PartitionedPS]


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _problem():
    rng = np.random.RandomState(0)
    params = {
        "dense": {"w": jnp.asarray(rng.randn(16, 8) * 0.2, jnp.float32),
                  "b": jnp.zeros((8,))},
        "emb": {"table": jnp.asarray(rng.randn(32, 8) * 0.2, jnp.float32)},
    }

    def loss_fn(p, batch):
        h = jnp.take(p["emb"]["table"], batch["ids"], axis=0).mean(axis=1)
        pred = (batch["x"] @ p["dense"]["w"] + p["dense"]["b"]) + h
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "ids": rng.randint(0, 32, (16, 3)).astype(np.int32),
             "y": rng.randn(16, 8).astype(np.float32)}
    return params, loss_fn, batch


def _single_device_losses(make_opt, params, loss_fn, batch):
    opt = make_opt()
    p, s = params, opt.init(params)
    vg = jax.value_and_grad(loss_fn)
    losses = []
    for _ in range(STEPS):
        loss, g = vg(p, batch)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("builder_cls", BUILDERS,
                         ids=[b.__name__ for b in BUILDERS])
@pytest.mark.parametrize("opt_name", list(OPTIMIZERS))
def test_optimizer_matrix_parity(opt_name, builder_cls):
    make_opt = OPTIMIZERS[opt_name]
    params, loss_fn, batch = _problem()
    ref = _single_device_losses(make_opt, params, loss_fn, batch)

    ad = AutoDist(strategy_builder=builder_cls())
    with ad.scope():
        ad.capture(params=params, optimizer=make_opt(), loss_fn=loss_fn,
                   sparse_vars=["emb/table"])
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(STEPS)]
    np.testing.assert_allclose(losses, ref, rtol=2e-4)


def test_adafactor_factored_slots_replicate():
    """With factoring ENGAGED, Adafactor's state is not isomorphic to
    params (v_row/v_col vectors + placeholder v), so the opt-state layout
    replicates it wholesale — the documented ``opt_spec_tree`` behavior:
    only param-shaped blocks ride the variables' sharded specs.  That is
    the right trade here: factored slots are O(rows+cols), the memory the
    factoring already saved.  Training parity under this layout is pinned
    by the matrix above; this test pins the layout itself (and that
    factoring really is active — the state must contain the (16,) and
    (8,) factor vectors for the dense kernel)."""
    params, loss_fn, batch = _problem()
    ad = AutoDist(strategy_builder=PartitionedPS())
    with ad.scope():
        ad.capture(params=params,
                   optimizer=optax.adafactor(1e-2, min_dim_size_to_factor=8),
                   loss_fn=loss_fn, sparse_vars=["emb/table"])
    sess = ad.create_distributed_session()
    sess.run(batch)
    leaves = jax.tree_util.tree_leaves(sess.opt_state)
    shapes = {tuple(np.shape(x)) for x in leaves}
    assert {(16,), (8,), (32,)} <= shapes, shapes   # real factor vectors
    from jax.sharding import PartitionSpec as P
    specs = {x.sharding.spec for x in leaves}
    assert specs == {P()}, specs
